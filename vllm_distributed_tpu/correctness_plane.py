"""Correctness sentinel (``VDT_CORRECTNESS``): fleet canary probes,
in-flight numerics watch, and auto-quarantine signals.

The serving stack routes hot paths through a dozen lossy-or-risky
mechanisms behind default-off flags — quantized collectives, the fused
block mega-kernel, TPLA latent sharding, tiered KV spill/restore,
disagg handoff, fleet warm starts. All of them are contractually
token-invisible, and none of them were *watched*: a corrupted spill
file or a bad HBM replica after a warm start would surface only as
user complaints. This module is the detector. Three mechanisms, one
suspicion ladder:

* **Canary probes** — the DP client's maintenance tick periodically
  fans one pinned greedy golden prompt out to every in-rotation
  replica (``VDT_CANARY_INTERVAL_S``). Canaries ride the REAL serving
  path (same wire, same scheduler, same kernels) but are marked
  best-effort (priority 1) under the reserved ``_canary`` tenant, so
  the QoS layer never charges them to anyone's quota, the SLO scorer
  never sees them (their outputs are absorbed here, before the output
  processor), and the failover journal never migrates them (a canary
  must pin to the replica it probes). Each completed round compares
  every replica's token output + final-position logprob fingerprint
  against a content-addressed **reference journal**: the key is a
  sha256 over (prompt ids, sampling knobs, flag-config fingerprint),
  so a fusion-on fleet and a fusion-off fleet self-seed DISJOINT
  references and a flag flip can never masquerade as corruption. The
  first unanimous round seeds the journal; after that any replica that
  strays diverges with cause ``reference`` (tokens) or ``logprob``
  (fingerprint drifted past tolerance).

* **Cross-replica voting** — the same round majority-votes the
  replicas against each other, which catches single-replica corruption
  the journal cannot *date* (a reference seeded from an already-bad
  majority is wrong forever; a vote is wrong only while the bad
  replicas outnumber the good). A minority replica diverges with cause
  ``vote`` and climbs the suspicion ladder. A FLEET-WIDE reference
  mismatch (every replica agrees, journal disagrees) counts a
  divergence per replica but suspects nobody — there is no odd one
  out to isolate, only an operator-visible signal.

* **Numerics watch** — the model runner's pre-sampling tap
  (:class:`NumericsTap`) reduces each step's logits to three scalars
  on device (non-finite count, mean entropy, mean top-1/top-2 margin)
  and feeds rolling histograms (``vdt:logits_entropy``,
  ``vdt:logits_top_margin``) plus a NaN counter per replica. The
  front-end drift detector compares each replica's rolling entropy
  window against the fleet mean (``VDT_NUMERICS_DRIFT_FRAC``); NaNs
  and sustained drift climb a second strike ladder.

Either ladder reaching ``VDT_CANARY_QUARANTINE_N`` hardens into a
**replica-quarantine hint** consumed by the fleet controller under
``VDT_FLEET_SIGNALS`` — drain + respawn through the PR-16 force-cycle
machinery, never a new actuation path. ``VDT_CORRECTNESS=0`` (the
default) constructs nothing: no injector, no tap, no new stats keys,
old wire bytes.
"""

import hashlib
import time
from collections import Counter, deque
from typing import Optional

from vllm_distributed_tpu.core.sched.qos import CANARY_TENANT
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.stats import Histogram
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)

# Canary request ids: "<prefix><round>-r<replica>". The prefix is the
# ownership test on the output path (absorbed before any front-end
# bookkeeping), so it must never collide with user request ids.
CANARY_PREFIX = "vdt-canary-"
# Decode length of every probe: long enough that a single corrupted
# page or a drifted logit actually lands in the compared window, short
# enough to be noise on a serving replica.
CANARY_DECODE_TOKENS = 8
# |logprob - reference| above this is a fingerprint divergence even
# with identical tokens (catches quality drift below the argmax).
CANARY_LOGPROB_TOL = 0.05
# A round that hasn't fully resolved after this many intervals is
# expired: responders are scored, silent replicas diverge as "timeout"
# (if at least one replica DID answer — a globally idle fleet is the
# wedge detector's problem, not a correctness signal).
CANARY_ROUND_TIMEOUT_INTERVALS = 4.0
# Pinned golden prompts (token ids — canaries are injected below the
# tokenizer). Small ids exist in every vocabulary; each round rotates
# so a position-dependent corruption can't hide behind one prompt.
GOLDEN_PROMPTS = (
    (11, 29, 7, 3, 17, 23, 5, 13),
    (2, 71, 41, 19, 31, 59, 43, 37),
    (89, 13, 61, 47, 83, 5, 67, 53),
    (73, 79, 3, 97, 11, 2, 19, 29),
)

# Rolling numerics window (per-step means) the drift detector compares
# against the fleet aggregate.
NUMERICS_WINDOW = 128
# The tap re-derives logits from the sampled hidden rows (an extra
# lm-head matmul), so it samples every Nth decode step instead of all
# of them — real numerics poison (a NaN'd KV page, a biased unit)
# persists across steps, so a strided watch still catches it while
# bounding the steady-state cost to ~1/N of a logits pass.
NUMERICS_TAP_STRIDE = 16
ENTROPY_BUCKETS = (0.125, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0)
MARGIN_BUCKETS = (0.125, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)


def canary_sampling_params() -> SamplingParams:
    """Pinned greedy knobs: temperature 0 (argmax — replicas serving
    the same weights MUST agree), fixed decode length, eos ignored so
    length never varies, chosen-token logprobs for the fingerprint."""
    return SamplingParams(temperature=0.0, max_tokens=CANARY_DECODE_TOKENS,
                          ignore_eos=True, logprobs=1)


def flag_config_fingerprint() -> str:
    """Hash of the full VDT flag configuration (minus the sentinel's
    own knobs): the reference-journal key component that keeps
    fusion-on and fusion-off references from ever crossing. Over-keying
    is safe — an unrelated flag flip merely re-seeds."""
    from vllm_distributed_tpu import envs
    parts = []
    for name in sorted(envs.environment_variables):
        if name.startswith(("VDT_CORRECTNESS", "VDT_CANARY",
                            "VDT_NUMERICS")):
            continue
        try:
            parts.append(f"{name}={envs.environment_variables[name]()!r}")
        except Exception:  # noqa: BLE001 - malformed env value; the
            # component reading it will raise on ITS read. Key on the
            # raw text so the fingerprint still separates configs.
            import os
            parts.append(f"{name}={os.getenv(name)!r}")
    return hashlib.sha256(";".join(parts).encode()).hexdigest()[:16]


def reference_key(prompt: tuple, sp: SamplingParams, flag_fp: str) -> str:
    """Content address of one golden prompt's reference entry."""
    text = (f"{flag_fp}|{list(prompt)}|t={sp.temperature}"
            f"|n={sp.max_tokens}|lp={sp.logprobs}")
    return hashlib.sha256(text.encode()).hexdigest()[:24]


class NumericsTap:
    """Per-replica pre-sampling numerics watch, host side. The model
    runner dispatches a tiny jitted reduction over the SAME gathered
    hidden rows the sampler consumes (one extra LM-head matmul per
    step — the plane's documented cost) and hands the device array
    here; the harvest runs one step behind so the tap never blocks the
    dispatch path. Constructed only under VDT_CORRECTNESS."""

    def __init__(self) -> None:
        self.nan_steps = 0
        self.entropy = Histogram(ENTROPY_BUCKETS)
        self.top_margin = Histogram(MARGIN_BUCKETS)
        self._window: deque = deque(maxlen=NUMERICS_WINDOW)
        self._pending = None

    def dispatch(self, dev) -> None:
        """Queue one step's [nonfinite, mean_entropy, mean_margin]
        device reduction; harvests the previous step's first."""
        self.harvest()
        self._pending = dev

    def harvest(self) -> None:
        dev, self._pending = self._pending, None
        if dev is None:
            return
        import numpy as np
        try:
            arr = np.asarray(dev)
        except Exception:  # noqa: BLE001 - a poisoned step (device
            # error) must not take the stats path down with it; the
            # step's own fetch surfaces the failure.
            return
        nonfinite = float(arr[0])
        if fault_injection.should_fire("numerics.nan_inject"):
            # Drill: a single NaN landed in this step's logits.
            nonfinite += 1.0
        if nonfinite > 0.0 or not np.isfinite(arr[1:]).all():
            self.nan_steps += 1
            return  # poisoned step: entropy/margin means are garbage
        self.entropy.observe(float(arr[1]))
        self.top_margin.observe(float(arr[2]))
        self._window.append(float(arr[1]))

    def stats(self) -> dict:
        """Flat per-replica entry for the runner's get_stats (the DP
        aggregator maps it per replica index — never summed)."""
        self.harvest()
        window = list(self._window)
        return {
            "nan_steps": self.nan_steps,
            "entropy": self.entropy.to_dict(),
            "top_margin": self.top_margin.to_dict(),
            "entropy_window_mean": (sum(window) / len(window)
                                    if window else None),
            "window_steps": len(window),
        }


class CorrectnessPlane:
    """Front-end correctness sentinel: canary round state machine,
    reference journal, vote, numerics drift, and the suspicion ladders
    that feed the fleet's quarantine hints. Owned by the DP client and
    driven from its maintenance tick under the balancer lock — no
    internal locking needed."""

    def __init__(self, events: Optional[ev.EventRecorder] = None) -> None:
        from vllm_distributed_tpu import envs
        self.interval_s = envs.VDT_CANARY_INTERVAL_S
        self.quarantine_n = envs.VDT_CANARY_QUARANTINE_N
        self.drift_frac = envs.VDT_NUMERICS_DRIFT_FRAC
        self.events = events if events is not None else ev.EventRecorder()
        self.sampling = canary_sampling_params()
        self.flag_fp = flag_config_fingerprint()
        # Reference journal: content address -> {"tokens", "lp"}.
        self.journal: dict[str, dict] = {}
        # Round state: replica -> {"tokens": [...], "lp": float|None,
        # "done": bool}; None between rounds.
        self._round: Optional[dict[int, dict]] = None
        self._round_idx = 0
        # Round id the in-flight probes were minted under: outputs
        # from an EXPIRED round can still stream in after the next
        # round opened (probes are never aborted — they finish on
        # their own token budget) and must not pollute its slots.
        self._round_id = -1
        self._round_started = float("-inf")
        self._round_deadline = 0.0
        self._round_key = ""
        # Counters (exact — one plane owns the fleet's canaries).
        self.probes: dict[int, int] = {}
        self.divergences: dict[int, dict[str, int]] = {}
        # Suspicion ladders: consecutive divergent canary rounds and
        # consecutive bad numerics observations, per replica. Either
        # reaching quarantine_n emits ONE hint per episode.
        self._canary_strikes: dict[int, int] = {}
        self._numerics_strikes: dict[int, int] = {}
        self._suspect: dict[int, int] = {}
        self._hinted: set[int] = set()
        self._pending_hints: dict[int, str] = {}
        self.quarantine_hints_emitted = 0
        # Numerics drift bookkeeping: replica -> last seen nan_steps.
        self._last_nan: dict[int, int] = {}
        logger.info(
            "correctness sentinel on: %d golden prompts every %.1fs, "
            "quarantine after %d strikes, flag fingerprint %s",
            len(GOLDEN_PROMPTS), self.interval_s, self.quarantine_n,
            self.flag_fp)

    # ------------------------------------------------------------------
    # Canary rounds
    # ------------------------------------------------------------------
    def owns(self, req_id: str) -> bool:
        return req_id.startswith(CANARY_PREFIX)

    def due_probes(self, targets: list[int],
                   now: Optional[float] = None) -> list[tuple]:
        """(replica, EngineCoreRequest) pairs to submit this tick —
        empty while a round is in flight or the interval hasn't
        elapsed. ``targets`` is the in-rotation replica set."""
        if now is None:
            now = time.monotonic()
        if self._round is not None:
            if now < self._round_deadline:
                return []
            self._expire_round()
        if now - self._round_started < self.interval_s or not targets:
            return []
        prompt = GOLDEN_PROMPTS[self._round_idx % len(GOLDEN_PROMPTS)]
        self._round_key = reference_key(prompt, self.sampling,
                                        self.flag_fp)
        self._round = {
            i: {"tokens": [], "lp": None, "done": False} for i in targets
        }
        self._round_started = now
        self._round_deadline = now + max(
            1.0, CANARY_ROUND_TIMEOUT_INTERVALS * max(self.interval_s, 1.0))
        rid_round = self._round_idx
        self._round_id = rid_round
        self._round_idx += 1
        out = []
        for i in targets:
            rid = f"{CANARY_PREFIX}{rid_round}-r{i}"
            req = EngineCoreRequest(
                request_id=rid,
                prompt_token_ids=list(prompt),
                sampling_params=canary_sampling_params(),
                priority=1,  # best-effort: shed/preempted first
                tenant=CANARY_TENANT,  # QoS-exempt reserved bucket
            )
            if ev.trace_plane_enabled():
                # A divergence links straight to its Perfetto trace.
                from vllm_distributed_tpu import trace_plane
                req.trace_ctx = trace_plane.mint_trace_ctx(rid)
            out.append((i, req))
        return out

    def on_submit_failed(self, req_id: str) -> None:
        """The replica refused the canary (mid-death): drop it from the
        round so the survivors still resolve."""
        i = self._replica_of(req_id)
        if self._round is not None and i in self._round:
            del self._round[i]
            self._maybe_resolve()

    def on_output(self, out) -> None:
        """Absorb one canary EngineCoreOutput (called from the DP
        client's output path, lock held). Canary outputs never reach
        the output processor — that is what keeps them out of SLO
        scoring and front-end stats."""
        i = self._replica_of(out.req_id)
        if (self._round is None or i not in self._round
                or self._round_of(out.req_id) != self._round_id):
            return  # stale round (expired, or a restarted replica)
        slot = self._round[i]
        tokens = list(out.new_token_ids or [])
        if tokens and fault_injection.should_fire("canary.flip_token"):
            # Drill: one replica's canary output perturbed in flight
            # (absorb order is fixed, so rate 0.5 on a 2-replica fleet
            # always corrupts the same replica).
            tokens = [t + 1 for t in tokens]
        slot["tokens"].extend(tokens)
        if out.logprobs:
            last = out.logprobs[-1]
            if isinstance(last, dict) and slot["tokens"]:
                lp = last.get(slot["tokens"][-1])
                if lp is not None:
                    slot["lp"] = float(lp)
        if out.finished:
            slot["done"] = True
            self.probes[i] = self.probes.get(i, 0) + 1
            self._maybe_resolve()

    def _replica_of(self, req_id: str) -> Optional[int]:
        try:
            return int(req_id.rsplit("-r", 1)[1])
        except (IndexError, ValueError):
            return None

    def _round_of(self, req_id: str) -> Optional[int]:
        try:
            return int(req_id[len(CANARY_PREFIX):].split("-", 1)[0])
        except (IndexError, ValueError):
            return None

    def _maybe_resolve(self) -> None:
        if self._round and all(s["done"] for s in self._round.values()):
            done, self._round = self._round, None
            self._resolve(done)

    def _expire_round(self) -> None:
        done, self._round = self._round, None
        responders = {i: s for i, s in done.items() if s["done"]}
        if not responders:
            # Globally idle/stuck fleet: no correctness signal at all —
            # liveness is the wedge detector's ladder, not ours.
            return
        for i in set(done) - set(responders):
            self._diverge(i, "timeout")
        self._resolve(responders)

    # -- Scoring --------------------------------------------------------
    def _resolve(self, round_state: dict[int, dict]) -> None:
        key = self._round_key
        results = {i: (tuple(s["tokens"]), s["lp"])
                   for i, s in round_state.items()}
        votes = Counter(tokens for tokens, _ in results.values())
        majority_tokens, majority_n = votes.most_common(1)[0]
        ref = self.journal.get(key)
        if ref is None and majority_n == len(results):
            # First unanimous round self-seeds the reference.
            lps = [lp for _, lp in results.values() if lp is not None]
            self.journal[key] = {
                "tokens": list(majority_tokens),
                "lp": (sum(lps) / len(lps)) if lps else None,
            }
            self._clean_round(results)
            return
        clean: set[int] = set()
        for i, (tokens, lp) in sorted(results.items()):
            if len(results) > 1 and tokens != majority_tokens \
                    and votes[tokens] < majority_n:
                # The vote isolates the odd one out — the strongest
                # signal (it can date corruption the journal predates).
                self._diverge(i, "vote")
            elif ref is not None and tokens != tuple(ref["tokens"]):
                # Tokens stray from the journal. With a majority intact
                # this replica still strayed alone; fleet-wide (every
                # replica agreeing against the journal) nobody is
                # suspected — there is no odd one out to isolate.
                self._diverge(i, "reference",
                              suspect=majority_n < len(results))
            elif (ref is not None and ref.get("lp") is not None
                  and lp is not None
                  and abs(lp - ref["lp"]) > CANARY_LOGPROB_TOL):
                self._diverge(i, "logprob")
            else:
                clean.add(i)
        self._clean_round({i: results[i] for i in clean})

    def _clean_round(self, results: dict) -> None:
        for i in results:
            self._canary_strikes[i] = 0
            if self._numerics_strikes.get(i, 0) == 0:
                self._suspect[i] = 0
                self._hinted.discard(i)

    def _diverge(self, i: int, cause: str, suspect: bool = True) -> None:
        per = self.divergences.setdefault(i, {})
        per[cause] = per.get(cause, 0) + 1
        self.events.record("", ev.CANARY_DIVERGENCE,
                           {"replica": i, "cause": cause})
        logger.warning("correctness: replica %s canary DIVERGED (%s)",
                       i, cause)
        if suspect:
            self._canary_strikes[i] = self._canary_strikes.get(i, 0) + 1
            self._bump_suspicion(i, cause, self._canary_strikes[i])

    # ------------------------------------------------------------------
    # Numerics feed (per stats poll, per replica)
    # ------------------------------------------------------------------
    def observe_numerics(self, per_replica: dict[int, dict]) -> None:
        """Per-replica numerics snapshots from the DP stats merge: NaN
        deltas and rolling-window entropy drift climb the numerics
        strike ladder; a clean poll resets it."""
        means = {i: nd.get("entropy_window_mean")
                 for i, nd in per_replica.items()
                 if isinstance(nd, dict)
                 and isinstance(nd.get("entropy_window_mean"),
                                (int, float))}
        # Median, not mean: a single poisoned replica drags the fleet
        # MEAN toward itself far enough to flag its healthy peers too
        # (3 replicas at 1, 1, 8 put the mean at 3.3 — every replica
        # "drifts"). The median stays with the healthy majority.
        fleet_mean = None
        if means:
            vals = sorted(means.values())
            m = len(vals)
            fleet_mean = (vals[m // 2] if m % 2
                          else 0.5 * (vals[m // 2 - 1] + vals[m // 2]))
        for i, nd in per_replica.items():
            if not isinstance(nd, dict):
                continue
            bad = None
            nan = int(nd.get("nan_steps", 0) or 0)
            if nan > self._last_nan.get(i, 0):
                bad = "nan_logits"
            self._last_nan[i] = nan
            if (bad is None and self.drift_frac > 0
                    and fleet_mean is not None and len(means) > 1
                    and i in means
                    and abs(means[i] - fleet_mean)
                    > self.drift_frac * max(abs(fleet_mean), 1e-6)):
                bad = "numerics_drift"
            if bad is None:
                self._numerics_strikes[i] = 0
                if self._canary_strikes.get(i, 0) == 0 \
                        and self._suspect.get(i):
                    self._suspect[i] = 0
                    self._hinted.discard(i)
                continue
            per = self.divergences.setdefault(i, {})
            per[bad] = per.get(bad, 0) + 1
            self._numerics_strikes[i] = \
                self._numerics_strikes.get(i, 0) + 1
            self._bump_suspicion(i, bad, self._numerics_strikes[i])

    # ------------------------------------------------------------------
    # Suspicion → quarantine
    # ------------------------------------------------------------------
    def _bump_suspicion(self, i: int, cause: str, strikes: int) -> None:
        self._suspect[i] = 1
        if strikes >= self.quarantine_n and i not in self._hinted:
            self._hinted.add(i)
            self._pending_hints[i] = cause
            self.quarantine_hints_emitted += 1
            logger.error(
                "correctness: replica %d QUARANTINE hint (%s, %d "
                "consecutive strikes)", i, cause, strikes)

    def quarantine_hints(self) -> dict[int, str]:
        """Drain pending replica-quarantine hints ({replica: cause}) —
        the fleet controller's VDT_FLEET_SIGNALS feed."""
        hints, self._pending_hints = self._pending_hints, {}
        return hints

    def suspects(self) -> dict[int, int]:
        return {i: v for i, v in sorted(self._suspect.items()) if v}

    def forget_replica(self, i: int) -> None:
        """A replica left rotation (retired or respawned fresh): its
        suspicion history died with it."""
        for store in (self._canary_strikes, self._numerics_strikes,
                      self._suspect, self._last_nan,
                      self._pending_hints):
            store.pop(i, None)
        self._hinted.discard(i)
        if self._round is not None and i in self._round:
            del self._round[i]
            self._maybe_resolve()

    # ------------------------------------------------------------------
    def get_stats(self) -> dict:
        """The ``correctness`` entry of the DP stats aggregation —
        per-replica maps, NEVER numeric-summed across replicas."""
        return {
            "probes": dict(sorted(self.probes.items())),
            "divergences": {i: dict(c) for i, c in
                            sorted(self.divergences.items())},
            "suspects": {i: int(bool(v)) for i, v in
                         sorted(self._suspect.items())},
            "quarantine_hints": self.quarantine_hints_emitted,
            "journal_entries": len(self.journal),
            "rounds": self._round_idx,
            "round_in_flight": self._round is not None,
            "flag_fingerprint": self.flag_fp,
        }
