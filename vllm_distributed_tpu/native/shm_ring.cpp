// Lock-free single-writer / multi-reader broadcast ring over POSIX
// shared memory.
//
// Native-runtime equivalent of the reference's
// vllm/distributed/device_communicators/shm_broadcast.py (ShmRingBuffer +
// MessageQueue): one producer broadcasts serialized control messages
// (scheduler outputs, engine RPCs) to N same-host consumer processes
// without a socket hop or per-message syscalls. The Python layer
// (distributed/shm_broadcast.py) chunks pickled payloads into fixed-size
// slots; this file owns the shared-memory layout and the atomic
// slot-handoff protocol only — no serialization, no Python objects.
//
// Layout (all cache-line aligned):
//   Header { magic, chunk_size, num_chunks, max_readers, num_readers,
//            writer_seq }                     -- one per segment
//   SlotState[num_chunks] { seq, read_mask }  -- per-slot handoff state
//   data[num_chunks][chunk_size]              -- payload slots
//
// Protocol (seqlock-flavored, same invariants as the reference's
// written_flag/read_count bytes but word-sized and explicitly atomic):
//   * Writer claims slot (writer_seq % num_chunks) and spins until every
//     registered reader has consumed the slot's PREVIOUS lap (read_mask
//     full or slot never written). It then copies the payload, publishes
//     by storing seq = writer_seq + 1 (release), clears read_mask, and
//     bumps writer_seq.
//   * Reader r spins on slot (reader_seq % num_chunks) until seq ==
//     reader_seq + 1 (acquire), copies the payload out, then sets bit r
//     in read_mask (release) and bumps its private reader_seq.
//   * All waits are bounded by a caller deadline; -2 = timeout.
//
// Spin-waits sleep 50us after a short hot phase: control messages are
// ~KHz, so the writer/readers are usually first-try; the sleep bounds
// burn when a reader stalls (e.g. under a debugger).

#include <atomic>
#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x53484d52494e4731ull;  // "SHMRING1"
constexpr int kMaxReaders = 64;

struct alignas(64) Header {
  std::atomic<uint64_t> magic;
  uint64_t chunk_size;
  uint64_t num_chunks;
  uint64_t max_readers;
  std::atomic<uint64_t> num_readers;
  std::atomic<uint64_t> writer_seq;
};

struct alignas(64) SlotState {
  std::atomic<uint64_t> seq;        // last published lap + 1; 0 = never
  std::atomic<uint64_t> read_mask;  // bit r: reader r consumed this lap
  std::atomic<uint64_t> len;        // payload bytes in this slot's lap
};

struct Ring {
  int fd;
  size_t map_len;
  Header* hdr;
  SlotState* slots;
  uint8_t* data;
};

size_t segment_len(uint64_t chunk_size, uint64_t num_chunks) {
  return sizeof(Header) + num_chunks * sizeof(SlotState) +
         num_chunks * chunk_size;
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

// Bounded spin: hot for ~20us, then 50us sleeps until the deadline.
// Returns false on timeout.
template <typename Cond>
bool spin_until(Cond cond, double timeout_s) {
  const double deadline = now_s() + timeout_s;
  for (int i = 0; i < 200; ++i) {
    if (cond()) return true;
  }
  while (now_s() < deadline) {
    if (cond()) return true;
    struct timespec ts = {0, 50 * 1000};
    nanosleep(&ts, nullptr);
  }
  return cond();
}

Ring* map_ring(int fd, size_t len) {
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) return nullptr;
  Ring* r = new Ring();
  r->fd = fd;
  r->map_len = len;
  r->hdr = static_cast<Header*>(mem);
  r->slots = reinterpret_cast<SlotState*>(static_cast<uint8_t*>(mem) +
                                          sizeof(Header));
  r->data = reinterpret_cast<uint8_t*>(r->slots) +
            r->hdr->num_chunks * sizeof(SlotState);
  return r;
}

}  // namespace

extern "C" {

// Create a fresh segment (unlinks any stale one). Returns handle or null.
void* shm_ring_create(const char* name, uint64_t chunk_size,
                      uint64_t num_chunks) {
  if (num_chunks == 0 || chunk_size == 0) return nullptr;
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t len = segment_len(chunk_size, num_chunks);
  if (ftruncate(fd, static_cast<off_t>(len)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Ring* r = map_ring(fd, len);
  if (!r) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  std::memset(static_cast<void*>(r->hdr), 0, sizeof(Header));
  r->hdr->chunk_size = chunk_size;
  r->hdr->num_chunks = num_chunks;
  r->hdr->max_readers = kMaxReaders;
  // data pointer depends on num_chunks, recompute after init
  r->slots = reinterpret_cast<SlotState*>(
      reinterpret_cast<uint8_t*>(r->hdr) + sizeof(Header));
  r->data = reinterpret_cast<uint8_t*>(r->slots) +
            num_chunks * sizeof(SlotState);
  for (uint64_t i = 0; i < num_chunks; ++i) {
    r->slots[i].seq.store(0, std::memory_order_relaxed);
    r->slots[i].read_mask.store(0, std::memory_order_relaxed);
    r->slots[i].len.store(0, std::memory_order_relaxed);
  }
  r->hdr->magic.store(kMagic, std::memory_order_release);
  return r;
}

// Attach to an existing segment; spins until the creator published the
// magic or the timeout lapses. Returns handle or null.
void* shm_ring_open(const char* name, double timeout_s) {
  const double deadline = now_s() + timeout_s;
  int fd = -1;
  while (fd < 0) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) {
      if (now_s() >= deadline) return nullptr;
      struct timespec ts = {0, 200 * 1000};
      nanosleep(&ts, nullptr);
    }
  }
  // Header first, to learn the geometry.
  void* head = mmap(nullptr, sizeof(Header), PROT_READ, MAP_SHARED, fd, 0);
  if (head == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = static_cast<Header*>(head);
  bool ok = spin_until(
      [&] { return h->magic.load(std::memory_order_acquire) == kMagic; },
      timeout_s);
  uint64_t chunk_size = h->chunk_size;
  uint64_t num_chunks = h->num_chunks;
  munmap(head, sizeof(Header));
  if (!ok) {
    close(fd);
    return nullptr;
  }
  Ring* r = map_ring(fd, segment_len(chunk_size, num_chunks));
  if (!r) close(fd);
  return r;
}

// Register this process as a reader; returns the reader rank, or -1 when
// the reader table is full.
int64_t shm_ring_register_reader(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  // CAS loop: a rejected (table-full) registration must NOT bump the
  // count, or the writer's all-readers-drained accounting becomes
  // permanently unsatisfiable and every write times out.
  uint64_t rank = r->hdr->num_readers.load();
  do {
    if (rank >= r->hdr->max_readers) return -1;
  } while (!r->hdr->num_readers.compare_exchange_weak(rank, rank + 1));
  return static_cast<int64_t>(rank);
}

uint64_t shm_ring_chunk_size(void* handle) {
  return static_cast<Ring*>(handle)->hdr->chunk_size;
}

uint64_t shm_ring_num_chunks(void* handle) {
  return static_cast<Ring*>(handle)->hdr->num_chunks;
}

// Broadcast one chunk (len <= chunk_size). Blocks until the target slot
// has been drained by every registered reader from the previous lap.
// Returns 0 ok, -1 bad args, -2 timeout.
int64_t shm_ring_write(void* handle, const uint8_t* buf, uint64_t len,
                       double timeout_s) {
  Ring* r = static_cast<Ring*>(handle);
  if (len > r->hdr->chunk_size) return -1;
  const uint64_t wseq = r->hdr->writer_seq.load(std::memory_order_relaxed);
  SlotState& slot = r->slots[wseq % r->hdr->num_chunks];
  // Previous lap fully consumed? Readers registered NOW must have read
  // it; readers that joined later start at the current writer_seq and
  // never touch old laps (the Python layer hands them the start seq).
  bool ok = spin_until(
      [&] {
        if (slot.seq.load(std::memory_order_acquire) == 0) return true;
        uint64_t readers = r->hdr->num_readers.load();
        uint64_t want = readers >= 64 ? ~0ull : ((1ull << readers) - 1);
        uint64_t mask = slot.read_mask.load(std::memory_order_acquire);
        return (mask & want) == want;
      },
      timeout_s);
  if (!ok) return -2;
  uint8_t* dst = r->data + (wseq % r->hdr->num_chunks) * r->hdr->chunk_size;
  std::memcpy(dst, buf, len);
  slot.len.store(len, std::memory_order_relaxed);
  slot.read_mask.store(0, std::memory_order_relaxed);
  slot.seq.store(wseq + 1, std::memory_order_release);
  r->hdr->writer_seq.store(wseq + 1, std::memory_order_release);
  return 0;
}

// Registered reader count (writer-side join handshake).
uint64_t shm_ring_reader_count(void* handle) {
  return static_cast<Ring*>(handle)->hdr->num_readers.load(
      std::memory_order_acquire);
}

// Current writer sequence — a new reader's starting cursor.
uint64_t shm_ring_writer_seq(void* handle) {
  return static_cast<Ring*>(handle)
      ->hdr->writer_seq.load(std::memory_order_acquire);
}

// Read the chunk at sequence `seq` as reader `rank` into buf. Blocks
// until the writer publishes it. Returns the payload length (only that
// many bytes are copied — control messages are ~KB in MB-sized slots),
// -2 timeout, -3 overrun (writer lapped this reader: the slot now holds
// a NEWER lap — the queue was sized too small for the lag).
int64_t shm_ring_read(void* handle, int64_t rank, uint64_t seq,
                      uint8_t* buf, double timeout_s) {
  Ring* r = static_cast<Ring*>(handle);
  SlotState& slot = r->slots[seq % r->hdr->num_chunks];
  bool ok = spin_until(
      [&] {
        return slot.seq.load(std::memory_order_acquire) >= seq + 1;
      },
      timeout_s);
  if (!ok) return -2;
  if (slot.seq.load(std::memory_order_acquire) != seq + 1) return -3;
  const uint64_t len = slot.len.load(std::memory_order_relaxed);
  const uint8_t* src =
      r->data + (seq % r->hdr->num_chunks) * r->hdr->chunk_size;
  std::memcpy(buf, src, len);
  // Torn read if the writer lapped mid-copy (it can't — it waits for
  // read_mask — but a reader that never registered could race): verify.
  if (slot.seq.load(std::memory_order_acquire) != seq + 1) return -3;
  slot.read_mask.fetch_or(1ull << rank, std::memory_order_release);
  return static_cast<int64_t>(len);
}

void shm_ring_close(void* handle, const char* unlink_name) {
  Ring* r = static_cast<Ring*>(handle);
  munmap(static_cast<void*>(r->hdr), r->map_len);
  close(r->fd);
  if (unlink_name != nullptr) shm_unlink(unlink_name);
  delete r;
}

}  // extern "C"
