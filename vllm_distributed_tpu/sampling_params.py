"""Sampling parameters for text generation.

Mirrors the reference's vllm/sampling_params.py surface (the fields the V1
sampler consumes: v1/sample/sampler.py:18, logits processors, penalties) with
TPU-friendly semantics: every field lowers to a static-shape tensor in the
sampler, so adding a parameter never triggers a recompile.
"""

from dataclasses import dataclass, field
from enum import IntEnum
from typing import Optional, Union


class SamplingType(IntEnum):
    GREEDY = 0
    RANDOM = 1
    RANDOM_SEED = 2


_SAMPLING_EPS = 1e-5

# Static sparse-bias buffer width in the sampler ([R, B] scatter; see
# worker/model_runner.py _BIAS_BUF). Validated at request admission so an
# oversized request is rejected instead of killing the engine mid-step.
# Reserve headroom for min-tokens stop-suppression entries sharing the
# buffer.
BIAS_BUF_WIDTH = 128
MAX_BIAS_ENTRIES = BIAS_BUF_WIDTH - 16  # headroom for stop suppression


@dataclass
class SamplingParams:
    n: int = 1
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 or -1 -> disabled
    min_p: float = 0.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    seed: Optional[int] = None
    max_tokens: Optional[int] = 16
    min_tokens: int = 0
    stop: Union[None, str, list[str]] = None
    stop_token_ids: Optional[list[int]] = None
    ignore_eos: bool = False
    logprobs: Optional[int] = None
    prompt_logprobs: Optional[int] = None
    # Sparse additive bias {token_id: bias}; OpenAI-compatible
    # (reference: v1/sample/logits_processor.py LogitBiasLogitsProcessor).
    logit_bias: Optional[dict[int, float]] = None
    # Restrict sampling to this token set (reference:
    # logits_processor.py AllowedTokenIdsLogitsProcessor).
    allowed_token_ids: Optional[list[int]] = None
    # Structured output / guided decoding (reference:
    # sampling_params.py GuidedDecodingParams + v1/structured_output/).
    # One of: {"regex": str}, {"choice": [str, ...]},
    # {"json": schema-dict-or-string}, {"json_object": True}.
    structured: Optional[dict] = None
    detokenize: bool = True
    skip_special_tokens: bool = True
    spaces_between_special_tokens: bool = True
    # Extra args passed through to plugins/logits processors.
    extra_args: Optional[dict] = None
    # Disaggregated prefill/decode routing metadata (reference:
    # kv_transfer_params plumbed through SamplingParams' sibling fields on
    # the request).
    _all_stop_token_ids: set[int] = field(default_factory=set, repr=False)

    def __post_init__(self) -> None:
        if self.n < 1:
            raise ValueError("n must be >= 1")
        if self.temperature < 0.0:
            raise ValueError("temperature must be non-negative")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if self.top_k < -1:
            raise ValueError("top_k must be -1, 0, or positive")
        if self.top_k == -1:
            self.top_k = 0
        if not 0.0 <= self.min_p <= 1.0:
            raise ValueError("min_p must be in [0, 1]")
        if not -2.0 <= self.presence_penalty <= 2.0:
            raise ValueError("presence_penalty must be in [-2, 2]")
        if not -2.0 <= self.frequency_penalty <= 2.0:
            raise ValueError("frequency_penalty must be in [-2, 2]")
        if self.repetition_penalty <= 0.0:
            raise ValueError("repetition_penalty must be positive")
        if self.max_tokens is not None and self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if self.min_tokens < 0:
            raise ValueError("min_tokens must be >= 0")
        if isinstance(self.stop, str):
            self.stop = [self.stop]
        elif self.stop is None:
            self.stop = []
        if self.stop_token_ids is None:
            self.stop_token_ids = []
        self._all_stop_token_ids = set(self.stop_token_ids)
        if self.logprobs is not None and not 0 <= self.logprobs <= 20:
            raise ValueError("logprobs must be in [0, 20]")
        if (self.prompt_logprobs is not None
                and not 0 <= self.prompt_logprobs <= 20):
            raise ValueError("prompt_logprobs must be in [0, 20]")
        if self.logit_bias is not None:
            self.logit_bias = {int(k): float(v)
                               for k, v in self.logit_bias.items()}
            if len(self.logit_bias) > MAX_BIAS_ENTRIES:
                raise ValueError(
                    f"logit_bias supports at most {MAX_BIAS_ENTRIES} "
                    "entries")
        if self.structured is not None:
            keys = set(self.structured) & {"regex", "choice", "json",
                                           "grammar",
                                           "json_object"}
            if len(keys) != 1:
                raise ValueError(
                    "structured needs exactly one of regex / choice / "
                    "json / grammar / json_object, got "
                    f"{sorted(self.structured)}")
        if self.allowed_token_ids is not None:
            if not self.allowed_token_ids:
                raise ValueError("allowed_token_ids must be non-empty")
            if len(self.allowed_token_ids) > MAX_BIAS_ENTRIES:
                raise ValueError(
                    f"allowed_token_ids supports at most "
                    f"{MAX_BIAS_ENTRIES} ids")
        if self.min_tokens > 0:
            # Stop-suppression entries share the sampler's static bias
            # buffer with logit_bias/allowed_token_ids while output <
            # min_tokens; the runner merges entries by token id, so count
            # the union. +1 reserves room for the tokenizer's EOS folded
            # in later by update_from_tokenizer (unless ignore_eos).
            bias_keys = (set(self.allowed_token_ids)
                         if self.allowed_token_ids is not None else
                         set(self.logit_bias or ()))
            need = (len(bias_keys | self._all_stop_token_ids) +
                    (0 if self.ignore_eos else 1))
            if need > BIAS_BUF_WIDTH:
                raise ValueError(
                    f"min_tokens with {len(self._all_stop_token_ids)} stop "
                    f"token ids plus {len(bias_keys)} bias/allowed entries "
                    f"needs {need} sampler-buffer slots; at most "
                    f"{BIAS_BUF_WIDTH} are available")

    @property
    def sampling_type(self) -> SamplingType:
        if self.temperature < _SAMPLING_EPS:
            return SamplingType.GREEDY
        if self.seed is not None:
            return SamplingType.RANDOM_SEED
        return SamplingType.RANDOM

    @property
    def all_stop_token_ids(self) -> set[int]:
        return self._all_stop_token_ids

    @property
    def has_penalties(self) -> bool:
        return (self.presence_penalty != 0.0
                or self.frequency_penalty != 0.0
                or self.repetition_penalty != 1.0)

    @property
    def needs_extended_static(self) -> bool:
        """Lifetime need for the extended (logits-processor) sampling
        graph: penalties, logit bias, allowed-token masks, top-k
        logprobs. min_tokens is NOT included — its stop suppression only
        matters while output < min_tokens (checked dynamically)."""
        return (self.has_penalties or bool(self.logit_bias)
                or self.allowed_token_ids is not None
                or bool(self.logprobs)
                or self.structured is not None)

    @property
    def needs_extended_sampling(self) -> bool:
        """True when sampling may ever need the extended graph."""
        return self.needs_extended_static or self.min_tokens > 0

    def update_from_tokenizer(self, eos_token_id: Optional[int]) -> None:
        """Fold the model's EOS into the stop set unless ignore_eos."""
        if eos_token_id is not None and not self.ignore_eos:
            self._all_stop_token_ids = set(self.stop_token_ids)
            self._all_stop_token_ids.add(eos_token_id)
