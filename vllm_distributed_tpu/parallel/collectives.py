"""Quantized in-graph collectives: block-scaled int8 over ICI/DCN.

Grounded in PAPERS.md "EQuARX: Efficient Quantized AllReduce in XLA":
cross-device bytes — not FLOPs — cap distributed decode throughput, and
a block-scaled int8 all-reduce composed INSIDE the sharded program (so
XLA fuses the quantize/dequantize casts into the collective schedule)
recovers most of the 4x wire reduction with negligible quality loss.

This module is the single home of that plane for the in-graph paths:

* ``psum(x, axis, path=...)`` — drop-in ``jax.lax.psum`` dispatcher.
  When the path is enabled it runs the EQuARX-shaped two-phase reduce:
  chunk the operand K ways, quantize each chunk (symmetric per-block
  int8, fp32 scales), ``all_to_all`` the chunks to their owner rank
  (the reduce-scatter leg), dequantize-accumulate in fp32, requantize
  the owned chunk, ``all_gather`` it back and dequantize. Both legs
  ship int8 + per-block scales instead of full-precision words.
* ``all_to_all(x, axis, ...)`` — quantized ``lax.all_to_all`` for the
  MoE expert-parallel dispatch/combine shuffles: payload rows quantize
  along their feature dim, the int8 payload and fp32 scales travel as
  two small collectives, and rows dequantize on the receiving rank.
* ``row_parallel_dot(x, w)`` — explicit reduce hook for the
  GSPMD-sharded dense-TP path: the row-parallel matmul runs under
  shard_map so its combining all-reduce is OURS to quantize instead of
  an implicit GSPMD psum.

Gating: ``VDT_QCOMM`` (default off) with per-path ``VDT_QCOMM_PATHS``
(see envs.py). The config is cached and read at TRACE time — a flipped
env var takes effect on the next trace (fresh engine), not mid-graph;
tests and the bench harness call :func:`refresh` between legs.

Accounting: collectives execute inside jitted graphs where per-step
host counters are unreachable, so the module records the analytic
per-execution wire savings of each TRACED quantized collective
(path-labeled, rendered into ``vdt:qcomm_bytes_saved_total``). The
KV-payload paths (kv_transfer/quant.py) count exact wire bytes through
the per-core telemetry recorder instead; both sources merge at render
time (metrics/prometheus.py).
"""

import math
import threading
from typing import Optional

_KV_PATHS = frozenset({"dcn_pull", "p2p", "shared_storage"})
_SCALE_BYTES = 4  # fp32 scale per quantized block

_lock = threading.Lock()
_config_cache: Optional[tuple] = None  # (enabled, paths|None, block)
_trace_bytes_saved: dict[str, int] = {}
_trace_fallbacks: dict[str, int] = {}


def _config() -> tuple:
    global _config_cache
    if _config_cache is None:
        from vllm_distributed_tpu import envs
        tokens = frozenset(
            t.strip() for t in envs.VDT_QCOMM_PATHS.split(",")
            if t.strip())
        _config_cache = (envs.VDT_QCOMM, tokens or None,
                         envs.VDT_QCOMM_BLOCK)
    return _config_cache


def refresh() -> None:
    """Re-read the VDT_QCOMM* env gating (tests / bench legs). Does not
    touch counters; note that already-compiled graphs keep the plane
    they were traced with."""
    global _config_cache
    _config_cache = None


def enabled(path: str) -> bool:
    """Is the quantized plane on for ``path``? Connector paths also
    answer to the "kv" group token."""
    on, paths, _ = _config()
    if not on:
        return False
    if paths is None:
        return True
    if path in paths:
        return True
    return path in _KV_PATHS and "kv" in paths


def block_size() -> int:
    return _config()[2]


def divisor_block(span: int, cap: Optional[int] = None) -> int:
    """Largest divisor of ``span`` not exceeding ``cap`` (the env block
    by default) — payload codecs use it so no scale block ever crosses
    a page/head boundary."""
    cap = min(span, cap if cap is not None else block_size())
    for b in range(cap, 0, -1):
        if span % b == 0:
            return b
    return 1


# ---------------------------------------------------------------------------
# Trace-time accounting (see module docstring: exact byte counters are
# unreachable inside jit, so each newly traced quantized collective
# records its analytic per-execution savings).
# ---------------------------------------------------------------------------

def _note_saved(path: str, nbytes: int) -> None:
    with _lock:
        _trace_bytes_saved[path] = (_trace_bytes_saved.get(path, 0)
                                    + max(int(nbytes), 0))


def note_fallback(path: str) -> None:
    """A path asked for the quantized plane but could not use it (axis
    size 1, payload already <= 1 byte/element, corrupt-scale degrade)."""
    with _lock:
        _trace_fallbacks[path] = _trace_fallbacks.get(path, 0) + 1


def traced_snapshot() -> dict:
    """Process-local in-graph counters. The front end reads its own at
    render time; spawned engine cores export theirs (pid-tagged) over
    the get_stats feed, where dp_client merges the follower snapshots
    so /metrics is fleet-exact (PR 19 — noted as process-local since
    PR 9)."""
    with _lock:
        return {"bytes_saved": dict(_trace_bytes_saved),
                "fallbacks": dict(_trace_fallbacks)}


def reset_counters() -> None:
    with _lock:
        _trace_bytes_saved.clear()
        _trace_fallbacks.clear()


def merged_qcomm_view(transport_qcomm: Optional[dict],
                      remote: Optional[dict] = None) -> dict:
    """One {path: {bytes_saved, fallbacks}} map combining the per-core
    telemetry recorders' exact payload counters (possibly DP-merged)
    with this process's trace-time in-graph counters — the shape the
    /metrics renderer and the /debug/engine dump share. ``remote``
    (same {"bytes_saved": {path: n}, "fallbacks": {path: n}} shape as
    traced_snapshot) folds in the pid-deduped follower-process
    snapshots dp_client merged from the get_stats feed."""
    merged: dict[str, dict] = {}
    for path, e in (transport_qcomm or {}).items():
        if isinstance(e, dict):
            merged[path] = {"bytes_saved": int(e.get("bytes_saved", 0)),
                            "fallbacks": int(e.get("fallbacks", 0))}
    traced = traced_snapshot()
    for snap in (traced, remote or {}):
        for path, n in (snap.get("bytes_saved") or {}).items():
            merged.setdefault(path, {"bytes_saved": 0, "fallbacks": 0})
            merged[path]["bytes_saved"] += int(n)
        for path, n in (snap.get("fallbacks") or {}).items():
            merged.setdefault(path, {"bytes_saved": 0, "fallbacks": 0})
            merged[path]["fallbacks"] += int(n)
    return merged


# ---------------------------------------------------------------------------
# Block quantize / dequantize (jnp; traced inside the sharded program)
# ---------------------------------------------------------------------------

def _block_quantize(x32, block: int):
    """[..., n] fp32 (n % block == 0) -> int8 [..., n/block, block] +
    fp32 scales [..., n/block, 1] (symmetric absmax/127 per block)."""
    import jax.numpy as jnp
    xb = x32.reshape(x32.shape[:-1] + (x32.shape[-1] // block, block))
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-30)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _block_dequantize(q, scale):
    """Inverse of _block_quantize, flattened back to [..., n] fp32."""
    x = q.astype(scale.dtype) * scale
    return x.reshape(x.shape[:-2] + (x.shape[-2] * x.shape[-1], ))


def _axis_size(axis_name) -> int:
    """Static size of a (possibly tuple) shard_map axis, from the
    registered global mesh — collectives here are only reachable inside
    shard_map over that mesh."""
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    if not mesh_state.has_global_mesh():
        return 1
    mesh = mesh_state.get_global_mesh()
    names = (axis_name, ) if isinstance(axis_name, str) else tuple(axis_name)
    size = 1
    for name in names:
        size *= mesh.shape[name]
    return size


# ---------------------------------------------------------------------------
# Quantized collectives
# ---------------------------------------------------------------------------

def quantized_psum(x, axis_name, *, axis_size: int,
                   block: Optional[int] = None):
    """EQuARX-shaped all-reduce: quantized reduce-scatter (all_to_all of
    int8 chunks + scales, fp32 accumulate) then quantized all-gather.
    Exact for all-zero inputs; otherwise error is bounded by one
    round-trip of per-block int8 rounding per leg."""
    import jax.numpy as jnp
    from jax import lax
    block = block or block_size()
    orig_dtype, orig_shape = x.dtype, x.shape
    n = math.prod(orig_shape) if orig_shape else 1
    K = axis_size
    per = max(-(-n // (K * block)), 1) * block  # chunk len, % block == 0
    flat = jnp.pad(x.astype(jnp.float32).reshape(-1), (0, K * per - n))
    q, s = _block_quantize(flat.reshape(K, per), block)
    # Reduce-scatter leg: chunk r of every rank lands on rank r.
    q_t = lax.all_to_all(q, axis_name, 0, 0)
    s_t = lax.all_to_all(s, axis_name, 0, 0)
    part = jnp.sum(q_t.astype(jnp.float32) * s_t, axis=0)  # [nb, block]
    # All-gather leg: requantize the owned (reduced) chunk and share it.
    q2, s2 = _block_quantize(part.reshape(per), block)
    qg = lax.all_gather(q2, axis_name)
    sg = lax.all_gather(s2, axis_name)
    full = _block_dequantize(qg, sg).reshape(K * per)[:n]
    return full.reshape(orig_shape).astype(orig_dtype)


def psum(x, axis_name, *, path: str):
    """``jax.lax.psum`` drop-in: quantized when ``path`` is enabled and
    the operand actually wins — non-float operands (lossy rounding of
    integer sums is silently wrong) and payloads whose quantized form
    would be no smaller (sub-byte floats, tiny operands dominated by
    padding/scales) fall back to the exact psum, counted."""
    import jax.numpy as jnp
    from jax import lax
    if not enabled(path):
        return lax.psum(x, axis_name)
    K = _axis_size(axis_name)
    if K <= 1 or not jnp.issubdtype(x.dtype, jnp.floating):
        note_fallback(path)
        return lax.psum(x, axis_name)
    block = block_size()
    n = math.prod(x.shape) if x.shape else 1
    per = max(-(-n // (K * block)), 1) * block
    # Ring all-reduce moves ~2*(K-1)/K * payload per device; both
    # quantized legs ship int8 + one fp32 scale per block over the
    # PADDED chunk layout instead.
    raw = 2 * (K - 1) * n * x.dtype.itemsize // K
    quant = 2 * (K - 1) * per * (block + _SCALE_BYTES) // block
    if quant >= raw:
        note_fallback(path)
        return lax.psum(x, axis_name)
    _note_saved(path, raw - quant)
    return quantized_psum(x, axis_name, axis_size=K, block=block)


def all_gather(x, axis_name, *, tiled: bool = False, path: str):
    """``jax.lax.all_gather`` drop-in: the local shard quantizes along
    its trailing feature dim (divisor block, like the all_to_all
    payload) and the int8 values + fp32 scales gather as two small
    collectives, dequantized on every rank. Used by the MoE-EP
    re-replicate step (each rank contributes its token slice of the
    combined expert output). Non-float payloads and feature dims whose
    divisor block is too small to win fall back to the raw gather,
    counted."""
    import jax.numpy as jnp
    from jax import lax
    if not enabled(path):
        return lax.all_gather(x, axis_name, tiled=tiled)
    K = _axis_size(axis_name)
    feat = x.shape[-1]
    n = math.prod(x.shape)
    block = divisor_block(feat)
    # Ring all-gather ships each rank's local shard to the K-1 others.
    raw = (K - 1) * n * x.dtype.itemsize
    quant = (K - 1) * (n + (n // block) * _SCALE_BYTES)
    if (K <= 1 or quant >= raw
            or not jnp.issubdtype(x.dtype, jnp.floating)):
        note_fallback(path)
        return lax.all_gather(x, axis_name, tiled=tiled)
    q, s = _block_quantize(x.astype("float32"), block)
    qg = lax.all_gather(q, axis_name, tiled=tiled)
    sg = lax.all_gather(s, axis_name, tiled=tiled)
    _note_saved(path, raw - quant)
    return _block_dequantize(qg, sg).astype(x.dtype)


def all_to_all(x, axis_name, split_axis: int = 0, concat_axis: int = 0,
               *, path: str):
    """``jax.lax.all_to_all`` drop-in for [K, rows, feature] payloads:
    quantized along the trailing feature dim when ``path`` is enabled
    and it wins — non-float payloads, and feature dims whose divisor
    block is so small the scales outweigh the dtype shrink (tiny or
    prime-ish spans), fall back to the raw shuffle, counted."""
    import jax.numpy as jnp
    from jax import lax
    if not enabled(path):
        return lax.all_to_all(x, axis_name, split_axis, concat_axis)
    K = _axis_size(axis_name)
    feat = x.shape[-1]
    n = math.prod(x.shape)
    block = divisor_block(feat)
    raw = (K - 1) * n * x.dtype.itemsize // K
    quant = (K - 1) * (n + (n // block) * _SCALE_BYTES) // K
    if (K <= 1 or quant >= raw
            or not jnp.issubdtype(x.dtype, jnp.floating)):
        note_fallback(path)
        return lax.all_to_all(x, axis_name, split_axis, concat_axis)
    q, s = _block_quantize(x.astype("float32"), block)
    q_t = lax.all_to_all(q, axis_name, split_axis, concat_axis)
    s_t = lax.all_to_all(s, axis_name, split_axis, concat_axis)
    _note_saved(path, raw - quant)
    return _block_dequantize(q_t, s_t).reshape(q_t.shape[:-2] + (feat, )
                                               ).astype(x.dtype)


# ---------------------------------------------------------------------------
# TKNP KV-write shuffle payload (path "tknp_kv")
# ---------------------------------------------------------------------------

def kv_shuffle_quantize(k_new, v_new, axis_size: int):
    """Quantize the step's new K/V rows for the TKNP KV-write shuffle —
    the [T, KVH, D] payloads crossing the token-axis shard_map boundary
    to the page-owning ranks (ops/attention._write_kv_cache_tknp). The
    last raw collective of ROADMAP item 5: the boundary reshard ships
    int8 + per-block fp32 scales instead of model-dtype words.

    Blocks divide D exactly (divisor block), so no scale ever crosses a
    head boundary. Returns ``(k_q, k_s, v_q, v_s)`` or ``None`` when
    the path is off or quantization would not win (non-float payload,
    sub-byte dtype, scales outweighing the shrink) — counted as the
    standard fallback."""
    import jax.numpy as jnp
    if not enabled("tknp_kv"):
        return None
    feat = k_new.shape[-1]
    n = math.prod(k_new.shape)
    block = divisor_block(feat)
    # Broadcast-to-owners model: each of the other K-1 token ranks
    # receives the payload it did not produce.
    raw = 2 * (axis_size - 1) * n * k_new.dtype.itemsize
    quant = 2 * (axis_size - 1) * (n + (n // block) * _SCALE_BYTES)
    if (axis_size <= 1 or quant >= raw
            or not jnp.issubdtype(k_new.dtype, jnp.floating)):
        note_fallback("tknp_kv")
        return None
    k_q, k_s = _block_quantize(k_new.astype(jnp.float32), block)
    v_q, v_s = _block_quantize(v_new.astype(jnp.float32), block)
    _note_saved("tknp_kv", raw - quant)
    return k_q, k_s, v_q, v_s


def kv_shuffle_dequantize(k_q, k_s, v_q, v_s, dtype):
    """Inverse of kv_shuffle_quantize on the receiving rank."""
    return (_block_dequantize(k_q, k_s).astype(dtype),
            _block_dequantize(v_q, v_s).astype(dtype))


# ---------------------------------------------------------------------------
# Dense-TP explicit reduce hook
# ---------------------------------------------------------------------------

def tp_reduce_applicable() -> bool:
    """Should the dense row-parallel projections take the explicit
    quantized reduce instead of GSPMD's implicit all-reduce? Requires
    the tp path enabled, a registered mesh with model-axis > 1, and the
    serving-engine data-axis layout (batch unsharded — an in_spec of
    replicated x must not force a gather)."""
    from vllm_distributed_tpu.config import (MESH_AXIS_DATA,
                                             MESH_AXIS_MODEL)
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    if not enabled("tp") or not mesh_state.has_global_mesh():
        return False
    mesh = mesh_state.get_global_mesh()
    return (mesh.shape[MESH_AXIS_MODEL] > 1
            and mesh.shape[MESH_AXIS_DATA] == 1)


def row_parallel_dot(x, w):
    """``x @ w`` for a row-parallel weight (input dim sharded over the
    model axis) with the combining all-reduce expressed EXPLICITLY so
    it can be quantized. The activation enters sharded on its feature
    dim — exactly the layout the preceding column-parallel matmul
    (attention heads / gated-MLP intermediate) already produced, so the
    shard_map boundary moves no data: each rank contracts its feature
    slice against its weight slab and the partial products merge
    through the quantized psum."""
    from jax.sharding import PartitionSpec as P

    from vllm_distributed_tpu.config import MESH_AXIS_MODEL
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    from vllm_distributed_tpu.parallel.mesh import shard_map

    def rank_fn(x_, w_):
        return psum(x_ @ w_, MESH_AXIS_MODEL, path="tp")

    return shard_map(
        rank_fn, mesh=mesh_state.get_global_mesh(),
        in_specs=(P(None, MESH_AXIS_MODEL), P(MESH_AXIS_MODEL, None)),
        out_specs=P(), check_vma=False)(x, w)
