"""Pipeline-parallel layout helpers.

TPU-native PP design (vs the reference's per-rank processes exchanging
IntermediateTensors over NCCL, vllm/distributed/utils.py:89
``get_pp_indices`` + parallel_state.py send/recv): the global device mesh
is sliced along the ``pipe`` axis into per-stage sub-meshes; each stage
is its own jitted program (embed + its layer slice, or layers + sampler)
holding that slice's weights and KV cache, and activations hop stages
with ``jax.device_put`` — an ICI/DCN transfer the runtime overlaps with
compute thanks to JAX async dispatch. Consecutive engine steps pipeline
naturally: stage p of step i runs while stage p-1 processes step i+1.
"""

import numpy as np
from jax.sharding import Mesh

from vllm_distributed_tpu.config import MESH_AXIS_PIPE

from vllm_distributed_tpu.parallel.mesh import AXIS_ORDER


def partition_layers(num_layers: int, pp_size: int) -> list[tuple[int, int]]:
    """Contiguous [start, end) layer ranges per stage; remainder layers go
    to the earlier stages (reference: distributed/utils.py:89
    get_pp_indices semantics with even spread)."""
    base = num_layers // pp_size
    extra = num_layers % pp_size
    ranges = []
    start = 0
    for p in range(pp_size):
        n = base + (1 if p < extra else 0)
        ranges.append((start, start + n))
        start += n
    assert start == num_layers
    return ranges


def stage_submesh(mesh: Mesh, stage: int) -> Mesh:
    """Sub-mesh of one pipeline stage: the slice of the device array at
    pipe index ``stage``, with the pipe axis kept at size 1 so every
    PartitionSpec naming it still resolves."""
    axis = AXIS_ORDER.index(MESH_AXIS_PIPE)
    devs = np.take(mesh.devices, [stage], axis=axis)
    return Mesh(devs, AXIS_ORDER)
