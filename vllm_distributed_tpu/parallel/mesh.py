"""Device mesh construction and global parallel state.

TPU-native replacement for the reference's process-group world
(vllm/distributed/parallel_state.py:1050 ``initialize_model_parallel``
builds ExternalDP x (DP|TKNP) x PP x TP NCCL groups): here the same axes
become dimensions of one ``jax.sharding.Mesh`` and XLA inserts the
collectives over ICI/DCN. Explicit groups survive only where control
matters (PP send/recv, MoE all2all, KV-pull), expressed via shard_map.
"""

from contextlib import contextmanager
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from vllm_distributed_tpu.config import (MESH_AXIS_DATA, MESH_AXIS_EXPERT,
                                         MESH_AXIS_MODEL, MESH_AXIS_PIPE,
                                         MESH_AXIS_TOKEN, ParallelConfig)
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Version-portable shard_map: jax >= 0.5 exposes it at the top level
# (kwarg ``check_vma``); older installs (0.4.x) keep it under
# jax.experimental with the kwarg spelled ``check_rep``.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax 0.4.x installs
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_04(f, **kwargs)

_GLOBAL_MESH: Optional[Mesh] = None

AXIS_ORDER = (MESH_AXIS_DATA, MESH_AXIS_TOKEN, MESH_AXIS_PIPE,
              MESH_AXIS_MODEL)


def build_mesh(parallel_config: ParallelConfig,
               devices: Optional[list] = None) -> Mesh:
    """Build the engine's device mesh.

    Axis order is (data, token, pipe, model), outermost to innermost:
    jax.experimental.mesh_utils would give ICI-contiguous innermost axes;
    we keep np.reshape ordering which matches device enumeration on a
    single slice (model-parallel neighbors are ICI neighbors).
    """
    if devices is None:
        devices = jax.devices()
    shape = parallel_config.mesh_shape
    sizes = tuple(shape[a] for a in AXIS_ORDER)
    world = int(np.prod(sizes))
    if world > len(devices):
        raise ValueError(
            f"mesh {dict(shape)} needs {world} devices, "
            f"only {len(devices)} available")
    pp = shape.get(MESH_AXIS_PIPE, 1) if isinstance(shape, dict) else 1
    try:
        procs = jax.process_count()
    except Exception:  # noqa: BLE001 - uninitialized backends
        procs = 1
    dpp = world // max(procs, 1)
    if pp > 1 and procs > 1 and dpp % pp == 0:
        # Multi-process pipeline: carve stages out of each process's
        # LOCAL devices so every process contributes devices to every
        # stage — the per-stage activation handoff (pp_runner's
        # device_put) then only moves data between locally-addressable
        # shards. Stages that own whole processes would strand the
        # handoff: the destination process holds no source shard.
        d, k, p_, m = (sizes[AXIS_ORDER.index(a)] for a in AXIS_ORDER)
        arr = np.array(devices[:world]).reshape(procs, pp, dpp // pp)
        stage_major = arr.transpose(1, 0, 2).reshape(pp, world // pp)
        dev_array = stage_major.reshape(pp, d, k, m).transpose(1, 2, 0,
                                                               3)
    else:
        dev_array = np.array(devices[:world]).reshape(sizes)
    return Mesh(dev_array, AXIS_ORDER)


def set_global_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh:
    assert _GLOBAL_MESH is not None, "mesh not initialized"
    return _GLOBAL_MESH


def has_global_mesh() -> bool:
    return _GLOBAL_MESH is not None


@contextmanager
def global_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    prev = _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    try:
        yield mesh
    finally:
        _GLOBAL_MESH = prev


def sharding(spec: PartitionSpec,
             mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_global_mesh(), spec)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    return sharding(PartitionSpec(), mesh)


# Common parameter specs -----------------------------------------------------

P = PartitionSpec


def tp_size(mesh: Optional[Mesh] = None) -> int:
    return (mesh or get_global_mesh()).shape[MESH_AXIS_MODEL]


def dp_size(mesh: Optional[Mesh] = None) -> int:
    return (mesh or get_global_mesh()).shape[MESH_AXIS_DATA]


def tknp_size(mesh: Optional[Mesh] = None) -> int:
    return (mesh or get_global_mesh()).shape[MESH_AXIS_TOKEN]
