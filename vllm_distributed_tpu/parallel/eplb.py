"""Expert-parallel load balancing (EPLB).

Reference: vllm/distributed/eplb/ — ``EplbState`` (eplb_state.py:48)
tracks per-expert load; ``rebalance_experts`` (rebalance_algo.py:179,
after DeepSeek EPLB) computes a physical-expert placement that REPLICATES
hot experts into spare physical slots and PACKS physical experts onto EP
ranks so per-rank load balances; rebalance_execute.py then moves weights.

TPU redesign: placement is pure host math (numpy, unit-testable); weight
movement is one ``jnp.take`` over the expert axis followed by re-placement
with the same NamedSharding — XLA turns that into the ICI shuffles the
reference does with P2P sends. The MoE router maps logical->physical
through a small per-layer index buffer that rides in the param tree, so
the jitted forward never recompiles on a rebalance (only buffer VALUES
change).
"""

from dataclasses import dataclass, field

import numpy as np


@dataclass
class EplbPlacement:
    """One rebalance decision.

    phys_to_logical: [L, P] — which logical expert each physical slot
      hosts (P = num physical slots, a multiple of the EP rank count).
    logical_replicas: [L, E] — replica count per logical expert.
    logical_to_phys: [L, E, R_max] — physical slot ids per logical
      expert, -1 padded to the max replica count.
    """

    phys_to_logical: np.ndarray
    logical_replicas: np.ndarray
    logical_to_phys: np.ndarray

    @property
    def max_replicas(self) -> int:
        return self.logical_to_phys.shape[-1]


def rebalance_experts(loads: np.ndarray, num_physical: int,
                      num_ranks: int) -> EplbPlacement:
    """Compute a balanced placement from per-layer expert loads [L, E].

    Per layer: (1) hand the P - E spare physical slots out greedily to
    the expert with the highest load-per-replica; (2) pack the resulting
    physical experts onto ranks — heaviest first, each to the least
    loaded rank with a free slot, avoiding ranks that already host a
    replica of the same expert when possible (a replica on the same rank
    adds no bandwidth).
    """
    loads = np.asarray(loads, np.float64)
    L, E = loads.shape
    assert num_physical >= E, "need at least one slot per expert"
    assert num_physical % num_ranks == 0, \
        "physical slots must split evenly over ranks"
    slots_per_rank = num_physical // num_ranks

    phys_to_logical = np.zeros((L, num_physical), np.int32)
    logical_replicas = np.zeros((L, E), np.int32)

    for layer in range(L):
        w = np.maximum(loads[layer], 1e-9)
        # --- replication: spare slots to the heaviest load/replica ---
        replicas = np.ones(E, np.int64)
        for _ in range(num_physical - E):
            replicas[np.argmax(w / replicas)] += 1
        # --- physical item list (expert id, weight share) ---
        items: list[tuple[int, float]] = []
        for e in range(E):
            items += [(e, w[e] / replicas[e])] * int(replicas[e])
        items.sort(key=lambda t: -t[1])
        # --- balanced packing onto ranks ---
        rank_load = np.zeros(num_ranks, np.float64)
        rank_fill = np.zeros(num_ranks, np.int64)
        rank_has: list[set[int]] = [set() for _ in range(num_ranks)]
        placement = np.full(num_physical, -1, np.int32)
        for e, share in items:
            open_ranks = [r for r in range(num_ranks)
                          if rank_fill[r] < slots_per_rank]
            fresh = [r for r in open_ranks if e not in rank_has[r]]
            pool = fresh or open_ranks
            r = min(pool, key=lambda r: rank_load[r])
            placement[r * slots_per_rank + rank_fill[r]] = e
            rank_fill[r] += 1
            rank_load[r] += share
            rank_has[r].add(e)
        phys_to_logical[layer] = placement
        logical_replicas[layer] = replicas

    r_max = int(logical_replicas.max())
    logical_to_phys = np.full((L, E, r_max), -1, np.int32)
    for layer in range(L):
        seen = np.zeros(E, np.int64)
        for p, e in enumerate(phys_to_logical[layer]):
            logical_to_phys[layer, e, seen[e]] = p
            seen[e] += 1
    return EplbPlacement(phys_to_logical=phys_to_logical,
                         logical_replicas=logical_replicas,
                         logical_to_phys=logical_to_phys)


def rank_loads(placement: EplbPlacement, loads: np.ndarray,
               num_ranks: int) -> np.ndarray:
    """Per-layer per-rank load under a placement (test/metric helper):
    each logical expert's load splits evenly across its replicas."""
    L, P = placement.phys_to_logical.shape
    slots = P // num_ranks
    out = np.zeros((L, num_ranks), np.float64)
    for layer in range(L):
        share = (loads[layer] /
                 np.maximum(placement.logical_replicas[layer], 1))
        for p, e in enumerate(placement.phys_to_logical[layer]):
            out[layer, p // slots] += share[e]
    return out


@dataclass
class EplbState:
    """Per-expert load tracking + rebalance cadence (reference:
    eplb_state.py:48 — EMA over per-step token counts)."""

    num_layers: int
    num_experts: int
    ema_decay: float = 0.9
    rebalance_interval: int = 100
    loads: np.ndarray = field(init=False)
    steps_since_rebalance: int = 0

    def __post_init__(self) -> None:
        self.loads = np.zeros((self.num_layers, self.num_experts),
                              np.float64)

    def record(self, step_counts: np.ndarray) -> None:
        """Fold one step's per-layer logical-expert token counts in."""
        self.loads = (self.ema_decay * self.loads +
                      (1.0 - self.ema_decay) *
                      np.asarray(step_counts, np.float64))
        self.steps_since_rebalance += 1

    def should_rebalance(self) -> bool:
        return self.steps_since_rebalance >= self.rebalance_interval

    def make_placement(self, num_physical: int,
                       num_ranks: int) -> EplbPlacement:
        self.steps_since_rebalance = 0
        return rebalance_experts(self.loads, num_physical, num_ranks)
