"""Executor layer (reference: vllm/v1/executor/abstract.py:30 Executor with
UniProc/Multiproc/Ray variants).

On TPU, SPMD over a mesh removes the per-GPU process fan-out inside one
host: ``UniProcExecutor`` drives the whole local mesh. Multi-host executors
(one process per pod host via jax.distributed) layer on later without
changing this interface.
"""

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.output import (ModelRunnerOutput,
                                                    SchedulerOutput)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.worker.worker import TPUWorker

logger = init_logger(__name__)


class Executor:
    """Interface the engine core drives."""

    @staticmethod
    def get_class(config: EngineConfig) -> type["Executor"]:
        pc = config.parallel_config
        if pc.num_hosts > 1 and pc.host_rank > 0 and pc.broadcast_addr:
            raise ValueError(
                "host_rank > 0 with broadcast_addr set: follower hosts "
                "run executor.multihost.run_worker_follower, not a full "
                "engine (a second scheduler would desynchronize the "
                "pod's collectives)")
        if pc.num_hosts > 1 and pc.host_rank == 0 and pc.broadcast_addr:
            from vllm_distributed_tpu.executor.multihost import \
                MultiHostExecutor
            return MultiHostExecutor
        if pc.num_hosts > 1:
            # No broadcast feed: LOCKSTEP mode — every host must run
            # this identical engine program on the identical request
            # stream (the torchrun/ExternalLauncher pattern); a host
            # that instead waits in run_worker_follower would deadlock
            # the pod's collectives, so say which mode this is.
            logger.info(
                "multi-host without broadcast_addr: lockstep SPMD mode "
                "(every host drives the same engine); set "
                "broadcast_addr for scheduler-broadcast mode")
        return UniProcExecutor

    def __init__(self, config: EngineConfig) -> None:
        self.config = config

    def determine_num_available_blocks(self) -> int:
        raise NotImplementedError

    def initialize_kv_cache(self, num_pages: int) -> None:
        raise NotImplementedError

    def execute_model(self,
                      scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        raise NotImplementedError

    def execute_model_async(self, scheduler_output: SchedulerOutput):
        """Dispatch a step without blocking; returns a handle for
        wait_model(). Used by the engine core's pipeline-parallel batch
        queue to keep several microbatches in flight."""
        raise NotImplementedError

    def wait_model(self, handle) -> ModelRunnerOutput:
        raise NotImplementedError

    def get_stats(self) -> dict:
        return {}

    def shutdown(self) -> None:
        pass


class UniProcExecutor(Executor):
    """Single-process executor over the local device mesh."""

    def __init__(self, config: EngineConfig) -> None:
        super().__init__(config)
        self.worker = TPUWorker(config)
        self.worker.init_device()
        self.worker.load_model()

    def determine_num_available_blocks(self) -> int:
        return self.worker.determine_num_available_blocks()

    def initialize_kv_cache(self, num_pages: int) -> None:
        self.worker.initialize_kv_cache(num_pages)
        self.worker.compile_or_warm_up_model()

    def execute_model(self,
                      scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        return self.worker.execute_model(scheduler_output)

    def execute_model_async(self, scheduler_output: SchedulerOutput):
        return self.worker.dispatch_model(scheduler_output)

    def wait_model(self, handle) -> ModelRunnerOutput:
        return self.worker.wait_model(handle)

    def get_stats(self) -> dict:
        return self.worker.get_stats()

    def shutdown(self) -> None:
        connector = getattr(self.worker.model_runner, "kv_connector", None)
        if connector is not None and hasattr(connector, "shutdown"):
            connector.shutdown()
