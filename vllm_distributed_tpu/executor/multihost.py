"""Multi-host executor: one scheduler (host 0) driving SPMD workers on
every host of a pod.

Reference boundary: vllm/v1/executor/multiproc_executor.py:42 — the
driver broadcasts SchedulerOutput to worker processes over the shm
MessageQueue and collects outputs. The TPU multi-controller analogue:
every HOST runs the same jitted programs over one global mesh
(jax.distributed), so the only control-plane traffic needed is the
SchedulerOutput itself — host 0 publishes each step over ZMQ, follower
hosts replay ``worker.execute_model`` with identical inputs, and the
XLA collectives tie the hosts' device programs together. Follower host
outputs are identical by construction (replicated sampling outputs), so
only host 0's are consumed.

Wire format: pickle — hosts of one pod run the same build and the
channel carries internal dataclasses (SchedulerOutput incl. numpy
masks), exactly like the reference's mp pickling.

Usage: host 0 builds the engine normally with
ParallelConfig(num_hosts=N, host_rank=0, broadcast_addr=...); hosts
1..N-1 call ``run_worker_follower(config)``.
"""

import pickle
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.output import (ModelRunnerOutput,
                                                    SchedulerOutput)
from vllm_distributed_tpu.executor import Executor, UniProcExecutor
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_STOP = b"__stop__"


class _ShmPub:
    """Publisher over the native shared-memory ring (broadcast_addr
    "shm://<name>"). Same-host pods skip the TCP hop — the reference's
    shm MessageQueue fast path (device_communicators/shm_broadcast.py)."""

    def __init__(self, name: str, num_readers: int) -> None:
        from vllm_distributed_tpu.distributed.shm_broadcast import (
            MessageQueue)
        self._mq = MessageQueue.create("/" + name, num_readers)

    def send(self, payload: bytes) -> None:
        # Callers pass pickled bytes already; skip a second pickle.
        self._mq.enqueue_bytes(payload, timeout=120.0)

    def close(self, linger: int = 0) -> None:
        self._mq.close()


class _ShmSub:

    def __init__(self, name: str) -> None:
        from vllm_distributed_tpu.distributed.shm_broadcast import (
            MessageQueue)
        self._mq = MessageQueue.join("/" + name, timeout=120.0)

    def recv(self) -> bytes:
        # Generous: the writer may spend minutes in model load / HBM
        # profiling between messages.
        return self._mq.dequeue_bytes(timeout=3600.0)

    def close(self) -> None:
        self._mq.close()


def _shm_name(addr: str) -> Optional[str]:
    return addr[len("shm://"):] if addr.startswith("shm://") else None


class MultiHostExecutor(UniProcExecutor):
    """Host 0's executor: local SPMD worker + step broadcast to the
    other hosts' followers."""

    def __init__(self, config: EngineConfig) -> None:
        pc = config.parallel_config
        assert pc.num_hosts > 1 and pc.host_rank == 0, \
            "MultiHostExecutor runs on host 0 of a multi-host pod"
        if pc.pipeline_parallel_size > 1:
            raise ValueError(
                "pipeline parallelism with the broadcast executor needs "
                "async-dispatch broadcasting (execute_model_async); not "
                "wired yet — use lockstep mode (no broadcast_addr)")
        addr = pc.broadcast_addr
        assert addr, "ParallelConfig.broadcast_addr required (host0 ip)"
        shm = _shm_name(addr)
        if shm is not None:
            self._pub = _ShmPub(shm, num_readers=pc.num_hosts - 1)
        else:
            import zmq
            self._ctx = zmq.Context.instance()
            self._pub = self._ctx.socket(zmq.PUB)
            self._pub.bind(addr)
        super().__init__(config)  # device init joins jax.distributed

    def _broadcast(self, payload: bytes) -> None:
        self._pub.send(payload)

    def execute_model(self,
                      scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        # Followers must enter the same jitted computation: ship the
        # step before launching locally (collectives would deadlock if
        # any host skipped a program).
        self._broadcast(pickle.dumps(scheduler_output))
        return super().execute_model(scheduler_output)

    def initialize_kv_cache(self, num_pages: int) -> None:
        # Followers size their caches identically from the broadcast.
        self._broadcast(pickle.dumps(("init_kv", num_pages)))
        super().initialize_kv_cache(num_pages)

    def determine_num_available_blocks(self) -> int:
        # Deterministic across hosts (same profile program over the same
        # mesh); run locally everywhere, broadcast host 0's result so
        # followers don't rely on float-identical HBM readings.
        num = super().determine_num_available_blocks()
        self._broadcast(pickle.dumps(("num_blocks", num)))
        return num

    def get_stats(self) -> dict:
        """Local worker stats plus any follower-published snapshots
        (VDT_FOLLOWER_STATS_DIR): follower worker labels union into the
        standard per-worker map (labels are fleet-unique — dp rank +
        host rank) and follower transport snapshots ride
        ``follower_transport`` for the engine core to merge into its
        own recorder's snapshot — this is where the shm ring's READ
        side (recorded only in follower processes) reaches /metrics."""
        stats = super().get_stats()
        from vllm_distributed_tpu import envs
        from vllm_distributed_tpu.metrics import telemetry
        snaps = telemetry.collect_follower_stats(
            envs.VDT_FOLLOWER_STATS_DIR)
        if snaps:
            workers = telemetry.merge_worker_telemetry(
                [stats.get("workers")] +
                [s.get("workers") for s in snaps])
            if workers:
                stats["workers"] = workers
            transports = [s.get("transport") for s in snaps
                          if isinstance(s.get("transport"), dict)]
            if transports:
                stats["follower_transport"] = transports
        return stats

    def shutdown(self) -> None:
        try:
            self._broadcast(_STOP)
        except Exception:  # noqa: BLE001 - best effort
            pass
        self._pub.close(linger=200)
        super().shutdown()


def run_worker_follower(config: EngineConfig,
                        max_steps: Optional[int] = None) -> int:
    """Follower-host loop (reference analogue:
    WorkerProc.worker_busy_loop, multiproc_executor.py:603): join the
    pod, build the local worker, replay broadcast steps until the stop
    sentinel. Returns the number of steps executed."""
    from vllm_distributed_tpu.worker.worker import TPUWorker
    pc = config.parallel_config
    assert pc.num_hosts > 1 and pc.host_rank > 0

    shm = _shm_name(pc.broadcast_addr)
    if shm is not None:
        sub = _ShmSub(shm)
    else:
        import zmq
        ctx = zmq.Context.instance()
        sub = ctx.socket(zmq.SUB)
        sub.setsockopt(zmq.SUBSCRIBE, b"")
        sub.connect(pc.broadcast_addr)

    # Every jitted program over the global mesh is a COLLECTIVE across
    # hosts: the follower must enter the same programs in the same
    # order as host 0's UniProc lifecycle — device init (barrier via
    # jax.distributed), weight placement, the HBM profile forward, KV
    # init + warm-up lattice, then the per-step programs from the
    # broadcast. Data-dependent decisions (page count) come from host 0
    # so rounding differences can't desynchronize the pod.
    worker = TPUWorker(config)
    worker.init_device()
    worker.load_model()
    worker.determine_num_available_blocks()  # mirrors host 0's profile

    # Telemetry export (VDT_FOLLOWER_STATS_DIR): this process is where
    # the shm ring's read side records (the MessageQueue above captured
    # the process recorder) — publish snapshots so host 0's executor
    # can fold them into the standard stats merge.
    from vllm_distributed_tpu import envs
    from vllm_distributed_tpu.metrics import telemetry
    stats_dir = envs.VDT_FOLLOWER_STATS_DIR
    _PUBLISH_EVERY = 32

    def publish() -> None:
        if not stats_dir:
            return
        try:
            telemetry.publish_follower_stats(stats_dir, pc.host_rank,
                                             worker)
        except Exception as e:  # noqa: BLE001 - telemetry must never
            # kill a follower mid-pod.
            logger.warning("follower stats publish failed: %s", e)

    steps = 0
    while True:
        payload = sub.recv()
        if payload == _STOP:
            break
        msg = pickle.loads(payload)
        if isinstance(msg, tuple) and msg[0] == "num_blocks":
            continue  # host 0's authoritative count follows in init_kv
        if isinstance(msg, tuple) and msg[0] == "init_kv":
            worker.initialize_kv_cache(msg[1])
            worker.compile_or_warm_up_model()
            publish()
            continue
        worker.execute_model(msg)  # output identical to host 0's; drop
        steps += 1
        if steps % _PUBLISH_EVERY == 0:
            publish()
        if max_steps is not None and steps >= max_steps:
            break
    publish()
    logger.info("follower done after %d steps", steps)
    return steps
