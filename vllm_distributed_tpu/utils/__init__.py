"""Small shared utilities (analogue of vllm/utils.py)."""

import socket
import time
import uuid
from collections.abc import Sequence


def cdiv(a: int, b: int) -> int:
    """Ceiling division."""
    return -(a // -b)


def round_up(x: int, multiple: int) -> int:
    return cdiv(x, multiple) * multiple


def next_power_of_2(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def random_uuid() -> str:
    return str(uuid.uuid4().hex)


def get_open_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_buckets(min_size: int, max_size: int, *,
                 padding_gap: int = 0) -> list[int]:
    """Exponential (power-of-2) bucket ladder from min_size up to max_size.

    Used for padding dynamic token/request counts to a small set of
    precompiled shapes, following the reference TPU runner's bucketing
    (reference: vllm/v1/worker/tpu_model_runner.py:1248-1443). If
    ``padding_gap`` is nonzero, buckets grow exponentially until the gap, then
    linearly by ``padding_gap``.
    """
    assert min_size >= 1
    buckets: list[int] = []
    size = next_power_of_2(min_size)
    if padding_gap == 0:
        while size < max_size:
            buckets.append(size)
            size *= 2
    else:
        while size < max_size and size < padding_gap:
            buckets.append(size)
            size *= 2
        size = round_up(max(size, padding_gap), padding_gap)
        while size < max_size:
            buckets.append(size)
            size += padding_gap
    buckets.append(max_size)
    # Deduplicate while preserving ascending order.
    out: list[int] = []
    for b in buckets:
        if not out or b > out[-1]:
            out.append(b)
    return out


def pad_to_bucket(x: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= x (buckets must be sorted ascending)."""
    for b in buckets:
        if x <= b:
            return b
    return buckets[-1]


class Counter:
    """Monotonic counter (request id generation)."""

    def __init__(self, start: int = 0) -> None:
        self.counter = start

    def __next__(self) -> int:
        i = self.counter
        self.counter += 1
        return i

    def reset(self) -> None:
        self.counter = 0


class StopWatch:
    def __enter__(self) -> "StopWatch":
        self.start = time.perf_counter()
        return self

    def __exit__(self, *args) -> None:
        self.elapsed = time.perf_counter() - self.start
