"""Shared retry/backoff policy for KV-transfer and registry network calls.

Reference analogue: the connection-retry loops scattered through the
reference's distributed bootstrap (StatelessProcessGroup.create retries,
the P2P proxy's re-register loop). Here the policy is one reusable
object so every connector classifies errors the same way: transient
transport errors (socket resets, refused connections, timeouts) retry
with exponential backoff + jitter under a wall-clock deadline; anything
else — protocol violations, injected faults, programming errors — is
fatal and surfaces immediately.
"""

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Transient transport errors worth retrying. OSError covers
# ConnectionError/TimeoutError/socket.timeout subclasses.
RETRYABLE_ERRORS: tuple[type[BaseException], ...] = (OSError, )


class RetryBudgetExceeded(RuntimeError):
    """All attempts (or the deadline) were exhausted; ``__cause__`` holds
    the last underlying error."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter under an attempt cap and an optional
    wall-clock deadline."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    # Fraction of each delay randomized (0 = deterministic backoff).
    jitter: float = 0.25
    # Total wall-clock budget across attempts (None = attempts only).
    deadline_s: Optional[float] = None

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        delay = min(self.base_delay_s * (self.multiplier ** (attempt - 1)),
                    self.max_delay_s)
        if self.jitter > 0:
            delay *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        return max(delay, 0.0)


def call_with_retry(
    fn: Callable,
    *,
    policy: RetryPolicy = RetryPolicy(),
    retryable: tuple[type[BaseException], ...] = RETRYABLE_ERRORS,
    description: str = "call",
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
):
    """Run ``fn()``; retry classified-transient failures per ``policy``.

    Non-retryable exceptions propagate unchanged. Exhausting the attempt
    cap or the deadline raises RetryBudgetExceeded chained to the last
    transient error, so callers can distinguish "network kept flaking"
    from a genuine protocol failure.
    """
    start = time.monotonic()
    last_err: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn()
        except retryable as e:  # noqa: PERF203 - retry loop
            last_err = e
            if attempt >= policy.max_attempts:
                break
            delay = policy.delay_for(attempt)
            if (policy.deadline_s is not None
                    and time.monotonic() + delay - start > policy.deadline_s):
                break
            if on_retry is not None:
                on_retry(attempt, delay, e)
            logger.debug("%s failed (%s); retry %d/%d in %.2fs",
                         description, e, attempt, policy.max_attempts - 1,
                         delay)
            time.sleep(delay)
    raise RetryBudgetExceeded(
        f"{description} failed after {policy.max_attempts} attempts: "
        f"{last_err}") from last_err
