"""Deterministic fault-injection registry for robustness testing.

A small process-global table of NAMED fault points threaded through the
fault-tolerance layers. Production code asks ``should_fire(name)`` /
``maybe_delay(name)`` at each point; with no faults configured every
check is a dict lookup on an empty table (one ``if`` on the hot path).

Activation is deterministic — a fault with rate r fires on exactly the
calls where ``floor(n*r)`` increments (rate 1.0 = every call, 0.5 =
every other call) — so a test that injects ``kv_pull.drop`` at 100%
observes the same failure sequence on every run, with no RNG seeding.

Configure programmatically (tests)::

    from vllm_distributed_tpu.utils import fault_injection as fi
    fi.inject("kv_pull.drop")                 # rate 1.0
    fi.inject("kv_pull.delay", delay_s=0.2)   # sleep 200ms per fire
    ...
    fi.clear()

or via the environment (survives engine-core subprocess spawn)::

    VDT_FAULT_INJECT="kv_pull.drop:1.0,kv_pull.delay:0.5@0.2"

(``name:rate`` entries, optional ``@delay_seconds`` suffix.)

Known points (layers consult this module; an unknown name is accepted
but never fired by production code):

* ``kv_pull.drop``      — consumer silently drops a staged KV pull (no
  worker report ever arrives; only the scheduler watchdog recovers).
* ``kv_pull.delay``     — injects ``delay_s`` of latency into a pull.
* ``registry.truncate`` — the P2P registry server answers one request
  with a malformed (non-msgpack) payload.
* ``engine_core.die``   — the engine-core busy loop raises on its next
  iteration (subprocess sends the dead sentinel; thread core surfaces
  the error through its output queue).
* ``heartbeat.stall``   — heartbeat senders (P2P registry client,
  engine-core liveness thread) skip their sends while active.
* ``core_proc.spawn_fail`` — engine-core construction (initial spawn or
  a supervisor restart) raises before the core comes up.
* ``restart.storm``     — each supervisor restart succeeds and then the
  fresh core immediately dies again (re-arms ``engine_core.die``),
  driving the restart budget to its circuit breaker.
* ``admission.stall``   — the API admission controller leaks one queue
  slot per fire (admitted work that never completes), deterministically
  building queue-depth pressure toward the shed watermark.
* ``step.reconcile_stall`` — fired at the engine core's batch-queue
  reconcile point (wait_model of the oldest in-flight batch). With a
  ``delay_s`` it stalls the host between device completion and
  reconciliation; without one it raises, killing the core with batches
  still in flight — the drill proving the crash-recovery ladder works
  mid-pipeline.
* ``router.stale_stats`` — the DP routing tier treats every replica's
  load snapshot as expired (refreshes are suppressed while armed), so
  tests can prove the router degrades to pure load balancing instead
  of herding affinity traffic onto one replica on blind signals.
* ``ssm.restore_corrupt`` — a restored SSM state checkpoint fails its
  checksum verification (core/state_cache.read_journal), proving the
  scheduler degrades the admission to a full re-prefill (counted in
  ``ssm_restore_corruptions``) instead of resuming from corrupt state.
* ``qcomm.scale_corrupt`` — the quantized KV-payload codec corrupts a
  scale header AFTER its checksum is computed (kv_transfer/quant.py
  encode), so the consumer's decode detects a CRC mismatch and
  degrades to re-requesting the raw-precision payload (counted in
  ``vdt:qcomm_fallbacks_total``), proving the recovery ladder holds
  under the quantized wire format.
* ``disagg.handoff_stall`` — the disagg coordinator hands the decode
  home broken pull coordinates (the producer will reject every pull
  for them), so the handoff's KV pull can never complete and the
  decode home is driven through the scheduler's full recovery ladder:
  bounded pull retries, then local re-prefill recompute (counted in
  ``vdt:disagg_fallbacks_total{reason="local_reprefill"}``). Greedy
  output must stay token-identical throughout.
* ``sched.quota_thrash`` — the QoS quota-preemption victim picker
  (core/sched/qos.py quota_victim, consulted on every allocation
  failure; requires ``VDT_QOS=1``) treats the per-tenant KV quota as
  ZERO, so every page-holding tenant reads as over-quota and each
  capacity preemption becomes a quota preemption targeting the
  biggest holder — a forced quota-preemption storm. The drill proves
  the per-tenant cooldown hysteresis bounds it: a tenant oscillating
  around its quota falls back to ordinary capacity preemption between
  quota evictions instead of livelocking in evict/resume cycles.
* ``perf.capture_stall`` — the profiler capture started by the
  profile RPC (engine/core.py) behaves as a WEDGED xprof session: the
  stop RPC fails (the stop is "lost") and only the VDT_PROFILE_MAX_S
  capture-window deadline, enforced by the step loop and stats polls,
  force-stops the trace. The drill proves a profiler client that dies
  (or a tunnel that drops) mid-capture can never wedge serving, with
  the fire counted in ``vdt:fault_injections_total``.
* ``kv_tier.spill_corrupt`` — a tier-2 spill page file is corrupted
  after its CRC is computed, so promotion detects the mismatch and
  degrades to recompute (core/kv_tier.py).
* ``fleet.scale_stall`` — an elastic-fleet scale-out (engine/fleet.py)
  stalls at replica construction: the new replica never comes up, the
  action is counted against the fleet's supervisor budget, and the
  drill proves hysteresis + the budget stop a wedged provisioner from
  thrashing the fleet (counted in
  ``vdt:fleet_freezes_total{reason="scale_stall"}``).
* ``fleet.replica_wedge`` — the fleet's wedge detector treats a live
  replica as alive-but-not-stepping (step-phase heartbeat age beyond
  VDT_FLEET_WEDGE_S): its journaled requests migrate off and the
  replica is force-cycled through the PR-2 restart budget, counted on
  exactly the ``vdt:fleet_wedge_cycles_total`` rung (NOT as a
  failover — the replica never died).
* ``fleet.controller_die`` — the leaseholder fleet controller
  (engine/control_plane.py) dies mid-tick: it stops ticking, renewing
  its lease, and actuating, exactly as if its front-end process was
  killed. The drill proves a standby acquires the lease within the
  TTL, replays the actuation journal, and finishes half-done
  drain→retire actions with greedy token parity.
* ``fleet.lease_expire`` — the leaseholder skips its lease renewal
  (a paused-then-resumed process: GC stall, SIGSTOP, VM migration)
  while still believing it leads. A standby takes over, the epoch
  bumps, and the ex-leader's next actuation fails the coordinator's
  fence check — rejected and counted in
  ``vdt:fleet_fenced_actions_total{action=...}``, never raised into
  serving.
* ``coordinator.partition`` — the front-end's coordinator RPCs
  (engine/coordinator.py DPCoordinatorClient) fail as if the network
  partitioned. The front-end keeps serving and routing with frozen
  placement (local least-loaded fallback, no actuation — counted in
  ``vdt:fleet_freezes_total{reason="partition"}``), mirroring the
  stale-stats freeze ladder.
* ``canary.flip_token`` — one replica's canary-probe output is
  perturbed in flight (correctness_plane.py absorbs per-replica canary
  outputs in a fixed order, so rate 0.5 on a 2-replica fleet always
  corrupts the same replica). The drill proves the correctness
  sentinel's detection ladder end to end: token mismatch → isolated by
  the cross-replica vote within <= 3 probes → ``vdt:replica_suspect``
  gauge → fleet replica-quarantine hint (under ``VDT_FLEET_SIGNALS``),
  with zero false positives on the clean replicas.
* ``numerics.nan_inject`` — a single NaN lands in one step's
  pre-sampling logits (consulted by the NumericsTap harvest, so the
  poisoned step is counted in ``vdt:logits_nan_steps_total`` and
  excluded from the entropy/margin histograms). Sustained fires climb
  the numerics strike ladder into the same quarantine path as the
  canary vote.
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

FAULT_POINTS = (
    "kv_pull.drop",
    "kv_pull.delay",
    "registry.truncate",
    "engine_core.die",
    "heartbeat.stall",
    "core_proc.spawn_fail",
    "restart.storm",
    "admission.stall",
    "step.reconcile_stall",
    "router.stale_stats",
    "ssm.restore_corrupt",
    "qcomm.scale_corrupt",
    "disagg.handoff_stall",
    "sched.quota_thrash",
    "perf.capture_stall",
    "kv_tier.spill_corrupt",
    "fleet.scale_stall",
    "fleet.replica_wedge",
    "fleet.controller_die",
    "fleet.lease_expire",
    "coordinator.partition",
    "canary.flip_token",
    "numerics.nan_inject",
)


class InjectedFault(RuntimeError):
    """Raised by fault points that surface as errors. Deliberately NOT a
    subclass of OSError: the retry layer classifies it fatal, so an
    injected fault exercises the failure path, not the retry path."""


@dataclass
class _FaultSpec:
    name: str
    rate: float = 1.0
    delay_s: float = 0.0
    # Stop firing after this many fires (None = unlimited).
    max_fires: Optional[int] = None
    calls: int = 0
    fires: int = 0


@dataclass
class FaultRegistry:
    """Per-process fault table (module-level singleton below)."""

    _specs: dict[str, _FaultSpec] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    # Cumulative fires per point, kept across clear() for metrics.
    counters: dict[str, int] = field(default_factory=dict)

    def inject(self, name: str, rate: float = 1.0, delay_s: float = 0.0,
               max_fires: Optional[int] = None) -> None:
        with self._lock:
            self._specs[name] = _FaultSpec(name=name, rate=rate,
                                           delay_s=delay_s,
                                           max_fires=max_fires)
        logger.warning("fault injection ARMED: %s rate=%.2f delay=%.3fs",
                       name, rate, delay_s)

    def clear(self, name: Optional[str] = None) -> None:
        with self._lock:
            if name is None:
                self._specs.clear()
            else:
                self._specs.pop(name, None)

    @property
    def active(self) -> bool:
        return bool(self._specs)

    def should_fire(self, name: str) -> bool:
        """One call at the named point; True when the fault fires."""
        if not self._specs:
            return False
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                return False
            if spec.max_fires is not None and spec.fires >= spec.max_fires:
                return False
            spec.calls += 1
            fire = int(spec.calls * spec.rate) > int(
                (spec.calls - 1) * spec.rate)
            if fire:
                spec.fires += 1
                self.counters[name] = self.counters.get(name, 0) + 1
        if fire:
            logger.warning("fault injection FIRED: %s (fire %d)", name,
                           self.counters[name])
        return fire

    def maybe_delay(self, name: str) -> float:
        """Fire a delay-style fault: sleeps and returns the injected
        seconds (0.0 when the fault does not fire)."""
        if not self._specs:
            return 0.0
        with self._lock:
            spec = self._specs.get(name)
        if spec is None or spec.delay_s <= 0:
            return 0.0
        if not self.should_fire(name):
            return 0.0
        time.sleep(spec.delay_s)
        return spec.delay_s

    def fire_or_raise(self, name: str) -> None:
        if self.should_fire(name):
            raise InjectedFault(f"injected fault: {name}")

    def delay_of(self, name: str) -> float:
        spec = self._specs.get(name)
        return spec.delay_s if spec is not None else 0.0


def _from_env() -> FaultRegistry:
    from vllm_distributed_tpu import envs
    reg = FaultRegistry()
    spec_str = envs.VDT_FAULT_INJECT
    for entry in filter(None, (s.strip() for s in spec_str.split(","))):
        try:
            name, _, tail = entry.partition(":")
            rate_s, _, delay_s = tail.partition("@")
            reg.inject(name.strip(), rate=float(rate_s or 1.0),
                       delay_s=float(delay_s or 0.0))
        except ValueError:
            logger.error("ignoring malformed VDT_FAULT_INJECT entry %r",
                         entry)
    return reg


# Process-global registry; engine-core subprocesses rebuild it from the
# inherited VDT_FAULT_INJECT environment at import time.
registry = _from_env()

# Module-level conveniences (the names production code imports).
inject = registry.inject
clear = registry.clear
should_fire = registry.should_fire
maybe_delay = registry.maybe_delay
fire_or_raise = registry.fire_or_raise


def counters() -> dict[str, int]:
    """Cumulative fires per fault point (metrics/bench)."""
    return dict(registry.counters)
