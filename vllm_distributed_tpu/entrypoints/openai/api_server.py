"""OpenAI-compatible HTTP server on aiohttp.

Reference: vllm/entrypoints/openai/api_server.py (run_server :1672,
build_async_engine_client :149, route set) and serving_chat/completion.
FastAPI/uvicorn are not in this image; aiohttp provides the same
lifecycle (background AsyncLLM, SSE streaming, graceful shutdown on
engine death — reference: entrypoints/launcher.py).
"""

import asyncio
import json
import signal
import time
from typing import Optional

from aiohttp import web

from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.engine.core_client import EngineDeadError
from vllm_distributed_tpu.entrypoints.openai import protocol
from vllm_distributed_tpu.entrypoints.openai.admission import (
    AdmissionController, AdmissionRejected)
from vllm_distributed_tpu.entrypoints.openai.protocol import RequestError
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import random_uuid

logger = init_logger(__name__)

ENGINE_KEY = web.AppKey("engine", AsyncLLM)
MODEL_KEY = web.AppKey("model_name", str)
TOOL_PARSER_KEY = web.AppKey("tool_parser", object)
# Served LoRA adapters: name -> checkpoint path (reference: the
# --lora-modules serve flag; requests select one via the "model" field).
LORA_MODULES_KEY = web.AppKey("lora_modules", dict)
# Admission gate (overload shedding + drain mode) for the generation
# endpoints below; health/metrics stay exempt so operators can observe
# an overloaded or draining server.
ADMISSION_KEY = web.AppKey("admission", AdmissionController)

GENERATION_PATHS = frozenset({
    "/v1/completions", "/v1/chat/completions", "/v1/embeddings",
    "/v1/score", "/v1/rerank", "/rerank", "/v1/responses",
    "/v1/audio/transcriptions",
})


def _error_response(e: Exception) -> web.Response:
    if isinstance(e, RequestError):
        return web.json_response(e.json(), status=e.code)
    if isinstance(e, EngineDeadError):
        # 503: the engine is gone/unresponsive — a load balancer should
        # stop routing here; the structured detail says why (and which
        # DP replica, when one died).
        detail = {"message": str(e), "type": "engine_unavailable",
                  "code": 503}
        if getattr(e, "replica", None) is not None:
            detail["replica"] = e.replica
        return web.json_response({"error": detail}, status=503)
    if isinstance(e, ValueError):
        # Admission-time validation (processor rejects) is the client's
        # fault: 400, matching the reference server's error mapping.
        return web.json_response(
            {"error": {"message": str(e), "type": "invalid_request_error",
                       "code": 400}}, status=400)
    return web.json_response(
        {"error": {"message": f"{type(e).__name__}: {e}",
                   "type": "internal_server_error", "code": 500}},
        status=500)


# Monotonic instant (stashed on the request by the admission
# middleware) past which a STREAMING handler must abort its pumps; the
# pumps enforce it because a fresh 408 response cannot be written once
# the SSE stream has started.
DEADLINE_AT_KEY = "vdt_deadline_at"


async def _admission_fields(
        request: web.Request) -> tuple[float, bool, int]:
    """Admission-relevant body fields: the per-request wall-clock
    deadline (the JSON body's ``timeout_s`` overrides
    VDT_REQUEST_TIMEOUT_S; 0 disables), whether the request asked for
    streaming, and its priority class (``priority`` body field, lower =
    more important; > 0 marks best-effort traffic the weighted shed
    gate evicts first)."""
    from vllm_distributed_tpu import envs
    deadline = envs.VDT_REQUEST_TIMEOUT_S
    stream = False
    priority = 0
    if request.content_type == "application/json":
        try:
            # Cheap byte scan first: most requests carry none of these
            # keys, and a full json.loads here would double the parse
            # cost of every body (the handler parses the cached bytes
            # again).
            raw = await request.read()
            if (b'"timeout_s"' in raw or b'"stream"' in raw
                    or b'"priority"' in raw):
                body = await request.json()
                if isinstance(body, dict):
                    stream = bool(body.get("stream"))
                    if body.get("timeout_s") is not None:
                        deadline = float(body["timeout_s"])
                    if body.get("priority") is not None:
                        priority = int(body["priority"])
        except Exception:  # noqa: BLE001 - handler reports bad JSON
            pass
    return max(0.0, deadline), stream, priority


async def _admission_middleware_factory(app, handler):
    """Overload protection for the generation endpoints: bounded
    admission with watermark shedding (429 + Retry-After), drain-mode
    refusal (503 + Retry-After), and a per-request deadline that aborts
    overdue work through the engine's abort path (cancelling the
    handler unwinds every generate() into AsyncLLM.abort)."""

    async def middleware(request: web.Request):
        ctrl = request.app.get(ADMISSION_KEY)
        if (ctrl is None or request.method != "POST"
                or request.path not in GENERATION_PATHS):
            return await handler(request)
        # Read the body BEFORE acquire only when the gate's answer can
        # actually depend on the priority class: a shed storm must stay
        # O(1) per refusal (no body buffering/parsing for requests the
        # gate refuses regardless of class). Admitted requests reuse
        # the parse (or do the one parse) right after.
        fields = None
        if ctrl.class_sensitive():
            fields = await _admission_fields(request)
        try:
            await ctrl.acquire(priority=fields[2] if fields else 0)
        except AdmissionRejected as e:
            kind = ("service_unavailable" if e.status == 503
                    else "overloaded")
            return web.json_response(
                {"error": {"message": str(e), "type": kind,
                           "code": e.status}},
                status=e.status,
                headers={"Retry-After": str(e.retry_after_s)})
        try:
            if fields is None:
                fields = await _admission_fields(request)
            deadline, stream, _ = fields
            if deadline > 0 and stream:
                # A 408 cannot be written once the SSE stream begins:
                # the stream pumps poll this instant and end the stream
                # cleanly (abort + [DONE]-less EOF) when it passes.
                request[DEADLINE_AT_KEY] = time.monotonic() + deadline
            elif deadline > 0:
                try:
                    return await asyncio.wait_for(handler(request),
                                                  deadline)
                except asyncio.TimeoutError:
                    logger.warning("request on %s exceeded its %.1fs "
                                   "deadline; aborted", request.path,
                                   deadline)
                    return web.json_response(
                        {"error": {
                            "message": f"request exceeded its "
                                       f"{deadline:.1f}s deadline",
                            "type": "timeout_error", "code": 408}},
                        status=408)
            return await handler(request)
        finally:
            ctrl.release()

    return middleware


async def _auth_middleware_factory(app, handler):
    from vllm_distributed_tpu import envs
    api_key = envs.VDT_API_KEY

    async def middleware(request: web.Request):
        if api_key and request.path.startswith("/v1"):
            auth = request.headers.get("Authorization", "")
            if auth != f"Bearer {api_key}":
                return web.json_response(
                    {"error": {"message": "invalid API key",
                               "type": "authentication_error",
                               "code": 401}}, status=401)
        return await handler(request)

    return middleware


# ---------------------------------------------------------------------------
async def health(request: web.Request) -> web.Response:
    engine = request.app[ENGINE_KEY]
    if engine.errored:
        return web.Response(status=503,
                            text=f"engine dead: {engine.dead_error}")
    # SLO burn-rate verdict: alive-but-degraded stays 200 (the probe
    # must not take a burning server out of rotation — autoscaling
    # reacts via the fleet hint), but the body flags it for operators
    # and external watchdogs.
    burn = getattr(getattr(getattr(engine, "output_processor", None),
                           "stats", None), "burn", None)
    if burn is not None and burn.degraded():
        return web.Response(text="OK (slo degraded)")
    # Correctness sentinel (VDT_CORRECTNESS=1): live replica suspicion
    # flags the same way — serving continues (quarantine is the fleet
    # controller's call), the body warns operators.
    plane = getattr(getattr(engine, "engine_core", None),
                    "correctness", None)
    if plane is not None and plane.suspects():
        return web.Response(text="OK (replica suspect)")
    return web.Response(text="OK")


async def list_models(request: web.Request) -> web.Response:
    return web.json_response({
        "object": "list",
        "data": [protocol.model_card(request.app[MODEL_KEY])] + [
            protocol.model_card(name)
            for name in request.app[LORA_MODULES_KEY]
        ],
    })


async def metrics(request: web.Request) -> web.Response:
    """Prometheus-format scrape of engine stats (reference:
    v1/metrics/prometheus.py mounted at /metrics)."""
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    engine = request.app[ENGINE_KEY]
    try:
        stats = await engine.get_stats()
    except Exception:  # noqa: BLE001 - engine busy/dead
        stats = {}
    text = render_metrics(stats)
    # Front-end latency histograms (TTFT / ITL / e2e; reference:
    # v1/metrics/loggers.py:143 PrometheusStatLogger families).
    processor = getattr(engine, "output_processor", None)
    if processor is not None:
        # Follower-process counter snapshots (pid-deduped by the DP
        # aggregator) fold into the front-end's render so /metrics is
        # fleet-exact, not process-local.
        text += processor.stats.render(
            fault_extra=stats.get("fault_injection_counts_remote"))
        # Per-tenant goodput feed into the fleet controller's richer
        # scaling signals (VDT_FLEET_SIGNALS): the front-end's SLO
        # scoring is the only place goodput exists, and the scrape is
        # its natural cadence. getattr-guarded — only the DP client
        # grows observe_goodput. A degraded burn-rate verdict rides the
        # same feed as a scale-up hint.
        feed = getattr(getattr(engine, "engine_core", None),
                       "observe_goodput", None)
        slo = getattr(processor.stats, "slo_by_tenant", None)
        if feed is not None and slo:
            burn = getattr(processor.stats, "burn", None)
            feed({t: good / max(scored, 1)
                  for t, (scored, good) in list(slo.items())},
                 degraded=(burn is not None and burn.degraded()))
    ctrl = request.app.get(ADMISSION_KEY)
    if ctrl is not None and ctrl.enabled:
        text += (
            "# HELP vdt:admission_queue_depth Admitted, unfinished "
            "generation requests at the API gate\n"
            "# TYPE vdt:admission_queue_depth gauge\n"
            f"vdt:admission_queue_depth {ctrl.depth}\n"
            "# HELP vdt:admission_draining 1 while the server is in "
            "SIGTERM drain mode\n"
            "# TYPE vdt:admission_draining gauge\n"
            f"vdt:admission_draining {int(ctrl.draining)}\n")
        if ctrl.shed_by_class:
            text += (
                "# HELP vdt:requests_shed_by_class_total Requests "
                "refused at the admission gate per priority class "
                "(weighted shedding evicts best_effort first)\n"
                "# TYPE vdt:requests_shed_by_class_total counter\n")
            text += "".join(
                f'vdt:requests_shed_by_class_total{{class="{c}"}} '
                f"{n}\n"
                for c, n in sorted(ctrl.shed_by_class.items()))
    return web.Response(text=text, content_type="text/plain")


def _profile_dirs(result) -> list[str]:
    # DP fan-out returns one dir per replica; uniproc returns a string.
    return result if isinstance(result, list) else [result]


# ---------------------------------------------------------------------------
# Debug introspection: GET /debug/requests, GET /debug/engine, SIGUSR1.
# GET routes are outside GENERATION_PATHS, so the admission gate never
# sheds them — an overloaded or draining server stays observable.
# ---------------------------------------------------------------------------
async def _core_debug_states(engine: AsyncLLM) -> list[dict]:
    """Per-core get_debug_state dicts (one per DP replica). Bounded and
    failure-tolerant: a dead/busy core degrades the dump to the
    front-end view instead of 500ing the endpoint."""
    try:
        dbg = await asyncio.wait_for(engine.get_debug_state(), timeout=2.0)
    except Exception:  # noqa: BLE001 - core dead/restarting/slow
        return []
    if not isinstance(dbg, dict):
        return []
    if "dp_replicas" in dbg:
        # DP fan-out aggregates dicts; the raw per-replica states
        # survive under dp_replicas.
        return [d for d in dbg["dp_replicas"] if isinstance(d, dict)]
    return [dbg]


def _phase_from_status(status: Optional[str], computed: Optional[int],
                       prompt_tokens: int) -> Optional[str]:
    """Map a scheduler RequestStatus name to a timeline phase name —
    the fallback when the front-end's per-request timeline is behind
    the core (events ride outputs, which stalled requests don't emit)
    or disabled."""
    if status == "WAITING_FOR_REMOTE_KVS":
        return "kv_pull"
    if status == "PREEMPTED":
        return "preempted"
    if status == "WAITING":
        return "queued"
    if status == "RUNNING":
        return ("prefill" if (computed or 0) < prompt_tokens
                else "decode")
    return None


async def _debug_requests_json(engine: AsyncLLM) -> dict:
    from vllm_distributed_tpu.metrics import events as ev
    core_reqs: dict[str, dict] = {}
    core_states = await _core_debug_states(engine)
    # AFTER the (up-to-2s) core RPC: events recorded during the await
    # would otherwise postdate `now` and phases_from_timeline would
    # silently drop the open phase they start.
    now = time.monotonic()
    for i, core in enumerate(core_states):
        for entry in core.get("scheduler", {}).get("requests", ()):
            entry = dict(entry, replica=i if len(core_states) > 1
                         else None)
            core_reqs[entry["request_id"]] = entry
    requests = []
    for rid, state in list(engine.output_processor.request_states.items()):
        # Re-base epoch resets (restarted core = fresh monotonic clock)
        # in arrival order BEFORE sorting — a raw sort interleaves the
        # replayed lifecycle into the pre-death one and both the phase
        # math and current_phase misreport the request.
        timeline = sorted(ev.rebase_epochs(state.timeline),
                          key=lambda e: e[0])
        phases = ev.phases_from_timeline(timeline, now=now)
        times = state.times
        entry = {
            "request_id": rid,
            "phase": ev.current_phase(timeline),
            "age_s": round(now - times.arrival, 3) if times else None,
            "phase_age_s": {p: round(d, 4) for p, d in
                            ev.phase_durations(phases).items()},
            "prompt_tokens": len(state.prompt_token_ids),
            "tokens_emitted": len(state.output_token_ids),
            "num_events": len(timeline),
        }
        core = core_reqs.pop(rid, None)
        if core is not None:
            entry.update({
                "status": core["status"],
                "tokens_computed": core["num_computed_tokens"],
                "kv_blocks": core["kv_blocks"],
                "inflight_refcount": core["inflight_refcount"],
                "num_preemptions": core["num_preemptions"],
                "replica": core.get("replica"),
            })
            if entry["phase"] in (None, "queued"):
                # Core-side events only reach the front-end riding an
                # EngineCoreOutput, which a request stuck mid-prefill
                # or in a KV-pull hold never emits — exactly the
                # requests this endpoint must diagnose. When the
                # timeline lags (or is disabled), derive the phase from
                # the authoritative scheduler status instead.
                entry["phase"] = _phase_from_status(
                    core["status"], core["num_computed_tokens"],
                    entry["prompt_tokens"]) or entry["phase"]
        requests.append(entry)
    # Core-only requests (e.g. a replay the front-end already dropped).
    for rid, core in core_reqs.items():
        requests.append(dict(core, phase=None, core_only=True))
    return {"now_monotonic": now, "num_requests": len(requests),
            "requests": requests}


async def _debug_engine_json(app: web.Application) -> dict:
    from vllm_distributed_tpu.metrics import events as ev
    engine = app[ENGINE_KEY]
    core_states = await _core_debug_states(engine)
    schedulers = []
    for core in core_states:
        sched = dict(core.get("scheduler", {}))
        sched.pop("requests", None)  # per-request detail lives in
        # /debug/requests; keep this endpoint a queue/pipeline summary.
        schedulers.append({
            "scheduler": sched,
            "batch_queue_depth": core.get("batch_queue_depth"),
            "batch_queue_size": core.get("batch_queue_size"),
            "async_scheduling": core.get("async_scheduling"),
            "steps_dispatched": core.get("steps_dispatched"),
            "max_concurrent_batches":
                core.get("max_concurrent_batches"),
        })
    try:
        # include_events=False: the drain is destructive and this
        # wait_for may abandon the RPC — a timed-out debug poll (the
        # wedged-engine case) must not discard the incident window's
        # events. The /metrics scrape is the draining consumer.
        stats = await asyncio.wait_for(
            engine.get_stats(include_events=False), timeout=2.0)
    except Exception:  # noqa: BLE001 - engine busy/dead
        stats = {}
    ctrl = app.get(ADMISSION_KEY)
    admission = None
    if ctrl is not None:
        admission = {
            "enabled": ctrl.enabled,
            "depth": ctrl.depth,
            "max_depth_seen": ctrl.max_depth_seen,
            "high_watermark": ctrl.high_watermark,
            "low_watermark": ctrl.low_watermark,
            "kv_high": ctrl.kv_high,
            "shedding": sorted(ctrl._shedding),
            "draining": ctrl.draining,
        }
    transport = stats.get("transport")
    from vllm_distributed_tpu.parallel import collectives
    qcomm = collectives.merged_qcomm_view(
        (transport or {}).get("qcomm")
        if isinstance(transport, dict) else None,
        stats.get("qcomm_traced_remote"))
    burn = getattr(engine.output_processor.stats, "burn", None)
    slo = None
    if burn is not None:
        slo = {"burn_rates": {w: round(r, 4)
                              for w, r in burn.burn_rates().items()},
               "degraded": burn.degraded(),
               "target": burn.target,
               "threshold": burn.threshold}
    return {
        "supervisor": engine.supervisor_state(),
        "engine_cores": schedulers,
        # Quantized communication plane: per-path bytes saved +
        # raw-precision fallbacks (empty when the plane never fired).
        "qcomm": qcomm,
        "kv_cache_usage": stats.get("kv_cache_usage"),
        "num_running_reqs": stats.get("num_running_reqs"),
        "num_waiting_reqs": stats.get("num_waiting_reqs"),
        "inflight_batches": stats.get("inflight_batches"),
        "admission": admission,
        # SLO burn-rate watchdog (None when no SLO target is set).
        "slo_burn": slo,
        # Correctness sentinel summary (None while VDT_CORRECTNESS=0;
        # the full view lives at /debug/correctness).
        "correctness": stats.get("correctness"),
        # Front-end ledger merged with the core-side events absorbed
        # from /metrics scrapes (the draining stats consumer).
        "recent_events": ev.merge_event_lists(
            engine.output_processor.events.snapshot(100),
            engine.output_processor.core_events.snapshot(100)),
    }


async def _debug_kv_cache_json(engine: AsyncLLM) -> dict:
    """Live block-pool state per engine core: pool occupancy
    (free/used/tombstoned/cached-free pages), fragmentation, the
    windowed prefix-cache hit rate, preemption causes, and each
    request's page footprint — the paged-KV view of the same scheduler
    snapshot /debug/requests reads."""
    cores = []
    for i, core in enumerate(await _core_debug_states(engine)):
        sched = core.get("scheduler", {})
        cores.append({
            "replica": i,
            "kv_cache": sched.get("kv_cache"),
            "kv_cache_usage": sched.get("kv_cache_usage"),
            "requests": [
                {"request_id": r.get("request_id"),
                 "status": r.get("status"),
                 "kv_blocks": r.get("kv_blocks"),
                 "num_computed_tokens": r.get("num_computed_tokens"),
                 "tknp_rank": r.get("tknp_rank")}
                for r in sched.get("requests", ())
            ],
            "waiting_for_remote_kvs":
                sched.get("waiting_for_remote_kvs"),
            "cancelled_remote_kv": sched.get("cancelled_remote_kv"),
        })
    return {"now_monotonic": time.monotonic(), "engine_cores": cores}


async def _debug_perf_json(engine: AsyncLLM) -> dict:
    """Performance-attribution snapshot (metrics/costmodel.py): the
    top-N (kernel family x phase x token bucket) rows by measured
    device-seconds, each with analytic FLOPs/bytes and achieved vs
    peak rates, plus fleet totals (MFU/MBU per worker, HBM traffic by
    kind, roofline placement per phase). DP-merged the same way
    /metrics is; include_events=False so this poll never steals the
    scrape's timeline drain."""
    from vllm_distributed_tpu import envs
    from vllm_distributed_tpu.metrics.costmodel import classify_roofline
    stats = await engine.get_stats(include_events=False)
    attrib = stats.get("perf_attrib") or {}
    peaks = stats.get("perf_peaks") or {}
    peak_f = float(peaks.get("flops", 0.0))
    peak_b = float(peaks.get("hbm", 0.0))
    rows = []
    for key, e in attrib.items():
        if not isinstance(e, dict):
            continue
        parts = key.split("/")
        dev_s = float(e.get("device_seconds", 0.0))
        flops = float(e.get("flops", 0.0))
        byts = float(e.get("bytes", 0.0))
        rows.append({
            "key": key,
            "kernel": parts[0] if parts else key,
            "phase": parts[1] if len(parts) > 1 else "",
            "bucket": parts[2] if len(parts) > 2 else "",
            "dispatches": int(e.get("dispatches", 0)),
            "device_seconds": dev_s,
            "flops": flops,
            "hbm_bytes": byts,
            "tflops_per_s": flops / dev_s / 1e12 if dev_s else 0.0,
            "gb_per_s": byts / dev_s / 1e9 if dev_s else 0.0,
            "frac_peak_flops": (flops / (dev_s * peak_f)
                                if dev_s and peak_f else 0.0),
            "frac_peak_bw": (byts / (dev_s * peak_b)
                             if dev_s and peak_b else 0.0),
        })
    rows.sort(key=lambda r: r["device_seconds"], reverse=True)
    top_n = envs.VDT_PERF_TOPN
    dropped = max(len(rows) - top_n, 0)
    phases = stats.get("perf_phases") or {}
    roofline = {p: classify_roofline(e, peaks)
                for p, e in phases.items() if isinstance(e, dict)}
    workers = stats.get("workers") or {}
    utilization = {
        w: {"mfu": s.get("mfu"), "mbu": s.get("mbu")}
        for w, s in sorted(workers.items())
        if isinstance(s, dict) and ("mfu" in s or "mbu" in s)
    }
    return {
        "attribution": rows[:top_n],
        "rows_dropped": dropped,
        "totals": {
            "model_flops": stats.get("model_flops"),
            "hbm_bytes": stats.get("hbm_bytes"),
            "device_seconds": sum(
                float(e.get("device_seconds", 0.0))
                for e in attrib.values() if isinstance(e, dict)),
        },
        "utilization": utilization,
        "roofline_bound": roofline,
        "phases": phases,
        "peaks": peaks,
    }


async def debug_requests(request: web.Request) -> web.Response:
    """Live per-request state: current phase, per-phase ages from the
    lifecycle timeline, progress counters, KV footprint."""
    return web.json_response(
        await _debug_requests_json(request.app[ENGINE_KEY]))


async def debug_perf(request: web.Request) -> web.Response:
    """Performance attribution: kernel-family device-seconds / FLOPs /
    bytes table, MFU/MBU, roofline placement. Admission-exempt GET —
    a saturated server is exactly the one worth attributing."""
    return web.json_response(
        await _debug_perf_json(request.app[ENGINE_KEY]))


async def debug_kv_cache(request: web.Request) -> web.Response:
    """Live paged-KV introspection: block-pool occupancy,
    fragmentation, windowed prefix-cache hit rate, preemption causes,
    per-request page footprints. Admission-exempt GET — a server
    shedding for KV pressure stays diagnosable."""
    return web.json_response(
        await _debug_kv_cache_json(request.app[ENGINE_KEY]))


async def debug_engine(request: web.Request) -> web.Response:
    """Live engine state: scheduler queues, batch pipeline, KV usage,
    restart-supervisor state, admission watermarks."""
    return web.json_response(await _debug_engine_json(request.app))


async def debug_trace(request: web.Request) -> web.Response:
    """One stitched causal trace as Chrome/Perfetto trace-event JSON
    (``?request_id=`` or ``?trace_id=``; ``?format=raw`` for the
    un-rendered event list, no params lists known trace ids). Requires
    VDT_TRACE_PLANE=1; the stats poll below drains any core-ring
    events not yet fed to the assembler so a trace requested right
    after a request finishes is already complete."""
    from vllm_distributed_tpu import trace_plane
    engine = request.app[ENGINE_KEY]
    assembler = getattr(getattr(engine, "output_processor", None),
                        "assembler", None)
    if assembler is None:
        return web.json_response(
            {"error": "trace plane disabled (set VDT_TRACE_PLANE=1)"},
            status=404)
    try:
        await asyncio.wait_for(engine.get_stats(), timeout=2.0)
    except Exception:  # noqa: BLE001 - engine busy/dead; serve cached
        pass
    rid = request.query.get("request_id")
    tid = request.query.get("trace_id")
    if not rid and not tid:
        return web.json_response({"trace_ids": assembler.trace_ids()})
    trace = assembler.get(request_id=rid, trace_id=tid)
    if trace is None:
        return web.json_response(
            {"error": f"no trace for {rid or tid!r}"}, status=404)
    if request.query.get("format") == "raw":
        return web.json_response(trace)
    return web.json_response(trace_plane.perfetto(trace))


async def debug_correctness(request: web.Request) -> web.Response:
    """Correctness-sentinel introspection (admission-exempt, like every
    /debug endpoint — registered outside the admission gate's guarded
    routes): canary probe/divergence counters, per-replica suspicion,
    the numerics snapshots and the quarantine tally. Requires
    VDT_CORRECTNESS=1."""
    engine = request.app[ENGINE_KEY]
    plane = getattr(getattr(engine, "engine_core", None),
                    "correctness", None)
    if plane is None:
        return web.json_response(
            {"error": "correctness sentinel disabled "
                      "(set VDT_CORRECTNESS=1)"},
            status=404)
    try:
        # include_events=False: the destructive event drain belongs to
        # the /metrics scrape (the debug_engine discipline).
        stats = await asyncio.wait_for(
            engine.get_stats(include_events=False), timeout=2.0)
    except Exception:  # noqa: BLE001 - engine busy/dead; the plane's
        # own counters below still serve
        stats = {}
    return web.json_response({
        "correctness": stats.get("correctness") or plane.get_stats(),
        "numerics": stats.get("numerics"),
        "fleet_quarantines": (stats.get("fleet") or {}).get(
            "quarantines"),
    })


def _thread_stacks() -> str:
    import sys
    import threading
    import traceback
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in frames.items():
        chunks.append(f"--- thread {names.get(ident, '?')} ({ident}) ---\n"
                      + "".join(traceback.format_stack(frame)))
    return "\n".join(chunks)


async def _dump_debug_to_log(app: web.Application) -> None:
    """SIGUSR1 forensics: the same JSON the /debug endpoints serve, plus
    every thread's stack, to the log — for the hung-server case where
    HTTP may no longer answer (a hung engine core or blocked handler;
    the loop itself stays alive since engine work runs off-loop).
    Never raises, never blocks serving. The signal callback logs the
    thread stacks synchronously BEFORE scheduling this coroutine, so
    the await-free part of the dump lands even when the engine calls
    in here stall."""
    try:
        engine_state = await _debug_engine_json(app)
        request_state = await _debug_requests_json(app[ENGINE_KEY])
        kv_state = await _debug_kv_cache_json(app[ENGINE_KEY])
        logger.warning(
            "SIGUSR1 debug dump:\n/debug/engine: %s\n/debug/requests: "
            "%s\n/debug/kv_cache: %s\nthread stacks:\n%s",
            json.dumps(engine_state, default=str),
            json.dumps(request_state, default=str),
            json.dumps(kv_state, default=str),
            _thread_stacks())
    except Exception:  # noqa: BLE001 - forensics must not kill serving
        logger.exception("SIGUSR1 debug dump failed")


async def embeddings(request: web.Request) -> web.Response:
    """OpenAI /v1/embeddings over the pooling path (reference:
    serving_embedding.py)."""
    engine = request.app[ENGINE_KEY]
    model = request.app[MODEL_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        inputs = body.get("input")
        if inputs is None:
            raise RequestError("embeddings need 'input'")
        if isinstance(inputs, str) or (isinstance(inputs, list) and inputs
                                       and isinstance(inputs[0], int)):
            inputs = [inputs]
        results = await asyncio.gather(
            *(engine.encode(item) for item in inputs))
        data = [{
            "object": "embedding",
            "index": i,
            "embedding": out.embedding,
        } for i, out in enumerate(results)]
        prompt_tokens = sum(out.num_prompt_tokens for out in results)
        return web.json_response({
            "object": "list",
            "data": data,
            "model": body.get("model", model),
            "usage": protocol.usage(prompt_tokens, 0),
        })
    except (RequestError, ValueError) as e:
        return _error_response(e if isinstance(e, RequestError)
                               else RequestError(str(e)))
    except EngineDeadError as e:
        return _error_response(RequestError(str(e), code=500))


def _score_pairs(engine, queries, documents):
    """Build (token_ids, pooling) per pair for cross-encoder scoring."""
    from vllm_distributed_tpu.entrypoints.score_utils import (
        build_score_pair)
    return [build_score_pair(engine.tokenizer, q, d)
            for q, d in zip(queries, documents)]


async def _pair_scores(engine, queries, documents):
    """(scores, prompt_tokens) for query x document pairs.

    Cross-encoder checkpoints run each pair through the classification
    head; embedding models fall back to cosine similarity over the
    encode path — the same two modes LLM.score serves (reference:
    serving_score.py supports both over HTTP)."""
    import math
    if engine.processor.is_cross_encoder:
        pairs = _score_pairs(engine, queries, documents)
        results = await asyncio.gather(
            *(engine.encode(ids, pooling_params=pooling)
              for ids, pooling in pairs))
        return ([out.embedding[0] for out in results],
                sum(out.num_prompt_tokens for out in results))
    # Embedding model: encode each distinct text once, score by cosine.
    unique: dict = {}
    for text in list(queries) + list(documents):
        unique.setdefault(text, None)
    texts = list(unique)
    results = await asyncio.gather(
        *(engine.encode(t) for t in texts))
    by_text = {t: out.embedding for t, out in zip(texts, results)}

    def cos(a, b):
        dot = sum(x * y for x, y in zip(a, b))
        return dot / (math.sqrt(sum(x * x for x in a)) *
                      math.sqrt(sum(x * x for x in b)) + 1e-12)

    return ([cos(by_text[q], by_text[d])
             for q, d in zip(queries, documents)],
            sum(out.num_prompt_tokens for out in results))


async def score(request: web.Request) -> web.Response:
    """/v1/score: cross-encoder relevance of text_1 x text_2 pairs
    (reference: serving_score.py)."""
    engine = request.app[ENGINE_KEY]
    model = request.app[MODEL_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        t1, t2 = body.get("text_1"), body.get("text_2")
        if t1 is None or t2 is None:
            raise RequestError("score needs 'text_1' and 'text_2'")
        if isinstance(t1, str):
            t1 = [t1]
        if isinstance(t2, str):
            t2 = [t2]
        if len(t1) == 1 and len(t2) > 1:
            t1 = t1 * len(t2)
        elif len(t2) == 1 and len(t1) > 1:
            t2 = t2 * len(t1)
        if len(t1) != len(t2):
            raise RequestError(
                f"text_1 x text_2 must match (or broadcast); got "
                f"{len(t1)} x {len(t2)}")
        scores, prompt_tokens = await _pair_scores(engine, t1, t2)
        data = [{
            "object": "score",
            "index": i,
            "score": s,
        } for i, s in enumerate(scores)]
        return web.json_response({
            "object": "list",
            "data": data,
            "model": body.get("model", model),
            "usage": protocol.usage(prompt_tokens, 0),
        })
    except (RequestError, ValueError) as e:
        return _error_response(e if isinstance(e, RequestError)
                               else RequestError(str(e)))
    except EngineDeadError as e:
        return _error_response(RequestError(str(e), code=500))


async def rerank(request: web.Request) -> web.Response:
    """/v1/rerank (and /rerank): order documents by cross-encoder
    relevance to a query (reference: serving_score.py rerank API)."""
    engine = request.app[ENGINE_KEY]
    model = request.app[MODEL_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        query = body.get("query")
        documents = body.get("documents")
        if isinstance(documents, str):
            documents = [documents]
        if query is None or not documents:
            raise RequestError("rerank needs 'query' and 'documents'")
        scores, prompt_tokens = await _pair_scores(
            engine, [query] * len(documents), documents)
        ranked = sorted(((s, i) for i, s in enumerate(scores)),
                        reverse=True)
        top_n = body.get("top_n", len(documents))
        data = [{
            "index": i,
            "relevance_score": s,
            "document": {"text": documents[i]},
        } for s, i in ranked[:top_n]]
        return web.json_response({
            "model": body.get("model", model),
            "results": data,
            "usage": protocol.usage(prompt_tokens, 0),
        })
    except (RequestError, ValueError) as e:
        return _error_response(e if isinstance(e, RequestError)
                               else RequestError(str(e)))
    except EngineDeadError as e:
        return _error_response(RequestError(str(e), code=500))


async def tokenize(request: web.Request) -> web.Response:
    """/tokenize (reference: the tokenize route of api_server.py:453):
    text (or chat messages) -> token ids."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        tokenizer = engine.tokenizer
        if tokenizer is None:
            raise RequestError("server has no tokenizer", code=400)
        if body.get("messages") is not None:
            # Same templating path chat generation uses (incl. the
            # template-less fallback); special tokens default OFF for
            # chat — the template already embeds them (reference:
            # the tokenize route's chat defaults, api_server.py:453).
            prompt, _mm = _chat_prompt(engine, body["messages"])
            add_special = bool(body.get("add_special_tokens", False))
        else:
            prompt = body.get("prompt")
            if prompt is None:
                raise RequestError("tokenize needs 'prompt' or "
                                   "'messages'")
            add_special = bool(body.get("add_special_tokens", True))
        if isinstance(prompt, list):
            # Templated chat paths return token ids directly.
            ids = [int(t) for t in prompt]
        else:
            ids = tokenizer.encode(prompt,
                                   add_special_tokens=add_special)
        return web.json_response({
            "tokens": ids,
            "count": len(ids),
            "max_model_len":
                engine.config.scheduler_config.max_model_len,
        })
    except (RequestError, ValueError) as e:
        return _error_response(e if isinstance(e, RequestError)
                               else RequestError(str(e)))


async def detokenize(request: web.Request) -> web.Response:
    """/detokenize (reference: api_server.py:491): token ids -> text."""
    engine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        tokenizer = engine.tokenizer
        if tokenizer is None:
            raise RequestError("server has no tokenizer", code=400)
        tokens = body.get("tokens")
        if not isinstance(tokens, list):
            raise RequestError("detokenize needs 'tokens' as a list "
                               "of token ids")
        text = tokenizer.decode([int(t) for t in tokens])
        return web.json_response({"prompt": text})
    except (RequestError, ValueError) as e:
        return _error_response(e if isinstance(e, RequestError)
                               else RequestError(str(e)))


async def responses(request: web.Request) -> web.Response:
    """/v1/responses minimal surface (reference: serving_responses.py):
    'input' (string or message list) + optional 'instructions' run as a
    chat completion; the reply is wrapped in the Responses output item
    shape. Background mode / response stores are not implemented."""
    engine = request.app[ENGINE_KEY]
    model = request.app[MODEL_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        if body.get("background"):
            raise RequestError(
                "background responses are not supported")
        if body.get("stream"):
            raise RequestError(
                "streaming responses are not supported; set "
                "stream=false")
        inp = body.get("input")
        if inp is None:
            raise RequestError("responses need 'input'")
        messages = ([{"role": "user", "content": inp}]
                    if isinstance(inp, str) else [
                        ({"role": "user", "content": m}
                         if isinstance(m, str) else m)
                        for m in inp
                    ])
        # Normalize Responses-typed content parts onto the chat part
        # types _chat_prompt knows (input_text -> text, input_image ->
        # image_url).
        for m in messages:
            if not isinstance(m, dict):
                raise RequestError(
                    "input items must be strings or message objects")
            parts = m.get("content")
            if isinstance(parts, list):
                m["content"] = [
                    ({**p, "type": "text"}
                     if p.get("type") == "input_text" else
                     {"type": "image_url",
                      "image_url": {"url": p.get("image_url")}}
                     if p.get("type") == "input_image" else p)
                    for p in parts
                ]
        if body.get("instructions"):
            messages.insert(
                0, {"role": "system", "content": body["instructions"]})
        max_len = engine.config.scheduler_config.max_model_len
        chat_body = dict(body, messages=messages)
        chat_body.pop("input", None)
        if "max_output_tokens" in body:
            chat_body["max_tokens"] = body["max_output_tokens"]
        params = protocol.sampling_params_from_request(chat_body,
                                                       max_len)
        prompt, mm = _chat_prompt(engine, messages)
        if mm is not None:
            # Image parts: encode pixels once, like chat_completions.
            mm = {"image_embeds": engine.processor._encode_pixels(
                mm["pixel_values"])}
        lora = _resolve_lora(request.app, body)
        rid = protocol.completion_id().replace("cmpl", "resp")
        priority, tenant = _priority_tenant(body)
        final = await _drain(engine.generate(prompt, params,
                                             request_id=rid,
                                             priority=priority,
                                             tenant=tenant,
                                             lora_request=lora,
                                             multi_modal_data=mm))
        text = final.outputs[0].text
        return web.json_response({
            "id": rid,
            "object": "response",
            "created_at": int(time.time()),  # wallclock-ok
            "model": body.get("model", model),
            "status": "completed",
            "output": [{
                "type": "message",
                "id": f"msg-{rid}",
                "role": "assistant",
                "status": "completed",
                "content": [{"type": "output_text", "text": text,
                             "annotations": []}],
            }],
            "output_text": text,
            "usage": {
                "input_tokens": len(final.prompt_token_ids),
                "output_tokens": len(final.outputs[0].token_ids),
                "total_tokens": (len(final.prompt_token_ids) +
                                 len(final.outputs[0].token_ids)),
            },
        })
    except (RequestError, ValueError) as e:
        return _error_response(e if isinstance(e, RequestError)
                               else RequestError(str(e)))
    except EngineDeadError as e:
        return _error_response(RequestError(str(e), code=500))


def _decode_wav(data: bytes):
    """PCM WAV bytes -> (mono float32 waveform, sample_rate) using only
    the stdlib (no audio libs in the image)."""
    import io
    import wave

    import numpy as np
    try:
        with wave.open(io.BytesIO(data)) as w:
            rate = w.getframerate()
            n = w.getnframes()
            width = w.getsampwidth()
            channels = w.getnchannels()
            raw = w.readframes(n)
    except (wave.Error, EOFError) as e:
        raise RequestError(f"invalid WAV payload: {e}") from e
    if width == 2:
        x = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(raw, np.int32).astype(np.float32) / 2**31
    elif width == 1:
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128) / 128
    else:
        raise RequestError(f"unsupported WAV sample width {width}")
    if channels > 1:
        x = x.reshape(-1, channels).mean(axis=1)
    return x, rate


def _transcription_prompt(engine) -> list[int]:
    """Decoder prompt for transcription: decoder_start + any forced ids
    from the generation config (<|lang|><|transcribe|><|notimestamps|>;
    reference: the prompt assembly of serving_transcription.py)."""
    hf = engine.config.model_config.maybe_load_hf_config()
    ids = [int(getattr(hf, "decoder_start_token_id", 0) or 0)]
    forced = getattr(hf, "forced_decoder_ids", None)
    if forced:
        ids.extend(int(t) for _, t in forced)
    return ids


async def transcriptions(request: web.Request) -> web.Response:
    """/v1/audio/transcriptions (reference: serving_transcription.py):
    multipart form with a WAV `file`, or JSON {"audio": <base64 wav>}.
    Requires a Whisper-family model."""
    engine = request.app[ENGINE_KEY]
    model = request.app[MODEL_KEY]
    try:
        if request.content_type.startswith("multipart/"):
            reader = await request.multipart()
            data = None
            async for part in reader:
                if part.name == "file":
                    data = await part.read()
                else:
                    await part.read()
            if data is None:
                raise RequestError("multipart needs a 'file' part")
        else:
            import base64
            body = await request.json()
            if body.get("audio") is None:
                raise RequestError(
                    "transcriptions need a multipart 'file' or JSON "
                    "'audio' (base64 WAV)")
            data = base64.b64decode(body["audio"])
        wav, rate = _decode_wav(data)
        if rate != 16000:
            raise RequestError(
                f"audio must be 16 kHz PCM WAV (got {rate} Hz); "
                f"resample client-side")
        from vllm_distributed_tpu.sampling_params import SamplingParams
        params = SamplingParams(
            temperature=0.0,
            max_tokens=engine.config.scheduler_config.max_model_len // 2)
        prompt = _transcription_prompt(engine)
        final = await _drain(engine.generate(
            prompt, params,
            request_id=protocol.completion_id().replace("cmpl", "trsc"),
            multi_modal_data={"audio": wav}))
        return web.json_response({
            "text": final.outputs[0].text,
            "model": model,
        })
    except (RequestError, ValueError) as e:
        return _error_response(e if isinstance(e, RequestError)
                               else RequestError(str(e)))
    except EngineDeadError as e:
        return _error_response(RequestError(str(e), code=500))


async def start_profile(request: web.Request) -> web.Response:
    """Begin a device trace (reference: api_server /start_profile).
    Hardened (ISSUE 14): the engine core auto-names each capture's
    trace dir, bounds it with a VDT_PROFILE_MAX_S force-stop deadline,
    and rejects a second concurrent capture — surfaced as 409 here so
    a retrying tunnel script can tell "busy" from "broken"."""
    try:
        dirs = _profile_dirs(
            await request.app[ENGINE_KEY].profile("start"))
    except ValueError as e:
        return web.json_response(
            {"error": {"message": str(e), "type": "capture_conflict",
                       "code": 409}}, status=409)
    return web.json_response({"status": "profiling", "dir": dirs[0],
                              "dirs": dirs})


async def stop_profile(request: web.Request) -> web.Response:
    """End the capture. The response bundles the trace dirs WITH the
    current performance-attribution snapshot, so one transient-tunnel
    RPC pair yields an xplane trace plus the self-describing analytic
    table that explains it."""
    try:
        dirs = _profile_dirs(
            await request.app[ENGINE_KEY].profile("stop"))
    except ValueError as e:
        return web.json_response(
            {"error": {"message": str(e), "type": "capture_conflict",
                       "code": 409}}, status=409)
    body = {"status": "stopped", "dir": dirs[0], "dirs": dirs}
    try:
        body["perf"] = await _debug_perf_json(request.app[ENGINE_KEY])
    except Exception:  # noqa: BLE001 - the trace dirs are the payload;
        # a stats hiccup must not fail the stop.
        pass
    return web.json_response(body)


# ---------------------------------------------------------------------------
def _gen_prompts(body: dict) -> list:
    """Completions `prompt` can be str | [str] | [int] | [[int]]."""
    prompt = body.get("prompt")
    if prompt is None:
        raise RequestError("`prompt` is required")
    if isinstance(prompt, str):
        return [prompt]
    if isinstance(prompt, list):
        if not prompt:
            raise RequestError("`prompt` must not be empty")
        if isinstance(prompt[0], int):
            return [prompt]
        return list(prompt)
    raise RequestError("`prompt` must be a string or list")


def _priority_tenant(body: dict) -> tuple[int, Optional[str]]:
    """Scheduling class + tenant identity off an OpenAI request body:
    ``priority`` (int, lower = more important, > 0 = best-effort) and
    ``tenant`` (falling back to the standard OpenAI ``user`` field).
    Both ride EngineCoreRequest: priority drives the scheduler's
    priority policy and the admission gate's weighted shedding, tenant
    labels introspection."""
    try:
        priority = int(body.get("priority", 0) or 0)
    except (TypeError, ValueError) as e:
        raise RequestError(f"invalid priority: {e}") from e
    tenant = body.get("tenant", body.get("user"))
    if tenant is not None:
        tenant = str(tenant)
    return priority, tenant


async def completions(request: web.Request) -> web.StreamResponse:
    engine = request.app[ENGINE_KEY]
    model = request.app[MODEL_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        prompts = _gen_prompts(body)
        n = int(body.get("n", 1) or 1)
        max_len = engine.config.scheduler_config.max_model_len
        params = protocol.sampling_params_from_request(body, max_len)
        stream = bool(body.get("stream", False))
        echo_texts = None
        if body.get("echo"):
            if stream:
                raise RequestError(
                    "echo with stream is not supported")
            if params.logprobs is not None:
                # Echoed logprobs need the prompt positions scored
                # (reference: the echo path of serving_completion.py).
                params.prompt_logprobs = params.logprobs
            # Token-id prompts echo their detokenized text so the text
            # stays aligned with the echoed logprobs arrays.
            tokenizer = engine.tokenizer
            echo_texts = [
                p if isinstance(p, str) else
                (tokenizer.decode(p) if tokenizer is not None else
                 " ".join(str(t) for t in p))
                for p in prompts
            ]
        cid = protocol.completion_id()
        created = int(time.time())  # wallclock-ok
        priority, tenant = _priority_tenant(body)

        # Fan out: one engine request per (prompt, sample) pair; choice
        # index follows OpenAI semantics (prompt-major, then n). Seeded
        # requests offset the seed per child so samples differ.
        lora = _resolve_lora(request.app, body)
        # Encoder-decoder text (BART): the source document rides an
        # extra body field and encodes once at admission (reference:
        # the encoder_prompt of the reference's encoder-decoder
        # serving).
        enc_mm = None
        if body.get("encoder_text") is not None:
            enc_mm = {"encoder_text": str(body["encoder_text"])}
        elif body.get("encoder_input_ids") is not None:
            ids = body["encoder_input_ids"]
            if (not isinstance(ids, list)
                    or not all(isinstance(t, int) for t in ids)):
                raise RequestError(
                    "encoder_input_ids must be a list of token ids")
            enc_mm = {"encoder_input_ids": ids}
        gens = []
        for pi, prompt in enumerate(prompts):
            for s in range(n):
                idx = pi * n + s
                child = params
                if n > 1 and params.seed is not None:
                    import copy as _copy
                    child = _copy.copy(params)
                    child.seed = params.seed + s
                gens.append((idx, engine.generate(
                    prompt, child, request_id=f"{cid}-{idx}",
                    priority=priority, tenant=tenant,
                    lora_request=lora, multi_modal_data=enc_mm)))

        if stream:
            return await _stream_completions(request, cid, created, model,
                                             gens)
        # Drain all generators CONCURRENTLY: engine.generate is an async
        # generator, so nothing is submitted until iteration starts —
        # sequential draining would serialize the batch.
        finals = await asyncio.gather(*(_drain(gen) for _, gen in gens))
        choices = [None] * len(gens)
        prompt_tokens = 0
        completion_tokens = 0
        for (idx, _), final in zip(gens, finals):
            prompt_tokens += len(final.prompt_token_ids) if idx % n == 0 \
                else 0
            completion_tokens += len(final.outputs[0].token_ids)
            choices[idx] = _completion_choice(
                idx, final, body,
                echo_text=(echo_texts[idx // n]
                           if echo_texts is not None else None))
        return web.json_response({
            "id": cid,
            "object": "text_completion",
            "created": created,
            "model": model,
            "choices": choices,
            "usage": protocol.usage(prompt_tokens, completion_tokens),
        })
    except (RequestError, EngineDeadError, ValueError) as e:
        return _error_response(e)


async def _drain(gen):
    final = None
    async for out in gen:
        final = out
    return final


def _completion_choice(idx: int, out, body: dict,
                       echo_text: str = None) -> dict:
    comp = out.outputs[0]
    echo = bool(body.get("echo"))
    prefix = (echo_text if echo_text is not None else
              (out.prompt or "")) if echo else ""
    choice = {
        "index": idx,
        "text": prefix + comp.text,
        "finish_reason": comp.finish_reason,
    }
    if body.get("logprobs") is not None and comp.logprobs:
        token_ids = list(comp.token_ids)
        token_lps = [lp.get(tok) if lp else None
                     for tok, lp in zip(comp.token_ids, comp.logprobs)]
        top = [{str(k): v for k, v in lp.items()} for lp in comp.logprobs]
        if echo and out.prompt_logprobs is not None:
            # Prompt positions lead (first entry None, OpenAI echo
            # semantics); ids follow the same str() convention as the
            # completion tokens.
            p_ids = list(out.prompt_token_ids)
            p_lps = [None] + [
                (d.get(t) if d else None)
                for t, d in zip(p_ids[1:], out.prompt_logprobs[1:])
            ]
            p_top = [({str(k): v for k, v in d.items()} if d else None)
                     for d in out.prompt_logprobs]
            token_ids = p_ids + token_ids
            token_lps = p_lps + token_lps
            top = p_top + top
        choice["logprobs"] = {
            # The sampled token's own logprob (keyed lookup — the map may
            # also carry top-k alternatives with higher probability).
            "token_logprobs": token_lps,
            "tokens": [str(t) for t in token_ids],
            "top_logprobs": top,
        }
    return choice


def _client_disconnected(request: web.Request) -> bool:
    """A dropped client closes the transport; the stream loops poll this
    so generation stops instead of running to completion unwatched
    (reference: the is_disconnected() checks of serving_completion)."""
    transport = request.transport
    return transport is None or transport.is_closing()


def _check_stream_alive(request: web.Request) -> None:
    """Stream guard: raises on client disconnect or on an expired
    per-request deadline (both end the stream through the engine's
    abort path)."""
    if _client_disconnected(request):
        raise ConnectionResetError("client disconnected")
    deadline_at = request.get(DEADLINE_AT_KEY)
    if deadline_at is not None and time.monotonic() > deadline_at:
        raise asyncio.TimeoutError("stream exceeded its deadline")


async def _stream_outputs(request: web.Request, gen):
    """Iterate an engine stream, enforcing the liveness guard once a
    second even when NO output arrives — a request stalled in the
    engine (queued, remote-KV hold) must still honor disconnects and
    deadlines instead of keeping its slot until the next token. The
    pending __anext__ survives across polls; it is cancelled only when
    the guard trips, which unwinds generate() into the abort path."""
    aiter = gen.__aiter__()
    task = None
    try:
        while True:
            if task is None:
                task = asyncio.ensure_future(aiter.__anext__())
            done, _ = await asyncio.wait({task}, timeout=1.0)
            _check_stream_alive(request)
            if not done:
                continue
            try:
                out = task.result()
            except StopAsyncIteration:
                task = None
                return
            task = None
            yield out
    finally:
        if task is not None and not task.done():
            task.cancel()
            try:
                # Let the cancellation reach generate()'s finally (the
                # upstream abort) before the handler returns.
                await task
            except BaseException:  # noqa: BLE001 - cancelled/aborted
                pass


async def _abort_stream(request: web.Request, cid: str,
                        gens: list) -> None:
    """Abort every child request of a dropped stream through the
    engine's abort path (frees their KV pages and scheduler slots)."""
    engine = request.app[ENGINE_KEY]
    for idx, _gen in gens:
        try:
            await engine.abort(f"{cid}-{idx}")
        except Exception:  # noqa: BLE001 - engine dead/racing shutdown
            pass


async def _stream_completions(request, cid, created, model,
                              gens) -> web.StreamResponse:
    resp = web.StreamResponse(headers={
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
    })
    await resp.prepare(request)

    async def pump(idx, gen):
        sent = 0
        async for out in _stream_outputs(request, gen):
            text = out.outputs[0].text
            delta = text[sent:]
            sent = len(text)
            finish = out.outputs[0].finish_reason
            if delta or finish:
                chunk = {
                    "id": cid,
                    "object": "text_completion",
                    "created": created,
                    "model": model,
                    "choices": [{
                        "index": idx,
                        "text": delta,
                        "finish_reason": finish,
                    }],
                }
                await resp.write(
                    f"data: {json.dumps(chunk)}\n\n".encode())

    try:
        await asyncio.gather(*(pump(idx, gen) for idx, gen in gens))
        await resp.write(b"data: [DONE]\n\n")
    except (EngineDeadError, ConnectionResetError,
            asyncio.TimeoutError) as e:
        logger.warning("stream aborted: %s", e)
        await _abort_stream(request, cid, gens)
    await resp.write_eof()
    return resp


# ---------------------------------------------------------------------------
def _chat_prompt(engine: AsyncLLM, messages: list):
    """-> (prompt, multi_modal_data | None). OpenAI structured content
    parts flatten to text with the model's image placeholder token
    standing in for each image (reference: entrypoints/chat_utils.py);
    data-URL images preprocess through the checkpoint's CLIP recipe."""
    tokenizer = engine.tokenizer
    if tokenizer is None:
        raise RequestError("chat requires a tokenizer for this model")
    hf = engine.config.model_config.maybe_load_hf_config()
    try:
        from vllm_distributed_tpu.models.registry import \
            resolve_architecture
        qwen_vl = getattr(resolve_architecture(hf), "VISION_STYLE",
                          None) == "qwen2_vl"
    except Exception:  # noqa: BLE001 - toy configs
        qwen_vl = False
    image_urls: list[str] = []
    video_frames: list[list[str]] = []
    flat: list[dict] = []
    for m in messages:
        content = m.get("content")
        if isinstance(content, list):
            from vllm_distributed_tpu.multimodal.image_processing import \
                image_token_string
            tok = image_token_string(tokenizer, hf)
            vtok = None
            if qwen_vl:
                from vllm_distributed_tpu.multimodal.qwen2_vl_processing \
                    import media_token_strings
                tok, vtok = media_token_strings(tokenizer, hf)
            parts: list[str] = []
            for part in content:
                ptype = part.get("type")
                if ptype == "text":
                    parts.append(part.get("text", ""))
                elif ptype == "image_url":
                    if tok is None:
                        raise RequestError(
                            "this model does not accept image inputs")
                    image_urls.append(
                        (part.get("image_url") or {}).get("url", ""))
                    parts.append(tok)
                elif ptype == "video_url":
                    # Videos arrive as FRAME LISTS of data-URL images
                    # (what the reference's video loader produces after
                    # container decode; multimodal/video.py).
                    if vtok is None:
                        raise RequestError(
                            "this model does not accept video inputs")
                    url = (part.get("video_url") or {}).get("url")
                    frames = url if isinstance(url, list) else [url]
                    video_frames.append([f or "" for f in frames])
                    parts.append(vtok)
                else:
                    raise RequestError(
                        f"unsupported content part type {ptype!r}")
            flat.append(dict(m, content="".join(parts)))
        else:
            flat.append(m)
    mm = None
    if qwen_vl and (image_urls or video_frames):
        from vllm_distributed_tpu.multimodal.qwen2_vl_processing import \
            preprocess_chat_media
        try:
            mm = preprocess_chat_media(image_urls, video_frames, hf)
        except ValueError as e:
            raise RequestError(str(e)) from e
    elif video_frames:
        raise RequestError("this model does not accept video inputs")
    elif image_urls:
        from vllm_distributed_tpu.multimodal.image_processing import \
            preprocess_data_urls
        try:
            pixels = preprocess_data_urls(
                image_urls, engine.config.model_config.model,
                engine.config.model_config.maybe_load_hf_config())
        except ValueError as e:
            raise RequestError(str(e)) from e
        mm = {"pixel_values": pixels}
    if getattr(tokenizer, "chat_template", None):
        prompt = tokenizer.apply_chat_template(
            flat, tokenize=True, add_generation_prompt=True)
    else:
        # Template-less tiny/test models: plain role-prefixed transcript.
        prompt = "".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}\n"
            for m in flat) + "assistant:"
    return prompt, mm


async def chat_completions(request: web.Request) -> web.StreamResponse:
    engine = request.app[ENGINE_KEY]
    model = request.app[MODEL_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error_response(RequestError(f"invalid JSON: {e}"))
    try:
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise RequestError("`messages` must be a non-empty list")
        prompt, mm = _chat_prompt(engine, messages)
        n = int(body.get("n", 1) or 1)
        if mm is not None and "image_grid_thw" not in mm \
                and "video_grid_thw" not in mm:
            # llava path: encode pixels ONCE; the n samples (and the
            # scheduler) reuse the embeddings instead of n vision-tower
            # passes. (Qwen2-VL grid payloads encode at admission.)
            mm = {"image_embeds": engine.processor._encode_pixels(
                mm["pixel_values"])}
        max_len = engine.config.scheduler_config.max_model_len
        params = protocol.sampling_params_from_request(body, max_len)
        stream = bool(body.get("stream", False))
        cid = protocol.chat_id()
        created = int(time.time())  # wallclock-ok
        lora = _resolve_lora(request.app, body)
        forced_tool = protocol.apply_tool_constraints(body, params)
        if stream and forced_tool is not None:
            raise RequestError(
                "streaming with a forced tool_choice is not supported "
                "yet; set stream=false")
        priority, tenant = _priority_tenant(body)
        gens = [(i, engine.generate(prompt, params,
                                    request_id=f"{cid}-{i}",
                                    priority=priority, tenant=tenant,
                                    lora_request=lora,
                                    multi_modal_data=mm))
                for i in range(n)]
        if stream:
            return await _stream_chat(request, cid, created, model, gens)
        finals = await asyncio.gather(*(_drain(gen) for _, gen in gens))
        choices = [None] * n
        prompt_tokens = 0
        completion_tokens = 0
        for (idx, _), final in zip(gens, finals):
            if idx == 0:
                prompt_tokens = len(final.prompt_token_ids)
            completion_tokens += len(final.outputs[0].token_ids)
            text = final.outputs[0].text
            parse_tools = (None if body.get("tool_choice") == "none"
                           else body.get("tools"))
            tool_calls = None
            content = text
            dialect = request.app[TOOL_PARSER_KEY]
            if (forced_tool is None and parse_tools
                    and dialect is not None):
                # Model-specific dialect parser (reference:
                # tool_parsers/): splits content from the dialect's
                # tool-call wrapping.
                content, calls = dialect.parse(text)
                if calls:
                    tool_calls = protocol.wrap_tool_calls(calls)
            else:
                tool_calls = protocol.parse_tool_calls(
                    text, forced_tool, parse_tools)
            if tool_calls is not None:
                message = {"role": "assistant",
                           "content": content or None,
                           "tool_calls": tool_calls}
                finish = "tool_calls"
            else:
                message = {"role": "assistant", "content": text}
                finish = final.outputs[0].finish_reason
            choices[idx] = {
                "index": idx,
                "message": message,
                "finish_reason": finish,
            }
        return web.json_response({
            "id": cid,
            "object": "chat.completion",
            "created": created,
            "model": model,
            "choices": choices,
            "usage": protocol.usage(prompt_tokens, completion_tokens),
        })
    except (RequestError, EngineDeadError, ValueError) as e:
        return _error_response(e)


async def _stream_chat(request, cid, created, model,
                       gens) -> web.StreamResponse:
    resp = web.StreamResponse(headers={
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
    })
    await resp.prepare(request)

    async def send(choices):
        chunk = {
            "id": cid,
            "object": "chat.completion.chunk",
            "created": created,
            "model": model,
            "choices": choices,
        }
        await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())

    async def pump(idx, gen):
        await send([{"index": idx,
                     "delta": {"role": "assistant", "content": ""},
                     "finish_reason": None}])
        sent = 0
        async for out in _stream_outputs(request, gen):
            text = out.outputs[0].text
            delta = text[sent:]
            sent = len(text)
            finish = out.outputs[0].finish_reason
            if delta or finish:
                await send([{"index": idx,
                             "delta": ({"content": delta} if delta else {}),
                             "finish_reason": finish}])

    try:
        await asyncio.gather(*(pump(idx, gen) for idx, gen in gens))
        await resp.write(b"data: [DONE]\n\n")
    except (EngineDeadError, ConnectionResetError,
            asyncio.TimeoutError) as e:
        logger.warning("stream aborted: %s", e)
        await _abort_stream(request, cid, gens)
    await resp.write_eof()
    return resp


# ---------------------------------------------------------------------------
def _resolve_lora(app: web.Application, body: dict) -> Optional[dict]:
    """A request whose ``model`` names a served adapter gets that
    adapter (reference: lora-modules model aliasing)."""
    name = body.get("model")
    path = app[LORA_MODULES_KEY].get(name)
    if path is None:
        return None
    return {"name": name, "path": path}


def build_app(engine: AsyncLLM, model_name: str,
              lora_modules: Optional[dict] = None,
              tool_call_parser: Optional[str] = None) -> web.Application:
    app = web.Application(middlewares=[_auth_middleware_factory,
                                       _admission_middleware_factory])
    app[ENGINE_KEY] = engine
    app[MODEL_KEY] = model_name
    app[LORA_MODULES_KEY] = dict(lora_modules or {})
    app[ADMISSION_KEY] = AdmissionController.from_envs(engine)
    if tool_call_parser:
        from vllm_distributed_tpu.entrypoints.openai.tool_parsers import \
            get_tool_parser
        app[TOOL_PARSER_KEY] = get_tool_parser(tool_call_parser)
    else:
        app[TOOL_PARSER_KEY] = None
    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", list_models)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/requests", debug_requests)
    app.router.add_get("/debug/engine", debug_engine)
    app.router.add_get("/debug/kv_cache", debug_kv_cache)
    app.router.add_get("/debug/perf", debug_perf)
    app.router.add_get("/debug/trace", debug_trace)
    app.router.add_get("/debug/correctness", debug_correctness)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_post("/v1/score", score)
    app.router.add_post("/tokenize", tokenize)
    app.router.add_post("/detokenize", detokenize)
    app.router.add_post("/v1/responses", responses)
    app.router.add_post("/v1/audio/transcriptions", transcriptions)
    app.router.add_post("/v1/rerank", rerank)
    app.router.add_post("/rerank", rerank)
    app.router.add_post("/start_profile", start_profile)
    app.router.add_post("/stop_profile", stop_profile)
    return app


async def drain_and_stop(controller: AdmissionController,
                         stop_event: asyncio.Event,
                         timeout_s: Optional[float] = None) -> float:
    """SIGTERM path: stop admitting, let in-flight requests finish (up
    to the drain deadline), then stop the server. Returns the drain
    duration (also recorded as vdt:drain_duration_seconds)."""
    from vllm_distributed_tpu import envs
    if timeout_s is None:
        timeout_s = envs.VDT_DRAIN_TIMEOUT_S
    controller.begin_drain()
    duration = await controller.wait_drained(timeout_s)
    logger.warning("graceful drain finished in %.2fs; stopping server",
                   duration)
    stop_event.set()
    return duration


async def serve(engine: AsyncLLM, model_name: str, host: str,
                port: int, ready_event=None,
                stop_event: Optional[asyncio.Event] = None,
                lora_modules: Optional[dict] = None,
                tool_call_parser: Optional[str] = None) -> None:
    """Run until stop_event (or SIGTERM drain); graceful engine
    shutdown on exit (reference: entrypoints/launcher.py serve_http)."""
    app = build_app(engine, model_name, lora_modules,
                    tool_call_parser=tool_call_parser)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("serving on http://%s:%d", host, port)
    if stop_event is None:
        stop_event = asyncio.Event()
    loop = asyncio.get_running_loop()
    drain_task = None

    def _on_sigterm() -> None:
        nonlocal drain_task
        if drain_task is None:
            drain_task = asyncio.ensure_future(
                drain_and_stop(app[ADMISSION_KEY], stop_event))

    def _on_sigusr1() -> None:
        # Hung-server forensics. Thread stacks first and SYNCHRONOUSLY
        # — they need no awaits, so they land even when the engine is
        # wedged and the async state dump below would stall on it.
        try:
            logger.warning("SIGUSR1 thread stacks:\n%s",
                           _thread_stacks())
        except Exception:  # noqa: BLE001 - forensics must not kill
            logger.exception("SIGUSR1 stack dump failed")
        asyncio.ensure_future(_dump_debug_to_log(app))

    try:
        loop.add_signal_handler(signal.SIGTERM, _on_sigterm)
        loop.add_signal_handler(signal.SIGUSR1, _on_sigusr1)
    except (NotImplementedError, ValueError, RuntimeError):
        # Non-main-thread loops (tests) and platforms without signal
        # support: drain stays reachable via drain_and_stop directly,
        # the debug dump via _dump_debug_to_log.
        pass
    if ready_event is not None:
        ready_event.set()
    try:
        await stop_event.wait()
    finally:
        if drain_task is not None:
            drain_task.cancel()
        try:
            loop.remove_signal_handler(signal.SIGTERM)
            loop.remove_signal_handler(signal.SIGUSR1)
        except (NotImplementedError, ValueError, RuntimeError):
            pass
        await runner.cleanup()
        engine.shutdown()


def run_server(engine_args, host: str = "0.0.0.0", port: int = 8000,
               lora_modules: Optional[dict] = None,
               tool_call_parser: Optional[str] = None) -> None:
    """Blocking entry used by the CLI (reference: api_server.py:1672)."""
    engine = AsyncLLM.from_engine_args(engine_args)
    asyncio.run(serve(engine, engine_args.model, host, port,
                      lora_modules=lora_modules,
                      tool_call_parser=tool_call_parser))
