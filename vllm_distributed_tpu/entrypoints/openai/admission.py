"""API-level admission control, overload shedding, and graceful drain.

Reference: the reference server's --max-concurrent-requests /
api_server_count front-door limits plus the scheduler's own waiting
queue; here the OpenAI server gets an explicit bounded admission gate so
overload degrades into fast 429s with ``Retry-After`` instead of an
unbounded queue whose tail latency IS the outage. Two pressure signals
feed the gate:

* **queue depth** — concurrent admitted generation requests, with
  high/low watermark hysteresis (above high: shed; keep shedding until
  depth falls back to low), and
* **free-KV-page pressure** — the engine's ``kv_cache_usage`` gauge,
  sampled at most twice a second, so a KV-saturated engine sheds before
  its waiting queue does.

Shedding is **class-weighted** for tenant fairness: requests carry a
priority (body field, lower = more important; > 0 marks best-effort
traffic) and an optional tenant identity, and best-effort requests
evaluate BOTH pressure signals against watermarks scaled down by
``VDT_ADMISSION_BEST_EFFORT_FRAC`` — so overload always evicts
best-effort traffic before interactive traffic, with the same
``Retry-After`` contract. Per-class shed counts render as
``vdt:requests_shed_by_class_total{class}``.

SIGTERM flips the gate into **drain mode**: no new admissions (503 +
``Retry-After``), in-flight requests run to completion, and the server
exits once the gate is empty or the drain deadline passes. The
``admission.stall`` fault point leaks one slot per fire, building
deterministic queue-depth pressure for overload drills.
"""

import asyncio
import time
from typing import Optional

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)


class AdmissionRejected(Exception):
    """Raised by acquire() when the gate refuses the request; carries
    the HTTP status (429 overload / 503 drain) and Retry-After hint."""

    def __init__(self, message: str, status: int,
                 retry_after_s: int) -> None:
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Bounded admission gate for the OpenAI server's generation
    endpoints. All state lives on the event loop thread — handlers call
    acquire()/release() without extra locking."""

    def __init__(self, engine, *, high_watermark: int,
                 low_watermark: int = 0, kv_high: float = 0.0,
                 retry_after_s: int = 1,
                 best_effort_frac: float = 1.0) -> None:
        self.engine = engine
        self.high_watermark = high_watermark
        self.low_watermark = (low_watermark if low_watermark > 0 else
                              max(1, (3 * high_watermark) // 4))
        self.kv_high = kv_high
        # KV hysteresis floor: stop shedding once usage drops 5 points.
        self.kv_low = max(0.0, kv_high - 0.05)
        self.retry_after_s = retry_after_s
        # Weighted per-class shedding: best-effort traffic (priority >
        # 0) evaluates every threshold scaled by this fraction, so it
        # sheds first and recovers last under overload.
        self.best_effort_frac = min(1.0, max(0.05, best_effort_frac))

        self.depth = 0  # admitted, unfinished generation requests
        self.max_depth_seen = 0
        # 429/503 refusals per class ("interactive"/"best_effort"),
        # rendered as vdt:requests_shed_by_class_total{class}.
        self.shed_by_class: dict[str, int] = {}
        # Classes currently in shedding mode. PER CLASS: best-effort
        # tripping its (lower) watermark must not flip interactive
        # traffic into hysteresis shedding.
        self._shedding: set[str] = set()
        self.draining = False
        self._drain_started: Optional[float] = None
        self._drain_done = asyncio.Event()
        # Cached KV usage sample (refreshed at most every 0.5 s).
        self._kv_usage = 0.0
        self._kv_sampled_at = 0.0

    @property
    def enabled(self) -> bool:
        return self.high_watermark > 0

    # ------------------------------------------------------------------
    async def _kv_pressure(self) -> float:
        if self.kv_high <= 0:
            return 0.0
        now = time.monotonic()
        if now - self._kv_sampled_at >= 0.5:
            self._kv_sampled_at = now
            try:
                # Hard-bounded: a slow stats RPC (e.g. an MP core whose
                # pump thread hasn't started yet) must never stall the
                # admission path — keep the stale sample instead.
                # include_events=False: this wait_for may abandon the
                # RPC mid-flight, and the event-ring drain is
                # destructive — a cancelled poll must not cost the
                # /debug recent-events history for the incident window.
                stats = await asyncio.wait_for(
                    self.engine.get_stats(include_events=False),
                    timeout=0.2)
                self._kv_usage = float(stats.get("kv_cache_usage", 0.0))
            except Exception:  # noqa: BLE001 - engine busy/restarting;
                # keep the stale sample rather than blocking admission.
                pass
        return self._kv_usage

    @staticmethod
    def request_class(priority: int) -> str:
        """Priority -> shed class: lower is more important (matching
        the scheduler's priority policy); > 0 marks best-effort."""
        return "best_effort" if priority > 0 else "interactive"

    def _thresholds(self, cls: str) -> tuple[int, int, float, float]:
        """(high, low, kv_high, kv_low) watermarks for one class:
        best-effort evaluates every signal against fractions of the
        interactive thresholds, so it sheds first, recovers last."""
        if cls != "best_effort" or self.best_effort_frac >= 1.0:
            return (self.high_watermark, self.low_watermark,
                    self.kv_high, self.kv_low)
        f = self.best_effort_frac
        high = max(1, int(self.high_watermark * f))
        low = min(max(1, int(self.low_watermark * f)), high - 1) \
            if high > 1 else 0
        kv_high = self.kv_high * f if self.kv_high > 0 else 0.0
        return high, low, kv_high, max(0.0, kv_high - 0.05)

    def _reject(self, message: str, status: int = 429,
                cls: str = "interactive") -> None:
        processor = getattr(self.engine, "output_processor", None)
        stats = getattr(processor, "stats", None)
        if stats is not None:
            stats.num_requests_shed += 1
        self.shed_by_class[cls] = self.shed_by_class.get(cls, 0) + 1
        # Timeline ledger: sheds happen before a request id exists.
        recorder = getattr(processor, "events", None)
        if recorder is not None:
            from vllm_distributed_tpu.metrics import events as ev
            recorder.record("", ev.SHED,
                            {"status": status, "reason": message,
                             "class": cls})
        raise AdmissionRejected(message, status, self.retry_after_s)

    def class_sensitive(self) -> bool:
        """True when the NEXT admission's outcome may depend on its
        priority class — some class is in shedding hysteresis, or depth
        / the cached KV sample is NEAR the best-effort thresholds. The
        middleware uses this to skip reading the request body before
        acquire() when classing cannot change the answer: a shed storm
        must stay O(1) per refusal, not O(body) (the body is read
        post-admission anyway for admitted requests). The margins below
        (a few depth slots, 0.1 of KV) absorb the signals moving while
        concurrent admissions land or the 0.5 s KV sample refreshes;
        a ramp steeper than that can class one request conservatively
        as interactive for one window — an accepted trade for not
        buffering bodies on every refusal."""
        if not self.enabled or self.best_effort_frac >= 1.0:
            return False
        if self._shedding:
            return True
        high, _, kv_high, kv_low = self._thresholds("best_effort")
        return (self.depth + 4 >= high
                or (kv_high > 0 and self._kv_usage >= kv_low - 0.1))

    async def acquire(self, priority: int = 0) -> None:
        """Admit one generation request or raise AdmissionRejected.
        The caller MUST pair a successful acquire with release().
        Depth is tracked even with shedding disabled (high_watermark=0)
        — the SIGTERM drain needs an accurate in-flight count either
        way. ``priority`` comes from the request body and picks which
        watermark set applies (weighted shedding); the Retry-After
        contract is identical for every class. Tenant identity does
        not enter the gate — it rides EngineCoreRequest for the
        scheduler and debug introspection."""
        cls = self.request_class(priority)
        if self.draining:
            self._reject("server is draining for shutdown", status=503,
                         cls=cls)
        if not self.enabled:
            self.depth += 1
            return
        if fault_injection.should_fire("admission.stall"):
            # Drill: a slot that is admitted but never released —
            # deterministic queue-depth pressure toward the watermark.
            self.depth += 1
            self.max_depth_seen = max(self.max_depth_seen, self.depth)
        high, low, kv_high, kv_low = self._thresholds(cls)
        kv = await self._kv_pressure()
        # Best-effort INHERITS interactive's shedding state: while
        # more-important traffic is still being refused by hysteresis,
        # admitting best-effort work would invert the priority order
        # (and push the depth interactive is waiting to drain back up).
        shedding = (cls in self._shedding
                    or (cls == "best_effort"
                        and "interactive" in self._shedding))
        if shedding:
            # Hysteresis: the class keeps shedding until BOTH signals
            # fall to ITS low watermarks, so the gate flaps once per
            # overload episode instead of once per request — and
            # best-effort traffic stays shed while interactive traffic
            # is already being re-admitted.
            if (self.depth > low or (kv_high > 0 and kv > kv_low)):
                self._reject(
                    f"shedding until load falls below the low "
                    f"watermark (depth {self.depth}/{low}, "
                    f"kv {kv:.2f}, class {cls})", cls=cls)
            self._shedding.discard(cls)
        if self.depth >= high:
            self._shedding.add(cls)
            self._reject(
                f"admission queue full ({self.depth}/{high}, "
                f"class {cls})", cls=cls)
        if kv_high > 0 and kv >= kv_high:
            self._shedding.add(cls)
            self._reject(
                f"KV cache pressure {kv:.2f} >= {kv_high:.2f} "
                f"(class {cls})", cls=cls)
        self.depth += 1
        self.max_depth_seen = max(self.max_depth_seen, self.depth)

    def release(self) -> None:
        self.depth = max(0, self.depth - 1)
        if self.draining and self.depth == 0:
            self._drain_done.set()

    # ------------------------------------------------------------------
    # Graceful drain (SIGTERM)
    # ------------------------------------------------------------------
    def begin_drain(self) -> None:
        """Stop admitting; release() of the last in-flight request (or
        the drain deadline) completes the drain."""
        if self.draining:
            return
        self.draining = True
        self._drain_started = time.monotonic()
        if self.depth == 0:
            self._drain_done.set()
        logger.warning("drain mode: admission stopped, %d request(s) "
                       "in flight", self.depth)

    async def wait_drained(self, timeout_s: float) -> float:
        """Block until in-flight work finishes or the deadline passes;
        returns (and records) the drain duration."""
        try:
            await asyncio.wait_for(self._drain_done.wait(), timeout_s)
        except asyncio.TimeoutError:
            logger.error("drain deadline (%.0fs) passed with %d "
                         "request(s) still in flight", timeout_s,
                         self.depth)
        duration = time.monotonic() - (self._drain_started or
                                       time.monotonic())
        stats = getattr(self.engine.output_processor, "stats", None)
        if stats is not None:
            stats.drain_duration_seconds = duration
        return duration

    @classmethod
    def from_envs(cls, engine) -> "AdmissionController":
        from vllm_distributed_tpu import envs
        return cls(
            engine,
            high_watermark=envs.VDT_ADMISSION_HIGH_WATERMARK,
            low_watermark=envs.VDT_ADMISSION_LOW_WATERMARK,
            kv_high=envs.VDT_ADMISSION_KV_HIGH,
            retry_after_s=envs.VDT_RETRY_AFTER_S,
            best_effort_frac=envs.VDT_ADMISSION_BEST_EFFORT_FRAC,
        )
