"""OpenAI-compatible request/response schemas (subset).

Reference: vllm/entrypoints/openai/protocol.py (pydantic models for
/v1/completions and /v1/chat/completions). pydantic is not a hard
dependency here: plain dict parsing with explicit validation keeps the
server dependency-light; the wire shapes match the reference.
"""

import json
import time
from typing import Any, Optional

from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import random_uuid


class RequestError(ValueError):
    """400-level error with an OpenAI-style error body."""

    def __init__(self, message: str, code: int = 400) -> None:
        super().__init__(message)
        self.code = code

    def json(self) -> dict:
        return {
            "error": {
                "message": str(self),
                "type": "invalid_request_error",
                "code": self.code,
            }
        }


_SAMPLING_KEYS = dict(
    temperature=float,
    top_p=float,
    top_k=int,
    min_p=float,
    seed=int,
    presence_penalty=float,
    frequency_penalty=float,
    repetition_penalty=float,
    min_tokens=int,
    ignore_eos=bool,
)


def sampling_params_from_request(body: dict,
                                 default_max_tokens: int) -> SamplingParams:
    kwargs: dict[str, Any] = {}
    max_tokens = body.get("max_tokens", body.get("max_completion_tokens"))
    kwargs["max_tokens"] = (int(max_tokens)
                            if max_tokens is not None else
                            default_max_tokens)
    for key, cast in _SAMPLING_KEYS.items():
        if body.get(key) is not None:
            kwargs[key] = cast(body[key])
    stop = body.get("stop")
    if stop is not None:
        kwargs["stop"] = [stop] if isinstance(stop, str) else list(stop)
    if body.get("stop_token_ids") is not None:
        kwargs["stop_token_ids"] = list(body["stop_token_ids"])
    if body.get("logit_bias") is not None:
        # OpenAI sends {"<token_id>": bias} with string keys.
        try:
            kwargs["logit_bias"] = {
                int(k): float(v) for k, v in body["logit_bias"].items()
            }
        except (AttributeError, TypeError, ValueError) as e:
            raise RequestError(f"invalid logit_bias: {e}") from e
    if body.get("allowed_token_ids") is not None:
        kwargs["allowed_token_ids"] = [int(t)
                                       for t in body["allowed_token_ids"]]
    if body.get("logprobs") is not None:
        lp = body["logprobs"]
        # Completions API: logprobs=<int>; chat API: logprobs=true +
        # top_logprobs=<int>.
        if isinstance(lp, bool):
            if lp:
                kwargs["logprobs"] = int(body.get("top_logprobs", 1) or 1)
        else:
            kwargs["logprobs"] = int(lp)
    structured = _structured_from_request(body)
    if structured is not None:
        kwargs["structured"] = structured
    try:
        return SamplingParams(**kwargs)
    except ValueError as e:
        raise RequestError(str(e)) from e


def _structured_from_request(body: dict) -> Optional[dict]:
    """OpenAI structured-output surfaces -> SamplingParams.structured.

    ``response_format``: {"type": "json_object"} or {"type":
    "json_schema", "json_schema": {"schema": ...}} (reference:
    protocol.py response_format handling); plus the guided_* extensions
    (guided_regex / guided_choice / guided_json) the reference accepts
    as extra body fields."""
    if body.get("guided_regex") is not None:
        return {"regex": str(body["guided_regex"])}
    if body.get("guided_grammar") is not None:
        return {"grammar": str(body["guided_grammar"])}
    if body.get("guided_choice") is not None:
        return {"choice": [str(c) for c in body["guided_choice"]]}
    if body.get("guided_json") is not None:
        return {"json": body["guided_json"]}
    rf = body.get("response_format")
    if not rf:
        return None
    if not isinstance(rf, dict) or "type" not in rf:
        raise RequestError(f"invalid response_format: {rf!r}")
    if rf["type"] == "text":
        return None
    if rf["type"] == "json_object":
        return {"json_object": True}
    if rf["type"] == "json_schema":
        js = rf.get("json_schema") or {}
        schema = js.get("schema") if isinstance(js, dict) else None
        if schema is None:
            raise RequestError(
                "response_format.json_schema.schema is required")
        return {"json": schema}
    raise RequestError(f"unsupported response_format type {rf['type']!r}")


def apply_tool_constraints(body: dict, params) -> Optional[str]:
    """OpenAI function calling (reference: serving_chat tool handling +
    tool_parsers/). A forced tool choice ("required" or a named
    function) constrains generation to the function's argument schema
    via structured output, so the emitted arguments ALWAYS parse.
    Returns the forced function name (or "*" for required-any) — the
    marker parse_tool_calls uses."""
    tools = body.get("tools")
    choice = body.get("tool_choice", "auto" if tools else "none")
    if not tools or choice == "none":
        return None
    functions = {t["function"]["name"]: t["function"]
                 for t in tools if t.get("type") == "function"}
    if isinstance(choice, dict):
        name = choice.get("function", {}).get("name")
        fn = functions.get(name)
        if fn is None:
            raise RequestError(f"unknown tool {name!r}")
        params.structured = {"json": fn.get("parameters")
                             or {"type": "object"}}
        return name
    if choice == "required":
        # One branch per function, binding the name to ITS argument
        # schema so emitted arguments always validate.
        params.structured = {"json": {"anyOf": [{
            "type": "object",
            "properties": {
                "name": {"const": name},
                "arguments": fn.get("parameters") or {"type": "object"},
            },
            "required": ["name", "arguments"],
        } for name, fn in functions.items()]}}
        return "*"
    return None  # auto: unconstrained; parsed best-effort


def parse_tool_calls(text: str, forced_tool: Optional[str],
                     tools) -> Optional[list[dict]]:
    """Build OpenAI tool_calls from generated text (reference: the
    JSON-style tool parsers under openai/tool_parsers/)."""
    import json as _json
    if forced_tool is None:
        if not tools:
            return None
        # auto: accept a bare {"name": ..., "arguments": {...}} object.
        try:
            obj = _json.loads(text)
        except (ValueError, TypeError):
            return None
        declared = {t["function"]["name"] for t in tools
                    if t.get("type") == "function"}
        if not (isinstance(obj, dict) and obj.get("name") in declared):
            return None
        arguments = obj.get("arguments")
        if isinstance(arguments, str):
            # Many fine-tunes imitate the OpenAI wire format, where
            # arguments is a JSON-encoded STRING.
            try:
                arguments = _json.loads(arguments)
            except ValueError:
                return None
        if not isinstance(arguments, dict):
            return None
        name = obj["name"]
    elif forced_tool == "*":
        try:
            obj = _json.loads(text)
        except (ValueError, TypeError):
            # The grammar guarantees parseability EXCEPT under
            # max_tokens truncation; fall back to plain content so the
            # client sees the length finish instead of an error.
            return None
        name, arguments = obj["name"], obj["arguments"]
    else:
        try:
            arguments = _json.loads(text)
        except (ValueError, TypeError):
            return None  # truncated mid-JSON (finish_reason length)
        name = forced_tool
    return [{
        "id": f"call-{random_uuid()[:24]}",
        "type": "function",
        "function": {"name": name,
                     "arguments": _json.dumps(arguments)},
    }]


def wrap_tool_calls(calls: list[dict]) -> list[dict]:
    """Canonical parsed calls -> OpenAI wire tool_calls entries."""
    return [{
        "id": f"call-{random_uuid()[:24]}",
        "type": "function",
        "function": {"name": c["name"],
                     "arguments": json.dumps(c["arguments"])},
    } for c in calls]


def completion_id() -> str:
    return f"cmpl-{random_uuid()}"


def chat_id() -> str:
    return f"chatcmpl-{random_uuid()}"


def usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def model_card(model: str) -> dict:
    return {
        "id": model,
        "object": "model",
        "created": int(time.time()),  # wallclock-ok
        "owned_by": "vllm-distributed-tpu",
    }
