"""Model-specific tool-call parsers (reference:
vllm/entrypoints/openai/tool_parsers/ — hermes_tool_parser.py,
mistral_tool_parser.py, llama_tool_parser.py, pythonic_tool_parser.py).

Each dialect knows how its model family wraps function calls in
generated text; ``parse(text)`` returns (content, tool_calls) where
``content`` is the text with tool sections removed and ``tool_calls``
is a list of {"name": str, "arguments": dict} (None when the text
contains no calls). Selected per server via ``--tool-call-parser``;
the default "json" dialect is the generic bare-JSON behavior the
grammar-forced path produces.
"""

import ast
import json
import re
from typing import Optional

_Calls = Optional[list[dict]]


class ToolParser:
    """Base: no dialect markers — a bare JSON object IS the call."""

    name = "json"

    def parse(self, text: str) -> tuple[str, _Calls]:
        try:
            obj = json.loads(text)
        except (ValueError, TypeError):
            return text, None
        call = self._normalize(obj)
        return ("", [call]) if call else (text, None)

    @staticmethod
    def _normalize(obj) -> Optional[dict]:
        """{"name", "arguments"|"parameters"} -> canonical call."""
        if not isinstance(obj, dict) or not isinstance(
                obj.get("name"), str):
            return None
        args = obj.get("arguments", obj.get("parameters"))
        if isinstance(args, str):
            try:
                args = json.loads(args)
            except ValueError:
                return None
        if not isinstance(args, dict):
            return None
        return {"name": obj["name"], "arguments": args}


class HermesToolParser(ToolParser):
    """NousResearch Hermes: ``<tool_call>{json}</tool_call>`` blocks,
    any number, interleaved with plain content (reference:
    hermes_tool_parser.py)."""

    name = "hermes"
    _RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.DOTALL)

    def parse(self, text: str) -> tuple[str, _Calls]:
        calls = []
        for m in self._RE.finditer(text):
            call = self._normalize(self._loads(m.group(1)))
            if call:
                calls.append(call)
        if not calls:
            return text, None
        content = self._RE.sub("", text).strip()
        return content, calls

    @staticmethod
    def _loads(s):
        try:
            return json.loads(s)
        except (ValueError, TypeError):
            return None


class MistralToolParser(ToolParser):
    """Mistral: ``[TOOL_CALLS]`` token followed by a JSON array of
    calls (reference: mistral_tool_parser.py)."""

    name = "mistral"
    _MARK = "[TOOL_CALLS]"

    def parse(self, text: str) -> tuple[str, _Calls]:
        if self._MARK not in text:
            return text, None
        before, _, after = text.partition(self._MARK)
        try:
            arr = json.loads(after.strip())
        except (ValueError, TypeError):
            return text, None
        if isinstance(arr, dict):
            arr = [arr]
        calls = [c for c in (self._normalize(o) for o in arr) if c]
        if not calls:
            return text, None
        return before.strip(), calls


class Llama3JsonToolParser(ToolParser):
    """Llama-3.x JSON-style calls: the message is one (or several
    ``;``-separated) ``{"name": ..., "parameters": {...}}`` objects,
    optionally behind the ``<|python_tag|>`` marker (reference:
    llama_tool_parser.py)."""

    name = "llama3_json"
    _TAG = "<|python_tag|>"

    def parse(self, text: str) -> tuple[str, _Calls]:
        body = text
        if self._TAG in body:
            body = body.split(self._TAG, 1)[1]
        body = body.strip()
        if not body.startswith("{"):
            return text, None
        calls = []
        for part in body.split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                obj = json.loads(part)
            except (ValueError, TypeError):
                return text, None
            call = self._normalize(obj)
            if call is None:
                return text, None
            calls.append(call)
        return ("", calls) if calls else (text, None)


class PythonicToolParser(ToolParser):
    """Pythonic calls (Llama-4 / functionary style): a list of python
    call expressions ``[f(x=1), g(y="a")]`` (reference:
    pythonic_tool_parser.py). Arguments must be literals."""

    name = "pythonic"

    def parse(self, text: str) -> tuple[str, _Calls]:
        body = text.strip()
        if not (body.startswith("[") and body.endswith("]")):
            return text, None
        try:
            tree = ast.parse(body, mode="eval")
        except SyntaxError:
            return text, None
        if not isinstance(tree.body, ast.List):
            return text, None
        calls = []
        for node in tree.body.elts:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and not node.args):
                return text, None
            try:
                args = {kw.arg: ast.literal_eval(kw.value)
                        for kw in node.keywords if kw.arg}
            except (ValueError, SyntaxError):
                return text, None
            calls.append({"name": node.func.id, "arguments": args})
        return ("", calls) if calls else (text, None)


_PARSERS = {
    cls.name: cls
    for cls in (ToolParser, HermesToolParser, MistralToolParser,
                Llama3JsonToolParser, PythonicToolParser)
}


def get_tool_parser(name: Optional[str]) -> ToolParser:
    if not name:
        name = "json"
    try:
        return _PARSERS[name]()
    except KeyError:
        raise ValueError(
            f"unknown tool-call parser {name!r} "
            f"(available: {sorted(_PARSERS)})") from None
