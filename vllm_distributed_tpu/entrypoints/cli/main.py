"""``vdt`` command line (reference: vllm/entrypoints/cli/main.py:23 —
`vllm serve|bench|...`; invoked here as `python -m vllm_distributed_tpu`
or the `vdt` console script)."""

import argparse
import json
import sys
import time

from vllm_distributed_tpu.engine.arg_utils import EngineArgs


def _add_serve(sub) -> None:
    p = sub.add_parser("serve", help="start the OpenAI-compatible server")
    p.add_argument("model_pos", nargs="?", default=None,
                   help="model name or path (positional, like vllm serve)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--lora-modules", nargs="*", default=[],
                   metavar="NAME=PATH",
                   help="served LoRA adapters; request them via the "
                        "'model' field (requires --enable-lora)")
    EngineArgs.add_cli_args(p)


def _add_bench(sub) -> None:
    p = sub.add_parser("bench", help="offline latency/throughput benchmark")
    p.add_argument("mode", choices=["latency", "throughput"])
    p.add_argument("--input-len", type=int, default=128)
    p.add_argument("--output-len", type=int, default=128)
    p.add_argument("--num-prompts", type=int, default=8)
    p.add_argument("--warmup", type=int, default=1)
    EngineArgs.add_cli_args(p)


def cmd_serve(args) -> None:
    from vllm_distributed_tpu.entrypoints.openai.api_server import \
        run_server
    if args.model_pos:
        args.model = args.model_pos
    lora_modules = {}
    for item in args.lora_modules:
        name, _, path = item.partition("=")
        if not path:
            raise SystemExit(
                f"--lora-modules entries are NAME=PATH, got {item!r}")
        lora_modules[name] = path
    if lora_modules and not args.enable_lora:
        raise SystemExit("--lora-modules requires --enable-lora")
    engine_args = EngineArgs.from_cli_args(args)
    run_server(engine_args, host=args.host, port=args.port,
               lora_modules=lora_modules or None)


def cmd_bench(args) -> None:
    """reference: vllm/benchmarks/latency.py:36 / throughput.py via the
    `vllm bench` CLI (entrypoints/cli/benchmark/)."""
    import numpy as np

    from vllm_distributed_tpu.entrypoints.llm import LLM
    from vllm_distributed_tpu.sampling_params import SamplingParams

    engine_args = EngineArgs.from_cli_args(args)
    llm = LLM(**{f: getattr(engine_args, f)
                 for f in engine_args.__dataclass_fields__})
    vocab = llm.llm_engine.config.model_config.get_vocab_size()
    rng = np.random.default_rng(0)
    prompts = [[int(x) for x in rng.integers(10, vocab - 1,
                                             size=args.input_len)]
               for _ in range(args.num_prompts)]
    sp = SamplingParams(temperature=0.0, max_tokens=args.output_len,
                        ignore_eos=True)
    for _ in range(args.warmup):
        llm.generate(prompts, sp)
    start = time.perf_counter()
    outs = llm.generate(prompts, sp)
    elapsed = time.perf_counter() - start
    gen_tokens = sum(len(o.outputs[0].token_ids) for o in outs)
    result = {
        "mode": args.mode,
        "elapsed_s": round(elapsed, 3),
        "num_prompts": args.num_prompts,
        "input_len": args.input_len,
        "output_len": args.output_len,
        "generated_tokens": gen_tokens,
        "tokens_per_s": round(gen_tokens / elapsed, 2),
        "latency_per_token_ms": round(1000 * elapsed / max(gen_tokens, 1),
                                      3),
    }
    print(json.dumps(result))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vdt",
                                     description="vllm-distributed-tpu CLI")
    sub = parser.add_subparsers(dest="command", required=True)
    _add_serve(sub)
    _add_bench(sub)
    args = parser.parse_args(argv)
    if args.command == "serve":
        cmd_serve(args)
    elif args.command == "bench":
        cmd_bench(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
