"""``vdt`` command line (reference: vllm/entrypoints/cli/main.py:23 —
`vllm serve|bench|...`; invoked here as `python -m vllm_distributed_tpu`
or the `vdt` console script)."""

import argparse
import json
import sys
import time

from vllm_distributed_tpu.engine.arg_utils import EngineArgs


def _add_serve(sub) -> None:
    p = sub.add_parser("serve", help="start the OpenAI-compatible server")
    p.add_argument("model_pos", nargs="?", default=None,
                   help="model name or path (positional, like vllm serve)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--lora-modules", nargs="*", default=[],
                   metavar="NAME=PATH",
                   help="served LoRA adapters; request them via the "
                        "'model' field (requires --enable-lora)")
    p.add_argument("--tool-call-parser", default=None,
                   choices=["json", "hermes", "mistral", "llama3_json",
                            "pythonic"],
                   help="model-specific tool-call dialect for "
                        "tool_choice=auto (reference: "
                        "openai/tool_parsers/)")
    EngineArgs.add_cli_args(p)


def _add_bench(sub) -> None:
    p = sub.add_parser("bench", help="offline latency/throughput or "
                                     "online serving benchmark")
    p.add_argument("mode", choices=["latency", "throughput", "serve"])
    p.add_argument("--input-len", type=int, default=128)
    p.add_argument("--output-len", type=int, default=128)
    p.add_argument("--num-prompts", type=int, default=8)
    p.add_argument("--warmup", type=int, default=1)
    # serve mode (reference: benchmarks/benchmark_serving.py — fixed-QPS
    # Poisson arrivals against a RUNNING server, TTFT/ITL percentiles).
    p.add_argument("--url", default="http://localhost:8000/v1",
                   help="[serve] server base URL (with /v1)")
    p.add_argument("--request-rate", type=float, default=4.0,
                   help="[serve] Poisson arrival rate (QPS); 0 = all "
                        "at once")
    p.add_argument("--bench-seed", type=int, default=0)
    p.add_argument("--prompt-vocab", type=int, default=1000,
                   help="[serve] exclusive upper bound for random "
                        "prompt token ids (set to the model's vocab "
                        "for offline-comparable distributions)")
    EngineArgs.add_cli_args(p)


def cmd_serve(args) -> None:
    from vllm_distributed_tpu.entrypoints.openai.api_server import \
        run_server
    if args.model_pos:
        args.model = args.model_pos
    lora_modules = {}
    for item in args.lora_modules:
        name, _, path = item.partition("=")
        if not path:
            raise SystemExit(
                f"--lora-modules entries are NAME=PATH, got {item!r}")
        lora_modules[name] = path
    if lora_modules and not args.enable_lora:
        raise SystemExit("--lora-modules requires --enable-lora")
    engine_args = EngineArgs.from_cli_args(args)
    run_server(engine_args, host=args.host, port=args.port,
               lora_modules=lora_modules or None,
               tool_call_parser=args.tool_call_parser)


def cmd_bench(args) -> None:
    """reference: vllm/benchmarks/latency.py:36 / throughput.py via the
    `vllm bench` CLI (entrypoints/cli/benchmark/); serve mode =
    benchmark_serving.py (Poisson arrivals over HTTP)."""
    if args.mode == "serve":
        # Pure HTTP client: no engine imports (runs from any box).
        return cmd_bench_serve(args)
    import numpy as np

    from vllm_distributed_tpu.entrypoints.llm import LLM
    from vllm_distributed_tpu.sampling_params import SamplingParams

    engine_args = EngineArgs.from_cli_args(args)
    llm = LLM(**{f: getattr(engine_args, f)
                 for f in engine_args.__dataclass_fields__})
    vocab = llm.llm_engine.config.model_config.get_vocab_size()
    rng = np.random.default_rng(0)
    prompts = [[int(x) for x in rng.integers(10, vocab - 1,
                                             size=args.input_len)]
               for _ in range(args.num_prompts)]
    sp = SamplingParams(temperature=0.0, max_tokens=args.output_len,
                        ignore_eos=True)
    for _ in range(args.warmup):
        llm.generate(prompts, sp)
    start = time.perf_counter()
    outs = llm.generate(prompts, sp)
    elapsed = time.perf_counter() - start
    gen_tokens = sum(len(o.outputs[0].token_ids) for o in outs)
    result = {
        "mode": args.mode,
        "elapsed_s": round(elapsed, 3),
        "num_prompts": args.num_prompts,
        "input_len": args.input_len,
        "output_len": args.output_len,
        "generated_tokens": gen_tokens,
        "tokens_per_s": round(gen_tokens / elapsed, 2),
        "latency_per_token_ms": round(1000 * elapsed / max(gen_tokens, 1),
                                      3),
    }
    print(json.dumps(result))


def cmd_bench_serve(args) -> None:
    """Online serving benchmark against a RUNNING server: random-token
    prompts arrive on a Poisson clock at --request-rate QPS; per-request
    TTFT and inter-token latencies come from the streaming endpoint
    (reference: benchmarks/benchmark_serving.py — the random dataset +
    fixed-QPS mode of the nightly suite)."""
    import asyncio
    import numpy as np

    async def one(session, url, prompt_ids, out_len, rec):
        body = {"prompt": prompt_ids, "max_tokens": out_len,
                "temperature": 0.0, "ignore_eos": True, "stream": True}
        if getattr(args, "model", None):
            # OpenAI-compatible servers require it; also selects a
            # served LoRA adapter.
            body["model"] = args.model
        t0 = time.perf_counter()
        ticks = []
        try:
            async with session.post(url.rstrip("/") + "/completions",
                                    json=body) as resp:
                if resp.status != 200:
                    rec["errors"] += 1
                    return
                async for raw in resp.content:
                    line = raw.decode().strip()
                    if (not line.startswith("data: ")
                            or line == "data: [DONE]"):
                        continue
                    # Count only chunks carrying text (final
                    # finish_reason-only chunks and coalesced deltas
                    # would otherwise skew tokens/ITL).
                    try:
                        chunk = json.loads(line[len("data: "):])
                        text = chunk["choices"][0].get("text", "")
                    except Exception:  # noqa: BLE001
                        text = ""
                    if text:
                        ticks.append(time.perf_counter())
        except Exception:  # noqa: BLE001 - count, keep benchmarking
            rec["errors"] += 1
            return
        if not ticks:
            rec["errors"] += 1
            return
        rec["ttft"].append(ticks[0] - t0)
        rec["itl"].extend(b - a for a, b in zip(ticks, ticks[1:]))
        rec["e2e"].append(ticks[-1] - t0)
        rec["tokens"] += len(ticks)

    async def run():
        import aiohttp
        rng = np.random.default_rng(args.bench_seed)
        hi = max(args.prompt_vocab, 11)
        prompts = [[int(x) for x in rng.integers(10, hi,
                                                 size=args.input_len)]
                   for _ in range(args.num_prompts)]
        rec = {"ttft": [], "itl": [], "e2e": [], "tokens": 0,
               "errors": 0}
        t0 = time.perf_counter()
        # Generous timeout: the benchmark exists to MEASURE the slow
        # tail, not to drop it (the reference sets multi-hour limits).
        timeout = aiohttp.ClientTimeout(total=6 * 3600)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            tasks = []
            for p in prompts:
                tasks.append(asyncio.create_task(
                    one(session, args.url, p, args.output_len, rec)))
                if args.request_rate > 0:
                    await asyncio.sleep(
                        rng.exponential(1.0 / args.request_rate))
            await asyncio.gather(*tasks)
        rec["wall"] = time.perf_counter() - t0
        return rec

    rec = asyncio.run(run())

    def pct(xs, q):
        return round(float(np.percentile(xs, q)) * 1e3, 2) if xs else None

    print(json.dumps({
        "mode": "serve",
        "num_prompts": args.num_prompts,
        "request_rate_qps": args.request_rate,
        "completed": len(rec["e2e"]),
        "errors": rec["errors"],
        "output_tokens": rec["tokens"],
        "throughput_tok_s": round(rec["tokens"] / rec["wall"], 2),
        "ttft_ms": {"p50": pct(rec["ttft"], 50),
                    "p90": pct(rec["ttft"], 90),
                    "p99": pct(rec["ttft"], 99)},
        "itl_ms": {"p50": pct(rec["itl"], 50),
                   "p90": pct(rec["itl"], 90),
                   "p99": pct(rec["itl"], 99)},
        "e2e_ms": {"p50": pct(rec["e2e"], 50),
                   "p99": pct(rec["e2e"], 99)},
    }))


def _add_run_batch(sub) -> None:
    p = sub.add_parser(
        "run-batch",
        help="process an OpenAI batch-API JSONL file offline")
    p.add_argument("-i", "--input-file", required=True)
    p.add_argument("-o", "--output-file", required=True)
    EngineArgs.add_cli_args(p)


def cmd_run_batch(args) -> None:
    """OpenAI batch format: one request per line with
    {custom_id, method, url, body}; results mirror the batch output
    shape (reference: entrypoints/openai/run_batch.py)."""
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.entrypoints.openai import protocol
    from vllm_distributed_tpu.sampling_params import SamplingParams

    engine = LLMEngine(EngineArgs.from_cli_args(args).
                       create_engine_config())
    tokenizer = engine.processor.tokenizer

    requests = []
    with open(args.input_file) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                requests.append(json.loads(line))
            except json.JSONDecodeError as e:
                requests.append({"_parse_error": f"invalid JSON: {e}"})

    id_to_custom: dict[str, dict] = {}
    for i, req in enumerate(requests):
        body = req.get("body", {})
        url = req.get("url", "/v1/completions")
        rid = f"batch-{i}"
        if "_parse_error" in req:
            id_to_custom[rid] = {"req": req, "url": url,
                                 "error": req["_parse_error"]}
            continue
        # Any malformed line becomes an error RECORD; the rest of the
        # batch still runs (OpenAI batch semantics).
        try:
            params = protocol.sampling_params_from_request(
                body, default_max_tokens=64)
            if url.endswith("/chat/completions"):
                prompt = tokenizer.apply_chat_template(
                    body["messages"], tokenize=False,
                    add_generation_prompt=True)
            else:
                prompt = body["prompt"]
            id_to_custom[rid] = {"req": req, "url": url, "error": None}
            engine.add_request(rid, prompt, params)
        except Exception as e:  # noqa: BLE001 - per-line error record
            id_to_custom[rid] = {"req": req, "url": url,
                                 "error": f"{type(e).__name__}: {e}"}

    results: dict[str, dict] = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if getattr(out, "finished", False):
                results[out.request_id] = {
                    "text": out.outputs[0].text,
                    "token_ids": out.outputs[0].token_ids,
                    "finish_reason": out.outputs[0].finish_reason,
                    "prompt_tokens": len(out.prompt_token_ids),
                }

    with open(args.output_file, "w") as f:
        for rid, meta in id_to_custom.items():
            req = meta["req"]
            if meta["error"] is not None:
                record = {
                    "custom_id": req.get("custom_id"),
                    "response": None,
                    "error": {"message": meta["error"]},
                }
            else:
                r = results.get(rid, {})
                completion = len(r.get("token_ids", []))
                is_chat = meta["url"].endswith("/chat/completions")
                body = {
                    "id": (protocol.chat_id() if is_chat
                           else protocol.completion_id()),
                    "object": ("chat.completion" if is_chat
                               else "text_completion"),
                    "model": args.model,
                    "choices": [{
                        "index": 0,
                        "finish_reason": r.get("finish_reason"),
                        **({"message": {"role": "assistant",
                                        "content": r.get("text", "")}}
                           if is_chat else {"text": r.get("text", "")}),
                    }],
                    "usage": protocol.usage(r.get("prompt_tokens", 0),
                                            completion),
                }
                record = {
                    "custom_id": req.get("custom_id"),
                    "response": {"status_code": 200, "body": body},
                    "error": None,
                }
            f.write(json.dumps(record) + "\n")
    print(f"wrote {len(id_to_custom)} results to {args.output_file}")


def _add_openai_client(sub) -> None:
    """reference: vllm/entrypoints/cli/openai.py — `vllm chat` and
    `vllm complete` talk to a RUNNING server over HTTP."""
    for name, help_ in (("chat", "interactive chat against a running "
                                 "server (/v1/chat/completions)"),
                        ("complete", "one-shot completions against a "
                                     "running server (/v1/completions)")):
        p = sub.add_parser(name, help=help_)
        p.add_argument("--url", default="http://localhost:8000/v1",
                       help="server base URL (with /v1)")
        p.add_argument("--model-name", default=None,
                       help="model field for requests (default: first "
                            "model the server lists)")
        p.add_argument("--api-key", default=None)
        p.add_argument("-q", "--quick", default=None,
                       help="send one message/prompt, print the "
                            "response, exit")
        p.add_argument("--max-tokens", type=int, default=256)
        p.add_argument("--temperature", type=float, default=0.7)
        if name == "chat":
            p.add_argument("--system-prompt", default=None)


class _ClientError(Exception):
    """Server-side rejection, surfaced as a message (the REPL keeps its
    history and continues; --quick exits non-zero)."""


def _client_request(url, api_key, path, body=None):
    """POST (or GET when body is None) with server errors wrapped as
    _ClientError."""
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + path,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {api_key}"}
                    if api_key else {})})
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors="replace")
        try:
            detail = json.loads(detail)["error"]["message"]
        except Exception:  # noqa: BLE001 - non-JSON error body
            pass
        raise _ClientError(f"server returned {e.code}: {detail}") from e
    except urllib.error.URLError as e:
        raise _ClientError(f"cannot reach {url}: {e.reason}") from e


def _client_model(args) -> str:
    if args.model_name:
        return args.model_name
    models = _client_request(args.url, args.api_key, "/models")["data"]
    if not models:
        raise _ClientError("server lists no models")
    return models[0]["id"]


def cmd_chat(args) -> None:
    model = _client_model(args)
    messages = []
    if args.system_prompt:
        messages.append({"role": "system", "content": args.system_prompt})

    def turn(content: str) -> str:
        messages.append({"role": "user", "content": content})
        out = _client_request(args.url, args.api_key,
                              "/chat/completions", {
                                  "model": model,
                                  "messages": messages,
                                  "max_tokens": args.max_tokens,
                                  "temperature": args.temperature,
                              })
        reply = out["choices"][0]["message"]["content"]
        messages.append({"role": "assistant", "content": reply})
        return reply

    if args.quick is not None:
        print(turn(args.quick))
        return
    print(f"chatting with {model} (ctrl-d to exit)")
    while True:
        try:
            line = input("> ")
        except EOFError:
            print()
            return
        if not line.strip():
            continue
        try:
            print(turn(line))
        except _ClientError as e:
            # Keep the session (and its history) alive on a rejection.
            messages.pop()  # the user turn that failed
            print(f"error: {e}", file=sys.stderr)


def cmd_complete(args) -> None:
    model = _client_model(args)

    def complete(prompt: str) -> str:
        out = _client_request(args.url, args.api_key, "/completions", {
            "model": model,
            "prompt": prompt,
            "max_tokens": args.max_tokens,
            "temperature": args.temperature,
        })
        return out["choices"][0]["text"]

    if args.quick is not None:
        print(complete(args.quick))
        return
    print(f"completing with {model} (ctrl-d to exit)")
    while True:
        try:
            line = input("> ")
        except EOFError:
            print()
            return
        if not line.strip():
            continue
        try:
            print(complete(line))
        except _ClientError as e:
            print(f"error: {e}", file=sys.stderr)


def cmd_collect_env(_args) -> None:
    """Environment report (reference: vllm collect-env CLI)."""
    import platform

    import jax

    import vllm_distributed_tpu
    info = {
        "framework_version": vllm_distributed_tpu.__version__,
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "jax": jax.__version__,
        "default_backend": None,
        "devices": None,
    }
    try:
        info["default_backend"] = jax.default_backend()
        info["devices"] = [str(d) for d in jax.devices()]
    except Exception as e:  # noqa: BLE001 - report, don't crash
        info["devices_error"] = str(e)
    for mod in ("flax", "optax", "orbax.checkpoint", "transformers",
                "numpy", "zmq", "msgpack"):
        try:
            import importlib
            info[mod] = importlib.import_module(mod).__version__
        except Exception:  # noqa: BLE001
            info[mod] = None
    print(json.dumps(info, indent=2))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vdt",
                                     description="vllm-distributed-tpu CLI")
    sub = parser.add_subparsers(dest="command", required=True)
    _add_serve(sub)
    _add_bench(sub)
    _add_run_batch(sub)
    _add_openai_client(sub)
    sub.add_parser("collect-env", help="print environment/debug info")
    args = parser.parse_args(argv)
    if args.command == "serve":
        cmd_serve(args)
    elif args.command == "bench":
        cmd_bench(args)
    elif args.command == "run-batch":
        cmd_run_batch(args)
    elif args.command in ("chat", "complete"):
        try:
            (cmd_chat if args.command == "chat" else cmd_complete)(args)
        except _ClientError as e:
            raise SystemExit(f"error: {e}")
    elif args.command == "collect-env":
        cmd_collect_env(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
