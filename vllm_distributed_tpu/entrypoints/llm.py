"""Offline inference API (reference: vllm/entrypoints/llm.py:64 ``LLM`` —
generate/chat with an internal _run_engine loop at :1694)."""

from typing import Optional, Union

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import Counter

logger = init_logger(__name__)

PromptType = Union[str, list[int]]


def _listify_prompts(prompts):
    """A single prompt (str or token list) becomes a one-element list."""
    if isinstance(prompts, str) or (isinstance(prompts, list) and prompts
                                    and isinstance(prompts[0], int)):
        return [prompts]
    return list(prompts)


class LLM:

    def __init__(self, model: str, **kwargs) -> None:
        engine_args = EngineArgs(model=model, **kwargs)
        self.llm_engine = LLMEngine.from_engine_args(engine_args)
        self.request_counter = Counter()

    def get_tokenizer(self):
        return self.llm_engine.tokenizer

    def generate(
        self,
        prompts: Union[PromptType, list[PromptType]],
        sampling_params: Optional[Union[SamplingParams,
                                        list[SamplingParams]]] = None,
        multi_modal_data: Optional[Union[dict, list[Optional[dict]]]] = None,
    ) -> list[RequestOutput]:
        prompts = _listify_prompts(prompts)
        if sampling_params is None:
            sampling_params = SamplingParams()
        if isinstance(sampling_params, SamplingParams):
            sampling_params = [sampling_params] * len(prompts)
        assert len(sampling_params) == len(prompts)
        if multi_modal_data is None or isinstance(multi_modal_data, dict):
            multi_modal_data = [multi_modal_data] * len(prompts)
        assert len(multi_modal_data) == len(prompts)

        # Parallel sampling: n > 1 fans out into n engine requests per
        # prompt, merged into one RequestOutput with n CompletionOutputs
        # (reference: the ParentRequest fan-out of v1/engine/
        # parallel_sampling.py). Seeded requests vary the seed per child
        # so samples differ.
        import copy
        groups: list[list[str]] = []
        for prompt, sp, mm in zip(prompts, sampling_params,
                                  multi_modal_data):
            ids = []
            for s in range(sp.n):
                child = sp
                if sp.n > 1:
                    child = copy.copy(sp)
                    child.n = 1
                    if sp.seed is not None:
                        child.seed = sp.seed + s
                request_id = str(next(self.request_counter))
                self.llm_engine.add_request(request_id, prompt, child,
                                            multi_modal_data=mm)
                ids.append(request_id)
            groups.append(ids)
        outputs = self._run_engine()
        by_id = {out.request_id: out for out in outputs}
        merged: list[RequestOutput] = []
        for ids in groups:
            outs = [by_id[rid] for rid in ids]
            first = outs[0]
            if len(outs) > 1:
                completions = []
                for i, o in enumerate(outs):
                    comp = o.outputs[0]
                    comp.index = i
                    completions.append(comp)
                first.outputs = completions
            merged.append(first)
        return merged

    def encode(self, prompts, pooling_type: str = None,
               _extra_pooling: list = None) -> list:
        """Embedding API: pooled hidden state per prompt (reference:
        entrypoints/llm.py LLM.encode -> PoolingOutput). Decoder models
        pool the last position; encoder-only (BERT-family) models
        default to CLS, with "mean"/"last" selectable."""
        from vllm_distributed_tpu.sampling_params import SamplingParams
        prompts = _listify_prompts(prompts)
        request_ids = []
        for i, prompt in enumerate(prompts):
            pooling = dict(_extra_pooling[i]) if _extra_pooling else {}
            if pooling_type is not None:
                pooling["type"] = pooling_type
            request_id = str(next(self.request_counter))
            self.llm_engine.add_request(
                request_id, prompt,
                SamplingParams(temperature=0.0, max_tokens=1),
                pooling_params=pooling)
            request_ids.append(request_id)
        outputs = self._run_engine()
        by_id = {out.request_id: out for out in outputs}
        return [by_id[rid] for rid in request_ids]

    def chat(self, messages, sampling_params=None) -> list[RequestOutput]:
        tokenizer = self.get_tokenizer()
        assert tokenizer is not None, "chat requires a tokenizer"
        if messages and isinstance(messages[0], dict):
            messages = [messages]
        prompts = [
            tokenizer.apply_chat_template(conv, tokenize=False,
                                          add_generation_prompt=True)
            for conv in messages
        ]
        return self.generate(prompts, sampling_params)

    def beam_search(self, prompt, beam_width: int = 4,
                    max_tokens: int = 16) -> list[dict]:
        """Client-side beam search (reference: entrypoints/llm.py
        beam_search — V1 runs beams as ordinary engine requests ranked
        by cumulative logprob). Returns beams sorted best-first as
        {"token_ids", "cum_logprob"}."""
        import math

        from vllm_distributed_tpu.sampling_params import SamplingParams
        if isinstance(prompt, str):
            tokenizer = self.get_tokenizer()
            assert tokenizer is not None, "string prompts need a tokenizer"
            prompt = tokenizer.encode(prompt)
        beams = [{"token_ids": list(prompt), "cum_logprob": 0.0,
                  "finished": False}]
        eos = self.llm_engine.processor.eos_token_id

        # One metric everywhere: length-normalized cumulative logprob
        # (the reference's sort_beams_key with length_penalty=1).
        def score_key(b):
            return -b["cum_logprob"] / max(
                len(b["token_ids"]) - len(prompt), 1)

        for _ in range(max_tokens):
            live = [b for b in beams if not b["finished"]]
            if not live:
                break
            sp = SamplingParams(temperature=0.0, max_tokens=1,
                                ignore_eos=True, logprobs=beam_width)
            ids = []
            for b in live:
                rid = str(next(self.request_counter))
                self.llm_engine.add_request(rid, b["token_ids"], sp)
                ids.append(rid)
            outs = {o.request_id: o for o in self._run_engine()}
            candidates = [b for b in beams if b["finished"]]
            for b, rid in zip(live, ids):
                lps = outs[rid].outputs[0].logprobs[0]
                for tok, lp in sorted(lps.items(), key=lambda kv: -kv[1]
                                      )[:beam_width]:
                    candidates.append({
                        "token_ids": b["token_ids"] + [tok],
                        "cum_logprob": b["cum_logprob"] + lp,
                        "finished": tok == eos,
                    })
            candidates.sort(key=score_key)
            beams = candidates[:beam_width]
        beams.sort(key=score_key)
        return [{"token_ids": b["token_ids"][len(prompt):],
                 "cum_logprob": b["cum_logprob"]} for b in beams]

    def score(self, queries, documents) -> list[float]:
        """Relevance scoring (reference: LLM.score / serving_score.py).

        Cross-encoder checkpoints (e.g. BertForSequenceClassification)
        run each (query, document) pair through the classification
        head; embedding models fall back to cosine similarity over the
        encode path — matching the reference's two scoring modes."""
        import math
        queries = _listify_prompts(queries)
        documents = _listify_prompts(documents)
        # Broadcast a single side against the other (reference
        # LLM.score semantics).
        if len(queries) == 1 and len(documents) > 1:
            queries = queries * len(documents)
        elif len(documents) == 1 and len(queries) > 1:
            documents = documents * len(queries)
        if len(queries) != len(documents):
            raise ValueError(
                f"score needs matching (or broadcastable) counts; got "
                f"{len(queries)} queries x {len(documents)} documents")
        if self._is_cross_encoder():
            return self._score_cross_encoder(queries, documents)
        # Encode each distinct prompt once (a single query against N
        # documents costs 1 + N forwards, not 2N).
        def key(p):
            return p if isinstance(p, str) else tuple(p)

        unique: dict = {}
        for p in list(queries) + list(documents):
            unique.setdefault(key(p), p)
        embs = self.encode(list(unique.values()))
        by_key = {k: e.embedding
                  for k, e in zip(unique.keys(), embs)}

        def cos(a, b):
            dot = sum(x * y for x, y in zip(a, b))
            na = math.sqrt(sum(x * x for x in a))
            nb = math.sqrt(sum(x * x for x in b))
            return dot / (na * nb + 1e-12)

        return [cos(by_key[key(q)], by_key[key(d)])
                for q, d in zip(queries, documents)]

    def _is_cross_encoder(self) -> bool:
        # The processor resolved this at engine construction.
        return self.llm_engine.processor.is_cross_encoder

    def _score_cross_encoder(self, queries, documents) -> list[float]:
        """Each pair runs as ONE encoder forward: [CLS] q [SEP] d [SEP]
        with token_type 1 on the document segment, scored by the
        checkpoint's classification head."""
        from vllm_distributed_tpu.entrypoints.score_utils import (
            build_score_pair)
        tokenizer = self.get_tokenizer()
        pairs, poolings = [], []
        for q, d in zip(queries, documents):
            ids, pooling = build_score_pair(tokenizer, q, d)
            pairs.append(ids)
            poolings.append(pooling)
        outs = self.encode(pairs, _extra_pooling=poolings)
        return [float(o.embedding[0]) for o in outs]

    def _run_engine(self) -> list[RequestOutput]:
        finished: list[RequestOutput] = []
        while self.llm_engine.has_unfinished_requests():
            for out in self.llm_engine.step():
                if out.finished:
                    finished.append(out)
        return finished

    def shutdown(self) -> None:
        self.llm_engine.shutdown()
