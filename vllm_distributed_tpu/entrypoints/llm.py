"""Offline inference API (reference: vllm/entrypoints/llm.py:64 ``LLM`` —
generate/chat with an internal _run_engine loop at :1694)."""

from typing import Optional, Union

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import Counter

logger = init_logger(__name__)

PromptType = Union[str, list[int]]


class LLM:

    def __init__(self, model: str, **kwargs) -> None:
        engine_args = EngineArgs(model=model, **kwargs)
        self.llm_engine = LLMEngine.from_engine_args(engine_args)
        self.request_counter = Counter()

    def get_tokenizer(self):
        return self.llm_engine.tokenizer

    def generate(
        self,
        prompts: Union[PromptType, list[PromptType]],
        sampling_params: Optional[Union[SamplingParams,
                                        list[SamplingParams]]] = None,
    ) -> list[RequestOutput]:
        if isinstance(prompts, (str, )) or (isinstance(prompts, list)
                                            and prompts
                                            and isinstance(prompts[0], int)):
            prompts = [prompts]  # single prompt (str or token ids)
        if sampling_params is None:
            sampling_params = SamplingParams()
        if isinstance(sampling_params, SamplingParams):
            sampling_params = [sampling_params] * len(prompts)
        assert len(sampling_params) == len(prompts)

        request_ids = []
        for prompt, sp in zip(prompts, sampling_params):
            request_id = str(next(self.request_counter))
            self.llm_engine.add_request(request_id, prompt, sp)
            request_ids.append(request_id)
        outputs = self._run_engine()
        # Return in submission order.
        by_id = {out.request_id: out for out in outputs}
        return [by_id[rid] for rid in request_ids]

    def encode(self, prompts) -> list:
        """Embedding API: pooled last-position hidden state per prompt
        (reference: entrypoints/llm.py LLM.encode -> PoolingOutput)."""
        from vllm_distributed_tpu.sampling_params import SamplingParams
        if isinstance(prompts, (str, )) or (isinstance(prompts, list)
                                            and prompts
                                            and isinstance(prompts[0], int)):
            prompts = [prompts]
        request_ids = []
        for prompt in prompts:
            request_id = str(next(self.request_counter))
            self.llm_engine.add_request(
                request_id, prompt,
                SamplingParams(temperature=0.0, max_tokens=1),
                pooling_params={"type": "last"})
            request_ids.append(request_id)
        outputs = self._run_engine()
        by_id = {out.request_id: out for out in outputs}
        return [by_id[rid] for rid in request_ids]

    def chat(self, messages, sampling_params=None) -> list[RequestOutput]:
        tokenizer = self.get_tokenizer()
        assert tokenizer is not None, "chat requires a tokenizer"
        if messages and isinstance(messages[0], dict):
            messages = [messages]
        prompts = [
            tokenizer.apply_chat_template(conv, tokenize=False,
                                          add_generation_prompt=True)
            for conv in messages
        ]
        return self.generate(prompts, sampling_params)

    def _run_engine(self) -> list[RequestOutput]:
        finished: list[RequestOutput] = []
        while self.llm_engine.has_unfinished_requests():
            for out in self.llm_engine.step():
                if out.finished:
                    finished.append(out)
        return finished

    def shutdown(self) -> None:
        self.llm_engine.shutdown()
