"""Shared cross-encoder pair construction for LLM.score and the
/v1/score + /v1/rerank endpoints (reference: the prompt assembly of
serving_score.py): one sequence per (query, document) pair with
token_type 1 on the document segment, scored by the checkpoint's
classification head via "score" pooling."""


def build_score_pair(tokenizer, query, document):
    """Returns (token_ids, pooling_params) for one pair. String inputs
    use the tokenizer's own pair encoding ([CLS] q [SEP] d [SEP] with
    its token_type_ids); token-list inputs are concatenated with
    type 1 on the document."""
    if isinstance(query, str) or isinstance(document, str):
        if tokenizer is None:
            raise ValueError("string inputs to score require a tokenizer")
        enc = tokenizer(query, document)
        ids = enc["input_ids"]
        tt = enc.get("token_type_ids") or [0] * len(ids)
    else:
        ids = list(query) + list(document)
        tt = [0] * len(query) + [1] * len(document)
    return ids, {"type": "score", "token_type_ids": list(tt)}
