"""vllm_distributed_tpu: a TPU-native distributed LLM inference framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of
susavlsh10/vllm-distributed (a vLLM fork): continuous-batching engine with a
paged KV cache, prefix caching, chunked prefill, tensor/pipeline/data/expert
parallelism plus token-parallel decode attention, disaggregated prefill via a
KV-transfer connector, and an OpenAI-compatible server.

The control plane follows the reference's V1 architecture
(/root/reference/vllm/v1/); the data plane is TPU-first: models are sharded
with jit + NamedSharding over a jax.sharding.Mesh, attention and KV-cache
update are Pallas kernels, and collectives ride ICI via XLA.
"""

from vllm_distributed_tpu.version import __version__

__all__ = [
    "__version__",
    "LLM",
    "AsyncLLM",
    "SamplingParams",
    "EngineArgs",
]


def __getattr__(name: str):
    # Lazy imports keep `import vllm_distributed_tpu` light (no jax import).
    if name == "LLM":
        from vllm_distributed_tpu.entrypoints.llm import LLM
        return LLM
    if name == "AsyncLLM":
        from vllm_distributed_tpu.engine.async_llm import AsyncLLM
        return AsyncLLM
    if name == "SamplingParams":
        from vllm_distributed_tpu.sampling_params import SamplingParams
        return SamplingParams
    if name == "EngineArgs":
        from vllm_distributed_tpu.engine.arg_utils import EngineArgs
        return EngineArgs
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
