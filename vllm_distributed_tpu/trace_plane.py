"""Distributed trace plane: fleet-wide causal request tracing.

PR 4's EventRecorder rings are per-replica islands: the router decision
lives in the front-end ring, the scheduler lifecycle in each core's
ring, and a disagg request's prefill and decode halves in two DIFFERENT
replicas' rings — nobody can answer "where did this request's 2 s go"
across a KV handoff or a mid-drain migration. This module is the
stitching layer on top of those rings:

* ``mint_trace_ctx`` — a ``{"trace_id", "span_id"}`` context minted at
  admission and carried on ``EngineCoreRequest`` over the msgpack wire
  (``serial.py``, old-wire tolerant). The disagg handoff re-admits the
  ORIGINAL request and crash-recovery replays deep-copy it, so every
  hop stamps the SAME trace id — that is the causal link; no new RPC
  exists anywhere in the plane.
* ``TraceAssembler`` — a bounded rolling flight recorder the front-end
  feeds with (a) its own lifecycle events and (b) the core rings
  drained over the existing get_stats feed, already replica-tagged and
  clock-rebased by the DP aggregator. Buckets events by trace id
  (falling back to the request-id map for front-end events recorded
  before the stamp existed).
* ``perfetto`` — one stitched trace rendered as Chrome/Perfetto
  trace-event JSON (``GET /debug/trace?request_id=``): pid = replica,
  tid = component, phase intervals as complete ("X") slices, lifecycle
  transitions as instants, and an explicit flow arrow (``s``/``f``)
  from the producer's ``disagg_handoff`` span to the consumer's
  ``kv_pull`` span.

Everything here is OFF-path: with ``VDT_TRACE_PLANE=0`` no context is
minted, no event is stamped, and no assembler is constructed — the
wire bytes and event details are byte-identical to the pre-trace-plane
behavior.
"""

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Optional

from vllm_distributed_tpu.metrics import events as ev

# Component lane (Perfetto tid) per event name: which subsystem emitted
# the event. Unknown (future) events land in "events".
_COMPONENT_BY_EVENT = {
    ev.ARRIVED: "frontend",
    ev.FIRST_TOKEN: "frontend",
    ev.FINISHED: "frontend",
    ev.ABORTED: "frontend",
    ev.SHED: "frontend",
    ev.ENGINE_DEATH: "frontend",
    ev.JOURNAL_REPLAY: "frontend",
    ev.ROUTER_PICK: "router",
    ev.DISAGG_HANDOFF: "disagg",
    ev.QUEUED: "scheduler",
    ev.SCHEDULED: "scheduler",
    ev.PREFILL_CHUNK: "scheduler",
    ev.PREEMPTED: "scheduler",
    ev.RESUMED: "scheduler",
    ev.SPEC_GRANT: "scheduler",
    ev.BATCH_DISPATCH: "engine",
    ev.BATCH_RETIRE: "engine",
    ev.KV_PULL_WAIT: "kv_transfer",
    ev.KV_PULL_DONE: "kv_transfer",
    ev.KV_PULL_RETRY: "kv_transfer",
    ev.KV_PULL_TIMEOUT: "kv_transfer",
    ev.KV_PULL_LOCAL: "kv_transfer",
    ev.KV_TIER_PROMOTE: "kv_tier",
    ev.KV_TIER_DEMOTE: "kv_tier",
}
for _name in (ev.FLEET_SCALE_OUT, ev.FLEET_SCALE_IN, ev.FLEET_RESPLIT,
              ev.FLEET_WEDGE_CYCLE, ev.FLEET_FREEZE,
              ev.FLEET_LEADER_TAKEOVER, ev.FLEET_FENCED,
              ev.FLEET_JOURNAL_REPLAY, ev.FLEET_CONTROLLER_DOWN):
    _COMPONENT_BY_EVENT[_name] = "fleet"


def component_of(event: str) -> str:
    return _COMPONENT_BY_EVENT.get(event, "events")


def mint_trace_ctx(request_id: str) -> dict[str, str]:
    """Trace context minted once at admission. Deterministic from the
    request id on purpose: a journal replay or failover re-admission of
    the same logical request re-mints the SAME trace id even if the
    carried context were ever lost, so forensic stitching survives the
    exact failure modes it exists to explain. (Request ids are already
    unique per logical request — uuid4 at the entrypoints.)"""
    digest = hashlib.sha256(request_id.encode()).hexdigest()
    return {"trace_id": digest[:16], "span_id": digest[16:24]}


class TraceAssembler:
    """Bounded rolling flight recorder of stitched traces.

    Buckets incoming events by trace id: the stamped ``tr`` detail key
    wins; events without a stamp fall back to the request-id -> trace
    map registered at admission (covers rid="" fleet events only via
    explicit window queries at export time). Oldest-admitted traces
    evict past ``max_traces``; a trace keeps its EARLIEST ``max_spans``
    events (the causal root matters most) and counts the rest.
    """

    def __init__(self, max_traces: Optional[int] = None,
                 max_spans: Optional[int] = None) -> None:
        from vllm_distributed_tpu import envs
        self.max_traces = (envs.VDT_TRACE_MAX_TRACES
                           if max_traces is None else max_traces)
        self.max_spans = (envs.VDT_TRACE_MAX_SPANS
                          if max_spans is None else max_spans)
        self._lock = threading.Lock()
        # trace_id -> {"trace_id", "request_ids": set, "events": list of
        # (ts, rid, event, detail, replica), "num_dropped": int}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._by_request: dict[str, str] = {}
        # rid="" events (fleet actuations, batch markers) kept in a
        # small side ring so exports can fold in the ones overlapping
        # the trace's time window.
        self._anon: list[tuple] = []
        self._anon_max = 512

    # ------------------------------------------------------------------
    def note_admission(self, request_id: str, trace_ctx: dict) -> None:
        """Register rid -> trace at admission (front-end)."""
        tid = (trace_ctx or {}).get("trace_id")
        if not tid:
            return
        with self._lock:
            bucket = self._traces.get(tid)
            if bucket is None:
                bucket = {"trace_id": tid, "request_ids": set(),
                          "events": [], "num_dropped": 0}
                self._traces[tid] = bucket
                while len(self._traces) > self.max_traces:
                    _, evicted = self._traces.popitem(last=False)
                    for rid in evicted["request_ids"]:
                        self._by_request.pop(rid, None)
            bucket["request_ids"].add(request_id)
            self._by_request[request_id] = tid

    def add_event(self, ts: float, rid: str, event: str,
                  detail: Optional[dict],
                  replica: Optional[int] = None) -> None:
        tid = None
        if isinstance(detail, dict):
            tid = detail.get(ev.TRACE_KEY)
            if replica is None:
                replica = detail.get(ev.REPLICA_KEY)
        with self._lock:
            if tid is None:
                tid = self._by_request.get(rid) if rid else None
            if tid is None:
                self._anon.append((ts, rid, event, detail, replica))
                if len(self._anon) > self._anon_max:
                    del self._anon[:len(self._anon) - self._anon_max]
                return
            bucket = self._traces.get(tid)
            if bucket is None:
                # Stamped event for a trace the flight recorder already
                # evicted (or a foreign front-end admitted): recreate a
                # bucket so cross-replica stitching still works.
                bucket = {"trace_id": tid, "request_ids": set(),
                          "events": [], "num_dropped": 0}
                self._traces[tid] = bucket
                while len(self._traces) > self.max_traces:
                    _, evicted = self._traces.popitem(last=False)
                    for r in evicted["request_ids"]:
                        self._by_request.pop(r, None)
            if rid:
                bucket["request_ids"].add(rid)
                self._by_request.setdefault(rid, tid)
            if len(bucket["events"]) >= self.max_spans:
                bucket["num_dropped"] += 1
                return
            bucket["events"].append((ts, rid, event, detail, replica))

    def feed(self, wire_events: Optional[list],
             replica: Optional[int] = None) -> None:
        """Absorb wire-shape ``[ts, rid, event, detail]`` lists (the
        drained core rings the DP aggregator already replica-tagged)."""
        if not wire_events:
            return
        for e in wire_events:
            try:
                ts, rid, event, detail = e[0], e[1], e[2], e[3]
            except (IndexError, TypeError):
                continue
            self.add_event(ts, rid, event, detail, replica=replica)

    # ------------------------------------------------------------------
    def get(self, request_id: Optional[str] = None,
            trace_id: Optional[str] = None) -> Optional[dict]:
        """One stitched trace (events in arrival order, epoch-rebased),
        or None. rid="" side-ring events overlapping the trace's time
        window fold in so fleet actuations that reshaped the fleet
        under the request are visible on their own lane."""
        with self._lock:
            if trace_id is None and request_id is not None:
                trace_id = self._by_request.get(request_id)
            if trace_id is None:
                return None
            bucket = self._traces.get(trace_id)
            if bucket is None:
                return None
            events = list(bucket["events"])
            if events:
                lo = min(e[0] for e in events)
                hi = max(e[0] for e in events)
                events += [e for e in self._anon if lo <= e[0] <= hi]
            return {"trace_id": trace_id,
                    "request_ids": sorted(bucket["request_ids"]),
                    "events": ev.rebase_epochs(events),
                    "num_dropped": bucket["num_dropped"]}

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces.keys())

    def replica_count(self, trace: dict) -> int:
        """Distinct replicas contributing spans to a stitched trace
        (bench: a disagg handoff must yield >= 2)."""
        return len({e[4] if e[4] is not None else -1
                    for e in trace["events"]})


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace-event export
# ---------------------------------------------------------------------------

def _flow_id(trace_id: str) -> int:
    return int(trace_id[:12], 16)


def perfetto(trace: dict) -> dict:
    """Render one stitched trace as Chrome/Perfetto trace-event JSON
    (the ``{"traceEvents": [...]}`` object form): pid = replica index
    (-1 = front-end / untagged), tid = component, per-replica phase
    intervals as complete ("X") slices, lifecycle transitions as
    instants ("i"), and a flow arrow ("s" -> "f") from each producer
    ``disagg_handoff`` to the consumer's next ``kv_pull`` event."""
    events = sorted(trace["events"], key=lambda e: e[0])
    out: list[dict] = []
    base = events[0][0] if events else 0.0
    replicas = sorted({e[4] if e[4] is not None else -1 for e in events})
    for rep in replicas:
        label = "frontend" if rep == -1 else f"replica {rep}"
        out.append({"name": "process_name", "ph": "M", "pid": rep,
                    "tid": 0, "args": {"name": label}})

    def us(ts: float) -> float:
        return round((ts - base) * 1e6, 3)

    flow = _flow_id(trace["trace_id"])
    flow_open = False
    for ts, rid, event, detail, replica in events:
        pid = replica if replica is not None else -1
        tid = component_of(event)
        args: dict[str, Any] = {"request_id": rid}
        if isinstance(detail, dict):
            args.update({k: v for k, v in detail.items()
                         if k not in (ev.TRACE_KEY, ev.REPLICA_KEY)})
        out.append({"name": event, "cat": tid, "ph": "i", "s": "p",
                    "ts": us(ts), "pid": pid, "tid": tid, "args": args})
        if event == ev.DISAGG_HANDOFF:
            out.append({"name": "kv_handoff", "cat": "flow", "ph": "s",
                        "id": flow, "ts": us(ts), "pid": pid,
                        "tid": tid})
            flow_open = True
        elif flow_open and event in (ev.KV_PULL_WAIT, ev.KV_PULL_DONE,
                                     ev.KV_PULL_LOCAL):
            out.append({"name": "kv_handoff", "cat": "flow", "ph": "f",
                        "bp": "e", "id": flow, "ts": us(ts), "pid": pid,
                        "tid": tid})
            flow_open = False

    # Phase slices per replica: each replica's view of the lifecycle
    # rendered as complete events on a "phases" lane.
    for rep in replicas:
        timeline = [(ts, event, detail)
                    for ts, _rid, event, detail, replica in events
                    if (replica if replica is not None else -1) == rep]
        now = max(e[0] for e in events) if events else None
        for p in ev.phases_from_timeline(timeline, now=now):
            dur = max(0.0, p["end"] - p["start"]) * 1e6
            out.append({"name": p["phase"], "cat": "phase", "ph": "X",
                        "ts": us(p["start"]), "dur": round(dur, 3),
                        "pid": rep, "tid": "phases"})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace["trace_id"],
                          "request_ids": trace["request_ids"],
                          "num_dropped": trace["num_dropped"]}}
