"""Non-recursive EBNF/Lark grammar -> regex, for guided_grammar.

Reference surface: the ``guided_grammar`` option of GuidedDecodingParams
(the reference delegates to xgrammar/outlines, which accept Lark-style
EBNF). This slice compiles the NON-RECURSIVE subset onto the engine's
own regex->DFA machinery (structured_output/fsm.py): every rule is
inlined into its references, so any recursive rule (directly or through
a cycle) is rejected honestly rather than approximated — matching this
codebase's fail-fast convention for unsupported config space.

Accepted syntax per rule line ``name : alternatives``:
  "literal" / 'literal'     terminal strings (escaped into the regex)
  /regex/                   inline regex terminal (passed through)
  rule_name                 reference (inlined; must be non-recursive)
  ( ... )                   grouping
  [ ... ]                   optional group
  x? x* x+                  the usual repetitions
  a | b                     alternatives
Comments (// ... or # ...) and blank lines are ignored. The start rule
is ``start`` when present, else the first rule.
"""

from __future__ import annotations

import re as _re


class GrammarError(ValueError):
    pass


# Hard bound on the total inlined-regex size (DoS guard: rule inlining
# is exponential in chained references).
_MAX_EXPANSION = 512 * 1024


_RULE_RE = _re.compile(r"^\s*([a-zA-Z_][\w]*)\s*:\s*(.+)$")
_TOKEN_RE = _re.compile(
    r"\s*(\"(?:\\.|[^\"\\])*\""      # "literal"
    r"|'(?:\\.|[^'\\])*'"            # 'literal'
    r"|/(?:\\.|[^/\\])+/"            # /regex/
    r"|[a-zA-Z_][\w]*"               # rule ref
    r"|[()\[\]|?*+])")


def _tokenize(body: str) -> list[str]:
    out, i = [], 0
    while i < len(body):
        if body[i].isspace():
            i += 1
            continue
        m = _TOKEN_RE.match(body, i)
        if not m:
            raise GrammarError(f"bad grammar syntax at {body[i:]!r}")
        out.append(m.group(1))
        i = m.end()
    return out


class _Parser:
    """Recursive-descent over one rule body -> regex fragment (rule
    references resolved through ``resolve``)."""

    def __init__(self, tokens: list[str], resolve) -> None:
        self.toks = tokens
        self.i = 0
        self.resolve = resolve

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def alternatives(self) -> str:
        parts = [self.sequence()]
        while self.peek() == "|":
            self.next()
            parts.append(self.sequence())
        if len(parts) == 1:
            return parts[0]
        return "(" + "|".join(parts) + ")"

    def sequence(self) -> str:
        out = []
        while self.peek() is not None and self.peek() not in ("|", ")",
                                                              "]"):
            out.append(self.atom())
        return "".join(out)

    def atom(self) -> str:
        t = self.next()
        if t == "(":
            inner = self.alternatives()
            if self.next() != ")":
                raise GrammarError("unbalanced '('")
            frag = "(" + inner + ")"
        elif t == "[":
            inner = self.alternatives()
            if self.next() != "]":
                raise GrammarError("unbalanced '['")
            frag = "(" + inner + ")?"
        elif t[0] in "\"'":
            lit = _unescape(t[1:-1])
            frag = _re.escape(lit)
        elif t[0] == "/":
            frag = "(" + t[1:-1] + ")"
        elif _RULE_RE.match(t + " : x"):
            frag = self.resolve(t)
        else:
            raise GrammarError(f"unexpected token {t!r}")
        while self.peek() in ("?", "*", "+"):
            frag = "(" + frag + ")" + self.next()
        return frag


def _strip_comment(line: str) -> str:
    """Drop a trailing // comment, skipping quoted strings and /regex/
    terminals (so "http://x" literals survive)."""
    i, n = 0, len(line)
    while i < n:
        ch = line[i]
        if ch == "#":
            return line[:i]
        if ch in "\"'/":
            if ch == "/" and i + 1 < n and line[i + 1] == "/":
                return line[:i]
            close = ch
            i += 1
            while i < n and line[i] != close:
                i += 2 if line[i] == "\\" else 1
            i += 1
        else:
            i += 1
    return line


def _unescape(s: str) -> str:
    # Char-by-char so "\\n" (escaped backslash + n) never turns into a
    # newline.
    out, i = [], 0
    table = {"n": "\n", "t": "\t", '"': '"', "'": "'", "\\": "\\"}
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(table.get(s[i + 1], s[i + 1]))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def ebnf_to_regex(grammar: str) -> str:
    """Compile a non-recursive EBNF grammar to one regex."""
    rules: dict[str, str] = {}
    order: list[str] = []
    for raw in grammar.splitlines():
        line = _strip_comment(raw)
        if line.lstrip().startswith("#"):
            continue
        if not line.strip():
            continue
        m = _RULE_RE.match(line)
        if not m:
            raise GrammarError(f"expected 'name : body', got {raw!r}")
        name, body = m.group(1), m.group(2)
        if name in rules:
            raise GrammarError(f"duplicate rule {name!r}")
        rules[name] = body
        order.append(name)
    if not rules:
        raise GrammarError("empty grammar")

    compiled: dict[str, str] = {}
    in_progress: set[str] = set()

    def resolve(name: str) -> str:
        if name in compiled:
            return compiled[name]
        if name not in rules:
            raise GrammarError(f"undefined rule {name!r}")
        if name in in_progress:
            raise GrammarError(
                f"rule {name!r} is recursive; only non-recursive "
                f"grammars compile onto the regex DFA (use a regex or "
                f"json schema spec for unbounded nesting)")
        in_progress.add(name)
        parser = _Parser(_tokenize(rules[name]), resolve)
        frag = parser.alternatives()
        if parser.peek() is not None:
            raise GrammarError(
                f"trailing tokens in rule {name!r}: "
                f"{parser.toks[parser.i:]}")
        in_progress.discard(name)
        compiled[name] = "(" + frag + ")"
        total = sum(len(v) for v in compiled.values())
        if total > _MAX_EXPANSION:
            # Inlining is exponential for chained doubling rules; cap
            # before a 1 KB grammar can balloon into a GB-scale regex
            # (admission-time DoS through guided_grammar).
            raise GrammarError(
                f"grammar expansion exceeds {_MAX_EXPANSION} regex "
                f"chars; restructure (or use a regex spec)")
        return compiled[name]

    start = "start" if "start" in rules else order[0]
    return resolve(start)
