"""Byte-level regex -> DFA engine for structured output.

Reference: vllm/v1/structured_output/ compiles grammars (xgrammar /
guidance / outlines backends) into per-step token bitmasks applied to the
logits (gpu_model_runner.py:1433). The TPU design keeps that split: this
module is the grammar half — a self-contained regex compiler (no
third-party grammar libs in the image) producing a byte-alphabet DFA,
plus a token-mask table that turns DFA states into vocabulary bitmasks.

Supported regex subset (enough for the JSON-schema compiler in
json_schema.py and typical guided_regex use): literals, ``.``, escapes
(``\\d \\w \\s \\n \\t \\r`` and escaped punctuation), character classes
``[...]``/``[^...]`` with ranges, groups ``(...)``, alternation ``|``,
quantifiers ``* + ? {m} {m,} {m,n}``, anchors are implicit (the whole
output must match).

The DFA is a dense ``[S, 256] -> S`` byte-transition table (state 0 =
dead). Token masks are computed lazily per visited state by vectorised
numpy walks of every vocab token's bytes — visited states during one
generation are few, so the S x V precompute cost is never paid up front.
"""

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# ---------------------------------------------------------------------------
# Regex parsing -> NFA (Thompson construction)
# ---------------------------------------------------------------------------

_DIGITS = frozenset(range(ord("0"), ord("9") + 1))
_WORD = (frozenset(range(ord("a"), ord("z") + 1))
         | frozenset(range(ord("A"), ord("Z") + 1)) | _DIGITS
         | {ord("_")})
_SPACE = frozenset(map(ord, " \t\n\r\f\v"))
_ALL = frozenset(range(256))


class _Parser:
    """Recursive-descent regex parser producing an NFA fragment list.

    NFA representation: states are ints; transitions are
    (state, byteset | None, next) — None byteset = epsilon.
    """

    def __init__(self, pattern: str) -> None:
        self.src = pattern
        self.pos = 0
        self.transitions: list[tuple[int, Optional[frozenset], int]] = []
        self.num_states = 0

    def new_state(self) -> int:
        self.num_states += 1
        return self.num_states - 1

    def edge(self, a: int, byteset: Optional[frozenset], b: int) -> None:
        self.transitions.append((a, byteset, b))

    # -- tokenizer helpers ------------------------------------------------
    def peek(self) -> Optional[str]:
        return self.src[self.pos] if self.pos < len(self.src) else None

    def take(self) -> str:
        ch = self.src[self.pos]
        self.pos += 1
        return ch

    # -- grammar ----------------------------------------------------------
    def parse(self) -> tuple[int, int]:
        start, end = self.alternation()
        if self.pos != len(self.src):
            raise ValueError(
                f"unexpected {self.src[self.pos]!r} at {self.pos} in "
                f"{self.src!r}")
        return start, end

    def alternation(self) -> tuple[int, int]:
        frags = [self.concat()]
        while self.peek() == "|":
            self.take()
            frags.append(self.concat())
        if len(frags) == 1:
            return frags[0]
        s, e = self.new_state(), self.new_state()
        for fs, fe in frags:
            self.edge(s, None, fs)
            self.edge(fe, None, e)
        return s, e

    def concat(self) -> tuple[int, int]:
        frags = []
        while self.peek() not in (None, "|", ")"):
            frags.append(self.repeat())
        if not frags:
            s = self.new_state()
            return s, s
        for (_, e1), (s2, _) in zip(frags, frags[1:]):
            self.edge(e1, None, s2)
        return frags[0][0], frags[-1][1]

    def repeat(self) -> tuple[int, int]:
        frag = self.atom()
        while self.peek() in ("*", "+", "?", "{"):
            ch = self.peek()
            if ch == "{":
                save = self.pos
                bounds = self._parse_bounds()
                if bounds is None:
                    self.pos = save
                    break
                frag = self._repeat_bounds(frag, *bounds)
            else:
                self.take()
                if ch == "*":
                    frag = self._star(frag)
                elif ch == "+":
                    frag = self._plus(frag)
                else:
                    frag = self._opt(frag)
        return frag

    def _parse_bounds(self) -> Optional[tuple[int, Optional[int]]]:
        # at self.src[self.pos] == "{"; returns (m, n|None) or None if not
        # a quantifier (treat "{" as a literal then).
        import re as _re
        m = _re.match(r"\{(\d+)(,(\d*))?\}", self.src[self.pos:])
        if not m:
            return None
        self.pos += m.end()
        lo = int(m.group(1))
        if m.group(2) is None:
            return lo, lo
        hi = int(m.group(3)) if m.group(3) else None
        return lo, hi

    # -- fragment combinators --------------------------------------------
    def _star(self, frag):
        s, e = self.new_state(), self.new_state()
        fs, fe = frag
        self.edge(s, None, fs)
        self.edge(s, None, e)
        self.edge(fe, None, fs)
        self.edge(fe, None, e)
        return s, e

    def _plus(self, frag):
        fs, fe = frag
        e = self.new_state()
        self.edge(fe, None, e)
        self.edge(e, None, fs)
        return fs, e

    def _opt(self, frag):
        s, e = self.new_state(), self.new_state()
        fs, fe = frag
        self.edge(s, None, fs)
        self.edge(s, None, e)
        self.edge(fe, None, e)
        return s, e

    def _clone(self, frag):
        """Deep-copy a fragment's states/transitions (for {m,n})."""
        fs, fe = frag
        reachable = self._frag_states(frag)
        mapping = {old: self.new_state() for old in reachable}
        for a, bs, b in list(self.transitions):
            if a in mapping and b in mapping:
                self.edge(mapping[a], bs, mapping[b])
        return mapping[fs], mapping[fe]

    def _frag_states(self, frag) -> set[int]:
        fs, fe = frag
        adj: dict[int, list[int]] = {}
        for a, _bs, b in self.transitions:
            adj.setdefault(a, []).append(b)
        seen = {fs}
        stack = [fs]
        while stack:
            s = stack.pop()
            for nxt in adj.get(s, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        seen.add(fe)
        return seen

    def _repeat_bounds(self, frag, lo: int, hi: Optional[int]):
        if hi == 0:
            # a{0} / a{0,0}: exactly zero occurrences — an epsilon
            # fragment, NOT an optional copy.
            s = self.new_state()
            return s, s
        parts = [frag]
        total = (hi if hi is not None else max(lo, 1))
        for _ in range(total - 1):
            parts.append(self._clone(frag))
        if hi is None:
            parts[-1] = self._plus(parts[-1]) if lo > 0 else \
                self._star(parts[-1])
            if lo == 0 and len(parts) == 1:
                return parts[0]
        opt_from = lo if lo > 0 else 1
        for i in range(opt_from, len(parts) - (1 if hi is None else 0)):
            parts[i] = self._opt(parts[i])
        if lo == 0 and hi is not None:
            parts[0] = self._opt(parts[0])
        for (_, e1), (s2, _) in zip(parts, parts[1:]):
            self.edge(e1, None, s2)
        return parts[0][0], parts[-1][1]

    # -- atoms ------------------------------------------------------------
    def atom(self) -> tuple[int, int]:
        ch = self.take()
        if ch == "(":
            if self.src[self.pos:self.pos + 2] == "?:":
                self.pos += 2
            frag = self.alternation()
            if self.peek() != ")":
                raise ValueError(f"unclosed group in {self.src!r}")
            self.take()
            return frag
        if ch == "[":
            return self._class_frag(*self._parse_class())
        if ch == ".":
            return self._charset(_ALL - {ord("\n")})
        if ch == "\\":
            nxt = self.take()
            if ord(nxt) >= 128:
                # Escaped non-ASCII char: the full UTF-8 byte chain,
                # not a set of its bytes.
                return self._charset(self._literal_bytes(nxt))
            return self._charset(self._escape(nxt))
        if ch in ")|*+?":
            raise ValueError(f"unexpected {ch!r} in {self.src!r}")
        return self._charset(frozenset(ch.encode("utf-8"))
                             if ord(ch) < 128 else
                             self._literal_bytes(ch))

    def _literal_bytes(self, ch: str) -> tuple[int, int]:
        # Multi-byte utf-8 literal: a byte chain, returned as a fragment.
        bs = ch.encode("utf-8")
        s = self.new_state()
        cur = s
        for b in bs:
            nxt = self.new_state()
            self.edge(cur, frozenset((b, )), nxt)
            cur = nxt
        # Sentinel: caller expects a charset for 1-byte atoms; for
        # multibyte we already built the chain — wrap via a tuple tag.
        self._mb_frag = (s, cur)
        return self._mb_frag

    def _charset(self, byteset) -> tuple[int, int]:
        if isinstance(byteset, tuple):  # multibyte chain fragment
            return byteset
        s, e = self.new_state(), self.new_state()
        self.edge(s, frozenset(byteset), e)
        return s, e

    def _class_frag(self, byteset: frozenset,
                    multibyte: frozenset) -> tuple[int, int]:
        """A character class with possible non-ASCII members: the ASCII
        byteset plus one full UTF-8 byte chain per multibyte member,
        joined as alternatives (so e.g. [aé] matches 'a' or the
        two-byte 'é' sequence — never a lone continuation byte)."""
        if not multibyte:
            return self._charset(byteset)
        s, e = self.new_state(), self.new_state()
        if byteset:
            ms, me = self._charset(byteset)
            self.edge(s, None, ms)
            self.edge(me, None, e)
        for chs in sorted(multibyte):
            ms, me = self._literal_bytes(chs)
            self.edge(s, None, ms)
            self.edge(me, None, e)
        return s, e

    def _escape(self, ch: str) -> frozenset:
        table = {
            "d": _DIGITS, "D": _ALL - _DIGITS,
            "w": _WORD, "W": _ALL - _WORD,
            "s": _SPACE, "S": _ALL - _SPACE,
            "n": frozenset((10, )), "t": frozenset((9, )),
            "r": frozenset((13, )), "f": frozenset((12, )),
            "v": frozenset((11, )), "0": frozenset((0, )),
        }
        if ch in table:
            return table[ch]
        if ch == "x":
            hexs = self.take() + self.take()
            return frozenset((int(hexs, 16), ))
        return frozenset(ch.encode("utf-8"))

    def _parse_class(self) -> tuple[frozenset, frozenset]:
        """-> (ASCII byteset, set of non-ASCII member chars). Non-ASCII
        members become whole UTF-8 sequences in _class_frag, never a
        set of their bytes; they are rejected in ranges and negations,
        where byte semantics would be ill-defined."""
        negate = False
        if self.peek() == "^":
            self.take()
            negate = True
        members: set[int] = set()
        multibyte: set[str] = set()
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise ValueError(f"unclosed class in {self.src!r}")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            self.take()
            if ch == "\\":
                esc = self.take()
                if ord(esc) >= 128:
                    multibyte.add(esc)
                    continue
                sub = self._escape(esc)
                if (len(sub) == 1
                        and self.peek() == "-"
                        and self.pos + 1 < len(self.src)
                        and self.src[self.pos + 1] != "]"):
                    # Single-byte escape starting a range: [\x20-\x7e].
                    lo = next(iter(sub))
                else:
                    members |= sub
                    continue
            else:
                lo = ord(ch)
            if (self.peek() == "-" and self.pos + 1 < len(self.src)
                    and self.src[self.pos + 1] != "]"):
                self.take()
                hi_ch = self.take()
                if hi_ch == "\\":
                    esc = self.take()
                    if ord(esc) >= 128:
                        raise ValueError(
                            f"non-ASCII range endpoint in {self.src!r}")
                    hi = max(self._escape(esc))
                    if hi >= 128:
                        # e.g. [a-\xe9]: the escape RESOLVES past ASCII,
                        # where a byte range would span UTF-8 lead/
                        # continuation bytes.
                        raise ValueError(
                            f"non-ASCII range endpoint in {self.src!r}")
                else:
                    if ord(hi_ch) >= 128:
                        raise ValueError(
                            f"non-ASCII range endpoint in {self.src!r}")
                    hi = ord(hi_ch)
                if lo >= 128:
                    raise ValueError(
                        f"non-ASCII range endpoint in {self.src!r}")
                members |= set(range(lo, hi + 1))
            else:
                if lo < 128:
                    members.add(lo)
                else:
                    multibyte.add(ch)
        if negate:
            if multibyte:
                raise ValueError(
                    f"negated class with non-ASCII member in {self.src!r}")
            return frozenset(_ALL - members), frozenset()
        return frozenset(members), frozenset(multibyte)


# ---------------------------------------------------------------------------
# NFA -> DFA (subset construction over the byte alphabet)
# ---------------------------------------------------------------------------


@dataclass
class DFA:
    """Dense byte DFA. State 0 is the dead state; start state is 1."""

    trans: np.ndarray  # [S, 256] int32
    accept: np.ndarray  # [S] bool
    # live[s]: some accepting state is reachable from s (s != dead).
    live: np.ndarray  # [S] bool

    @property
    def num_states(self) -> int:
        return self.trans.shape[0]

    def walk_bytes(self, state: int, data: bytes) -> int:
        for b in data:
            state = int(self.trans[state, b])
            if state == 0:
                return 0
        return state


MAX_NFA_STATES = 200_000
MAX_DFA_STATES = 20_000


def compile_regex(pattern: str) -> DFA:
    parser = _Parser(pattern)
    start, end = parser.parse()
    n = parser.num_states
    if n > MAX_NFA_STATES:
        raise ValueError(
            f"grammar too complex ({n} NFA states; bounded repetitions "
            "clone their fragment — prefer * / + loops)")

    eps: list[list[int]] = [[] for _ in range(n)]
    by_byte: list[list[tuple[frozenset, int]]] = [[] for _ in range(n)]
    for a, bs, b in parser.transitions:
        if bs is None:
            eps[a].append(b)
        else:
            by_byte[a].append((bs, b))

    def closure(states: frozenset) -> frozenset:
        stack = list(states)
        seen = set(states)
        while stack:
            s = stack.pop()
            for nxt in eps[s]:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return frozenset(seen)

    start_set = closure(frozenset((start, )))
    dfa_ids: dict[frozenset, int] = {frozenset(): 0, start_set: 1}
    rows: list[np.ndarray] = [np.zeros(256, np.int32),
                              np.zeros(256, np.int32)]
    accepts: list[bool] = [False, end in start_set]
    work = [start_set]
    while work:
        cur = work.pop()
        cur_id = dfa_ids[cur]
        # Gather per-byte targets.
        targets: dict[int, set[int]] = {}
        for s in cur:
            for bs, nxt in by_byte[s]:
                for b in bs:
                    targets.setdefault(b, set()).add(nxt)
        row = np.zeros(256, np.int32)
        # Group identical target sets to avoid recomputing closures.
        by_set: dict[frozenset, list[int]] = {}
        for b, tset in targets.items():
            by_set.setdefault(frozenset(tset), []).append(b)
        for tset, byte_list in by_set.items():
            nxt_set = closure(tset)
            if nxt_set not in dfa_ids:
                if len(rows) >= MAX_DFA_STATES:
                    raise ValueError(
                        f"grammar too complex (> {MAX_DFA_STATES} DFA "
                        "states)")
                dfa_ids[nxt_set] = len(rows)
                rows.append(np.zeros(256, np.int32))
                accepts.append(end in nxt_set)
                work.append(nxt_set)
            nid = dfa_ids[nxt_set]
            for b in byte_list:
                row[b] = nid
        rows[cur_id] = row

    trans = np.stack(rows)
    accept = np.asarray(accepts, bool)
    # Liveness: backward reachability from accepting states.
    S = trans.shape[0]
    live = accept.copy()
    changed = True
    while changed:
        changed = False
        reaches = live[trans].any(axis=1) & (np.arange(S) != 0)
        new_live = live | reaches
        if (new_live != live).any():
            live = new_live
            changed = True
    return DFA(trans=trans, accept=accept, live=live)


# ---------------------------------------------------------------------------
# Token-mask table: DFA states -> vocab bitmasks
# ---------------------------------------------------------------------------


@dataclass
class TokenMaskTable:
    """Lazily-computed per-state vocabulary masks for one DFA + vocab.

    allow(state)[t] is True when emitting token t from ``state`` keeps
    the automaton in a LIVE state (an accepting state stays reachable).
    next_states(state)[t] is the state after emitting t (0 = dead).
    EOS handling is the manager's job: EOS is allowed iff the current
    state is accepting.
    """

    dfa: DFA
    token_bytes: list[bytes]
    max_len: int = field(init=False)
    _tok_mat: np.ndarray = field(init=False)  # [V, Lmax] int16 (-1 pad)
    _cache: dict[int, tuple[np.ndarray, np.ndarray]] = \
        field(default_factory=dict)

    def __post_init__(self) -> None:
        V = len(self.token_bytes)
        self.max_len = max((len(b) for b in self.token_bytes), default=1)
        mat = np.full((V, max(self.max_len, 1)), -1, np.int16)
        for i, bs in enumerate(self.token_bytes):
            if bs:
                mat[i, :len(bs)] = np.frombuffer(bs, np.uint8)
        self._tok_mat = mat

    def _compute_raw(self, state: int) -> tuple[np.ndarray, np.ndarray]:
        """Byte-level mask: token t allowed iff its bytes land in a
        byte-LIVE DFA state."""
        V, L = self._tok_mat.shape
        cur = np.full(V, state, np.int32)
        for j in range(L):
            col = self._tok_mat[:, j]
            active = col >= 0
            nxt = self.dfa.trans[cur, np.where(active, col, 0)]
            cur = np.where(active, nxt, cur)
        # Empty tokens (no bytes) keep the state; dead-end tokens -> 0.
        allow = self.dfa.live[cur]
        # Tokens with no bytes cannot advance the grammar; disallow them
        # so generation always makes progress.
        empty = self._tok_mat[:, 0] < 0
        allow = allow & ~empty
        return allow, cur

    _raw_cache: dict = field(default_factory=dict)
    _live_cache: dict = field(default_factory=dict)
    # Token-closure exploration bound: past this many states the
    # refinement assumes live (= the byte-level answer), never the
    # other way — masks only ever get STRICTER than byte liveness.
    _LIVE_CAP = 4096

    def _raw(self, state: int) -> tuple[np.ndarray, np.ndarray]:
        if state not in self._raw_cache:
            self._raw_cache[state] = self._compute_raw(state)
        return self._raw_cache[state]

    def _token_live(self, state: int) -> bool:
        """Can an ACCEPTING state be reached via whole-token emissions?
        Byte liveness is not enough when the vocabulary lacks the
        bridging bytes (a token may be a valid PREFIX whose required
        continuation byte exists in no token — emitting it would strand
        the generation). BFS over the token closure, memoized."""
        cached = self._live_cache.get(state)
        if cached is not None:
            return cached
        seen = {state}
        frontier = [state]
        live = False
        while frontier:
            s = frontier.pop()
            if self.dfa.accept[s]:
                live = True
                break
            if len(seen) > self._LIVE_CAP:
                live = True  # give up safely: byte-level answer
                break
            allow, cur = self._raw(s)
            for s2 in np.unique(cur[allow]):
                s2 = int(s2)
                if self._live_cache.get(s2):
                    live = True
                    frontier = []
                    break
                if s2 not in seen:
                    seen.add(s2)
                    frontier.append(s2)
        if not live and len(seen) <= self._LIVE_CAP:
            # Everything reachable from a dead state is dead too.
            for s in seen:
                self._live_cache[s] = False
        self._live_cache[state] = live
        return live

    def _compute(self, state: int) -> tuple[np.ndarray, np.ndarray]:
        allow, cur = self._raw(state)
        # Token-level refinement: drop tokens stranding the generation
        # in a byte-live but token-dead state (reference behavior: the
        # grammar engine guarantees every emission can still complete).
        for s2 in np.unique(cur[allow]):
            if not self._token_live(int(s2)):
                allow = allow & (cur != s2)
        return allow, cur

    def allow(self, state: int) -> np.ndarray:
        if state not in self._cache:
            self._cache[state] = self._compute(state)
        return self._cache[state][0]

    def next_states(self, state: int) -> np.ndarray:
        if state not in self._cache:
            self._cache[state] = self._compute(state)
        return self._cache[state][1]
