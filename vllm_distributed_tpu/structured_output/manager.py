"""Structured-output manager: per-request grammar state in the engine
core, per-step vocab bitmasks shipped to the worker.

Reference: vllm/v1/structured_output/__init__.py
``StructuredOutputManager`` — grammars compile next to the scheduler,
each step fills a token bitmask for the scheduled structured requests
(riding SchedulerOutput), the model runner applies it to the logits
(gpu_model_runner.py:1433), and sampled tokens advance the grammar FSM.

The TPU twist: masks must be static-shape, so each mask is a dense
[V] bool array; the runner stacks them into the extended-sampling
graph's [R, V] mask input (a separate compiled variant keyed by a
static want_mask flag, like want_topk).
"""

import hashlib
from typing import Optional

import numpy as np

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.structured_output.fsm import (TokenMaskTable,
                                                        compile_regex)
from vllm_distributed_tpu.structured_output.json_schema import (
    json_object_regex, schema_to_regex)

logger = init_logger(__name__)


def spec_to_regex(spec: dict) -> str:
    """A request's structured spec -> regex. Spec forms (mirroring the
    reference's GuidedDecodingParams): {"regex": ...}, {"choice": [...]},
    {"json": schema-or-string}, {"json_object": True}."""
    if "regex" in spec:
        return spec["regex"]
    if "choice" in spec:
        import re as _stdre
        return "(" + "|".join(_stdre.escape(str(c))
                              for c in spec["choice"]) + ")"
    if "json" in spec:
        return schema_to_regex(spec["json"])
    if spec.get("json_object"):
        return json_object_regex()
    raise ValueError(f"unsupported structured spec {spec!r}")


class _RequestGrammar:
    __slots__ = ("table", "state", "eos_token_id")

    def __init__(self, table: TokenMaskTable,
                 eos_token_id: Optional[int]) -> None:
        self.table = table
        self.state = 1  # DFA start
        self.eos_token_id = eos_token_id


class StructuredOutputManager:

    def __init__(self, vocab_bytes: list[bytes]) -> None:
        self.vocab_bytes = vocab_bytes
        self.vocab_size = len(vocab_bytes)
        # Compiled DFAs shared across requests with the same spec.
        self._tables: dict[str, TokenMaskTable] = {}
        self._requests: dict[str, _RequestGrammar] = {}

    # ------------------------------------------------------------------
    def add_request(self, req_id: str, spec: dict,
                    eos_token_id: Optional[int] = None) -> None:
        pattern = spec_to_regex(spec)
        key = hashlib.sha256(pattern.encode()).hexdigest()
        table = self._tables.get(key)
        if table is None:
            dfa = compile_regex(pattern)
            table = TokenMaskTable(dfa=dfa, token_bytes=self.vocab_bytes)
            self._tables[key] = table
            logger.info("compiled grammar (%d DFA states) for %r...",
                        dfa.num_states, pattern[:60])
        self._requests[req_id] = _RequestGrammar(table, eos_token_id)

    def remove_request(self, req_id: str) -> None:
        self._requests.pop(req_id, None)

    def has(self, req_id: str) -> bool:
        return req_id in self._requests

    # ------------------------------------------------------------------
    def mask_for(self, req_id: str) -> Optional[np.ndarray]:
        """[V] bool mask for the request's NEXT token; None if the
        request has no grammar. EOS is allowed exactly in accepting
        states; if the grammar is complete-and-closed (accepting with no
        live continuation) only EOS remains."""
        g = self._requests.get(req_id)
        if g is None:
            return None
        allow = g.table.allow(g.state).copy()
        eos = g.eos_token_id
        if eos is not None and 0 <= eos < self.vocab_size:
            allow[eos] = bool(g.table.dfa.accept[g.state])
        if not allow.any():
            # Dead grammar (shouldn't happen: advance() rejects dead
            # transitions) — allow EOS so the request can terminate.
            if eos is not None and 0 <= eos < self.vocab_size:
                allow[eos] = True
        return allow

    def advance(self, req_id: str, token_ids: list[int]) -> None:
        g = self._requests.get(req_id)
        if g is None:
            return
        for t in token_ids:
            if t == g.eos_token_id:
                self.remove_request(req_id)
                return
            nxt = int(g.table.next_states(g.state)[t])
            if nxt == 0:
                # The sampler should make this impossible; a desync
                # (e.g. stop-string cut) must not crash the core.
                logger.warning(
                    "structured request %s: token %d leaves the "
                    "grammar; freezing state", req_id, t)
                return
            g.state = nxt


def vocab_bytes_from_tokenizer(tokenizer) -> list[bytes]:
    """token id -> utf-8 bytes table for mask precomputation.

    Uses per-token decode with a leading anchor token where needed so
    sentencepiece-style leading-space markers decode faithfully."""
    V = getattr(tokenizer, "vocab_size", None) or len(tokenizer)
    try:
        V = max(V, len(tokenizer))
    except TypeError:
        pass
    out: list[bytes] = []
    specials = set(getattr(tokenizer, "all_special_ids", ()) or ())
    for i in range(V):
        if i in specials:
            out.append(b"")
            continue
        try:
            s = tokenizer.decode([i], skip_special_tokens=False,
                                 clean_up_tokenization_spaces=False)
        except Exception:  # noqa: BLE001 - holes in exotic vocabs
            s = ""
        out.append(s.encode("utf-8"))
    return out
