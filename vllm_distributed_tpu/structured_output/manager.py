"""Structured-output manager: per-request grammar state in the engine
core, per-step vocab bitmasks shipped to the worker.

Reference: vllm/v1/structured_output/__init__.py
``StructuredOutputManager`` — grammars compile next to the scheduler,
each step fills a token bitmask for the scheduled structured requests
(riding SchedulerOutput), the model runner applies it to the logits
(gpu_model_runner.py:1433), and sampled tokens advance the grammar FSM.

The TPU twist: masks must be static-shape, so each mask is a dense
[V] bool array; the runner stacks them into the extended-sampling
graph's [R, V] mask input (a separate compiled variant keyed by a
static want_mask flag, like want_topk).
"""

import hashlib
from typing import Optional

import numpy as np

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.structured_output.fsm import (TokenMaskTable,
                                                        compile_regex)
from vllm_distributed_tpu.structured_output.json_schema import (
    json_object_regex, schema_to_regex)

logger = init_logger(__name__)


def spec_to_regex(spec: dict) -> str:
    """A request's structured spec -> regex. Spec forms (mirroring the
    reference's GuidedDecodingParams): {"regex": ...}, {"choice": [...]},
    {"json": schema-or-string}, {"json_object": True}."""
    if "regex" in spec:
        return spec["regex"]
    if "choice" in spec:
        import re as _stdre
        return "(" + "|".join(_stdre.escape(str(c))
                              for c in spec["choice"]) + ")"
    if "json" in spec:
        return schema_to_regex(spec["json"])
    if spec.get("json_object"):
        return json_object_regex()
    if "grammar" in spec:
        from vllm_distributed_tpu.structured_output.ebnf import \
            ebnf_to_regex
        return ebnf_to_regex(spec["grammar"])
    raise ValueError(f"unsupported structured spec {spec!r}")


class _RequestGrammar:
    __slots__ = ("table", "state", "eos_token_id")

    def __init__(self, table: TokenMaskTable,
                 eos_token_id: Optional[int]) -> None:
        self.table = table
        self.state = 1  # DFA start
        self.eos_token_id = eos_token_id


class StructuredOutputManager:

    def __init__(self, vocab_bytes: list[bytes]) -> None:
        self.vocab_bytes = vocab_bytes
        self.vocab_size = len(vocab_bytes)
        # Compiled DFAs shared across requests with the same spec.
        self._tables: dict[str, TokenMaskTable] = {}
        self._requests: dict[str, _RequestGrammar] = {}

    # ------------------------------------------------------------------
    def add_request(self, req_id: str, spec: dict,
                    eos_token_id: Optional[int] = None) -> None:
        pattern = spec_to_regex(spec)
        key = hashlib.sha256(pattern.encode()).hexdigest()
        table = self._tables.get(key)
        if table is None:
            dfa = compile_regex(pattern)
            table = TokenMaskTable(dfa=dfa, token_bytes=self.vocab_bytes)
            self._tables[key] = table
            logger.info("compiled grammar (%d DFA states) for %r...",
                        dfa.num_states, pattern[:60])
        self._requests[req_id] = _RequestGrammar(table, eos_token_id)

    def remove_request(self, req_id: str) -> None:
        self._requests.pop(req_id, None)

    def has(self, req_id: str) -> bool:
        return req_id in self._requests

    # ------------------------------------------------------------------
    def mask_for(self, req_id: str) -> Optional[np.ndarray]:
        """[V] bool mask for the request's NEXT token; None if the
        request has no grammar. EOS is allowed exactly in accepting
        states; if the grammar is complete-and-closed (accepting with no
        live continuation) only EOS remains."""
        g = self._requests.get(req_id)
        if g is None:
            return None
        allow = g.table.allow(g.state).copy()
        eos = g.eos_token_id
        if eos is not None and 0 <= eos < self.vocab_size:
            allow[eos] = bool(g.table.dfa.accept[g.state])
        if not allow.any():
            # Dead grammar (shouldn't happen: advance() rejects dead
            # transitions) — allow EOS so the request can terminate.
            if eos is not None and 0 <= eos < self.vocab_size:
                allow[eos] = True
        return allow

    def advance(self, req_id: str, token_ids: list[int]) -> None:
        g = self._requests.get(req_id)
        if g is None:
            return
        for t in token_ids:
            if t == g.eos_token_id:
                self.remove_request(req_id)
                return
            nxt = int(g.table.next_states(g.state)[t])
            if nxt == 0:
                # The sampler should make this impossible; a desync
                # (e.g. stop-string cut) must not crash the core.
                logger.warning(
                    "structured request %s: token %d leaves the "
                    "grammar; freezing state", req_id, t)
                return
            g.state = nxt


_BYTE_FALLBACK_RE = None  # lazily compiled <0xHH> matcher


def _bytes_to_unicode_map() -> dict[str, int]:
    """Inverse of GPT-2's public byte->printable-unicode table (the one
    byte-level BPE vocabs — GPT-2, Llama-3, Qwen — store pieces in):
    printable bytes map to themselves, the rest shift into U+0100+."""
    bs = (list(range(ord("!"), ord("~") + 1)) +
          list(range(0xA1, 0xAC + 1)) + list(range(0xAE, 0xFF + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def vocab_bytes_from_tokenizer(tokenizer) -> list[bytes]:
    """token id -> utf-8 bytes table for mask precomputation.

    Plain per-token ``decode([i])`` is wrong for the two dominant vocab
    encodings: sentencepiece (Llama-2/Mistral) strips the leading-space
    marker on a lone token, and byte-level BPE (GPT-2/Llama-3/Qwen)
    decodes partial-UTF-8 pieces to U+FFFD. So this derives bytes from
    the raw vocab pieces instead (ref: what xgrammar/outlines do before
    handing vllm its token tables, vllm/v1/structured_output/backend_*):

    - ``<0xHH>`` byte-fallback pieces -> that raw byte;
    - pieces containing the sentencepiece space marker U+2581 -> marker
      replaced by a real space, then UTF-8;
    - byte-level-BPE vocabs (detected by the GPT-2 marker chars) ->
      each piece char mapped through the inverse byte table;
    - anything else -> per-token decode (correct for WordLevel-style
      vocabs, where token text is the piece itself).
    """
    import re as _stdre
    global _BYTE_FALLBACK_RE
    if _BYTE_FALLBACK_RE is None:
        _BYTE_FALLBACK_RE = _stdre.compile(r"<0x([0-9A-Fa-f]{2})>\Z")
    V = getattr(tokenizer, "vocab_size", None) or len(tokenizer)
    try:
        V = max(V, len(tokenizer))
    except TypeError:
        pass
    specials = set(getattr(tokenizer, "all_special_ids", ()) or ())
    try:
        pieces = tokenizer.convert_ids_to_tokens(list(range(V)))
    except Exception:  # noqa: BLE001 - tokenizer without piece access
        pieces = [None] * V
    # Classify the vocab encoding from its BASE pieces (added tokens are
    # stored literally and must not flip the mode): sentencepiece pieces
    # carry U+2581; byte-level BPE pieces carry U+0120 ('Ġ', the space
    # byte) or U+010A ('Ċ', newline). Majority vote — a real vocab has
    # thousands of its own marker and ~none of the other; a vocab with
    # neither (WordLevel) decodes per token.
    base_v = getattr(tokenizer, "vocab_size", None) or len(pieces)
    base = [p for p in pieces[:base_v] if isinstance(p, str)]
    n_sp = sum(1 for p in base if "▁" in p)
    n_bl = sum(1 for p in base if "Ġ" in p or "Ċ" in p)
    sp_mode = n_sp > n_bl
    byte_mode = n_bl > n_sp
    u2b = _bytes_to_unicode_map() if byte_mode else None

    def _decode(i: int) -> bytes:
        try:
            s = tokenizer.decode([i], skip_special_tokens=False,
                                 clean_up_tokenization_spaces=False)
        except Exception:  # noqa: BLE001 - holes in exotic vocabs
            s = ""
        return s.encode("utf-8")

    out: list[bytes] = []
    for i in range(V):
        if i in specials:
            out.append(b"")
            continue
        p = pieces[i]
        if not isinstance(p, str):
            out.append(_decode(i))
            continue
        m = _BYTE_FALLBACK_RE.match(p)
        if m:
            out.append(bytes([int(m.group(1), 16)]))
        elif sp_mode:
            out.append(p.replace("▁", " ").encode("utf-8"))
        elif byte_mode:
            try:
                out.append(bytes(u2b[c] for c in p))
            except KeyError:
                # Added token (stored literally, not byte-mapped).
                out.append(_decode(i))
        else:
            out.append(_decode(i))
    return out
