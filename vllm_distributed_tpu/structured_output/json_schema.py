"""JSON-schema -> regex compiler (outlines-style) for structured output.

Reference: vllm/v1/structured_output/ backends compile
``response_format={"type": "json_schema"}`` into a token-level grammar.
Context-free JSON needs a pushdown automaton in general; like outlines,
this compiler sidesteps that by bounding nesting depth and emitting a
plain regex for the schema (or for generic JSON-object mode), which the
fsm module turns into a DFA + token masks.

Supported schema subset: type object (properties in declaration order,
``required`` honoured — optional properties may be omitted only from the
tail), string, integer, number, boolean, null, enum (of scalars), const,
array (items, minItems/maxItems up to a small bound), anyOf, and
``{}``/missing-type (any bounded-depth JSON value).
"""

import json
import re as _stdre
from typing import Any

_WS = r"[ \n\t]*"
_STRING = r'"([^"\\\x00-\x1f]|\\["\\/bfnrtu])*"'
_INTEGER = r"-?(0|[1-9][0-9]*)"
_NUMBER = _INTEGER + r"(\.[0-9]+)?([eE][+-]?[0-9]+)?"
_BOOLEAN = r"(true|false)"
_NULL = r"null"

# NFA size grows ~4x per nesting level (a value appears twice in the
# array form and twice in the object form), so unbounded ``*`` loops are
# essential (a bounded {0,n} would CLONE the value fragment n times) and
# depth stays small. Deeper nesting than this in json mode falls back to
# the model simply not closing braces it cannot open.
MAX_ARRAY_ITEMS = 8
ANY_VALUE_DEPTH = 3


def _list_of(item: str) -> str:
    return rf"({item}({_WS},{_WS}{item})*)?"


def _any_value(depth: int) -> str:
    """Regex for an arbitrary JSON value with nesting bounded at
    ``depth`` (generic json_object mode)."""
    scalar = f"({_STRING}|{_NUMBER}|{_BOOLEAN}|{_NULL})"
    value = scalar
    for _ in range(depth):
        arr = rf"\[{_WS}{_list_of(value)}{_WS}\]"
        member = rf"{_STRING}{_WS}:{_WS}{value}"
        obj = rf"\{{{_WS}{_list_of(member)}{_WS}\}}"
        value = f"({scalar}|{arr}|{obj})"
    return value


def json_object_regex() -> str:
    """Generic ``response_format: json_object``: one JSON object."""
    member = rf"{_STRING}{_WS}:{_WS}{_any_value(ANY_VALUE_DEPTH - 1)}"
    return rf"\{{{_WS}{_list_of(member)}{_WS}\}}"


def schema_to_regex(schema: Any) -> str:
    if isinstance(schema, str):
        schema = json.loads(schema)
    return _compile(schema, depth=ANY_VALUE_DEPTH)


def _literal(value: Any) -> str:
    return _stdre.escape(json.dumps(value))


def _compile(schema: Any, depth: int) -> str:
    if not isinstance(schema, dict) or not schema:
        return _any_value(max(depth - 1, 0))
    if "const" in schema:
        return _literal(schema["const"])
    if "enum" in schema:
        return "(" + "|".join(_literal(v) for v in schema["enum"]) + ")"
    if "anyOf" in schema:
        return ("(" + "|".join(_compile(s, depth)
                               for s in schema["anyOf"]) + ")")
    t = schema.get("type")
    if isinstance(t, list):
        return ("(" + "|".join(_compile({**schema, "type": one}, depth)
                               for one in t) + ")")
    if t == "string":
        if "pattern" in schema:
            # The schema's pattern matches the string CONTENT.
            return f'"{schema["pattern"]}"'
        return _STRING
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return _BOOLEAN
    if t == "null":
        return _NULL
    if t == "array":
        item = _compile(schema.get("items", {}), depth - 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        if lo == 0 and hi is None:
            body = _list_of(item)
        else:
            hi = MAX_ARRAY_ITEMS if hi is None else \
                min(int(hi), MAX_ARRAY_ITEMS)
            lo = min(lo, hi)
            if lo == 0:
                body = (f"({item}({_WS},{_WS}{item}){{0,{hi - 1}}})?"
                        if hi > 0 else "")
            else:
                body = f"{item}({_WS},{_WS}{item}){{{lo - 1},{hi - 1}}}"
        return rf"\[{_WS}{body}{_WS}\]"
    if t == "object" or "properties" in schema:
        props = schema.get("properties", {})
        required = set(schema.get("required", props.keys()))
        if not props:
            return json_object_regex()
        parts = []
        for name, sub in props.items():
            entry = (rf"{_stdre.escape(json.dumps(name))}{_WS}:{_WS}"
                     + _compile(sub, depth - 1))
            parts.append((entry, name in required))
        # Declaration order; optional properties may drop from the tail
        # (full optionality of middle keys would blow the regex up
        # combinatorially).
        body = ""
        for i in reversed(range(len(parts))):
            entry, is_req = parts[i]
            sep = rf"{_WS},{_WS}" if i > 0 else ""
            if body:
                seg = f"{sep}{entry}{body}"
            else:
                seg = f"{sep}{entry}"
            if not is_req:
                seg = f"({seg})?"
            body = seg
        return rf"\{{{_WS}{body}{_WS}\}}"
    # Unknown schema form: any value.
    return _any_value(max(depth - 1, 0))
