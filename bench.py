"""Headline benchmark: decode throughput on one TPU chip.

Mirrors the reference fork's TKNP harness defaults (tknp_inference_
benchmarks.py:31-58: Llama-3.2-1B architecture, batch 8, 128-token prompt,
100 decode steps) driven through THIS framework's full engine stack
(scheduler -> runner -> jitted forward+sample), and like that harness
(tknp_inference_benchmarks.py:66-90) reports BOTH prefill time and decode
throughput, plus a computed MFU (model FLOPs / chip peak).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` compares against a conservative single-chip reference
estimate for the same workload (see BASELINE.md: the reference publishes
no absolute numbers; we anchor to ~8 * 45 tok/s/stream ≈ 360 tok/s
aggregate for Llama-3.2-1B bs=8 on one accelerator of this class).
"""

import json
import os
import signal
import subprocess
import sys
import time
import traceback

# Keep the engine quiet so stdout stays a single JSON line.
os.environ.setdefault("VDT_LOGGING_LEVEL", "WARNING")

# The routing/disagg legs drive 2-3-replica DP fleets; the CPU platform
# exposes one device unless told otherwise, and the flag only takes
# effect before the first jax import in this process. Irrelevant on TPU
# (it shapes the HOST platform only).
if ("xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()

import numpy as np  # noqa: E402

TINY = os.environ.get("VDT_BENCH_TINY", "0") == "1"  # CPU smoke mode

BATCH = 8
PROMPT_LEN = 16 if TINY else 128
# Tiny mode still runs >= num_scheduler_steps decode steps so the
# multi-step burst (and its device-time attribution) engages.
DECODE_STEPS = 24 if TINY else 100
BASELINE_TOKS_PER_S = 360.0

# Peak FLOP/s and HBM-bandwidth tables moved to
# vllm_distributed_tpu/metrics/costmodel.py (PEAK_FLOPS_PER_CHIP /
# PEAK_HBM_PER_CHIP) — one source for bench records and the in-engine
# vdt:mfu / vdt:mbu plane; _peak_flops()/_peak_hbm() below delegate.

_PROBE = ("import jax, time; t0=time.time(); d = jax.devices(); "
          "import jax.numpy as jnp; "
          "x = jnp.ones((256, 256), jnp.bfloat16); "
          "(x @ x).block_until_ready(); "
          "print('PLATFORM=' + d[0].platform, 'KIND=' + d[0].device_kind, "
          "'INIT_S=%.1f' % (time.time() - t0))")

_PROBE_LOG: list[str] = []  # diagnostics carried into the final JSON
_JSON_EMITTED = False  # set once the one JSON line has been printed

# Hard wall-clock caps (seconds). The driver kills bench.py at an unknown
# wall clock; round 3 proved the probe budget alone can exceed it
# (rc=124, no JSON). Everything before the fallback JSON must be bounded:
# probing <= _PROBE_BUDGET total, and a SIGTERM/SIGALRM backstop prints
# the best-known record if we are killed anyway.
_PROBE_BUDGET = float(os.environ.get("VDT_BENCH_PROBE_BUDGET", "300"))
_TOTAL_DEADLINE = float(os.environ.get("VDT_BENCH_DEADLINE", "3300"))


def _emit(record: dict) -> None:
    """Print the single JSON line exactly once, even under signals."""
    global _JSON_EMITTED
    if _JSON_EMITTED:
        return
    _JSON_EMITTED = True
    print(json.dumps(record), flush=True)


def _fallback_record(reason: str) -> dict:
    return {
        "metric": "decode_throughput_llama1b_bs8",
        "value": 0.0,
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "error": reason,
        "probe_log": _PROBE_LOG[-4:],
    }


def _install_backstop() -> None:
    """If the driver SIGTERMs us (timeout) or our own alarm fires, emit
    the diagnostic JSON line and exit 0 — a run with no parsed record
    must be impossible."""
    def _handler(signum, frame):  # noqa: ARG001
        _emit(_fallback_record(f"killed by signal {signum} before a bench "
                               f"record was produced"))
        os._exit(0)
    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGALRM, _handler)
    signal.alarm(int(_TOTAL_DEADLINE))


# A hung experimental-platform plugin emits ONLY this class of stderr
# line and then blocks jax.devices() forever (BENCH_r05.json probe_log:
# two full 120 s timeouts with nothing but the 'Platform ... is
# experimental' warning). Warning-only output that has gone quiet is a
# liveness VERDICT, not a timeout: the plugin loaded, device init hung,
# and a retry will hang identically — fall back to CPU in seconds.
_WARNING_LINE = ("warning", "experimental")
# Seconds of warning-only stderr silence before the probe concludes the
# platform is hung (well under the 120 s per-attempt timeout).
_PROBE_LIVENESS = float(os.environ.get("VDT_BENCH_PROBE_LIVENESS", "15"))


def _stderr_warning_only(text: str) -> bool:
    """True when every non-empty stderr line is a warning (the
    experimental-platform banner class) — no traceback, no error."""
    lines = [ln for ln in text.splitlines() if ln.strip()]
    return bool(lines) and all(
        any(tok in ln.lower() for tok in _WARNING_LINE) for ln in lines)


def _probe_attempt(timeout: float,
                   liveness: float | None = None) -> tuple[str, str]:
    """One streamed probe subprocess. Returns (verdict, detail):
    'accel' | 'cpu' (clean results), 'hung-warning' (warning-only
    stderr went quiet for ``liveness`` seconds), 'fail' | 'timeout'
    (retryable).

    The child's pipes are polled with os.pread: Popen dup2s the fds, so
    the child SHARES the file description (and offset) with the parent —
    a seek+read here would move the shared offset under a concurrent
    child write and corrupt the capture."""
    import tempfile
    if liveness is None:
        liveness = _PROBE_LIVENESS
    with tempfile.TemporaryFile("w+b") as out_f, \
            tempfile.TemporaryFile("w+b") as err_f:

        def snap(f) -> str:
            return os.pread(f.fileno(), 1 << 20, 0).decode(
                "utf-8", "replace")

        proc = subprocess.Popen([sys.executable, "-c", _PROBE],
                                stdout=out_f, stderr=err_f)
        start = time.monotonic()
        # last_novel: the last instant stderr grew with NON-warning
        # content. A hung plugin that re-prints its experimental banner
        # periodically keeps plain "growth" alive forever (BENCH_r05:
        # two full 120 s timeouts on exactly that shape), so the
        # liveness clock must ignore warning-only growth — only novel
        # content (a traceback, device enumeration) proves progress.
        last_novel = start
        last_len = 0
        try:
            while True:
                try:
                    proc.wait(timeout=1.0)
                    break
                except subprocess.TimeoutExpired:
                    pass
                if time.monotonic() - start >= timeout:
                    proc.kill()
                    proc.wait()
                    err_txt = snap(err_f)
                    if err_txt and _stderr_warning_only(err_txt):
                        # Belt and braces: however the liveness clock
                        # was kept alive, a full attempt that produced
                        # nothing but warnings is the hung-platform
                        # signature, not a retryable timeout.
                        return ("hung-warning",
                                f"warning-only stderr through full "
                                f"{timeout:.0f}s attempt: "
                                f"{err_txt.strip()[-300:]}")
                    return ("timeout",
                            f"after {timeout:.0f}s: "
                            f"{err_txt.strip()[-300:]}")
                err_txt = snap(err_f)
                if len(err_txt) != last_len:
                    last_len = len(err_txt)
                    if not _stderr_warning_only(err_txt):
                        last_novel = time.monotonic()
                stalled = time.monotonic() - last_novel
                if (err_txt and _stderr_warning_only(err_txt)
                        and stalled >= liveness
                        and time.monotonic() - start >= liveness):
                    proc.kill()
                    proc.wait()
                    return ("hung-warning",
                            f"warning-only stderr for "
                            f"{stalled:.0f}s: {err_txt.strip()[-300:]}")
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        stdout, stderr = snap(out_f), snap(err_f)
    if proc.returncode == 0 and "PLATFORM=" in stdout:
        platform = stdout.split("PLATFORM=")[1].split()[0]
        verdict = "cpu" if platform == "cpu" else "accel"
        return (verdict, stdout.strip())
    return ("fail", f"rc={proc.returncode}: {stderr.strip()[-300:]}")


def _probe_accelerator() -> bool:
    """Check in a SUBPROCESS that the default JAX backend initializes AND
    executes a matmul: the tunnelled TPU plugin can hang jax.devices()
    for many minutes or die with Unavailable; probing out-of-process
    keeps this process clean for the CPU fallback. Failed init is cached
    per-process in jax, so every retry must be a fresh subprocess.

    Total wall clock here is hard-capped at _PROBE_BUDGET regardless of
    the per-attempt timeout, and a hung experimental platform (warning-
    only stderr, then silence) short-circuits the whole probe so the
    CPU fallback starts in seconds rather than after 2x120 s timeouts."""
    from vllm_distributed_tpu import envs
    deadline = time.monotonic() + _PROBE_BUDGET
    liveness = _PROBE_LIVENESS
    hung_once = False
    for attempt, backoff in enumerate((20, 40, 0)):
        remaining = deadline - time.monotonic()
        if remaining <= 5:
            _PROBE_LOG.append(f"probe budget ({_PROBE_BUDGET}s) exhausted "
                              f"before attempt {attempt}")
            break
        verdict, detail = _probe_attempt(
            min(envs.VDT_TPU_PROBE_TIMEOUT, remaining),
            liveness=liveness)
        msg = f"attempt {attempt} {verdict}: {detail}"
        _PROBE_LOG.append(msg)
        if verdict == "accel":
            return True
        if verdict == "cpu":
            return False  # only CPU available; use the fallback path
        print(f"bench: probe {msg}", file=sys.stderr)
        if verdict == "hung-warning":
            if hung_once:
                # Confirmed: alive but wedged twice, even with the
                # extended window — further retries hang identically.
                return False
            # A healthy tunnelled init can also be warning-then-silent
            # for a while: confirm the hang ONCE with a 4x liveness
            # window (still far cheaper than a full attempt timeout)
            # before concluding.
            hung_once = True
            liveness = liveness * 4
            continue
        if backoff:
            time.sleep(min(backoff, max(0.0, deadline - time.monotonic())))
    return False


def _enter_cpu_fallback() -> None:
    global TINY, PROMPT_LEN, DECODE_STEPS
    os.environ["VDT_PLATFORM"] = "cpu"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["VDT_PALLAS_INTERPRET"] = "1"
    os.environ["VDT_ATTENTION_BACKEND"] = "xla"
    TINY = True
    PROMPT_LEN = 16
    DECODE_STEPS = 24  # >= num_scheduler_steps so the burst engages


def _model_params(hf: dict) -> int:
    """Parameter count of the bench model from its dims (embed + lm_head
    counted once each; decode FLOPs/token ≈ 2 * params)."""
    H = hf["hidden_size"]
    L = hf["num_hidden_layers"]
    I = hf["intermediate_size"]
    V = hf["vocab_size"]
    hd = hf.get("head_dim") or H // hf["num_attention_heads"]
    Dq = hf["num_attention_heads"] * hd
    Dkv = hf["num_key_value_heads"] * hd
    per_layer = H * Dq + 2 * H * Dkv + Dq * H + 3 * H * I + 2 * H
    return L * per_layer + 2 * V * H + H


def _bench_cost_model(hf: dict):
    """The engine's analytic cost model priced for the bench dims
    (metrics/costmodel.py — the same arithmetic the in-engine
    vdt:mfu/vdt:mbu plane charges with, so bench records and /metrics
    stay directly comparable)."""
    import jax

    from vllm_distributed_tpu.metrics.costmodel import CostModel
    dev = jax.devices()[0]
    return CostModel.from_hf_dims(
        hf, dtype_bytes=2,
        device_kind=getattr(dev, "device_kind", dev.platform),
        num_chips=1)


def _stamp_engine_perf(record: dict, prefix: str, engine=None,
                       stats=None, hf: dict = None, tok_s=None,
                       avg_ctx=None) -> None:
    """Stamp one leg's engine-sourced MFU/MBU (max across workers —
    DP replicas share identical hardware). Falls back to the analytic
    cost model at the leg's measured tok/s when the telemetry plane
    was off for the leg (VDT_PERF_ATTRIB=0 / off-legs), so every
    record row carries comparable utilization numbers either way."""
    try:
        if stats is None and engine is not None:
            stats = engine.get_stats()
        workers = (stats or {}).get("workers") or {}
        mfus = [w.get("mfu") for w in workers.values()
                if isinstance(w, dict) and w.get("mfu") is not None]
        mbus = [w.get("mbu") for w in workers.values()
                if isinstance(w, dict) and w.get("mbu") is not None]
        if mfus:
            record[f"{prefix}_mfu"] = round(max(mfus), 6)
            record[f"{prefix}_mbu"] = round(max(mbus or [0.0]), 6)
            record[f"{prefix}_mfu_source"] = "engine"
            return
        if hf is not None and tok_s:
            cm = _bench_cost_model(hf)
            ctx = (avg_ctx if avg_ctx is not None
                   else PROMPT_LEN + DECODE_STEPS / 2)
            record[f"{prefix}_mfu"] = round(
                tok_s * cm.decode_flops_per_token(ctx) / cm.peak_flops,
                6)
            record[f"{prefix}_mbu"] = round(
                cm.decode_step_bytes(BATCH, ctx) * (tok_s / BATCH)
                / cm.peak_hbm, 6)
            record[f"{prefix}_mfu_source"] = "analytic"
    except Exception:  # noqa: BLE001 - diagnostic stamp only
        pass


def _peak_flops() -> float:
    # Single source with the in-engine plane (metrics/costmodel.py)
    # so bench records and /metrics use identical denominators.
    import jax

    from vllm_distributed_tpu.metrics.costmodel import peak_flops_per_chip
    return peak_flops_per_chip(jax.devices()[0].device_kind)


def _peak_hbm() -> float:
    import jax

    from vllm_distributed_tpu.metrics.costmodel import peak_hbm_per_chip
    return peak_hbm_per_chip(jax.devices()[0].device_kind)


def _time_decode(engine, prompts, sp, tag):
    """Warmup + prefill + timed decode of one engine; returns
    (decode_tok_s, decode_time_s)."""
    n = len(prompts)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}warm-{i}", p, sp)
    while engine.has_unfinished_requests():
        engine.step()
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp)
    prod = {f"{tag}-{i}": 0 for i in range(n)}
    while any(v == 0 for v in prod.values()):
        for o in engine.step():
            prod[o.request_id] = len(o.outputs[0].token_ids)
    start_toks = sum(prod.values())
    t0 = time.perf_counter()
    while engine.has_unfinished_requests():
        for o in engine.step():
            prod[o.request_id] = len(o.outputs[0].token_ids)
    decode_time = time.perf_counter() - t0
    return (sum(prod.values()) - start_toks) / decode_time, decode_time


def _async_overlap_legs(config, prompts, sp, record) -> None:
    """Tentpole trajectory legs: the same decode workload through a
    single-step SYNC engine and the ASYNC depth-2 pipeline, reported as
    steps_per_s (decode steps per stream per second — comparable across
    scheduling modes) plus decode_overlap_frac from the engine core's
    own dispatch counters. Overlap is measured by counters, NOT by
    blocking device timers — blocking inside the pipeline would
    serialize exactly the overlap under test (the headline leg's
    decode_host_s/decode_device_s attribution stays on the synchronous
    multi-step burst, where blocking is correct)."""
    import gc

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    batch = len(prompts)
    for leg, flag in (("sync1", False), ("async", True)):
        cfg = EngineConfig(
            model_config=config.model_config,
            cache_config=CacheConfig(block_size=16),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=2048, max_num_seqs=64,
                max_model_len=2048, num_scheduler_steps=1,
                async_scheduling=flag),
            load_config=LoadConfig(load_format="dummy"),
        )
        engine = LLMEngine(cfg, load_tokenizer=False)
        tok_s, _ = _time_decode(engine, prompts, sp, leg)
        stats = engine.get_stats()
        _stamp_engine_perf(record, leg, stats=stats)
        if flag:
            record["steps_per_s"] = round(tok_s / batch, 2)
            record["async_decode_tok_s"] = round(tok_s, 1)
            record["decode_overlap_frac"] = round(
                float(stats.get("decode_overlap_frac", 0.0)), 3)
            record["async_max_concurrent_batches"] = int(
                stats.get("max_concurrent_batches", 0))
        else:
            record["sync_steps_per_s"] = round(tok_s / batch, 2)
        del engine
        gc.collect()


def _routing_leg(config, record) -> None:
    """Routing-tier leg (ROADMAP item 3 acceptance): a 2-replica
    in-process DP fleet under repeated-session traffic — each turn's
    prompt extends the previous turn's full sequence, the chat pattern
    prefix-affinity exists for — measured with the router ON vs the
    VDT_ROUTER=0 round-robin balancer on IDENTICAL traffic. Reports the
    fleet-merged prefix-cache window hit rate, SLO goodput, and turn
    throughput per leg: the hit-rate delta is the multi-replica
    prefix-reuse win, directly comparable to the
    vdt:prefix_cache_hit_rate_window gauge in production."""
    import gc

    import jax

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    if len(jax.devices()) < 2:
        record["routing_leg_error"] = (
            "needs >= 2 devices for a 2-replica DP fleet")
        return
    # Odd session count on purpose: an even wave re-aligns with the
    # round-robin cursor every turn and would hand RR accidental
    # affinity, understating the router's win.
    sessions, turns, gen_tokens = 5, 4, 16
    sp = SamplingParams(temperature=0.0, max_tokens=gen_tokens,
                        ignore_eos=True)
    rng = np.random.default_rng(7)
    base = {s: [int(x) for x in rng.integers(10, 5000, size=64)]
            for s in range(sessions)}
    # Pre-drawn per-turn user tokens so both legs replay byte-identical
    # traffic (greedy generation makes the rest deterministic).
    extra = {(t, s): int(rng.integers(10, 5000))
             for t in range(turns) for s in range(sessions)}
    saved = os.environ.get("VDT_ROUTER")
    try:
        for leg, flag in (("routed", "1"), ("rr", "0")):
            os.environ["VDT_ROUTER"] = flag
            cfg = EngineConfig(
                model_config=config.model_config,
                cache_config=CacheConfig(block_size=16,
                                         num_gpu_blocks=256),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=2048, max_num_seqs=64,
                    max_model_len=2048, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            cfg.parallel_config.data_parallel_size = 2
            engine = LLMEngine(cfg, load_tokenizer=False)
            prompts = {s: list(base[s]) for s in range(sessions)}
            t0 = time.perf_counter()
            for t in range(turns):
                done = {}
                for s in range(sessions):
                    engine.add_request(f"{leg}-{t}-{s}",
                                       list(prompts[s]), sp)
                while engine.has_unfinished_requests():
                    for o in engine.step():
                        if o.finished:
                            done[o.request_id] = o
                for s in range(sessions):
                    toks = list(
                        done[f"{leg}-{t}-{s}"].outputs[0].token_ids)
                    prompts[s] = prompts[s] + toks + [extra[(t, s)]]
            wall = time.perf_counter() - t0
            stats = engine.get_stats()
            _stamp_engine_perf(record, f"routing_{leg}", stats=stats)
            kv = stats.get("kv_cache") or {}
            record[f"routing_{leg}_hit_rate_window"] = round(
                float(kv.get("window_hit_rate", 0.0)), 4)
            record[f"routing_{leg}_turns_per_s"] = round(
                sessions * turns / wall, 2)
            fe = getattr(engine.output_processor, "stats", None)
            if fe is not None and fe.slo_enabled and fe.slo_scored:
                record[f"routing_{leg}_goodput_frac"] = round(
                    fe.slo_good / fe.slo_scored, 4)
            if flag == "1":
                router = stats.get("router") or {}
                record["routing_affinity_hits"] = int(
                    router.get("affinity_hits", 0))
                record["routing_spillovers"] = int(
                    router.get("spillovers", 0))
            engine.shutdown()
            del engine
            gc.collect()
    finally:
        if saved is None:
            os.environ.pop("VDT_ROUTER", None)
        else:
            os.environ["VDT_ROUTER"] = saved


def _qos_leg(config, record) -> None:
    """Per-tenant QoS leg (ISSUE 13 acceptance): a two-tenant
    adversarial flood on ONE engine — an interactive tenant's short
    chat turns against a flood tenant's long-prompt greedy-max_tokens
    requests — QoS on vs ``VDT_QOS=0`` on byte-identical traffic.
    Reports the interactive tenant's p50/p99 inter-token latency
    (user-perceived: each back-to-back turn's queue wait counts as its
    first gap), per-tenant goodput against a fixed worst-stall target,
    and quota preemption counts per leg:
    the interactive p99 delta is the execution-isolation win the
    scheduler's DRR + quota machinery buys (fair placement and fair
    admission cannot provide it — this is in-scheduler starvation),
    directly comparable to ``vdt:tenant_goodput_frac`` in
    production."""
    import gc

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    rng = np.random.default_rng(13)
    # Rolling flood pipeline: a fresh 1408-token prompt (11 full budget
    # chunks at 128 tokens/step, then 4 decode tokens) is queued the
    # moment one finishes, 2 in flight, and floods keep coming until
    # every chat turn is done — so there is ALWAYS a flood
    # chunk-prefilling around the chat turns, the positional-starvation
    # shape the pre-QoS scheduler cannot defend (a budget-exhausting
    # chunk walls off every request behind it in the running list, and
    # the waiting loop never runs).  Prompts are unique per flood so
    # prefix caching cannot deduplicate the prefill work.
    flood_len, max_floods, flood_cap = 1408, 2, 60
    sessions, turns = 4, 4
    chat_prompts = {(s, t): [int(x) for x in
                             rng.integers(10, 5000, size=24)]
                    for s in range(sessions) for t in range(turns)}
    flood_sp = SamplingParams(temperature=0.0, max_tokens=4,
                              ignore_eos=True)
    chat_sp = SamplingParams(temperature=0.0, max_tokens=16,
                             ignore_eos=True)
    # Per-tenant goodput target: a chat turn is GOOD when no
    # inter-token stall exceeds this bound (computed bench-side for
    # BOTH legs so the off leg — whose metric plane is off by
    # definition — compares). Gap streams are USER-PERCEIVED: each
    # turn's first gap runs from add_request to its first token —
    # sessions issue turns back to back, so that queue wait IS the
    # inter-token stall the session sees, and it is exactly where the
    # pre-QoS scheduler hurts (a chunking flood walls the budget so
    # the waiting loop never runs; once admitted, arrival order
    # protects a decode in BOTH modes). ~2-3x a healthy CPU-smoke
    # step under a 128-token flood chunk.
    tpot_target_s = 1.0
    leg_wall_cap_s = 150.0
    saved = os.environ.get("VDT_QOS")
    try:
        for leg, flag in (("on", "1"), ("off", "0")):
            # Fresh identically-seeded stream per leg: flood/warmup
            # draw counts depend on leg timing, so a shared stream
            # would hand the second leg different prompt bytes.
            rng = np.random.default_rng(131)
            os.environ["VDT_QOS"] = flag
            cfg = EngineConfig(
                model_config=config.model_config,
                # Pool sized BELOW the rolling steady footprint
                # (one full 89-page flood + the next flood's first
                # chunks + four chat turns want ~110 pages) so
                # allocation fails under pressure and preemption must
                # run: QoS on quota-evicts the flood tenant (one flood
                # is alone over the soft 50% quota of 52 pages; chat
                # far under), QoS off capacity-evicts the newest
                # request — routinely an interactive chat turn.
                cache_config=CacheConfig(block_size=16,
                                         num_gpu_blocks_override=104),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=128, max_num_seqs=16,
                    max_model_len=2048, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            engine = LLMEngine(cfg, load_tokenizer=False)

            flood_idx = 0
            floods_alive: set[str] = set()
            done_turns = 0

            def add_flood():
                nonlocal flood_idx
                if flood_idx >= flood_cap or done_turns >= sessions * turns:
                    return
                rid = f"qos{leg}-flood-{flood_idx}"
                prompt = [int(x) for x in
                          rng.integers(10, 5000, size=flood_len)]
                engine.add_request(rid, prompt, flood_sp, priority=1,
                                   tenant="flood")
                floods_alive.add(rid)
                flood_idx += 1

            # Warmup wave (unmeasured): the SAME mixed composition as
            # the measured phase — 2 floods chunk-prefilling around 4
            # chat turns — so every graph bucket the measurement hits
            # (chunk + decode-batch mixes, preemption resumes) is
            # compiled here and first-compile stalls don't pollute p99.
            warm_alive = set()
            for _ in range(max_floods):
                add_flood()
            for s in range(sessions):
                rid = f"warm{leg}chat{s}"
                engine.add_request(rid,
                                   [int(x) for x in
                                    rng.integers(10, 5000, size=24)],
                                   chat_sp, priority=0, tenant="chat")
                warm_alive.add(rid)
            warm_alive |= floods_alive
            warm_deadline = time.perf_counter() + leg_wall_cap_s
            while (engine.has_unfinished_requests()
                   and time.perf_counter() < warm_deadline):
                for out in engine.step():
                    if out.finished:
                        warm_alive.discard(out.request_id)
                        floods_alive.discard(out.request_id)
            if warm_alive:  # wall-capped: nothing warm may leak into
                engine.abort_request(sorted(warm_alive))  # measurement
            floods_alive.clear()
            flood_idx = 0  # rids are namespaced per leg phase below

            def add_flood():  # noqa: F811 - measured-phase ids
                nonlocal flood_idx
                if flood_idx >= flood_cap or done_turns >= sessions * turns:
                    return
                rid = f"qos{leg}-mflood-{flood_idx}"
                prompt = [int(x) for x in
                          rng.integers(10, 5000, size=flood_len)]
                engine.add_request(rid, prompt, flood_sp, priority=1,
                                   tenant="flood")
                floods_alive.add(rid)
                flood_idx += 1

            # Floods first — chat turns always queue BEHIND a flood.
            for _ in range(max_floods):
                add_flood()
            add_times: dict[str, float] = {}
            for s in range(sessions):
                rid = f"qos{leg}-chat-{s}-0"
                add_times[rid] = time.perf_counter()
                engine.add_request(rid, list(chat_prompts[(s, 0)]),
                                   chat_sp, priority=0, tenant="chat")
            token_times: dict[str, list[float]] = {}
            deadline = time.perf_counter() + leg_wall_cap_s
            for _ in range(20000):
                if (done_turns >= sessions * turns
                        or time.perf_counter() > deadline):
                    break
                for out in engine.step():
                    rid = out.request_id
                    if "-chat-" in rid:
                        n = len(out.outputs[0].token_ids)
                        ts = token_times.setdefault(rid, [])
                        ts.extend([time.perf_counter()] * max(
                            n - len(ts), 0))
                    if not out.finished:
                        continue
                    if rid in floods_alive:
                        floods_alive.discard(rid)
                        add_flood()  # keep the interference rolling
                    elif "-chat-" in rid:
                        done_turns += 1
                        s, t = map(int, rid.rsplit("-", 2)[-2:])
                        if t + 1 < turns:
                            nxt = f"qos{leg}-chat-{s}-{t + 1}"
                            add_times[nxt] = time.perf_counter()
                            engine.add_request(
                                nxt, list(chat_prompts[(s, t + 1)]),
                                chat_sp, priority=0, tenant="chat")
            if floods_alive:
                engine.abort_request(sorted(floods_alive))
            tpots: list[float] = []
            per_turn_worst: dict[str, float] = {}
            for req, ts in token_times.items():
                # First gap: add_request -> first token (the queue
                # wait the session experiences between turns).
                gaps = [ts[0] - add_times[req]]
                gaps += [b - a for a, b in zip(ts, ts[1:])]
                gaps = [g for g in gaps if g > 0]  # same-step batches
                if gaps:
                    tpots += gaps
                    per_turn_worst[req] = max(gaps)
            tpots.sort()
            if tpots:
                record[f"qos_{leg}_chat_tpot_p50_ms"] = round(
                    1e3 * tpots[len(tpots) // 2], 1)
                record[f"qos_{leg}_chat_tpot_p99_ms"] = round(
                    1e3 * tpots[min(int(len(tpots) * 0.99),
                                    len(tpots) - 1)], 1)
            if add_times:
                # Denominator = every ISSUED turn: a turn that never
                # produced a token inside the wall cap (total
                # starvation, the worst outcome) counts as not-good.
                good = sum(1 for v in per_turn_worst.values()
                           if v <= tpot_target_s)
                record[f"qos_{leg}_chat_goodput_frac"] = round(
                    good / len(add_times), 3)
            # Wall-capped legs report partial turns — the off leg may
            # never finish the chat work inside the cap; that IS the
            # starvation result, so record how far it got.
            record[f"qos_{leg}_chat_turns_done"] = done_turns
            stats = engine.get_stats()
            causes = ((stats.get("kv_cache") or {})
                      .get("preemption_causes") or {})
            record[f"qos_{leg}_quota_preemptions"] = int(
                causes.get("quota", 0))
            record[f"qos_{leg}_preemptions"] = int(
                stats.get("num_preemptions", 0))
            tenants = stats.get("tenants") or {}
            for t in ("flood", "chat"):
                if t in tenants:
                    record[f"qos_{leg}_{t}_granted_tokens"] = int(
                        tenants[t]["granted_tokens"])
                    record[f"qos_{leg}_{t}_tenant_preemptions"] = int(
                        tenants[t]["preemptions"])
            _stamp_engine_perf(record, f"qos_{leg}", engine=engine)
            engine.shutdown()
            del engine
            gc.collect()
    finally:
        if saved is None:
            os.environ.pop("VDT_QOS", None)
        else:
            os.environ["VDT_QOS"] = saved


def _disagg_leg(config, record) -> None:
    """Disagg serving-tier leg (ROADMAP item 2 acceptance): a mixed
    long-prompt/chat workload on the SAME total device budget (a
    2-replica in-process DP fleet), disagg (1 prefill + 1 decode pool,
    VDT_DISAGG=1) vs monolithic, on byte-identical traffic. Records
    chat decode tok/s under long-prompt interference (the number disagg
    exists to protect — monolithic mixed waves pad every co-resident
    decode token to the prefill chunk's big bucket), TTFT p50/p95,
    handoff count/latency, fallback counters, and greedy parity. Two
    recovery drills ride along: disagg.handoff_stall -> local
    re-prefill on the decode home, and a prefill-replica death ->
    re-admission, each with its fallback counter recorded."""
    import gc

    import jax

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    if len(jax.devices()) < 2:
        record["disagg_leg_error"] = (
            "needs >= 2 devices for a 2-replica fleet")
        return
    rng = np.random.default_rng(11)
    chat_n, long_n = 4, 4
    chat_len, long_len = 16, 768
    chat_tokens, long_tokens = 32, 2
    budget = 256  # long prompts chunk through 3 waves each
    chat_sp = SamplingParams(temperature=0.0, max_tokens=chat_tokens,
                             ignore_eos=True)
    long_sp = SamplingParams(temperature=0.0, max_tokens=long_tokens,
                             ignore_eos=True)
    chats = [[int(x) for x in rng.integers(10, 5000, size=chat_len)]
             for _ in range(chat_n)]
    longs = [[int(x) for x in rng.integers(10, 5000, size=long_len)]
             for _ in range(long_n)]

    def build_engine():
        cfg = EngineConfig(
            model_config=config.model_config,
            cache_config=CacheConfig(block_size=16, num_gpu_blocks=512),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=budget, max_num_seqs=16,
                max_model_len=2048, num_scheduler_steps=1),
            load_config=LoadConfig(load_format="dummy"),
        )
        cfg.parallel_config.data_parallel_size = 2
        return LLMEngine(cfg, load_tokenizer=False)

    def drive(engine, tag):
        """One mixed wave: all requests at t0; returns (tokens by rid,
        chat decode tok/s, ttft list)."""
        t_add = {}
        for i, p in enumerate(longs):
            rid = f"{tag}-long-{i}"
            engine.add_request(rid, list(p), long_sp)
            t_add[rid] = time.perf_counter()
        for i, p in enumerate(chats):
            rid = f"{tag}-chat-{i}"
            engine.add_request(rid, list(p), chat_sp)
            t_add[rid] = time.perf_counter()
        first_tok, done, toks = {}, {}, {}
        chat_done_at = None
        while engine.has_unfinished_requests():
            outs = engine.step()
            now = time.perf_counter()
            for o in outs:
                rid = o.request_id
                if rid not in first_tok and o.outputs[0].token_ids:
                    first_tok[rid] = now
                if o.finished:
                    done[rid] = now
                    toks[rid] = list(o.outputs[0].token_ids)
                    if ("chat" in rid and all(
                            f"{tag}-chat-{i}" in done
                            for i in range(chat_n))):
                        chat_done_at = now
        t0 = min(first_tok[f"{tag}-chat-{i}"] for i in range(chat_n))
        chat_toks = sum(len(toks[f"{tag}-chat-{i}"]) - 1
                        for i in range(chat_n))
        tok_s = chat_toks / max(chat_done_at - t0, 1e-9)
        ttfts = sorted(first_tok[r] - t_add[r] for r in first_tok)
        return toks, tok_s, ttfts

    saved = os.environ.get("VDT_DISAGG")
    outputs = {}
    try:
        for leg, flag in (("mono", "0"), ("disagg", "1")):
            os.environ["VDT_DISAGG"] = flag
            engine = build_engine()
            drive(engine, f"{leg}warm")  # compile every shape first
            # Snapshot disagg stats AFTER the warm pass: its handoffs
            # pay XLA compilation on their first decode steps (seconds,
            # not ms) and would swamp the steady-state mean.
            warm = (engine.get_stats().get("disagg") or {}
                    if flag == "1" else {})
            toks, tok_s, ttfts = drive(engine, leg)
            outputs[leg] = {k.split("-", 1)[1]: v
                            for k, v in toks.items()}
            record[f"disagg_{leg}_chat_decode_tok_s"] = round(tok_s, 1)
            record[f"disagg_{leg}_ttft_p50_ms"] = round(
                ttfts[len(ttfts) // 2] * 1e3, 1)
            p95 = min(len(ttfts) - 1, -(-len(ttfts) * 19 // 20) - 1)
            record[f"disagg_{leg}_ttft_p95_ms"] = round(
                ttfts[p95] * 1e3, 1)
            if flag == "1":
                d = engine.get_stats().get("disagg") or {}
                wh = d.get("handoff_seconds") or {}
                w0 = warm.get("handoff_seconds") or {}
                record["disagg_handoffs"] = (
                    int(d.get("handoffs", 0))
                    - int(warm.get("handoffs", 0)))
                count = wh.get("count", 0) - w0.get("count", 0)
                if count > 0:
                    record["disagg_handoff_mean_ms"] = round(
                        (wh.get("sum", 0.0) - w0.get("sum", 0.0))
                        / count * 1e3, 1)
                record["disagg_fallbacks"] = d.get("fallbacks", {})
            _stamp_engine_perf(record, f"disagg_{leg}", engine=engine)
            engine.shutdown()
            del engine
            gc.collect()
        record["disagg_parity"] = outputs["mono"] == outputs["disagg"]
        record["disagg_vs_mono_chat_tok_s"] = round(
            record["disagg_disagg_chat_decode_tok_s"] /
            max(record["disagg_mono_chat_decode_tok_s"], 1e-9), 3)

        # --- drill 1: stalled handoff pull -> local re-prefill -------
        from vllm_distributed_tpu.utils import fault_injection as fi
        os.environ["VDT_DISAGG"] = "1"
        drill_prompts = [[int(x) for x in rng.integers(10, 5000,
                                                       size=48)]
                         for _ in range(2)]
        sp = SamplingParams(temperature=0.0, max_tokens=6,
                            ignore_eos=True)

        def drill_run(engine, tag):
            for i, p in enumerate(drill_prompts):
                engine.add_request(f"{tag}-{i}", list(p), sp)
            out = {}
            while engine.has_unfinished_requests():
                for o in engine.step():
                    if o.finished:
                        out[o.request_id] = list(
                            o.outputs[0].token_ids)
                time.sleep(0.001)
            return [out[f"{tag}-{i}"]
                    for i in range(len(drill_prompts))]

        os.environ["VDT_DISAGG"] = "0"
        engine = build_engine()
        drill_base = drill_run(engine, "dbase")
        engine.shutdown()
        os.environ["VDT_DISAGG"] = "1"
        engine = build_engine()
        engine.engine_core.clients[1].engine_core.scheduler \
            .kv_pull_timeout_s = 2.0
        fi.inject("disagg.handoff_stall")
        try:
            got = drill_run(engine, "dstall")
        finally:
            fi.clear("disagg.handoff_stall")
        d = engine.get_stats().get("disagg") or {}
        record["disagg_drill_stall_local_reprefill"] = int(
            (d.get("fallbacks") or {}).get("local_reprefill", 0))
        record["disagg_drill_stall_parity"] = got == drill_base
        engine.shutdown()
        gc.collect()

        # --- drill 2: prefill death mid-handoff -> re-admission ------
        if len(jax.devices()) < 3:
            record["disagg_drill_death_readmissions"] = (
                "needs >= 3 devices")
            return
        from vllm_distributed_tpu.engine.core_client import \
            EngineDeadError

        class _DeadProxy:
            def __getattr__(self, name):
                def _boom(*a, **k):
                    raise EngineDeadError("killed by bench drill")
                return _boom

        os.environ["VDT_DISAGG_PREFILL_REPLICAS"] = "2"
        cfg = EngineConfig(
            model_config=config.model_config,
            cache_config=CacheConfig(block_size=16, num_gpu_blocks=512),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=budget, max_num_seqs=16,
                max_model_len=2048, num_scheduler_steps=1),
            load_config=LoadConfig(load_format="dummy"),
        )
        cfg.parallel_config.data_parallel_size = 3
        engine = LLMEngine(cfg, load_tokenizer=False)
        client = engine.engine_core
        for i, p in enumerate(drill_prompts):
            engine.add_request(f"ddeath-{i}", list(p), sp)
        victim = min(client._owner[f"ddeath-{i}"]
                     for i in range(len(drill_prompts)))
        client.clients[victim] = _DeadProxy()
        out = {}
        while engine.has_unfinished_requests():
            for o in engine.step():
                if o.finished:
                    out[o.request_id] = list(o.outputs[0].token_ids)
            time.sleep(0.001)
        d = engine.get_stats().get("disagg") or {}
        record["disagg_drill_death_readmissions"] = int(
            (d.get("fallbacks") or {}).get("prefill_death", 0))
        record["disagg_drill_death_parity"] = [
            out[f"ddeath-{i}"] for i in range(len(drill_prompts))
        ] == drill_base
        engine.shutdown()
        del engine
        gc.collect()
    finally:
        os.environ.pop("VDT_DISAGG_PREFILL_REPLICAS", None)
        if saved is None:
            os.environ.pop("VDT_DISAGG", None)
        else:
            os.environ["VDT_DISAGG"] = saved


def _ssm_leg(record) -> None:
    """SSM state-cache leg (ROADMAP item 5 acceptance): multi-turn
    session traffic on a tiny dummy-weight Mamba model, state cache on
    vs VDT_SSM_STATE_CACHE=0 on byte-identical traffic. Reports turn
    throughput, resume-prefill tokens saved (the O(prompt) work the
    snapshot restores skipped), and — with the checkpoint journal
    armed — recovery-replay wall time after an injected
    engine_core.die, comparable across cache on/off because the tail
    re-prefill is the only difference."""
    import asyncio
    import gc
    import shutil
    import tempfile

    from transformers import MambaConfig

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, ModelConfig,
                                             SchedulerConfig)
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.utils import fault_injection as fi

    def make_config():
        mc = ModelConfig(model="dummy-ssm-bench", dtype="float32",
                         max_model_len=2048, skip_tokenizer_init=True)
        mc.hf_config = MambaConfig(
            vocab_size=2048, hidden_size=256, state_size=16,
            num_hidden_layers=4, conv_kernel=4, expand=2,
            time_step_rank=16, use_conv_bias=True, use_bias=False,
            architectures=["MambaForCausalLM"])
        cfg = EngineConfig(
            model_config=mc,
            cache_config=CacheConfig(block_size=16, num_gpu_blocks=512),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=1024, max_num_seqs=16,
                max_model_len=2048, num_scheduler_steps=1),
            load_config=LoadConfig(load_format="dummy"),
        )
        cfg.fault_tolerance_config.restart_backoff_base_s = 0.01
        cfg.fault_tolerance_config.restart_backoff_max_s = 0.05
        return cfg

    sessions, turns, gen_tokens = 4, 4, 16
    sp = SamplingParams(temperature=0.0, max_tokens=gen_tokens,
                        ignore_eos=True)
    rng = np.random.default_rng(11)
    base = {s: [int(x) for x in rng.integers(10, 2000, size=256)]
            for s in range(sessions)}
    extra = {(t, s): int(rng.integers(10, 2000))
             for t in range(turns) for s in range(sessions)}
    ckpt_dir = tempfile.mkdtemp(prefix="vdt_ssm_bench_")
    saved = {k: os.environ.get(k)
             for k in ("VDT_SSM_STATE_CACHE", "VDT_SSM_CKPT_INTERVAL",
                       "VDT_SSM_CKPT_DIR")}
    try:
        os.environ["VDT_SSM_CKPT_INTERVAL"] = "64"
        for leg, flag in (("on", "1"), ("off", "0")):
            os.environ["VDT_SSM_STATE_CACHE"] = flag
            os.environ.pop("VDT_SSM_CKPT_DIR", None)
            engine = LLMEngine(make_config(), load_tokenizer=False)
            prompts = {s: list(base[s]) for s in range(sessions)}
            t0 = time.perf_counter()
            for t in range(turns):
                done = {}
                for s in range(sessions):
                    engine.add_request(f"ssm-{leg}-{t}-{s}",
                                       list(prompts[s]), sp)
                while engine.has_unfinished_requests():
                    for o in engine.step():
                        if o.finished:
                            done[o.request_id] = o
                for s in range(sessions):
                    toks = list(
                        done[f"ssm-{leg}-{t}-{s}"].outputs[0].token_ids)
                    prompts[s] = prompts[s] + toks + [extra[(t, s)]]
            wall = time.perf_counter() - t0
            stats = engine.get_stats()
            _stamp_engine_perf(record, f"ssm_{leg}", stats=stats)
            record[f"ssm_{leg}_turns_per_s"] = round(
                sessions * turns / wall, 2)
            if flag == "1":
                record["ssm_resume_tokens_saved"] = int(
                    stats.get("ssm_resume_tokens_saved", 0))
                record["ssm_state_cache_hits"] = int(
                    stats.get("ssm_state_cache_hits", 0))
                record["ssm_checkpoints"] = int(
                    stats.get("ssm_checkpoints", 0))
            engine.shutdown()
            del engine
            gc.collect()

        # Recovery leg: die mid-decode on a long prompt; the journal
        # checkpoint bounds the replayed prefill to the interval tail.
        async def run_once(engine, rid):
            final, first = None, False
            async for out in engine.generate(
                    list(base[0]), sp, request_id=rid):
                if not first:
                    first = True
                    fi.inject("engine_core.die", max_fires=1)
                final = out
            return final

        for leg, flag in (("on", "1"), ("off", "0")):
            os.environ["VDT_SSM_STATE_CACHE"] = flag
            os.environ["VDT_SSM_CKPT_DIR"] = ckpt_dir
            engine = AsyncLLM(make_config(), load_tokenizer=False)
            try:
                t0 = time.perf_counter()
                asyncio.run(asyncio.wait_for(
                    run_once(engine, f"ssm-rec-{leg}"), timeout=300))
                record[f"ssm_recovery_{leg}_wall_s"] = round(
                    time.perf_counter() - t0, 3)
            finally:
                fi.clear("engine_core.die")
                engine.shutdown()
                del engine
                gc.collect()
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _mla_leg(record) -> None:
    """TPLA latent-sharding leg (ROADMAP item 4 acceptance): the same
    dummy DeepSeek config served TPLA on vs VDT_TPLA=0 at TP=2, with
    the latent page pool sized from ONE fixed synthetic HBM budget per
    leg (CPU exposes no memory stats, so the budget applies the
    worker's real per-rank page-bytes accounting explicitly). Reports
    pages fitted, max admitted concurrent MLA requests, decode tok/s
    and greedy token parity — the capacity headroom is the point; a
    real-TPU capture rides the standard record when a tunnel window
    opens."""
    import gc

    from transformers import DeepseekV2Config

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, ModelConfig,
                                             ParallelConfig,
                                             SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    tp = 2
    budget = 1 << 20  # synthetic per-device HBM budget for the pool
    n_reqs, prompt_len, gen_tokens = 24, 64, 16
    saved = os.environ.get("VDT_TPLA")

    def make_config(pages):
        mc = ModelConfig(model="dummy-dsv2-bench", dtype="float32",
                         max_model_len=256, skip_tokenizer_init=True)
        mc.hf_config = DeepseekV2Config(
            vocab_size=2048, hidden_size=128, intermediate_size=256,
            moe_intermediate_size=128, num_hidden_layers=3,
            num_attention_heads=8, num_key_value_heads=8,
            q_lora_rank=None, kv_lora_rank=64, qk_nope_head_dim=16,
            qk_rope_head_dim=8, v_head_dim=16, n_routed_experts=4,
            num_experts_per_tok=2, n_shared_experts=1,
            first_k_dense_replace=1, routed_scaling_factor=1.0,
            topk_method="greedy", n_group=1, topk_group=1,
            norm_topk_prob=False, max_position_embeddings=256,
            eos_token_id=1, head_dim=8,
            architectures=["DeepseekV2ForCausalLM"])
        return EngineConfig(
            model_config=mc,
            cache_config=CacheConfig(block_size=16,
                                     num_gpu_blocks_override=pages),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=256, max_num_seqs=n_reqs,
                max_model_len=256),
            parallel_config=ParallelConfig(tensor_parallel_size=tp),
            load_config=LoadConfig(load_format="dummy"),
        )

    rng = np.random.default_rng(23)
    prompts = [[int(x) for x in rng.integers(10, 2000, size=prompt_len)]
               for _ in range(n_reqs)]
    sp = SamplingParams(temperature=0.0, max_tokens=gen_tokens,
                       ignore_eos=True)
    outputs = {}
    try:
        for leg, flag in (("tpla", "1"), ("repl", "0")):
            os.environ["VDT_TPLA"] = flag
            # Pool sized by the worker's accounting at a FIXED budget:
            # page bytes shrink ~TP-fold with the latent sharded, so
            # the same budget fits ~TP x the pages -> more admitted
            # concurrency.
            cfg = make_config(16)  # probe config for page-bytes only
            probe = LLMEngine(cfg, load_tokenizer=False)
            runner = probe.engine_core.engine_core.executor.worker \
                .model_runner
            page_bytes = runner.model.kv_cache_page_bytes(16)
            shards = runner.model.tpla_shards
            probe.shutdown()
            del probe
            gc.collect()
            pages = budget // page_bytes
            record[f"mla_{leg}_page_bytes"] = int(page_bytes)
            record[f"mla_{leg}_pages"] = int(pages)
            record[f"mla_{leg}_latent_shards"] = int(shards)

            engine = LLMEngine(make_config(pages), load_tokenizer=False)
            for i, p in enumerate(prompts):
                engine.add_request(f"mla-{leg}-{i}", list(p), sp)
            done = {}
            max_running = 0
            t0 = time.perf_counter()
            while engine.has_unfinished_requests():
                for o in engine.step():
                    if o.finished:
                        done[o.request_id] = list(o.outputs[0].token_ids)
                max_running = max(
                    max_running,
                    int(engine.get_stats().get("num_running_reqs", 0)))
            wall = time.perf_counter() - t0
            outputs[leg] = [done[f"mla-{leg}-{i}"]
                            for i in range(n_reqs)]
            record[f"mla_{leg}_max_concurrent"] = max_running
            record[f"mla_{leg}_decode_tok_s"] = round(
                n_reqs * gen_tokens / wall, 1)
            _stamp_engine_perf(record, f"mla_{leg}", engine=engine)
            engine.shutdown()
            del engine
            gc.collect()
        record["mla_capacity_ratio"] = round(
            record["mla_tpla_pages"] / max(record["mla_repl_pages"], 1),
            2)
        record["mla_token_parity"] = outputs["tpla"] == outputs["repl"]
    finally:
        if saved is None:
            os.environ.pop("VDT_TPLA", None)
        else:
            os.environ["VDT_TPLA"] = saved


def _qcomm_leg(record) -> None:
    """Quantized-communication leg (ROADMAP item 2 acceptance):
    disaggregated prefill over the dcn_pull connector with the
    block-scaled int8 KV codec on vs VDT_QCOMM=0, on byte-identical
    traffic. Reports connector transfer bytes (the >= 3.5x reduction
    gate), greedy token parity, decode tokens/s on the consumer, and
    the consumer-side bytes-saved counter (credited after a successful
    decode)."""
    import gc
    import shutil
    import tempfile

    import torch
    from transformers import LlamaConfig
    from transformers import LlamaForCausalLM as HFLlama

    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.parallel import collectives
    from vllm_distributed_tpu.sampling_params import SamplingParams

    ckpt = tempfile.mkdtemp(prefix="vdt_qcomm_bench_")
    torch.manual_seed(0)
    HFLlama(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=512,
        eos_token_id=1)).eval().save_pretrained(
            ckpt, safe_serialization=True)

    def make_engine(role):
        return LLMEngine(EngineArgs(
            model=ckpt, dtype="float32", block_size=16,
            num_gpu_blocks_override=256, max_model_len=512,
            max_num_batched_tokens=512, max_num_seqs=8,
            skip_tokenizer_init=True, kv_connector="DCNPullConnector",
            kv_role=role,
            kv_connector_extra_config={"pull_port": 0},
        ).create_engine_config())

    def run(engine, prompts, tag, max_tokens):
        sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                            ignore_eos=True)
        for i, p in enumerate(prompts):
            engine.add_request(f"{tag}-{i}", p, sp)
        done = {}
        for _ in range(4000):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out
            if not engine.has_unfinished_requests():
                break
        order = sorted(done, key=lambda s: int(s.split("-")[-1]))
        return [done[k] for k in order]

    def transfer_bytes(engine):
        kv = (engine.get_stats().get("transport") or {}).get("kv") or {}
        return sum(int(e.get("tx_bytes", 0)) + int(e.get("rx_bytes", 0))
                   for conn, e in kv.items()
                   if isinstance(e, dict) and conn != "page_io")

    rng = np.random.default_rng(7)
    prompts = [[int(x) for x in rng.integers(2, 250, size=128)]
               for _ in range(8)]
    gen_tokens = 16
    saved = os.environ.get("VDT_QCOMM")
    outputs = {}
    try:
        for leg, flag in (("off", "0"), ("on", "1")):
            os.environ["VDT_QCOMM"] = flag
            collectives.refresh()
            producer = make_engine("kv_producer")
            prod_outs = run(producer, prompts, f"qprod-{leg}",
                            max_tokens=1)
            params = [o.kv_transfer_params for o in prod_outs]
            consumer = make_engine("kv_consumer")
            sp = SamplingParams(temperature=0.0, max_tokens=gen_tokens,
                                ignore_eos=True)
            t0 = time.perf_counter()
            for i, (p, kvp) in enumerate(zip(prompts, params)):
                consumer.add_request(f"qcons-{leg}-{i}", p, sp,
                                     kv_transfer_params=kvp)
            done = {}
            for _ in range(8000):
                for out in consumer.step():
                    if out.finished:
                        done[out.request_id] = out
                producer.step()
                if len(done) == len(prompts):
                    break
            wall = time.perf_counter() - t0
            outputs[leg] = [done[k].outputs[0].token_ids
                            for k in sorted(done)]
            record[f"qcomm_{leg}_transfer_bytes"] = (
                transfer_bytes(producer) + transfer_bytes(consumer))
            record[f"qcomm_{leg}_decode_tok_s"] = round(
                len(done) * gen_tokens / wall, 1)
            if flag == "1":
                # Savings are credited consumer-side on successful
                # decode (a degraded pull never counts).
                qc = (consumer.get_stats().get("transport")
                      or {}).get("qcomm") or {}
                record["qcomm_bytes_saved"] = int(
                    qc.get("dcn_pull", {}).get("bytes_saved", 0))
                record["qcomm_fallbacks"] = int(
                    qc.get("dcn_pull", {}).get("fallbacks", 0))
            _stamp_engine_perf(record, f"qcomm_{leg}", engine=consumer)
            producer.engine_core.shutdown()
            consumer.engine_core.shutdown()
            del producer, consumer
            gc.collect()
        on_b = max(record.get("qcomm_on_transfer_bytes", 0), 1)
        record["qcomm_transfer_bytes_ratio"] = round(
            record.get("qcomm_off_transfer_bytes", 0) / on_b, 2)
        record["qcomm_token_parity"] = outputs.get("on") == \
            outputs.get("off")
    finally:
        if saved is None:
            os.environ.pop("VDT_QCOMM", None)
        else:
            os.environ["VDT_QCOMM"] = saved
        collectives.refresh()
        shutil.rmtree(ckpt, ignore_errors=True)


def _phase_percentiles(engine, record) -> None:
    """p50/p95/p99 per lifecycle phase (queue/prefill/decode/...) from
    the output processor's timeline-derived durations — the per-request
    attribution the flat throughput number can't give."""
    processor = getattr(engine, "output_processor", None)
    banks = getattr(processor, "phase_durations", None) or {}
    for phase, samples in sorted(banks.items()):
        if not samples:
            continue
        arr = np.asarray(samples, np.float64) * 1e3  # ms
        for pct in (50, 95, 99):
            record[f"phase_{phase}_p{pct}_ms"] = round(
                float(np.percentile(arr, pct)), 3)


def _timeline_overhead_legs(config, prompts, sp, record) -> None:
    """Acceptance leg: the same decode workload with the lifecycle
    timeline enabled and disabled, both recorded, so the event
    recorder's overhead is bounded by measurement (target: within 2%).

    The 2-core container's run-to-run variance (~15% between identical
    legs) swamps a single-shot A/B, so each leg runs several timed
    rounds with the first DISCARDED (the first-timed engine pays
    residual compile/cache effects that would be misread as timeline
    overhead) and reports best-of-rest. Engines live sequentially —
    keeping two full-size KV pools resident skews whichever engine was
    built first."""
    import gc

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    batch = len(prompts)
    # The off leg drops the WHOLE observability surface (lifecycle
    # timeline + device + transport telemetry + the perf-attribution
    # plane), so timeline_overhead_frac bounds the full telemetry
    # plane, not just the event recorder.
    _SWITCHES = ("VDT_REQUEST_TIMELINE", "VDT_DEVICE_TELEMETRY",
                 "VDT_TRANSPORT_TELEMETRY", "VDT_PERF_ATTRIB")
    saved = {k: os.environ.get(k) for k in _SWITCHES}
    try:
        for leg, flag in (("timeline_on", "1"), ("timeline_off", "0")):
            for k in _SWITCHES:
                os.environ[k] = flag
            cfg = EngineConfig(
                model_config=config.model_config,
                cache_config=CacheConfig(block_size=16),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=2048, max_num_seqs=64,
                    max_model_len=2048, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            engine = LLMEngine(cfg, load_tokenizer=False)
            best = 0.0
            for rnd in range(4):
                tok_s, _ = _time_decode(engine, prompts, sp,
                                        f"{leg}-r{rnd}")
                if rnd > 0:
                    best = max(best, tok_s)
            record[f"{leg}_steps_per_s"] = round(best / batch, 2)
            # Off leg: the plane is disabled, so the stamp exercises
            # the analytic fallback path (mfu_source = "analytic").
            _stamp_engine_perf(record, leg, engine=engine,
                               hf=config.model_config.hf_overrides,
                               tok_s=best)
            if flag == "1" and not any(k.startswith("phase_")
                                       for k in record):
                # Fallback attribution only: when the headline run
                # already recorded phase percentiles (timeline on, the
                # default), this toy leg must not overwrite them.
                _phase_percentiles(engine, record)
            del engine
            gc.collect()
        on = record.get("timeline_on_steps_per_s")
        off = record.get("timeline_off_steps_per_s")
        if on and off:
            record["timeline_overhead_frac"] = round(1.0 - on / off, 4)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _trace_leg(config, prompts, sp, record) -> None:
    """Trace-plane acceptance leg (ISSUE 19), two halves:

    (a) overhead pair — the same decode workload with VDT_TRACE_PLANE
    off vs on, the lifecycle timeline ON in both legs so the delta
    isolates what the plane adds (minting, stamping, assembler feeds):
    ``trace_overhead_frac`` must stay <= 3% (lint_bench, schema v6).

    (b) stitched disagg run — a 2-replica prefill/decode fleet with the
    plane on must yield >= 1 trace carrying spans from BOTH replicas
    (``trace_stitched_traces``) and an explicit Perfetto flow link
    across the KV handoff (``trace_flow_links``), with the export
    JSON-serializable end to end."""
    import gc

    import jax

    from vllm_distributed_tpu import trace_plane
    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    batch = len(prompts)
    _SWITCHES = ("VDT_TRACE_PLANE", "VDT_REQUEST_TIMELINE", "VDT_DISAGG")
    saved = {k: os.environ.get(k) for k in _SWITCHES}
    try:
        os.environ["VDT_REQUEST_TIMELINE"] = "1"
        os.environ.pop("VDT_DISAGG", None)
        for leg, flag in (("trace_off", "0"), ("trace_on", "1")):
            os.environ["VDT_TRACE_PLANE"] = flag
            cfg = EngineConfig(
                model_config=config.model_config,
                cache_config=CacheConfig(block_size=16),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=2048, max_num_seqs=64,
                    max_model_len=2048, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            engine = LLMEngine(cfg, load_tokenizer=False)
            best = 0.0
            # Best-of-rest like _timeline_overhead_legs: the 2-core
            # container's run-to-run variance swamps a single-shot A/B.
            for rnd in range(4):
                tok_s, _ = _time_decode(engine, prompts, sp,
                                        f"{leg}-r{rnd}")
                if rnd > 0:
                    best = max(best, tok_s)
            record[f"{leg}_steps_per_s"] = round(best / batch, 2)
            del engine
            gc.collect()
        on = record.get("trace_on_steps_per_s")
        off = record.get("trace_off_steps_per_s")
        if on and off:
            record["trace_overhead_frac"] = round(1.0 - on / off, 4)

        # --- (b) one disagg request -> ONE stitched two-replica trace
        if len(jax.devices()) < 2:
            record["trace_leg_error"] = (
                "needs >= 2 devices for the disagg stitch")
            return
        os.environ["VDT_TRACE_PLANE"] = "1"
        os.environ["VDT_DISAGG"] = "1"
        rng = np.random.default_rng(19)
        cfg = EngineConfig(
            model_config=config.model_config,
            cache_config=CacheConfig(block_size=16, num_gpu_blocks=512),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=256, max_num_seqs=16,
                max_model_len=2048, num_scheduler_steps=1),
            load_config=LoadConfig(load_format="dummy"),
        )
        cfg.parallel_config.data_parallel_size = 2
        engine = LLMEngine(cfg, load_tokenizer=False)
        tsp = SamplingParams(temperature=0.0, max_tokens=4,
                             ignore_eos=True)
        tprompts = [[int(x) for x in rng.integers(10, 5000, size=48)]
                    for _ in range(2)]
        for i, p in enumerate(tprompts):
            engine.add_request(f"trace-{i}", list(p), tsp)
        while engine.has_unfinished_requests():
            engine.step()
            time.sleep(0.001)
        # The stats poll drains the core rings into the assembler
        # (clock-rebased + replica-tagged by the DP aggregator).
        engine.get_stats()
        asm = engine.output_processor.assembler
        stitched = flows = 0
        for tid in (asm.trace_ids() if asm is not None else []):
            t = asm.get(trace_id=tid)
            if t is None or not any(r.startswith("trace-")
                                    for r in t["request_ids"]):
                continue
            if asm.replica_count(t) >= 2:
                stitched += 1
            export = trace_plane.perfetto(t)
            json.dumps(export)  # must be Perfetto-valid JSON
            phs = [e.get("ph") for e in export["traceEvents"]]
            flows += min(phs.count("s"), phs.count("f"))
        record["trace_stitched_traces"] = stitched
        record["trace_flow_links"] = flows
        engine.shutdown()
        del engine
        gc.collect()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _find_runner(engine):
    """The model runner behind an in-process engine (None when the
    engine core runs out-of-process)."""
    try:
        return (engine.engine_core.engine_core.executor
                .worker.model_runner)
    except AttributeError:
        return None


def _mixed_batch_leg(config, prompts, sp, record) -> None:
    """Mega-kernel acceptance leg: decode tok/s while a chunked-prefill
    chunk shares every wave (the mixed-batch dispatch the unified kernel
    exists for), next to the same engine's pure-decode rate, plus the
    precompile lattice size and warmup seconds (the collapsed lattice
    must show up as fewer graphs / less warmup at unchanged buckets)."""
    import gc

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    batch = len(prompts)
    saved = os.environ.get("VDT_PRECOMPILE")
    os.environ["VDT_PRECOMPILE"] = "1"
    try:
        cfg = EngineConfig(
            model_config=config.model_config,
            cache_config=CacheConfig(block_size=16),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=256, max_num_seqs=64,
                max_model_len=2048, num_scheduler_steps=1),
            load_config=LoadConfig(load_format="dummy"),
        )
        t0 = time.perf_counter()
        engine = LLMEngine(cfg, load_tokenizer=False)
        record["precompile_seconds"] = round(time.perf_counter() - t0, 1)
    finally:
        if saved is None:
            os.environ.pop("VDT_PRECOMPILE", None)
        else:
            os.environ["VDT_PRECOMPILE"] = saved
    runner = _find_runner(engine)
    if runner is not None:
        record["precompile_graphs"] = int(
            getattr(runner, "precompile_graphs", 0))

    # Pure-decode reference on THIS engine (single-step scheduling, so
    # the comparison is decode-vs-decode at identical bucket configs).
    tok_s, _ = _time_decode(engine, prompts, sp, "mixpure")
    record["mixed_leg_pure_decode_tok_s"] = round(tok_s, 1)

    # Mixed waves: the decode streams run while ONE long prompt is
    # always chunk-prefilling alongside (max_tokens=1; replaced the
    # moment it finishes), so nearly every wave carries a prefill chunk
    # plus the running decodes.
    rng = np.random.default_rng(3)
    for i, p in enumerate(prompts):
        engine.add_request(f"mixd-{i}", p, sp)
    prod = {f"mixd-{i}": 0 for i in range(batch)}
    while any(v == 0 for v in prod.values()):
        for o in engine.step():
            if o.request_id in prod:
                prod[o.request_id] = len(o.outputs[0].token_ids)
    sp1 = SamplingParams(temperature=0.0, max_tokens=1, ignore_eos=True)
    start_toks = sum(prod.values())
    pending = None
    n_prefills = 0
    t0 = time.perf_counter()
    while any(v < sp.max_tokens for v in prod.values()):
        if pending is None:
            pending = f"mixp-{n_prefills}"
            n_prefills += 1
            engine.add_request(
                pending,
                [int(x) for x in rng.integers(10, 1000, size=512)], sp1)
        for o in engine.step():
            if o.request_id in prod:
                prod[o.request_id] = len(o.outputs[0].token_ids)
            elif o.finished and o.request_id == pending:
                pending = None
    mixed_time = time.perf_counter() - t0
    mixed_toks = sum(prod.values()) - start_toks
    while engine.has_unfinished_requests():
        engine.step()
    record["mixed_decode_tok_s"] = round(mixed_toks / mixed_time, 1)
    record["mixed_prefill_interference_frac"] = round(
        1.0 - (mixed_toks / mixed_time) / max(tok_s, 1e-9), 4)
    record["mixed_concurrent_prefills"] = n_prefills
    _stamp_engine_perf(record, "mixed", engine=engine)
    try:
        stats = engine.get_stats()
        calls = stats.get("attn_kernel_calls")
        if isinstance(calls, dict) and calls:
            record["attn_kernel_calls"] = {
                k: int(v) for k, v in sorted(calls.items())}
            # Per-LAYER dispatch counts: every layer of a step runs the
            # step's kernel family, so layers = steps x depth — the
            # number the fused-block leg compares against (how many
            # per-layer kernel invocations each family absorbed).
            runner = _find_runner(engine)
            depth = (int(runner.model.cfg.num_layers)
                     if runner is not None and runner.model is not None
                     else 0)
            if depth:
                record["kernel_dispatch_per_layer"] = {
                    k: int(v) * depth for k, v in sorted(calls.items())}
        if "block_fusion_calls" in stats:
            record["mixed_block_fusion_calls"] = int(
                stats["block_fusion_calls"])
            record["mixed_block_fusion_fallbacks"] = {
                k: int(v) for k, v in sorted(
                    (stats.get("block_fusion_fallbacks") or {}).items())}
    except Exception:  # noqa: BLE001 - diagnostic only
        pass
    del engine
    gc.collect()


def _block_fusion_leg(config, prompts, sp, record) -> None:
    """Fused decode-block acceptance leg (ISSUE 11): greedy decode
    tok/s and the per-layer kernel dispatch mix with VDT_BLOCK_FUSION
    on vs off, token parity asserted. On CPU this is a smoke (the
    Pallas kernels run in interpret mode, so the tok/s ratio is NOT the
    hardware story — the dispatch counts and parity are the signal);
    the real-TPU capture rides ROADMAP item 5."""
    import gc

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    import jax as _jax
    on_tpu = _jax.default_backend() == "tpu"
    keys = ("VDT_BLOCK_FUSION", "VDT_ATTENTION_BACKEND",
            "VDT_PALLAS_INTERPRET")
    saved = {k: os.environ.get(k) for k in keys}
    sp_g = SamplingParams(temperature=0.0, max_tokens=sp.max_tokens,
                          ignore_eos=True)
    tokens_by_leg = {}
    # f32 for the parity gate: this leg runs DUMMY random weights whose
    # greedy logit gaps sit near the bf16 rounding floor — at f32 the
    # gaps dwarf interpret-vs-XLA accumulation-order noise, so token
    # parity tests the kernel, not tie-breaking luck. (Real checkpoints
    # hold parity at serving dtype — the tier-1 engine gate pins that.)
    import dataclasses as _dc
    model_cfg = _dc.replace(config.model_config, dtype="float32")
    try:
        if not on_tpu:
            # The fused path only dispatches on the Pallas backend.
            os.environ["VDT_ATTENTION_BACKEND"] = "pallas"
            os.environ["VDT_PALLAS_INTERPRET"] = "1"
        for leg, flag in (("block_fusion_off", "0"),
                          ("block_fusion_on", "1")):
            os.environ["VDT_BLOCK_FUSION"] = flag
            cfg = EngineConfig(
                model_config=model_cfg,
                cache_config=CacheConfig(block_size=16),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=256, max_num_seqs=64,
                    max_model_len=2048, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            engine = LLMEngine(cfg, load_tokenizer=False)
            for i, p in enumerate(prompts):
                engine.add_request(f"{leg}-{i}", p, sp_g)
            toks = {f"{leg}-{i}": [] for i in range(len(prompts))}
            t0 = time.perf_counter()
            n_out = 0
            while engine.has_unfinished_requests():
                for o in engine.step():
                    if o.request_id in toks:
                        new = o.outputs[0].token_ids
                        n_out += len(new) - len(toks[o.request_id])
                        toks[o.request_id] = list(new)
            dt = time.perf_counter() - t0
            tokens_by_leg[leg] = [toks[f"{leg}-{i}"]
                                  for i in range(len(prompts))]
            record[f"{leg}_decode_tok_s"] = round(n_out / dt, 1)
            stats = engine.get_stats()
            calls = stats.get("attn_kernel_calls") or {}
            depth = 0
            runner = _find_runner(engine)
            if runner is not None and runner.model is not None:
                depth = int(runner.model.cfg.num_layers)
            record[f"{leg}_dispatch"] = {
                k: int(v) for k, v in sorted(calls.items())}
            if depth:
                record[f"{leg}_dispatch_per_layer"] = {
                    k: int(v) * depth for k, v in sorted(calls.items())}
            if flag == "1":
                record["block_fusion_calls"] = int(
                    stats.get("block_fusion_calls", 0))
                record["block_fusion_fallbacks"] = {
                    k: int(v) for k, v in sorted(
                        (stats.get("block_fusion_fallbacks")
                         or {}).items())}
            _stamp_engine_perf(record, leg, stats=stats)
            del engine
            gc.collect()
        parity = (tokens_by_leg["block_fusion_on"]
                  == tokens_by_leg["block_fusion_off"])
        record["block_fusion_token_parity"] = parity
        assert parity, "block fusion changed greedy output"
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _hist_percentile_ms(h, q: float):
    """Approximate percentile (ms) from a serialized histogram dict:
    the upper bound of the bucket where the cumulative count crosses
    q. +Inf tail falls back to the last finite bound."""
    if not isinstance(h, dict) or not h.get("count"):
        return None
    target = q * h["count"]
    cum = 0
    for bound, c in zip(h["buckets"], h["counts"]):
        cum += int(c)
        if cum >= target:
            return round(float(bound) * 1e3, 3)
    return round(float(h["buckets"][-1]) * 1e3, 3)


def _tiering_leg(config, record) -> None:
    """Hierarchical KV-memory acceptance leg (ISSUE 15): multi-turn
    session traffic whose combined prefix working set runs well past a
    PINNED device page budget (num_gpu_blocks_override — plain
    num_gpu_blocks is overwritten by profiling, the PR 13 trap), with
    VDT_KV_TIERING on vs off on byte-identical traffic. The host
    budget is sized to ~half the device pool so host-pool eviction
    exercises the disk tier too. Reports window hit rate, turns/s,
    promotion p50/p95, demotion bytes by tier, greedy parity, and the
    corrupt-spill drill (every disk read corrupted for one extra
    turn -> recompute, token-identical, misses counted)."""
    import gc
    import shutil
    import tempfile

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.utils import fault_injection as fi

    hf = config.model_config.hf_config
    block_size, pool_pages = 16, 64
    # Analytic per-page KV bytes of the bench model (checkpoint KV
    # heads, serving dtype) -> host budget = half the device pool, so
    # the session working set spills through BOTH tiers.
    head_dim = getattr(hf, "head_dim", None) or (
        hf.hidden_size // hf.num_attention_heads)
    dtype_bytes = 2 if config.model_config.dtype == "bfloat16" else 4
    page_bytes = (2 * hf.num_hidden_layers * hf.num_key_value_heads *
                  block_size * head_dim * dtype_bytes)
    host_mb = (pool_pages // 2) * page_bytes / 2**20

    # 8 sessions x 256-token base prompts = 2x the 1024-token pool at
    # turn 0, ~2.7x by the last turn: with tiering OFF every returning
    # session re-prefills (its pages were evicted for the other
    # sessions), ON restores the prefix from the tiers.
    sessions, turns = 8, 4
    rng = np.random.default_rng(15)
    base_prompts = [[int(x) for x in rng.integers(10, 5000, size=256)]
                    for _ in range(sessions)]
    sp_g = SamplingParams(temperature=0.0, max_tokens=16,
                          ignore_eos=True)
    keys = ("VDT_KV_TIERING", "VDT_KV_TIER_HOST_MB", "VDT_KV_TIER_DIR",
            "VDT_KV_TIER_DEMOTE_PAGES")
    saved = {k: os.environ.get(k) for k in keys}
    tier_dir = tempfile.mkdtemp(prefix="vdt_bench_kv_tier_")

    def run_turn(engine, prompts, outs, leg, turn):
        for s in range(sessions):
            engine.add_request(f"{leg}-s{s}t{turn}", list(prompts[s]),
                               sp_g)
        while engine.has_unfinished_requests():
            for o in engine.step():
                if o.finished:
                    outs[f"s{o.request_id.split('-s')[1]}"] = \
                        list(o.outputs[0].token_ids)
        for s in range(sessions):
            gen = outs[f"s{s}t{turn}"]
            prompts[s] = prompts[s] + gen + [
                int(x) for x in rng.integers(10, 5000, size=16)]

    outputs = {}
    prompts_by_leg = {}
    engines = {}
    try:
        for leg, flag in (("off", "0"), ("on", "1")):
            rng = np.random.default_rng(151)
            os.environ["VDT_KV_TIERING"] = flag
            os.environ["VDT_KV_TIER_HOST_MB"] = f"{host_mb:.4f}"
            os.environ["VDT_KV_TIER_DIR"] = tier_dir
            # A session wave can evict >64 pages in one admission
            # round; the default per-step demote cap would drop the
            # tail and starve the tiers the leg measures.
            os.environ["VDT_KV_TIER_DEMOTE_PAGES"] = "256"
            cfg = EngineConfig(
                model_config=config.model_config,
                cache_config=CacheConfig(
                    block_size=block_size,
                    num_gpu_blocks_override=pool_pages),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=256, max_num_seqs=8,
                    max_model_len=2048, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            engine = LLMEngine(cfg, load_tokenizer=False)
            engines[leg] = engine
            prompts = [list(p) for p in base_prompts]
            outs: dict = {}
            # Warmup turn (unmeasured): compiles every bucket the
            # measured turns hit; its prefixes also SEED the tier so
            # the measured window includes tier restores.
            run_turn(engine, prompts, outs, leg, 0)
            t0 = time.perf_counter()
            for turn in range(1, turns):
                run_turn(engine, prompts, outs, leg, turn)
            wall = time.perf_counter() - t0
            outputs[leg] = dict(outs)
            prompts_by_leg[leg] = prompts
            n_turns = sessions * (turns - 1)
            record[f"tiering_{leg}_turns_per_s"] = round(
                n_turns / wall, 3)
            stats = engine.get_stats()
            kv = stats.get("kv_cache") or {}
            record[f"tiering_{leg}_hit_rate_window"] = round(
                kv.get("window_hits", 0)
                / max(kv.get("window_queries", 0), 1), 4)
            if flag == "1":
                tier = stats.get("kv_tier") or {}
                record["tiering_promote_p50_ms"] = _hist_percentile_ms(
                    tier.get("promotion_seconds"), 0.50)
                record["tiering_promote_p95_ms"] = _hist_percentile_ms(
                    tier.get("promotion_seconds"), 0.95)
                for t in ("host", "disk"):
                    record[f"tiering_demotion_bytes_{t}"] = int(
                        (tier.get("demotion_bytes") or {}).get(t, 0))
                    record[f"tiering_promotions_{t}"] = int(
                        (tier.get("promotions") or {}).get(t, 0))
                record["tiering_pages_host"] = int(
                    (tier.get("pages") or {}).get("host", 0))
                record["tiering_pages_disk"] = int(
                    (tier.get("pages") or {}).get("disk", 0))
        # Session working set vs the pinned pool (the leg's premise).
        total_tokens = sum(len(p) for p in prompts_by_leg["on"])
        record["tiering_working_set_x"] = round(
            total_tokens / (pool_pages * block_size), 2)
        record["tiering_parity"] = outputs["on"] == outputs["off"]

        # Corrupt-spill drill: one extra turn with EVERY disk read
        # corrupted — tiering must degrade to recompute and stay
        # token-identical to the untiered engine's same turn.
        fi.registry.inject("kv_tier.spill_corrupt", rate=1.0)
        try:
            drill: dict = {}
            for leg in ("off", "on"):
                rng = np.random.default_rng(1515)
                outs: dict = {}
                run_turn(engines[leg], prompts_by_leg[leg], outs, leg,
                         turns)
                drill[leg] = outs
        finally:
            fi.clear("kv_tier.spill_corrupt")
        record["tiering_drill_spill_corrupt_parity"] = (
            drill["on"] == drill["off"])
        on_stats = engines["on"].get_stats()
        record["tiering_drill_disk_misses"] = int(
            ((on_stats.get("kv_tier") or {}).get("misses")
             or {}).get("disk", 0))
    finally:
        for e in engines.values():
            del e
        engines.clear()
        gc.collect()
        shutil.rmtree(tier_dir, ignore_errors=True)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _fleet_leg(config, record) -> None:
    """Elastic-fleet acceptance leg (ISSUE 16): a diurnal two-phase
    trace — an interactive peak wave, a trough, a second peak — on a
    2-replica in-process DP fleet with the controller ON (walks
    2 -> 1 -> 2: scale-in to the floor during the trough with the
    live stragglers journal-migrated, warm scale-out back into the
    retired slot at the second peak) vs ``VDT_FLEET=0`` (static 2
    replicas) on byte-identical traffic. Reports the settled replica
    count per phase, scale/freeze/wedge counters, warm-start pages,
    peak-phase request-latency p50/p99 per leg (the elastic leg's p99
    honestly includes the inline provisioning stall), and greedy token
    parity across the migrations — elasticity is contractually
    token-invisible."""
    import gc

    import jax

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    if len(jax.devices()) < 2:
        record["fleet_leg_error"] = (
            "needs >= 2 devices for a 2-replica DP fleet")
        return
    phases = (("peak1", 8), ("trough", 2), ("peak2", 8))
    sp = SamplingParams(temperature=0.0, max_tokens=16,
                        ignore_eos=True)
    rng = np.random.default_rng(16)
    prompts = {(ph, s): [int(x) for x in rng.integers(10, 5000,
                                                      size=64)]
               for ph, n in phases for s in range(n)}
    keys = ("VDT_FLEET", "VDT_FLEET_TICK_S", "VDT_FLEET_EVAL_TICKS",
            "VDT_FLEET_STALE_S", "VDT_FLEET_DRAIN_S",
            "VDT_FLEET_MIN_REPLICAS", "VDT_FLEET_MAX_REPLICAS",
            "VDT_FLEET_HIGH_WATERMARK", "VDT_FLEET_LOW_WATERMARK",
            "VDT_FLEET_ACTIONS")
    saved = {k: os.environ.get(k) for k in keys}
    outputs: dict = {}
    try:
        for leg, flag in (("on", "1"), ("off", "0")):
            os.environ.update({
                "VDT_FLEET": flag,
                "VDT_FLEET_TICK_S": "0",
                "VDT_FLEET_EVAL_TICKS": "3",
                "VDT_FLEET_STALE_S": "0",
                # Zero drain grace: retirement mid-trough must
                # journal-migrate the live stragglers; the parity
                # flag below is what proves that path token-exact.
                "VDT_FLEET_DRAIN_S": "0",
                "VDT_FLEET_MIN_REPLICAS": "1",
                "VDT_FLEET_MAX_REPLICAS": "2",
                # Peak occupancy on ONE replica is 1.0, on two it is
                # 0.5; the trough sits near 0.12 — the watermarks
                # bracket exactly the 2 -> 1 -> 2 walk.
                "VDT_FLEET_HIGH_WATERMARK": "0.7",
                "VDT_FLEET_LOW_WATERMARK": "0.2",
                "VDT_FLEET_ACTIONS": "20",
            })
            cfg = EngineConfig(
                model_config=config.model_config,
                cache_config=CacheConfig(block_size=16,
                                         num_gpu_blocks=256),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=1024, max_num_seqs=8,
                    max_model_len=512, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            cfg.parallel_config.data_parallel_size = 2
            engine = LLMEngine(cfg, load_tokenizer=False)
            outs: dict = {}
            peak_lat: list = []
            timeline: list = []
            t0 = time.perf_counter()
            for ph, n in phases:
                t_add = {}
                for s in range(n):
                    rid = f"{leg}-{ph}-{s}"
                    engine.add_request(rid, list(prompts[(ph, s)]), sp)
                    t_add[rid] = time.perf_counter()
                while engine.has_unfinished_requests():
                    for o in engine.step():
                        if o.finished:
                            outs[o.request_id] = list(
                                o.outputs[0].token_ids)
                            if ph != "trough":
                                peak_lat.append(
                                    (time.perf_counter()
                                     - t_add[o.request_id]) * 1e3)
                fleet = getattr(engine.engine_core, "fleet", None)
                if fleet is not None:
                    timeline.append(fleet.get_stats()["replicas"])
                    # Idle ticks settle an in-progress drain so the
                    # next phase starts from the converged fleet (the
                    # trace ends at the last phase: no trailing ticks,
                    # or the counters would show a post-trace retire).
                    if ph != phases[-1][0]:
                        for _ in range(8):
                            engine.engine_core._tick()
            wall = time.perf_counter() - t0
            outputs[leg] = outs
            n_reqs = sum(n for _, n in phases)
            record[f"fleet_{leg}_reqs_per_s"] = round(n_reqs / wall, 2)
            record[f"fleet_{leg}_req_p50_ms"] = round(
                float(np.percentile(peak_lat, 50)), 1)
            record[f"fleet_{leg}_req_p99_ms"] = round(
                float(np.percentile(peak_lat, 99)), 1)
            if flag == "1":
                stats = engine.get_stats()
                fs = stats.get("fleet") or {}
                record["fleet_replica_timeline"] = timeline
                record["fleet_scale_outs"] = int(fs.get("scale_outs",
                                                        0))
                record["fleet_scale_ins"] = int(fs.get("scale_ins", 0))
                record["fleet_warm_start_pages"] = int(
                    fs.get("warm_start_pages", 0))
                record["fleet_wedge_cycles"] = int(
                    fs.get("wedge_cycles", 0))
                record["fleet_freezes"] = {
                    k: int(v)
                    for k, v in (fs.get("freezes") or {}).items()}
                record["fleet_replica_failovers"] = int(
                    stats.get("replica_failovers", 0))
            engine.shutdown()
            del engine
            gc.collect()
        on = {k.split("-", 1)[1]: v for k, v in outputs["on"].items()}
        off = {k.split("-", 1)[1]: v
               for k, v in outputs["off"].items()}
        record["fleet_parity"] = on == off
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _canary_leg(config, record) -> None:
    """Correctness-sentinel acceptance leg (ISSUE 20): a 2-replica DP
    fleet with ``VDT_CORRECTNESS=1`` runs (a) a clean 60-probe canary
    soak — zero divergences tolerated (the false-positive budget is
    literally zero: a sentinel that cries wolf gets its quarantine feed
    ignored); (b) a seeded single-replica corruption drill — replica
    1's canary outputs are token-perturbed at the absorption point and
    the vote must isolate it within 3 probes, raise the suspect gauge
    for replica 1 ONLY, and emit a quarantine hint; (c) a plane-off
    overhead pair on byte-identical tenant traffic (the always-on cost
    is the per-step numerics tap; canary probes are interval-paced and
    amortize out), with greedy token parity — the sentinel is
    contractually invisible to tenant tokens."""
    import gc

    import jax

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    if len(jax.devices()) < 2:
        record["canary_leg_error"] = (
            "needs >= 2 devices for a 2-replica DP fleet")
        return
    keys = ("VDT_CORRECTNESS", "VDT_CANARY_INTERVAL_S",
            "VDT_CANARY_QUARANTINE_N", "VDT_NUMERICS_DRIFT_FRAC",
            "VDT_FLEET")
    saved = {k: os.environ.get(k) for k in keys}

    def make_engine():
        cfg = EngineConfig(
            model_config=config.model_config,
            cache_config=CacheConfig(block_size=16, num_gpu_blocks=256),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=1024, max_num_seqs=8,
                max_model_len=512, num_scheduler_steps=1),
            load_config=LoadConfig(load_format="dummy"),
        )
        cfg.parallel_config.data_parallel_size = 2
        return LLMEngine(cfg, load_tokenizer=False)

    def pump_probes(engine, plane, n, budget=4000):
        """Drive the DP output pump until ``n`` more canary probes have
        finished (get_output's tick both injects due probes and steps
        the replicas that hold them)."""
        target = sum(plane.probes.values()) + n
        while sum(plane.probes.values()) < target and budget > 0:
            engine.engine_core.get_output()
            budget -= 1
        return sum(plane.probes.values()) >= target

    try:
        os.environ.update({
            "VDT_CORRECTNESS": "1",
            # Interval 0: a fresh round every tick — the soak and the
            # drill are probe-count-paced, not wall-clock-paced.
            "VDT_CANARY_INTERVAL_S": "0",
            "VDT_CANARY_QUARANTINE_N": "2",
            # The drill perturbs tokens, not logits: keep the numerics
            # drift detector out of the attribution being scored.
            "VDT_NUMERICS_DRIFT_FRAC": "0",
            "VDT_FLEET": "0",
        })
        engine = make_engine()
        plane = getattr(engine.engine_core, "correctness", None)
        if plane is None:
            record["canary_leg_error"] = (
                "VDT_CORRECTNESS=1 built no correctness plane "
                "(single-replica engine?)")
            return
        # (a) Clean soak: 60 probes (30 rounds x 2 replicas), the first
        # round self-seeds the reference journal.
        if not pump_probes(engine, plane, 60):
            record["canary_leg_error"] = "soak stalled before 60 probes"
            return
        stats = plane.get_stats()
        record["canary_soak_probes"] = sum(stats["probes"].values())
        record["canary_false_positives"] = sum(
            sum(c.values()) for c in stats["divergences"].values())
        # (b) Corruption drill: perturb replica 1's canary tokens at
        # the absorption point (same engine — the journal is seeded).
        orig_absorb = plane.on_output

        def corrupted(out):
            if plane._replica_of(out.req_id) == 1 and out.new_token_ids:
                out.new_token_ids = [t + 1 for t in out.new_token_ids]
            orig_absorb(out)

        plane.on_output = corrupted
        p0 = plane.probes.get(1, 0)
        detection = None
        for _ in range(3):
            if not pump_probes(engine, plane, 2):
                break
            if detection is None and plane.divergences.get(1):
                detection = plane.probes.get(1, 0) - p0
        del plane.on_output
        stats = plane.get_stats()
        record["canary_detection_probes"] = detection
        record["canary_vote_attribution"] = (
            [i for i, v in stats["suspects"].items() if v] == [1])
        record["canary_quarantine_hint"] = (
            stats["quarantine_hints"] >= 1)
        engine.shutdown()
        del engine
        gc.collect()
        # (c) Overhead pair: plane on vs off, identical greedy traffic.
        # A long interval parks the canary injector so the measured
        # cost is the always-on numerics tap.
        os.environ["VDT_CANARY_INTERVAL_S"] = "3600"
        sp = SamplingParams(temperature=0.0, max_tokens=16,
                            ignore_eos=True)
        rng = np.random.default_rng(20)
        prompts = [[int(x) for x in rng.integers(10, 5000, size=64)]
                   for _ in range(8)]
        walls: dict = {}
        outs: dict = {}
        for leg, flag in (("on", "1"), ("off", "0")):
            os.environ["VDT_CORRECTNESS"] = flag
            engine = make_engine()
            # Warm pass (untimed: compiles, allocator steady state)
            # then best-of-3 — the pair measures the plane, not the
            # process's thermal noise.
            best = None
            got: dict = {}
            for rep in range(4):
                got = {}
                for s, p in enumerate(prompts):
                    engine.add_request(f"{leg}-{rep}-{s}", list(p), sp)
                t0 = time.perf_counter()
                while engine.has_unfinished_requests():
                    for o in engine.step():
                        if o.finished:
                            got[o.request_id.rsplit("-", 1)[1]] = list(
                                o.outputs[0].token_ids)
                wall = time.perf_counter() - t0
                if rep > 0 and (best is None or wall < best):
                    best = wall
            walls[leg] = best
            outs[leg] = got
            engine.shutdown()
            del engine
            gc.collect()
        record["canary_overhead_frac"] = round(
            walls["on"] / walls["off"] - 1.0, 4)
        record["canary_parity"] = outs["on"] == outs["off"]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _ha_leg(config, record) -> None:
    """HA control-plane acceptance leg (ISSUE 17): the fleet leg's
    diurnal trace on a 2-replica DP fleet with the lease-fenced shared
    controller ON (``VDT_FLEET_CONTROLLER=1``) and a second front-end
    controller standing by on the same coordinator socket and journal
    namespace. The leader is killed mid-scale-in
    (``fleet.controller_die`` fires between the drain's journaled
    intent and its completion); the standby acquires the lease within
    the TTL, replays the journal, finishes the retire, and runs the
    second peak's scale-out as the new leader. Records the leader
    transition count, merged fenced-action counters, the observed
    failover gap, journal replays, the replica timeline, and greedy
    token parity vs a static ``VDT_FLEET=0`` baseline on
    byte-identical traffic — leader failover is contractually
    token-invisible."""
    import gc
    import shutil
    import tempfile

    import jax

    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, SchedulerConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.utils import fault_injection as fi
    if len(jax.devices()) < 2:
        record["ha_leg_error"] = (
            "needs >= 2 devices for a 2-replica DP fleet")
        return
    phases = (("peak1", 8), ("trough", 2), ("peak2", 8))
    sp = SamplingParams(temperature=0.0, max_tokens=16,
                        ignore_eos=True)
    rng = np.random.default_rng(17)
    prompts = {(ph, s): [int(x) for x in rng.integers(10, 5000,
                                                      size=64)]
               for ph, n in phases for s in range(n)}
    keys = ("VDT_FLEET", "VDT_FLEET_CONTROLLER",
            "VDT_FLEET_LEASE_TTL_S", "VDT_FLEET_JOURNAL_DIR",
            "VDT_FLEET_TICK_S", "VDT_FLEET_EVAL_TICKS",
            "VDT_FLEET_STALE_S", "VDT_FLEET_DRAIN_S",
            "VDT_FLEET_MIN_REPLICAS", "VDT_FLEET_MAX_REPLICAS",
            "VDT_FLEET_HIGH_WATERMARK", "VDT_FLEET_LOW_WATERMARK",
            "VDT_FLEET_ACTIONS")
    saved = {k: os.environ.get(k) for k in keys}
    journal_dir = tempfile.mkdtemp(prefix="vdt-bench-ha-journal-")
    outputs: dict = {}
    try:
        for leg in ("on", "off"):
            os.environ.update({
                "VDT_FLEET": "1" if leg == "on" else "0",
                "VDT_FLEET_CONTROLLER": "1" if leg == "on" else "0",
                "VDT_FLEET_LEASE_TTL_S": "0.3",
                "VDT_FLEET_JOURNAL_DIR": journal_dir,
                "VDT_FLEET_TICK_S": "0",
                "VDT_FLEET_EVAL_TICKS": "3",
                "VDT_FLEET_STALE_S": "0",
                "VDT_FLEET_DRAIN_S": "0",
                "VDT_FLEET_MIN_REPLICAS": "1",
                "VDT_FLEET_MAX_REPLICAS": "2",
                "VDT_FLEET_HIGH_WATERMARK": "0.7",
                # Below the trough's in-flight occupancy (2/16): the
                # scale-in decision only accumulates on the IDLE ticks
                # driven manually below, so the leader kill lands
                # deterministically between the drain's journaled
                # intent and its completion.
                "VDT_FLEET_LOW_WATERMARK": "0.05",
                "VDT_FLEET_ACTIONS": "20",
            })
            cfg = EngineConfig(
                model_config=config.model_config,
                cache_config=CacheConfig(block_size=16,
                                         num_gpu_blocks=256),
                scheduler_config=SchedulerConfig(
                    max_num_batched_tokens=1024, max_num_seqs=8,
                    max_model_len=512, num_scheduler_steps=1),
                load_config=LoadConfig(load_format="dummy"),
            )
            cfg.parallel_config.data_parallel_size = 2
            engine = LLMEngine(cfg, load_tokenizer=False)
            dp = engine.engine_core
            standby = None
            outs: dict = {}
            timeline: list = []

            def _run_phase(ph: str, n: int) -> None:
                for s in range(n):
                    engine.add_request(f"{leg}-{ph}-{s}",
                                       list(prompts[(ph, s)]), sp)
                while engine.has_unfinished_requests():
                    for o in engine.step():
                        if o.finished:
                            outs[o.request_id] = list(
                                o.outputs[0].token_ids)
                    if standby is not None:
                        standby.tick()

            _run_phase(*phases[0])
            _run_phase(*phases[1])
            if leg == "on":
                from vllm_distributed_tpu.engine.control_plane import \
                    HAFleetController
                primary = dp.fleet
                timeline.append(primary.get_stats()["replicas"])
                # Idle ticks walk the trough's scale-in up to (not
                # past) the drain start: intent journaled, retire
                # incomplete.
                for _ in range(50):
                    dp._tick()
                    if primary._draining:
                        break
                if not primary._draining:
                    record["ha_leg_error"] = \
                        "trough scale-in never began a drain"
                    engine.shutdown()
                    return
                # Kill the leader mid-scale-in, then time the standby's
                # takeover (lease expiry + election + journal replay).
                fi.inject("fleet.controller_die", max_fires=1)
                try:
                    dp._tick()
                finally:
                    fi.clear("fleet.controller_die")
                t_dead = time.perf_counter()
                standby = HAFleetController(dp, dp.config,
                                            holder="fe-standby")
                while (not standby.is_leader
                       and time.perf_counter() - t_dead < 5.0):
                    standby.tick()
                    time.sleep(0.02)
                if not standby.is_leader:
                    record["ha_leg_error"] = \
                        "standby never acquired the lease"
                    engine.shutdown()
                    return
                record["ha_failover_gap_s"] = round(
                    time.perf_counter() - t_dead, 3)
                # The successor completes the journaled retire.
                for _ in range(20):
                    standby.tick()
                    if standby.get_stats()["replicas"] == 1:
                        break
                timeline.append(standby.get_stats()["replicas"])
            _run_phase(*phases[2])
            if leg == "on":
                st = standby.get_stats()
                timeline.append(st["replicas"])
                record["ha_replica_timeline"] = timeline
                record["ha_leader_transitions"] = int(
                    st["leader_transitions"])
                record["ha_journal_replays"] = int(
                    st["journal_replays"])
                fenced = dict(primary.fenced_actions)
                for a, n in st["fenced_actions"].items():
                    fenced[a] = fenced.get(a, 0) + int(n)
                record["ha_fenced_actions"] = {
                    a: int(n) for a, n in sorted(fenced.items())}
                standby.close()
            outputs[leg] = outs
            engine.shutdown()
            del engine
            gc.collect()
        on = {k.split("-", 1)[1]: v for k, v in outputs["on"].items()}
        off = {k.split("-", 1)[1]: v
               for k, v in outputs["off"].items()}
        record["ha_parity"] = on == off
    finally:
        shutil.rmtree(journal_dir, ignore_errors=True)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> None:
    from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                             LoadConfig, ModelConfig,
                                             SchedulerConfig,
                                             SpeculativeConfig)
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    # Llama-3.2-1B architecture with dummy weights (no checkpoint on the
    # bench host; compute cost is identical to real weights).
    hf_dims = (dict(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=2048,
        architectures=["LlamaForCausalLM"],
    ) if TINY else dict(
        vocab_size=128256, hidden_size=2048,
        intermediate_size=8192, num_hidden_layers=16,
        num_attention_heads=32, num_key_value_heads=8,
        head_dim=64, rope_theta=500000.0,
        max_position_embeddings=2048,
        architectures=["LlamaForCausalLM"],
    ))
    config = EngineConfig(
        model_config=ModelConfig(
            model="llama-3.2-1b-dummy",
            dtype="bfloat16",
            max_model_len=2048,
            hf_overrides=hf_dims,
        ),
        cache_config=CacheConfig(block_size=16),
        scheduler_config=SchedulerConfig(max_num_batched_tokens=2048,
                                         max_num_seqs=64,
                                         max_model_len=2048,
                                         num_scheduler_steps=16),
        load_config=LoadConfig(load_format="dummy"),
    )
    # Build the HF config locally (no hub access).
    from transformers import LlamaConfig
    config.model_config.hf_config = LlamaConfig(
        **config.model_config.hf_overrides)

    # SLO goodput leg: score the headline workload against TTFT/TPOT
    # targets (defaults sized for the TPU bench shape; operator-set
    # targets win). Read by the OutputProcessor at engine construction.
    os.environ.setdefault("VDT_SLO_TTFT_MS", "2000")
    os.environ.setdefault("VDT_SLO_TPOT_MS", "200")

    engine = LLMEngine(config, load_tokenizer=False)
    rng = np.random.default_rng(0)
    sp = SamplingParams(temperature=0.0, max_tokens=DECODE_STEPS,
                        ignore_eos=True)
    prompts = [[int(x) for x in rng.integers(10, 100000, size=PROMPT_LEN)]
               for _ in range(BATCH)]

    # Warmup: run the full workload once so every shape in the bench path
    # (batched prefill + the multi-step decode burst) is compiled before
    # timing starts (reference TPU runner precompiles its shape lattice,
    # tpu_model_runner.py:1248; here the same batch plays that role).
    for i, p in enumerate(prompts):
        engine.add_request(f"warmup-{i}", p, sp)
    while engine.has_unfinished_requests():
        engine.step()

    # Instrument the multi-step decode burst so the record separates
    # on-device time from host/scheduler overhead (the round-4 verdict
    # could not attribute the 0.68% MFU; now every TPU capture can).
    import jax
    runner = _find_runner(engine)
    device_decode = {"s": 0.0, "bursts": 0}
    if runner is not None and hasattr(runner, "_multi_step_fn"):
        orig_msf = runner._multi_step_fn

        def timed_msf(*a, **k):
            t0 = time.perf_counter()
            out = orig_msf(*a, **k)
            jax.block_until_ready(out[1])
            device_decode["s"] += time.perf_counter() - t0
            device_decode["bursts"] += 1
            return out

        runner._multi_step_fn = timed_msf

    for i, p in enumerate(prompts):
        engine.add_request(f"bench-{i}", p, sp)
    # Prefill phase (timed separately): step until every request emitted
    # its first token (matches the reference harness separating prefill
    # time from decode throughput, tknp_inference_benchmarks.py:66-90).
    produced = {f"bench-{i}": 0 for i in range(BATCH)}
    t_prefill = time.perf_counter()
    while any(v == 0 for v in produced.values()):
        for o in engine.step():
            produced[o.request_id] = len(o.outputs[0].token_ids)
    prefill_ms = (time.perf_counter() - t_prefill) * 1e3
    tokens_at_decode_start = sum(produced.values())
    t0 = time.perf_counter()
    while engine.has_unfinished_requests():
        for o in engine.step():
            produced[o.request_id] = len(o.outputs[0].token_ids)
    decode_time = time.perf_counter() - t0
    decode_tokens = sum(produced.values()) - tokens_at_decode_start
    decode_tok_s = decode_tokens / decode_time
    if runner is not None and hasattr(runner, "_multi_step_fn"):
        runner._multi_step_fn = orig_msf

    # Sampler microbench: one fused sample over [BATCH, V] — the
    # round-4 sampler sorted the full vocab every step; this leg keeps
    # its cost attributable.
    sampler_ms = None
    try:
        import jax.numpy as jnp

        from vllm_distributed_tpu.sample.metadata import SamplingMetadata
        from vllm_distributed_tpu.sample.sampler import sample_tokens
        V = hf_dims["vocab_size"]
        logits = jnp.asarray(
            rng.standard_normal((BATCH, V)), jnp.float32)
        md = SamplingMetadata(
            temperature=jnp.zeros((BATCH, )),
            top_k=jnp.zeros((BATCH, ), jnp.int32),
            top_p=jnp.ones((BATCH, )),
            min_p=jnp.zeros((BATCH, )),
            seeds=jnp.arange(BATCH, dtype=jnp.int64))
        jax.block_until_ready(sample_tokens(logits, md))  # compile
        t0 = time.perf_counter()
        for _ in range(20):
            out = sample_tokens(logits, md)
        jax.block_until_ready(out)
        sampler_ms = (time.perf_counter() - t0) / 20 * 1e3
    except Exception:  # noqa: BLE001 - diagnostic leg only
        pass

    backend = jax.devices()[0].platform
    is_tpu = backend not in ("cpu", )
    params = _model_params(hf_dims)
    # Decode MFU/MBU from the engine's analytic cost model (ISSUE 14):
    # FLOPs credit attention at the run's average context (the old
    # 2*params formula ignored attention and KV traffic entirely —
    # the unattributable 0.0068 of BENCH_tpu.json), bytes credit the
    # weight stream + per-sequence KV window + activations. The legacy
    # 2*params figure rides along as decode_mfu_2np so the old
    # scoreboard rows stay comparable.
    cm = _bench_cost_model(hf_dims)
    avg_ctx = PROMPT_LEN + DECODE_STEPS / 2
    mfu = (decode_tok_s * cm.decode_flops_per_token(avg_ctx)
           / cm.peak_flops) if is_tpu else None
    mfu_2np = ((decode_tok_s * 2 * params) / _peak_flops()
               if is_tpu else None)
    steps_per_s = decode_tok_s / BATCH
    mbu = (cm.decode_step_bytes(BATCH, avg_ctx) * steps_per_s
           / cm.peak_hbm) if is_tpu else None

    dev_s = device_decode["s"]
    record = {
        "metric": "decode_throughput_llama1b_bs8",
        # v7: _canary_leg fields (or canary_leg_error) join the v6
        # _trace_leg requirements — scripts/lint_bench.py keeps future
        # records machine-comparable.
        "schema_version": 7,
        "value": round(decode_tok_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(decode_tok_s / BASELINE_TOKS_PER_S, 3),
        "backend": "tpu" if is_tpu else "cpu-fallback",
        "device_kind": jax.devices()[0].device_kind,
        "prefill_ms_bs8": round(prefill_ms, 1),
        "prefill_mfu": round(
            (2 * params * BATCH * PROMPT_LEN) /
            (prefill_ms / 1e3) / _peak_flops(), 4) if is_tpu else None,
        "decode_mfu": round(mfu, 4) if mfu is not None else None,
        "decode_mfu_2np": (round(mfu_2np, 4)
                           if mfu_2np is not None else None),
        "decode_mbu": round(mbu, 4) if mbu is not None else None,
        "decode_device_s": round(dev_s, 3) if dev_s else None,
        "decode_host_s": round(decode_time - dev_s, 3)
        if dev_s else None,
        "decode_device_tok_s": round(decode_tokens / dev_s, 1)
        if dev_s else None,
        "sampler_step_ms": round(sampler_ms, 3)
        if sampler_ms is not None else None,
        "model_params": params,
    }
    if not is_tpu and _PROBE_LOG:
        record["probe_log"] = _PROBE_LOG[-4:]

    # Per-phase latency attribution of the headline run (queue/prefill/
    # decode p50/p95/p99 from the request-lifecycle timeline).
    _phase_percentiles(engine, record)

    # Robustness overhead tracking: the fault-tolerance layer's counters
    # ride every BENCH_*.json so a regression that starts tripping the
    # watchdog (or burning pull retries) on the bench workload is
    # visible next to the throughput it costs.
    try:
        rstats = engine.get_stats()
        record["watchdog_timeouts"] = int(
            rstats.get("watchdog_timeouts", 0))
        record["kv_pull_retries"] = int(rstats.get("kv_pull_retries", 0))
        record["kv_pull_failures"] = int(
            rstats.get("kv_pull_failures", 0))
        # Recovery-layer counters (PR 2): replica failovers show up in
        # DP bench legs; replay/shed stay 0 offline but keep the record
        # schema aligned with the serving /metrics families.
        record["replica_failovers"] = int(
            rstats.get("replica_failovers", 0))
        fstats = getattr(engine, "output_processor", None)
        record["requests_replayed"] = int(
            getattr(getattr(fstats, "stats", None),
                    "num_requests_replayed", 0))
        record["requests_shed"] = int(
            getattr(getattr(fstats, "stats", None),
                    "num_requests_shed", 0))
        # Telemetry plane (PR 5): SLO attainment at the measured load,
        # the device-memory high-water mark, and total KV-transfer
        # bytes (0 unless a connector leg ran).
        fe = getattr(fstats, "stats", None)
        if fe is not None and fe.slo_enabled:
            record["slo_ttft_target_ms"] = fe.slo_ttft_ms
            record["slo_tpot_target_ms"] = fe.slo_tpot_ms
            record["slo_requests_scored"] = fe.slo_scored
            record["slo_goodput_frac"] = round(
                fe.slo_good / max(fe.slo_scored, 1), 4)
        workers = rstats.get("workers") or {}
        peaks = [w.get("device_memory_peak_bytes", 0)
                 for w in workers.values() if isinstance(w, dict)]
        record["device_memory_peak_bytes"] = (max(peaks) if any(peaks)
                                              else None)
        record["recompiles"] = sum(
            int(w.get("num_recompiles", 0)) for w in workers.values()
            if isinstance(w, dict))
        # Engine-sourced utilization (ISSUE 14): the runner's own
        # charged-FLOPs-over-measured-device-time MFU/MBU — what a
        # real-TPU capture should be compared against, analytic
        # fallback when the plane is off.
        _stamp_engine_perf(record, "engine", stats=rstats, hf=hf_dims,
                           tok_s=decode_tok_s, avg_ctx=avg_ctx)
        record["model_flops_total"] = rstats.get("model_flops")
        # "page_io" is the device-side gather/scatter leg of the SAME
        # payloads the network/filesystem connectors move — summing it
        # in would double-count every transferred byte.
        kv_conn = (rstats.get("transport") or {}).get("kv") or {}
        record["kv_transfer_total_bytes"] = sum(
            int(e.get("tx_bytes", 0)) + int(e.get("rx_bytes", 0))
            for conn, e in kv_conn.items()
            if isinstance(e, dict) and conn != "page_io")
    except Exception:  # noqa: BLE001 - diagnostic leg only
        pass

    if is_tpu and not TINY:
        import gc
        del engine
        gc.collect()
        # Async-scheduling overlap legs (before the int4 leg mutates the
        # model config): steps_per_s + decode_overlap_frac trajectory.
        try:
            _async_overlap_legs(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["async_leg_error"] = f"{type(e).__name__}: {e}"
        # Timeline-overhead legs (observability acceptance: steps_per_s
        # with the event recorder on within 2% of off).
        try:
            _timeline_overhead_legs(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["timeline_leg_error"] = f"{type(e).__name__}: {e}"
        # Trace-plane leg: VDT_TRACE_PLANE overhead pair + a stitched
        # two-replica disagg trace with its Perfetto flow link.
        try:
            _trace_leg(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["trace_leg_error"] = f"{type(e).__name__}: {e}"
        # Mixed-batch leg: decode tok/s under chunked-prefill
        # interference + precompile graph count / warmup seconds.
        try:
            _mixed_batch_leg(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["mixed_leg_error"] = f"{type(e).__name__}: {e}"
        # Fused decode-block leg: tok/s + dispatch mix, fusion on vs
        # off, greedy token parity asserted (ISSUE 11).
        try:
            _block_fusion_leg(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["block_fusion_leg_error"] = f"{type(e).__name__}: {e}"
        # Routing leg: 2-replica fleet prefix-reuse, router vs RR.
        try:
            _routing_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["routing_leg_error"] = f"{type(e).__name__}: {e}"
        # QoS leg: two-tenant adversarial flood, VDT_QOS on vs off.
        try:
            _qos_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["qos_leg_error"] = f"{type(e).__name__}: {e}"
        # Disagg leg: two-pool fleet vs monolithic on a mixed
        # long-prompt/chat workload + both recovery drills.
        try:
            _disagg_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["disagg_leg_error"] = f"{type(e).__name__}: {e}"
        # SSM state-cache leg: multi-turn session traffic on a mamba
        # model, cache on vs off + recovery-replay wall time.
        try:
            _ssm_leg(record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["ssm_leg_error"] = f"{type(e).__name__}: {e}"
        # Hierarchical KV-memory leg: session working set past the
        # pinned device pool, tiering on vs off + corrupt-spill drill.
        try:
            _tiering_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["tiering_leg_error"] = f"{type(e).__name__}: {e}"
        # Elastic-fleet leg: diurnal 2 -> 1 -> 2 walk, controller on
        # vs static fleet, token parity across the migrations.
        try:
            _fleet_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["fleet_leg_error"] = f"{type(e).__name__}: {e}"
        # Correctness-sentinel leg: clean canary soak, seeded
        # single-replica corruption drill, plane-off overhead pair.
        try:
            _canary_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["canary_leg_error"] = f"{type(e).__name__}: {e}"
        # HA control-plane leg: leader killed mid-scale-in, standby
        # takes over inside the lease TTL, token parity across the
        # failover.
        try:
            _ha_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["ha_leg_error"] = f"{type(e).__name__}: {e}"
        # Quantized-communication leg: dcn_pull transfer bytes + parity
        # with the int8 KV codec on vs off.
        try:
            _qcomm_leg(record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["qcomm_leg_error"] = f"{type(e).__name__}: {e}"
        # TPLA leg: MLA latent-pool capacity + decode tok/s, sharded vs
        # replicated latent cache at a fixed HBM budget.
        try:
            _mla_leg(record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["mla_leg_error"] = f"{type(e).__name__}: {e}"
        # int4 leg: the fused dequant-GEMM path must BEAT bf16 decode
        # on-chip (VERDICT r4 #3's done criterion) — weight streaming
        # drops from 2 bytes to 4 bits per param.
        try:
            config.model_config.quantization = "int4"
            q_engine = LLMEngine(config, load_tokenizer=False)
            q_tok_s, _ = _time_decode(q_engine, prompts, sp, "qbench")
            record["int4_decode_tok_s"] = round(q_tok_s, 1)
            record["int4_vs_bf16"] = round(q_tok_s / decode_tok_s, 3)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["int4_error"] = f"{type(e).__name__}: {e}"

        # Spec-decode leg: ngram drafts on repetitive prompts (the
        # workload the proposer exists for). VERDICT r4 #2's done
        # criterion asks for an end-to-end decode-speedup signal; the
        # acceptance rate rides along so speedup is attributable.
        try:
            import gc
            del q_engine
            gc.collect()
            config.model_config.quantization = None
            config.speculative_config = SpeculativeConfig(
                method="ngram", num_speculative_tokens=3)
            s_engine = LLMEngine(config, load_tokenizer=False)
            pat = [int(x) for x in rng.integers(10, 5000, size=16)]
            rep_prompts = [list(pat) * (PROMPT_LEN // 16)
                           for _ in range(BATCH)]
            s_tok_s, _ = _time_decode(s_engine, rep_prompts, sp, "sbench")
            record["spec_ngram_decode_tok_s"] = round(s_tok_s, 1)
            stats = s_engine.get_stats()
            record["spec_acceptance"] = round(
                stats.get("spec_acceptance_rate", 0.0), 3)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["spec_error"] = f"{type(e).__name__}: {e}"
    else:
        # CPU smoke / tiny mode: the overlap legs are the acceptance
        # signal (decode_overlap_frac > 0 with steps_per_s no worse
        # than sync proves the pipeline overlaps host and device work).
        try:
            _async_overlap_legs(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["async_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _timeline_overhead_legs(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["timeline_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _trace_leg(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["trace_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _mixed_batch_leg(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["mixed_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _block_fusion_leg(config, prompts, sp, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["block_fusion_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _routing_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["routing_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _qos_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["qos_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _disagg_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["disagg_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _ssm_leg(record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["ssm_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _tiering_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["tiering_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _fleet_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["fleet_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _canary_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["canary_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _ha_leg(config, record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["ha_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _qcomm_leg(record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["qcomm_leg_error"] = f"{type(e).__name__}: {e}"
        try:
            _mla_leg(record)
        except Exception as e:  # noqa: BLE001 - diagnostic leg only
            record["mla_leg_error"] = f"{type(e).__name__}: {e}"
    _emit(record)


def _run_with_retries() -> Exception | None:
    """Run main() with backoff (transient Unavailable from a tunnelled
    chip — observed flaps last minutes, so later retries wait long);
    returns the last exception, or None on success."""
    last_err = None
    for backoff in (30, 90, None):
        try:
            main()
            return None
        except Exception as e:  # noqa: BLE001 - report, retry, fall back
            last_err = e
            traceback.print_exc()
            if backoff:
                time.sleep(backoff)
    return last_err


def _reexec_cpu_fallback() -> Exception | None:
    """Once main() has run, JAX backends are initialized and an in-process
    platform switch is a silent no-op — the CPU fallback after an
    accelerator failure must re-exec bench.py in a FRESH process."""
    env = dict(os.environ, VDT_BENCH_TINY="1")
    try:
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=900)
    except subprocess.TimeoutExpired:
        return RuntimeError("cpu fallback subprocess timed out")
    if out.returncode == 0 and out.stdout.strip():
        try:
            _emit(json.loads(out.stdout.strip().splitlines()[-1]))
            return None
        except ValueError:
            return RuntimeError("cpu fallback subprocess emitted non-JSON")
    return RuntimeError(f"cpu fallback subprocess rc={out.returncode}: "
                        f"{out.stderr[-400:]}")


if __name__ == "__main__":
    _install_backstop()
    if TINY:
        # CPU smoke mode: pin the platform so a tunnelled TPU plugin can't
        # hang backend init (the plugin ignores the JAX_PLATFORMS env var;
        # the worker's jax.config update is what wins).
        _enter_cpu_fallback()
        err = _run_with_retries()
    elif not _probe_accelerator():
        # Probe runs out-of-process, so this process is still jax-clean
        # and can pin CPU in-process.
        print("bench: no usable accelerator backend; CPU fallback "
              "(diagnostic only)", file=sys.stderr)
        _enter_cpu_fallback()
        err = _run_with_retries()
    else:
        err = _run_with_retries()
        if err is not None:
            print("bench: accelerator run failed; CPU fallback",
                  file=sys.stderr)
            err = _reexec_cpu_fallback()
    if err is not None:
        # Always emit a parseable JSON line with a diagnostic.
        _emit(_fallback_record(f"{type(err).__name__}: {err}"))
        sys.exit(0)
