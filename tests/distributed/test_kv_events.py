"""KV cache event publishing: an external subscriber sees BlockStored /
BlockRemoved as the prefix cache changes (model: reference
tests/distributed/test_events.py over kv_events.py)."""

import time

import pytest
import torch
import zmq
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine import serial
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import get_open_port


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_ev")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def test_block_events_published(checkpoint):
    port = get_open_port()
    endpoint = f"tcp://127.0.0.1:{port}"

    ctx = zmq.Context.instance()
    sub = ctx.socket(zmq.SUB)
    sub.setsockopt(zmq.SUBSCRIBE, b"kv-events")

    engine = LLMEngine(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=16, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True, enable_kv_cache_events=True,
        kv_events_endpoint=endpoint).create_engine_config())
    sub.connect(endpoint)
    time.sleep(0.3)  # PUB/SUB slow-joiner settle

    prompt = [3, 17, 92, 45, 8, 21, 33, 64, 90]  # 2 full pages
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    engine.add_request("e-0", prompt, sp)
    while engine.has_unfinished_requests():
        engine.step()

    events = []
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            _topic, _seq, payload = sub.recv_multipart(
                flags=zmq.NOBLOCK)
            events.extend(serial.unpack(payload)["events"])
        except zmq.Again:
            if any(e[0] == "stored" for e in events):
                break
            time.sleep(0.05)
    stored = [e for e in events if e[0] == "stored"]
    assert stored, "no BlockStored events received"
    # First stored block's tokens = the first full prompt page.
    assert stored[0][3] == prompt[:4]
    assert stored[0][2] is None  # no parent for the first page
    if len(stored) > 1:
        assert stored[1][2] == stored[0][1][0]  # chained parent hash

    # Fill the tiny pool with fresh prompts until eviction fires.
    for i in range(8):
        engine.add_request(f"f-{i}", [40 + i, 50 + i, 60 + i, 70 + i,
                                      80 + i], sp)
    while engine.has_unfinished_requests():
        engine.step()
    deadline = time.time() + 10
    removed = []
    while time.time() < deadline and not removed:
        try:
            _t, _s, payload = sub.recv_multipart(flags=zmq.NOBLOCK)
            removed += [e for e in serial.unpack(payload)["events"]
                        if e[0] == "removed"]
        except zmq.Again:
            time.sleep(0.05)
    assert removed, "no BlockRemoved events after cache pressure"

    engine.shutdown()
    sub.close(linger=0)
