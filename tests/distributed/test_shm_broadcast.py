"""Native shared-memory broadcast MessageQueue (model: the reference's
tests/distributed/test_shm_broadcast.py exercising ShmRingBuffer /
MessageQueue): FIFO broadcast to every reader, multi-chunk framing,
join handshake, and writer backpressure when a reader stalls."""

import os
import subprocess
import sys
import threading

import pytest

from vllm_distributed_tpu.distributed.shm_broadcast import (MessageQueue,
                                                            ShmRingError)


def _name(tag):
    return f"/vdt_shmtest_{tag}_{os.getpid()}"


def test_roundtrip_including_multichunk():
    name = _name("rt")
    w = MessageQueue.create(name, num_readers=1, chunk_size=64,
                            num_chunks=4)
    r = MessageQueue.join(name)
    msgs = ["hello", {"a": 1, "b": [2, 3]}, list(range(400)),
            b"x" * 5000, None]
    got = []
    t = threading.Thread(
        target=lambda: [got.append(r.dequeue(10)) for _ in msgs])
    t.start()
    for m in msgs:
        w.enqueue(m, timeout=10)
    t.join(20)
    assert not t.is_alive()
    assert got == msgs
    r.close()
    w.close()


def test_writer_handshake_times_out_without_readers():
    name = _name("hs")
    w = MessageQueue.create(name, num_readers=1)
    with pytest.raises(ShmRingError, match="readers joined"):
        w.enqueue("x", timeout=0.2)
    w.close()


def test_writer_blocks_on_stalled_reader():
    """Ring full + a reader that never drains -> bounded enqueue error,
    not silent overwrite (broadcast must be lossless)."""
    name = _name("bp")
    w = MessageQueue.create(name, num_readers=1, chunk_size=32,
                            num_chunks=2)
    r = MessageQueue.join(name)
    w.enqueue("a", timeout=5)
    w.enqueue("b", timeout=5)  # ring now full, reader consumed nothing
    with pytest.raises(ShmRingError, match="not drained"):
        w.enqueue("c", timeout=0.3)
    # Draining un-wedges the writer.
    assert r.dequeue(5) == "a"
    w.enqueue("c", timeout=5)
    assert r.dequeue(5) == "b"
    assert r.dequeue(5) == "c"
    r.close()
    w.close()


_READER = r"""
import sys
from vllm_distributed_tpu.distributed.shm_broadcast import MessageQueue
mq = MessageQueue.join(sys.argv[1], timeout=30)
got = []
while True:
    m = mq.dequeue(timeout=30)
    if m == "__done__":
        break
    got.append(m)
print("GOT", got, flush=True)
mq.close()
"""


def test_two_process_broadcast_every_reader_sees_every_message():
    name = _name("mp")
    w = MessageQueue.create(name, num_readers=2, chunk_size=128,
                            num_chunks=8)
    env = dict(os.environ, PYTHONPATH="/root/repo")
    procs = [
        subprocess.Popen([sys.executable, "-c", _READER, name],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True, env=env)
        for _ in range(2)
    ]
    msgs = [f"m{i}" for i in range(20)] + [{"big": "y" * 600}]
    for m in msgs:
        w.enqueue(m, timeout=30)
    w.enqueue("__done__", timeout=30)
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    w.close()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"reader {i} failed:\n{out[-2000:]}"
        assert f"GOT {msgs!r}"[:40] in out or str(msgs) in out, out[-500:]


def test_rejected_reader_does_not_wedge_the_writer():
    """A 65th registration must be refused WITHOUT bumping the reader
    count, or the writer's drained-by-all accounting can never be
    satisfied again (ADVICE round 3)."""
    name = _name("full")
    w = MessageQueue.create(name, num_readers=1, chunk_size=64,
                            num_chunks=4)
    readers = [MessageQueue.join(name) for _ in range(64)]
    with pytest.raises(ShmRingError, match="table full"):
        MessageQueue.join(name)
    # The failed join left accounting intact: broadcasting to the 64
    # registered readers still completes.
    w.enqueue("after-reject", timeout=10)
    assert readers[0].dequeue(10) == "after-reject"
    assert readers[63].dequeue(10) == "after-reject"
    for r in readers:
        r.close()
    w.close()
