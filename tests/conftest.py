"""Test config: force an 8-device virtual CPU mesh before JAX loads.

Mirrors the reference's layered-fake strategy (SURVEY.md §4): all scheduler/
KV-manager logic runs device-free; worker/model/kernel tests run on 8
virtual CPU devices so every multi-chip sharding path is exercised without
TPU hardware (reference TPU CI does the analogous thing with
xla_force_host_platform_device_count).
"""

import os

# The container has no DNS: hub lookups only ever time out, and the
# retry backoff costs ~72s per ModelConfig load. Force offline mode
# before transformers/huggingface_hub import anywhere in the session.
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")

# Force CPU even when the environment pre-sets a TPU platform: unit tests
# must run on the 8-device virtual CPU mesh, never the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
# Pallas kernels run in interpret mode on CPU.
os.environ.setdefault("VDT_PALLAS_INTERPRET", "1")

import jax  # noqa: E402

# The installed TPU plugin ignores JAX_PLATFORMS; the config flag wins.
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must not run on the TPU chip"

import pytest  # noqa: E402

from vllm_distributed_tpu.config import (CacheConfig, EngineConfig,
                                         ModelConfig, SchedulerConfig)
from vllm_distributed_tpu.request import Request
from vllm_distributed_tpu.sampling_params import SamplingParams

_REQ_COUNTER = [0]


def make_config(
    *,
    block_size: int = 4,
    num_blocks: int = 64,
    max_num_batched_tokens: int = 64,
    max_num_seqs: int = 8,
    max_model_len: int = 128,
    enable_prefix_caching: bool = True,
    enable_chunked_prefill: bool = True,
    policy: str = "fcfs",
) -> EngineConfig:
    cfg = EngineConfig(
        model_config=ModelConfig(model="dummy", max_model_len=max_model_len),
        cache_config=CacheConfig(
            block_size=block_size,
            num_gpu_blocks=num_blocks,
            enable_prefix_caching=enable_prefix_caching,
        ),
        scheduler_config=SchedulerConfig(
            max_num_batched_tokens=max_num_batched_tokens,
            max_num_seqs=max_num_seqs,
            max_model_len=max_model_len,
            enable_chunked_prefill=enable_chunked_prefill,
            policy=policy,
        ),
    )
    return cfg


def make_request(
    num_tokens: int = 8,
    *,
    req_id: str | None = None,
    max_tokens: int = 16,
    priority: int = 0,
    token_ids: list[int] | None = None,
    **sp_kwargs,
) -> Request:
    if req_id is None:
        _REQ_COUNTER[0] += 1
        req_id = f"req-{_REQ_COUNTER[0]}"
    if token_ids is None:
        # Unique tokens per request so tests don't hit the prefix cache
        # accidentally (pass token_ids explicitly to test sharing).
        base = 1000 * _REQ_COUNTER[0]
        token_ids = list(range(base + 1, base + num_tokens + 1))
    return Request(
        request_id=req_id,
        prompt_token_ids=token_ids,
        sampling_params=SamplingParams(temperature=0.0,
                                       max_tokens=max_tokens,
                                       **sp_kwargs),
        eos_token_id=2,
        priority=priority,
    )


@pytest.fixture
def config() -> EngineConfig:
    return make_config()


# ---------------------------------------------------------------------------
# Smoke tier: `pytest -m smoke` — a <5-min-on-1-core slice touching every
# subsystem (scheduler/KV control plane, sampler, a Pallas-interpret
# kernel, one engine parity, one connector, one server roundtrip, tool
# parsers). VERDICT r4 #4: a judge/CI box without many cores must be
# able to re-verify the stack cheaply; the full suite stays the long
# tier.
# ---------------------------------------------------------------------------

_SMOKE = {
    # module (relative to tests/): None = every test, else a name set.
    # Measured on a 1-core box: the device-free control-plane files run
    # in seconds; test_scheduler/test_token_parallel_sched take minutes
    # (full engine fixtures) and stay in the long tier.
    "core/test_block_pool.py": None,
    "core/test_kv_cache_manager.py": None,
    "sample/test_sampler.py": None,
    "ops/test_pallas_attention_small.py": None,
    "entrypoints/test_tool_parsers.py": None,
    "kv_transfer/test_shared_storage.py": {
        "test_producer_saves_consumer_skips_and_matches"},
    "entrypoints/test_openai_server.py": {"test_completion_token_parity",
                                          "test_spec_stats_render_in_metrics"},
    # Round-5 subsystems, engine-free fast slices.
    "kv_transfer/test_p2p_registry.py": {
        "test_registry_register_expire_and_leave"},
    "models/test_gguf.py": {"test_reader_roundtrip"},
    "models/test_qwen2_vl.py": {"test_mrope_positions_match_hf"},
    # Fault-tolerance layer: the engine-free slices (scheduler watchdog
    # unit + registry truncate survival) run in seconds.
    "test_fault_tolerance.py": {
        "test_watchdog_sweeps_stuck_remote_kv_hold",
        "test_registry_truncate_does_not_kill_heartbeat",
        "test_retry_policy_classification",
    },
}


def pytest_collection_modifyitems(config, items):
    import pathlib
    root = pathlib.Path(__file__).parent
    for item in items:
        try:
            rel = str(pathlib.Path(item.fspath).relative_to(root))
        except ValueError:
            continue
        names = _SMOKE.get(rel.replace("\\", "/"))
        if names is None and rel.replace("\\", "/") not in _SMOKE:
            continue
        base = item.name.split("[")[0]
        if names is None or base in names:
            item.add_marker(pytest.mark.smoke)
