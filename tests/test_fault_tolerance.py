"""Fault-tolerance layer: deterministic fault-injection tests.

Watchdog (WAITING_FOR_REMOTE_KVS deadline sweep), KV-pull retry /
degradation to local recompute, registry-heartbeat survival of malformed
responses, and engine-core death surfacing EngineDeadError — all driven
through the named fault points of utils/fault_injection with tight
injected timeouts (no real network/device faults needed)."""

import time

import pytest

from tests.conftest import make_config, make_request
from vllm_distributed_tpu.core.sched.output import ModelRunnerOutput
from vllm_distributed_tpu.core.sched.scheduler import Scheduler
from vllm_distributed_tpu.distributed.kv_transfer.base import (
    KVConnectorBase, KVConnectorRole)
from vllm_distributed_tpu.request import RequestStatus
from vllm_distributed_tpu.utils import fault_injection as fi
from vllm_distributed_tpu.utils.retry import (RetryBudgetExceeded,
                                              RetryPolicy, call_with_retry)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


# ---------------------------------------------------------------------------
# utils/retry.py
# ---------------------------------------------------------------------------

def test_retry_policy_classification():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0.0)
    assert call_with_retry(flaky, policy=policy) == "ok"
    assert calls["n"] == 3

    # Fatal (non-OSError) errors surface immediately, no retries.
    calls["n"] = 0

    def fatal():
        calls["n"] += 1
        raise ValueError("protocol violation")

    with pytest.raises(ValueError):
        call_with_retry(fatal, policy=policy)
    assert calls["n"] == 1

    # Exhausted budget raises RetryBudgetExceeded chained to the cause.
    def always_down():
        raise ConnectionRefusedError("down")

    with pytest.raises(RetryBudgetExceeded) as ei:
        call_with_retry(always_down, policy=policy)
    assert isinstance(ei.value.__cause__, ConnectionRefusedError)

    # Injected faults are classified fatal, not retryable.
    def injected():
        calls["n"] += 1
        raise fi.InjectedFault("injected fault: kv_pull.drop")

    calls["n"] = 0
    with pytest.raises(fi.InjectedFault):
        call_with_retry(injected, policy=policy)
    assert calls["n"] == 1


def test_fault_registry_deterministic_rates():
    fi.inject("kv_pull.drop", rate=0.5)
    fired = [fi.should_fire("kv_pull.drop") for _ in range(10)]
    assert sum(fired) == 5
    assert fired == [False, True] * 5  # deterministic, not random
    assert fi.counters()["kv_pull.drop"] == 5


# ---------------------------------------------------------------------------
# Scheduler watchdog (engine-free unit)
# ---------------------------------------------------------------------------

class _NeverDeliversConnector(KVConnectorBase):
    """Async connector that stages external loads and then never
    delivers a worker report — the exact hang the watchdog exists for."""

    def __init__(self, config, pages_external: int = 2) -> None:
        super().__init__(config, KVConnectorRole.SCHEDULER)
        self.block_size = config.cache_config.block_size
        self.pages_external = pages_external
        self.alloc_failures: set[str] = set()
        self.reset_calls: list[tuple[str, bool]] = []

    def get_num_new_matched_tokens(self, request, num_computed_tokens):
        if request.kv_transfer_params is None:
            return 0, False
        return self.pages_external * self.block_size, True

    def take_alloc_failures(self):
        failed, self.alloc_failures = self.alloc_failures, set()
        return failed

    def reset_for_retry(self, request, pull_resolved):
        self.reset_calls.append((request.request_id, pull_resolved))
        return False  # force degradation to local recompute


def _sweep_step(scheduler):
    out = scheduler.schedule()
    return scheduler.update_from_output(out, ModelRunnerOutput())


def test_watchdog_sweeps_stuck_remote_kv_hold():
    config = make_config()
    config.fault_tolerance_config.kv_pull_timeout_s = 0.05
    config.fault_tolerance_config.kv_pull_abandon_timeout_s = 0.1
    connector = _NeverDeliversConnector(config)
    scheduler = Scheduler(config, kv_connector=connector)
    free0 = scheduler.kv_cache_manager.block_pool.get_num_free_blocks()

    req = make_request(num_tokens=12, max_tokens=4)
    req.kv_transfer_params = {"remote": True}
    scheduler.add_request(req)

    _sweep_step(scheduler)
    assert req.request_id in scheduler.waiting_for_remote_kv
    assert req.status == RequestStatus.WAITING_FOR_REMOTE_KVS

    # Before the deadline the hold stays put.
    _sweep_step(scheduler)
    assert req.request_id in scheduler.waiting_for_remote_kv

    # Past the deadline the sweep requeues it: pages parked under a
    # tombstone (the never-reporting pull may still be in flight),
    # params cleared (connector refused a retry), request WAITING.
    time.sleep(0.06)
    out = scheduler.schedule()
    # The re-queued request must not re-enter the remote-KV path.
    scheduler.update_from_output(out, ModelRunnerOutput())
    assert req.request_id not in scheduler.waiting_for_remote_kv
    assert scheduler.watchdog_timeouts == 1
    assert scheduler.kv_pull_failures == 1
    assert req.kv_transfer_params is None
    assert connector.reset_calls == [(req.request_id, False)]
    assert req.request_id in scheduler.cancelled_remote_kv  # tombstone

    # The request now prefills LOCALLY (fresh pages, full prompt).
    out = scheduler.schedule()
    assert out.num_scheduled_tokens.get(req.request_id) == 12

    # The parked pages are reclaimed by the abandon backstop.
    time.sleep(0.11)
    _sweep_step(scheduler)
    assert req.request_id not in scheduler.cancelled_remote_kv
    # Finish the request: every page returns to the pool.
    scheduler.finish_requests(req.request_id,
                              RequestStatus.FINISHED_ABORTED)
    assert scheduler.kv_cache_manager.block_pool.get_num_free_blocks() \
        == free0


def test_alloc_failure_drains_to_requeue_without_deadline():
    """A connector-reported admission failure (P2P producer resolution
    failed after alloc) requeues on the NEXT sweep — no deadline wait."""
    config = make_config()
    config.fault_tolerance_config.kv_pull_timeout_s = 60.0  # never fires
    connector = _NeverDeliversConnector(config)
    scheduler = Scheduler(config, kv_connector=connector)

    req = make_request(num_tokens=12, max_tokens=4)
    req.kv_transfer_params = {"remote": True}
    scheduler.add_request(req)
    _sweep_step(scheduler)
    assert req.request_id in scheduler.waiting_for_remote_kv

    # The connector reports the admission failure (as the P2P connector
    # does when the producer vanished between finish and pull).
    req.kv_transfer_params = None
    connector.alloc_failures.add(req.request_id)
    _sweep_step(scheduler)
    assert req.request_id not in scheduler.waiting_for_remote_kv
    assert req.status == RequestStatus.WAITING
    assert scheduler.watchdog_timeouts == 0  # not a deadline sweep
    assert scheduler.kv_pull_failures == 1
    # No pull was staged, so no pages were parked.
    assert req.request_id not in scheduler.cancelled_remote_kv

    out = scheduler.schedule()
    assert out.num_scheduled_tokens.get(req.request_id) == 12


def test_watchdog_retries_pull_when_connector_allows():
    """When the connector CAN cleanly re-stage (worker definitively
    reported failure), the scheduler retries the pull — bounded by
    kv_pull_max_retries — before degrading."""

    class _RetriableConnector(_NeverDeliversConnector):
        def reset_for_retry(self, request, pull_resolved):
            self.reset_calls.append((request.request_id, pull_resolved))
            return True

    config = make_config()
    config.fault_tolerance_config.kv_pull_timeout_s = 60.0
    config.fault_tolerance_config.kv_pull_max_retries = 1
    connector = _RetriableConnector(config)
    scheduler = Scheduler(config, kv_connector=connector)

    req = make_request(num_tokens=12, max_tokens=4)
    req.kv_transfer_params = {"remote": True}
    scheduler.add_request(req)
    _sweep_step(scheduler)
    assert req.request_id in scheduler.waiting_for_remote_kv

    # Worker reports a failed pull: retry #1 re-enters the remote path.
    out = scheduler.schedule()
    scheduler.update_from_output(
        out, ModelRunnerOutput(failed_recving={req.request_id}))
    assert scheduler.kv_pull_retries == 1
    assert req.kv_transfer_params is not None
    _sweep_step(scheduler)  # re-admission stages the retry pull
    assert req.request_id in scheduler.waiting_for_remote_kv

    # Second failure exhausts the budget: degrade to local recompute.
    out = scheduler.schedule()
    scheduler.update_from_output(
        out, ModelRunnerOutput(failed_recving={req.request_id}))
    assert scheduler.kv_pull_retries == 1
    assert scheduler.kv_pull_failures == 2
    assert req.kv_transfer_params is None
    out = scheduler.schedule()
    assert out.num_scheduled_tokens.get(req.request_id) == 12


# ---------------------------------------------------------------------------
# Registry truncate -> heartbeat survival
# ---------------------------------------------------------------------------

def test_registry_truncate_does_not_kill_heartbeat():
    """A malformed registry response must not end heartbeating: the
    instance would silently expire while alive (ADVICE r5)."""
    from vllm_distributed_tpu.distributed.kv_transfer.p2p_registry import (
        P2PRegistryClient, P2PRegistryServer)
    srv = P2PRegistryServer()
    client = P2PRegistryClient(srv.address, "inst-ft", "producer",
                               ttl=0.6)
    try:
        client.register(("127.0.0.1", 4321), heartbeat=True)
        assert "inst-ft" in srv.members()
        # Two truncated responses: the client's msgpack decode raises
        # (a non-OSError the old heartbeat loop died on).
        fi.inject("registry.truncate", max_fires=2)
        deadline = time.time() + 3.0
        while time.time() < deadline and fi.counters().get(
                "registry.truncate", 0) < 2:
            time.sleep(0.05)
        assert fi.counters()["registry.truncate"] == 2
        # Past the TTL, the instance is still registered: heartbeats
        # survived the malformed responses and kept renewing.
        time.sleep(0.9)
        assert "inst-ft" in srv.members(), \
            "heartbeat daemon died on a malformed response"
        assert client._hb.is_alive()
    finally:
        client.leave()
        srv.shutdown()


# ---------------------------------------------------------------------------
# Engine-level: kv_pull.drop -> watchdog -> local recompute parity
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    import torch
    from transformers import LlamaConfig
    from transformers import LlamaForCausalLM as HFLlama
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_faults")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def _make_engine(path, **overrides):
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


PROMPT = [3, 17, 92, 45, 8, 21, 33, 64, 90]  # 9 tokens, 2 full pages


def _run_engine(engine, prompts, tag, max_tokens=6):
    from vllm_distributed_tpu.sampling_params import SamplingParams
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    return [done[k] for k in sorted(done)]


def test_kv_pull_drop_recovers_via_watchdog_local_recompute(checkpoint):
    """kv_pull.drop at 100%: the staged pull silently vanishes at the
    worker (no failed_recving report ever arrives), yet the request
    completes via local recompute within the watchdog deadline, with
    baseline-identical output."""
    import socket as _socket

    from vllm_distributed_tpu.sampling_params import SamplingParams
    baseline = _run_engine(_make_engine(checkpoint), [PROMPT],
                           "base")[0].outputs[0].token_ids

    fi.inject("kv_pull.drop")  # rate 1.0: every pull dropped
    consumer = _make_engine(checkpoint, kv_connector="DCNPullConnector",
                            kv_role="kv_consumer",
                            kv_connector_extra_config={"pull_port": 0},
                            kv_pull_timeout_s=0.3)
    sched = consumer.engine_core.engine_core.scheduler
    sched.kv_pull_abandon_timeout_s = 0.6
    # Valid-looking pull coordinates; the drop fires before any connect.
    holder = _socket.socket()
    holder.bind(("127.0.0.1", 0))
    params = {"remote_req_id": "ghost", "pull_host": "127.0.0.1",
              "pull_port": holder.getsockname()[1], "num_tokens": 8,
              "remote_page_ids": [0, 1]}
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    consumer.add_request("drop-0", PROMPT, sp, kv_transfer_params=params)

    consumer.step()
    assert "drop-0" in sched.waiting_for_remote_kv
    assert fi.counters()["kv_pull.drop"] >= 1

    # No request remains in WAITING_FOR_REMOTE_KVS past the deadline:
    # within a small margin of the 0.3s timeout the hold must be gone.
    done = {}
    t0 = time.time()
    hold_cleared_at = None
    while time.time() - t0 < 20.0:
        for out in consumer.step():
            if out.finished:
                done[out.request_id] = out
        if (hold_cleared_at is None
                and "drop-0" not in sched.waiting_for_remote_kv):
            hold_cleared_at = time.time() - t0
        if "drop-0" in done:
            break
        time.sleep(0.002)
    assert "drop-0" in done, "request never completed after dropped pull"
    assert hold_cleared_at is not None and hold_cleared_at < 5.0, \
        "hold outlived the watchdog deadline"
    assert sched.watchdog_timeouts == 1
    # Local recompute: byte-identical output, nothing counted as cached.
    assert done["drop-0"].outputs[0].token_ids == baseline
    assert done["drop-0"].num_cached_tokens == 0
    # Parked pages are reclaimed by the abandon backstop.
    t0 = time.time()
    while time.time() - t0 < 5.0 and sched.cancelled_remote_kv:
        consumer.step()
        time.sleep(0.01)
    assert not sched.cancelled_remote_kv
    stats = consumer.get_stats()
    assert stats["watchdog_timeouts"] == 1
    holder.close()


# ---------------------------------------------------------------------------
# Engine-level: engine_core.die -> EngineDeadError, not a hang
# ---------------------------------------------------------------------------

def test_engine_core_die_fails_pending_requests(checkpoint):
    """engine_core.die: pending requests surface a structured
    EngineDeadError through AsyncLLM within the heartbeat window —
    never a hang."""
    import asyncio

    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.engine.core_client import EngineDeadError
    from vllm_distributed_tpu.sampling_params import SamplingParams

    # restart_max_attempts=0: this test pins the TERMINAL death path
    # (recovery disabled); tests/test_crash_recovery.py covers the
    # supervisor respawn + replay path.
    engine = AsyncLLM(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True, restart_max_attempts=0,
        heartbeat_timeout_s=5.0).create_engine_config(),
        load_tokenizer=False)

    async def run():
        sp = SamplingParams(temperature=0.0, max_tokens=32,
                            ignore_eos=True)
        gen = engine.generate(PROMPT, sp, request_id="die-0")
        got_first = False
        async for _ in gen:
            if not got_first:
                got_first = True
                # The core is demonstrably serving; now kill it.
                fi.inject("engine_core.die", max_fires=1)
        return got_first

    try:
        with pytest.raises(EngineDeadError):
            asyncio.run(asyncio.wait_for(run(), timeout=60.0))
        assert engine.errored
        assert isinstance(engine.dead_error, EngineDeadError)
        # New requests are refused immediately with the same error.
        async def refused():
            async for _ in engine.generate(
                    PROMPT, SamplingParams(max_tokens=2),
                    request_id="after-death"):
                pass
        with pytest.raises(EngineDeadError):
            asyncio.run(refused())
    finally:
        engine.shutdown()


def test_background_core_silent_death_detected(checkpoint):
    """A core thread that exits without queueing its error (simulated)
    is still detected by the pump's health check — EngineDeadError, not
    an eternal block."""
    import asyncio

    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.engine.core_client import EngineDeadError
    from vllm_distributed_tpu.sampling_params import SamplingParams

    engine = AsyncLLM(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8, skip_tokenizer_init=True,
        restart_max_attempts=0).create_engine_config(),
        load_tokenizer=False)

    async def run():
        # Simulate an abrupt thread death that never reports: shut the
        # run loop down without marking _dead.
        engine.core.input_queue.put(("shutdown", None))
        deadline = time.time() + 10
        while engine.core._thread.is_alive() and time.time() < deadline:
            await asyncio.sleep(0.01)
        assert not engine.core._thread.is_alive()
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        async for _ in engine.generate(PROMPT, sp, request_id="h0"):
            pass

    try:
        with pytest.raises(EngineDeadError):
            asyncio.run(asyncio.wait_for(run(), timeout=30.0))
    finally:
        try:
            engine.shutdown()
        except Exception:
            pass
