"""CI guard: BENCH_*.json scoreboard records stay machine-comparable.

Runs scripts/lint_bench.py over the real repo records (tier-1
mechanical check) and unit-tests the linter's failure modes on
synthetic records: a tpu capture without decode_mfu, a schema>=2 tpu
capture without decode_mbu / engine-sourced fields, unparseable JSON,
and the driver-wrapper shape."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_bench.py"


def _run(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, str(SCRIPT), *args],
                          capture_output=True, text=True, timeout=60)


def _write(tmp_path, name: str, rec) -> None:
    (tmp_path / name).write_text(
        rec if isinstance(rec, str) else json.dumps(rec))


GOOD_V2_TPU = {
    "metric": "decode_throughput", "value": 448.1, "unit": "tok/s",
    "backend": "tpu", "schema_version": 2, "decode_mfu": 0.21,
    "decode_mbu": 0.63, "engine_mfu": 0.2, "engine_mbu": 0.6,
}

GOOD_V3_TPU = {
    **GOOD_V2_TPU, "schema_version": 3,
    "tiering_on_turns_per_s": 1.4, "tiering_off_turns_per_s": 1.1,
    "tiering_on_hit_rate_window": 0.7,
    "tiering_off_hit_rate_window": 0.4, "tiering_parity": True,
}

GOOD_V4_TPU = {
    **GOOD_V3_TPU, "schema_version": 4,
    "fleet_on_reqs_per_s": 3.1, "fleet_off_reqs_per_s": 3.4,
    "fleet_on_req_p99_ms": 410.0, "fleet_off_req_p99_ms": 350.0,
    "fleet_scale_outs": 1, "fleet_scale_ins": 1,
    "fleet_replica_timeline": [2, 1, 2], "fleet_parity": True,
}

GOOD_V5_TPU = {
    **GOOD_V4_TPU, "schema_version": 5,
    "ha_leader_transitions": 2, "ha_failover_gap_s": 0.31,
    "ha_journal_replays": 1, "ha_fenced_actions": {"resurrect": 1},
    "ha_replica_timeline": [2, 1, 2], "ha_parity": True,
}

GOOD_V6_TPU = {
    **GOOD_V5_TPU, "schema_version": 6,
    "trace_overhead_frac": 0.011, "trace_stitched_traces": 2,
    "trace_flow_links": 2,
}

GOOD_V7_TPU = {
    **GOOD_V6_TPU, "schema_version": 7,
    "canary_soak_probes": 60, "canary_false_positives": 0,
    "canary_detection_probes": 1, "canary_vote_attribution": True,
    "canary_quarantine_hint": True, "canary_overhead_frac": 0.009,
    "canary_parity": True,
}


def test_repo_records_are_clean():
    res = _run()
    assert res.returncode == 0, (
        f"BENCH record schema drifted:\n{res.stderr}")


def test_good_v2_record_passes(tmp_path):
    _write(tmp_path, "BENCH_x.json", GOOD_V2_TPU)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_tpu_record_without_mfu_fails(tmp_path):
    rec = dict(GOOD_V2_TPU)
    del rec["decode_mfu"]
    del rec["schema_version"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "decode_mfu" in res.stderr


def test_v2_tpu_record_without_mbu_fails(tmp_path):
    rec = dict(GOOD_V2_TPU)
    del rec["decode_mbu"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "decode_mbu" in res.stderr


def test_v2_record_without_engine_perf_fails(tmp_path):
    rec = dict(GOOD_V2_TPU)
    del rec["engine_mbu"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "engine_mbu" in res.stderr


def test_v1_cpu_record_is_grandfathered(tmp_path):
    # Pre-plane records carry no schema_version and no mfu on CPU.
    _write(tmp_path, "BENCH_old.json", {
        "metric": "decode_throughput", "value": 385.0,
        "unit": "tok/s", "backend": "cpu-fallback"})
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_unparseable_and_bad_backend_fail(tmp_path):
    _write(tmp_path, "BENCH_broken.json", "{not json")
    _write(tmp_path, "BENCH_weird.json", {
        "metric": "m", "value": 1.0, "unit": "tok/s",
        "backend": "quantum"})
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "unparseable" in res.stderr
    assert "quantum" in res.stderr


def test_wrapper_shape_validates_payload(tmp_path):
    # rc!=0 with parsed=null is a capture failure, not schema drift...
    _write(tmp_path, "BENCH_fail.json",
           {"n": 1, "rc": 1, "parsed": None})
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr
    # ...but an rc=0 wrapper must carry a record, and the payload is
    # held to the same schema.
    _write(tmp_path, "BENCH_empty.json",
           {"n": 2, "rc": 0, "parsed": None})
    bad = dict(GOOD_V2_TPU, decode_mfu=7.0)
    _write(tmp_path, "BENCH_wrap.json", {"n": 3, "rc": 0,
                                         "parsed": bad})
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "no parsed record" in res.stderr
    assert "decode_mfu" in res.stderr


def test_good_v3_record_passes(tmp_path):
    _write(tmp_path, "BENCH_x.json", GOOD_V3_TPU)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_v3_record_without_tiering_fields_fails(tmp_path):
    rec = dict(GOOD_V3_TPU)
    del rec["tiering_on_turns_per_s"]
    del rec["tiering_parity"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "tiering_on_turns_per_s" in res.stderr
    assert "tiering_parity" in res.stderr


def test_v3_parity_false_fails(tmp_path):
    # Tiering is contractually token-invisible: a recorded parity
    # failure is schema drift, not a shrug.
    _write(tmp_path, "BENCH_x.json",
           dict(GOOD_V3_TPU, tiering_parity=False))
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "token-invisible" in res.stderr


def test_good_v4_record_passes(tmp_path):
    _write(tmp_path, "BENCH_x.json", GOOD_V4_TPU)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_v4_record_without_fleet_fields_fails(tmp_path):
    rec = dict(GOOD_V4_TPU)
    del rec["fleet_on_reqs_per_s"]
    del rec["fleet_replica_timeline"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "fleet_on_reqs_per_s" in res.stderr
    assert "fleet_replica_timeline" in res.stderr


def test_v4_fleet_parity_false_fails(tmp_path):
    # Elasticity is contractually token-invisible — a migration that
    # changed a token is a correctness bug the scoreboard must flag.
    _write(tmp_path, "BENCH_x.json",
           dict(GOOD_V4_TPU, fleet_parity=False))
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "token-invisible" in res.stderr


def test_v4_fleet_leg_error_is_accepted(tmp_path):
    rec = {k: v for k, v in GOOD_V4_TPU.items()
           if not k.startswith("fleet_")}
    rec["fleet_leg_error"] = "RuntimeError: needs >= 2 devices"
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr
    rec["fleet_leg_error"] = ""
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1


def test_good_v5_record_passes(tmp_path):
    _write(tmp_path, "BENCH_x.json", GOOD_V5_TPU)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_v5_record_without_ha_fields_fails(tmp_path):
    rec = dict(GOOD_V5_TPU)
    del rec["ha_leader_transitions"]
    del rec["ha_journal_replays"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "ha_leader_transitions" in res.stderr
    assert "ha_journal_replays" in res.stderr


def test_v5_ha_parity_false_fails(tmp_path):
    # Leader failover is contractually token-invisible — a takeover
    # that changed a stream is a correctness bug, not a shrug.
    _write(tmp_path, "BENCH_x.json",
           dict(GOOD_V5_TPU, ha_parity=False))
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "token-invisible" in res.stderr


def test_v5_ha_leg_error_is_accepted(tmp_path):
    rec = {k: v for k, v in GOOD_V5_TPU.items()
           if not k.startswith("ha_")}
    rec["ha_leg_error"] = "RuntimeError: needs >= 2 devices"
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr
    rec["ha_leg_error"] = ""
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1


def test_good_v6_record_passes(tmp_path):
    _write(tmp_path, "BENCH_x.json", GOOD_V6_TPU)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_v6_record_without_trace_fields_fails(tmp_path):
    rec = dict(GOOD_V6_TPU)
    del rec["trace_stitched_traces"]
    del rec["trace_flow_links"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "trace_stitched_traces" in res.stderr
    assert "trace_flow_links" in res.stderr


def test_v6_overhead_above_budget_fails(tmp_path):
    # The ISSUE 19 acceptance bound: the trace plane may cost at most
    # 3% of decode steps/s; a hotter capture is a regression.
    _write(tmp_path, "BENCH_x.json",
           dict(GOOD_V6_TPU, trace_overhead_frac=0.08))
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "trace_overhead_frac" in res.stderr


def test_v6_trace_leg_error_is_accepted(tmp_path):
    rec = {k: v for k, v in GOOD_V6_TPU.items()
           if not k.startswith("trace_")}
    rec["trace_leg_error"] = "RuntimeError: needs >= 2 devices"
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr
    rec["trace_leg_error"] = ""
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1


def test_good_v7_record_passes(tmp_path):
    _write(tmp_path, "BENCH_x.json", GOOD_V7_TPU)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr


def test_v7_record_without_canary_fields_fails(tmp_path):
    rec = dict(GOOD_V7_TPU)
    del rec["canary_soak_probes"]
    del rec["canary_vote_attribution"]
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "canary_soak_probes" in res.stderr
    assert "canary_vote_attribution" in res.stderr


def test_v7_false_positives_and_slow_detection_fail(tmp_path):
    # The sentinel's acceptance bounds are hard: ANY false positive on
    # the clean soak, or detection slower than 3 probes, is drift.
    _write(tmp_path, "BENCH_a.json",
           dict(GOOD_V7_TPU, canary_false_positives=1))
    _write(tmp_path, "BENCH_b.json",
           dict(GOOD_V7_TPU, canary_detection_probes=7))
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
    assert "canary_false_positives" in res.stderr
    assert "canary_detection_probes" in res.stderr


def test_v7_canary_leg_error_is_accepted(tmp_path):
    rec = {k: v for k, v in GOOD_V7_TPU.items()
           if not k.startswith("canary_")}
    rec["canary_leg_error"] = "RuntimeError: needs >= 2 devices"
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr
    rec["canary_leg_error"] = ""
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1


def test_v3_leg_error_is_accepted(tmp_path):
    rec = {k: v for k, v in GOOD_V3_TPU.items()
           if not k.startswith("tiering_")}
    rec["tiering_leg_error"] = "RuntimeError: no devices"
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 0, res.stderr
    # ...but an empty error string is not an excuse.
    rec["tiering_leg_error"] = ""
    _write(tmp_path, "BENCH_x.json", rec)
    res = _run("--dir", str(tmp_path))
    assert res.returncode == 1
