"""Distributed trace plane mechanics (trace_plane.py, the epoch
rebase in metrics/events.py, and the SLO burn-rate watchdog in
metrics/stats.py)."""

import json

from vllm_distributed_tpu import trace_plane as tp
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.stats import BurnRateWatchdog

CTX = tp.mint_trace_ctx("req-1")
TID = CTX["trace_id"]


# ---------------------------------------------------------------------------
# Epoch rebase (restarted-core fresh monotonic epoch)
# ---------------------------------------------------------------------------


def test_rebase_identity_on_sane_timeline():
    tl = [(100.0, ev.QUEUED, None), (100.5, ev.SCHEDULED, None),
          (101.0, ev.FINISHED, None)]
    assert ev.rebase_epochs(tl) == tl


def test_rebase_shifts_restarted_epoch_forward():
    # A core restart hands the timeline a fresh monotonic epoch: the
    # replay events jump backward by the dead core's uptime. Sorting
    # raw timestamps would misorder the lifecycle (satellite fix: the
    # rebase runs BEFORE any sort).
    tl = [(500.0, ev.QUEUED, None), (501.0, ev.ENGINE_DEATH, None),
          (3.0, ev.JOURNAL_REPLAY, None), (4.0, ev.FINISHED, None)]
    out = ev.rebase_epochs(tl)
    ts = [e[0] for e in out]
    assert ts == sorted(ts)
    assert ts[2] > 501.0
    # Intra-epoch spacing survives the shift.
    assert abs((ts[3] - ts[2]) - 1.0) < 1e-6
    # Names/details untouched, shape preserved.
    assert [e[1] for e in out] == [e[1] for e in tl]
    assert all(isinstance(e, tuple) for e in out)


def test_rebase_tolerates_jitter_and_accumulates_resets():
    # Backward jitter under the threshold is real reordering across
    # sources, not a reset — identity.
    tl = [(100.0, "a", None), (99.9, "b", None)]
    assert ev.rebase_epochs(tl) == tl
    # Restart storm: two resets accumulate, order stays monotonic.
    tl = [(500.0, "a", None), (2.0, "b", None), (400.0, "c", None),
          (1.0, "d", None)]
    ts = [e[0] for e in ev.rebase_epochs(tl)]
    assert ts == sorted(ts) and len(set(ts)) == 4


def test_rebase_preserves_wire_list_shape():
    tl = [[500.0, "r", ev.QUEUED, None], [2.0, "r", ev.FINISHED, None]]
    out = ev.rebase_epochs(tl)
    assert all(isinstance(e, list) and len(e) == 4 for e in out)
    assert out[1][0] > out[0][0]


# ---------------------------------------------------------------------------
# stamp_trace
# ---------------------------------------------------------------------------


def test_stamp_trace_copies_and_merges():
    detail = {"prompt_tokens": 4}
    stamped = ev.stamp_trace(detail, CTX)
    assert stamped[ev.TRACE_KEY] == TID
    assert stamped["prompt_tokens"] == 4
    assert ev.TRACE_KEY not in detail  # caller's dict untouched
    assert ev.stamp_trace(None, CTX) == {ev.TRACE_KEY: TID}
    assert ev.stamp_trace(detail, None) is detail


# ---------------------------------------------------------------------------
# TraceAssembler
# ---------------------------------------------------------------------------


def test_assembler_stitches_two_replicas_into_one_trace():
    asm = tp.TraceAssembler(max_traces=8, max_spans=64)
    asm.note_admission("req-1", CTX)
    # Front-end event: unstamped, resolved via the rid map.
    asm.add_event(1.0, "req-1", ev.ARRIVED, None)
    # Producer (replica 0) and consumer (replica 1) ring events arrive
    # stamped + replica-tagged through the get_stats drain.
    asm.feed([[1.1, "req-1", ev.DISAGG_HANDOFF,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 0}],
              [1.2, "req-1", ev.KV_PULL_WAIT,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 1}],
              [1.3, "req-1", ev.KV_PULL_DONE,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 1}]])
    t = asm.get(request_id="req-1")
    assert t is not None and t["trace_id"] == TID
    assert t["request_ids"] == ["req-1"]
    assert len(t["events"]) == 4
    # frontend (None -> -1) + replicas 0 and 1.
    assert asm.replica_count(t) == 3
    assert asm.get(trace_id=TID)["trace_id"] == TID
    assert asm.get(request_id="nope") is None


def test_assembler_span_cap_keeps_earliest_and_counts_drops():
    asm = tp.TraceAssembler(max_traces=8, max_spans=3)
    asm.note_admission("req-1", CTX)
    for i in range(6):
        asm.add_event(float(i), "req-1", ev.SCHEDULED, None)
    t = asm.get(request_id="req-1")
    assert [e[0] for e in t["events"]] == [0.0, 1.0, 2.0]
    assert t["num_dropped"] == 3


def test_assembler_evicts_oldest_and_recreates_on_stamped_event():
    asm = tp.TraceAssembler(max_traces=2, max_spans=16)
    ctxs = {r: tp.mint_trace_ctx(r) for r in ("a", "b", "c")}
    for rid in ("a", "b", "c"):
        asm.note_admission(rid, ctxs[rid])
    assert asm.get(request_id="a") is None  # oldest evicted
    assert asm.get(request_id="c") is not None
    # A stamped event for the evicted trace (late consumer ring drain)
    # recreates its bucket so stitching still works.
    asm.add_event(9.0, "a", ev.KV_PULL_DONE,
                  {ev.TRACE_KEY: ctxs["a"]["trace_id"],
                   ev.REPLICA_KEY: 1})
    t = asm.get(trace_id=ctxs["a"]["trace_id"])
    assert t is not None and len(t["events"]) == 1


def test_assembler_folds_anonymous_fleet_events_in_window():
    asm = tp.TraceAssembler(max_traces=8, max_spans=64)
    asm.note_admission("req-1", CTX)
    asm.add_event(1.0, "req-1", ev.ARRIVED, None)
    asm.add_event(3.0, "req-1", ev.FINISHED, None)
    # rid="" fleet actuations: inside the window folds in, outside not.
    asm.add_event(2.0, "", ev.FLEET_SCALE_OUT, None)
    asm.add_event(9.0, "", ev.FLEET_SCALE_IN, None)
    names = [e[2] for e in asm.get(request_id="req-1")["events"]]
    assert ev.FLEET_SCALE_OUT in names
    assert ev.FLEET_SCALE_IN not in names


def test_dp_aggregator_rebases_replica_clocks_and_tags():
    """Cross-process clock alignment: a subprocess replica's ring
    events carry ITS monotonic epoch; the front-end aggregator pairs
    the riding clock_mono with its own clock and re-bases drained
    events into the front-end epoch, replica-tagging each one."""
    import time

    from vllm_distributed_tpu.engine.dp_client import DPEngineClient
    dp = object.__new__(DPEngineClient)
    dp.trace_enabled = True
    dp._clock_offsets = {}
    dp.clients = [object(), object()]
    dp._down = set()
    dp.replica_failovers = 0
    dp.replica_resurrections = 0
    dp.request_counts = lambda: [0, 0]
    now = time.monotonic()
    rep0_clock = now - 100.0  # subprocess booted 100 s "behind"
    per = [
        {"clock_mono": rep0_clock,
         "timeline_events": [[rep0_clock - 0.5, "r1", ev.SCHEDULED,
                              {ev.TRACE_KEY: TID}]]},
        {"clock_mono": now,
         "timeline_events": [[now - 0.2, "r2", ev.QUEUED, None]]},
    ]
    agg = dp._aggregate_stats(per, indices=[0, 1])
    by_rid = {e[1]: e for e in agg["timeline_events"]}
    # Replica 0's event lands ~0.5 s ago in the FRONT-END epoch, not
    # 100 s in the past; the estimated offset is recorded.
    assert abs(by_rid["r1"][0] - (now - 0.5)) < 1.0
    assert abs(dp._clock_offsets[0] - 100.0) < 1.0
    # Replica tags added for the assembler's pid lanes; stamps survive.
    assert by_rid["r1"][3][ev.REPLICA_KEY] == 0
    assert by_rid["r1"][3][ev.TRACE_KEY] == TID
    assert by_rid["r2"][3][ev.REPLICA_KEY] == 1
    # clock_mono is per-process bookkeeping, not a summed fleet stat.
    assert "clock_mono" not in agg


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------


def _stitched_trace():
    asm = tp.TraceAssembler(max_traces=8, max_spans=64)
    asm.note_admission("req-1", CTX)
    asm.add_event(1.0, "req-1", ev.ARRIVED, None)
    asm.feed([[1.05, "req-1", ev.QUEUED,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 0}],
              [1.1, "req-1", ev.SCHEDULED,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 0}],
              [1.2, "req-1", ev.DISAGG_HANDOFF,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 0}],
              [1.3, "req-1", ev.KV_PULL_WAIT,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 1}],
              [1.4, "req-1", ev.KV_PULL_DONE,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 1}],
              [1.5, "req-1", ev.FIRST_TOKEN,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 1}],
              [1.6, "req-1", ev.FINISHED,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 1}]])
    return asm.get(request_id="req-1")


def test_perfetto_shape_and_flow_link():
    out = tp.perfetto(_stitched_trace())
    json.dumps(out)  # must be valid JSON end to end
    evs = out["traceEvents"]
    assert out["displayTimeUnit"] == "ms"
    assert out["otherData"]["trace_id"] == TID
    # Process-name metadata for frontend (-1) and both replicas.
    meta = {e["pid"]: e["args"]["name"]
            for e in evs if e["ph"] == "M"}
    assert meta == {-1: "frontend", 0: "replica 0", 1: "replica 1"}
    # The handoff flow arrow: "s" on the producer, "f" on the consumer.
    s = [e for e in evs if e["ph"] == "s"]
    f = [e for e in evs if e["ph"] == "f"]
    assert len(s) == 1 and len(f) == 1
    assert s[0]["pid"] == 0 and f[0]["pid"] == 1
    assert s[0]["id"] == f[0]["id"] == tp._flow_id(TID)
    assert f[0]["bp"] == "e"
    # Instants ride component lanes; timestamps are relative µs.
    instants = [e for e in evs if e["ph"] == "i"]
    assert {e["tid"] for e in instants} >= {"frontend", "scheduler",
                                            "disagg", "kv_transfer"}
    assert all(e["ts"] >= 0 for e in instants)
    # Per-replica phase slices exist ("X" on the phases lane).
    assert any(e["ph"] == "X" and e["tid"] == "phases" for e in evs)


def test_perfetto_strips_trace_keys_from_args():
    out = tp.perfetto(_stitched_trace())
    for e in out["traceEvents"]:
        args = e.get("args") or {}
        assert ev.TRACE_KEY not in args
        assert ev.REPLICA_KEY not in args


def test_perfetto_flow_needs_open_handoff():
    # A kv_pull event with no preceding handoff must NOT close a flow
    # that never opened (monolithic pulls, replica-local recompute).
    asm = tp.TraceAssembler(max_traces=4, max_spans=16)
    asm.note_admission("req-1", CTX)
    asm.feed([[1.0, "req-1", ev.KV_PULL_WAIT,
               {ev.TRACE_KEY: TID, ev.REPLICA_KEY: 0}]])
    out = tp.perfetto(asm.get(request_id="req-1"))
    assert not [e for e in out["traceEvents"] if e["ph"] in ("s", "f")]


def test_component_of_maps_lanes():
    assert tp.component_of(ev.ROUTER_PICK) == "router"
    assert tp.component_of(ev.DISAGG_HANDOFF) == "disagg"
    assert tp.component_of(ev.KV_TIER_PROMOTE) == "kv_tier"
    assert tp.component_of(ev.FLEET_SCALE_OUT) == "fleet"
    assert tp.component_of("future_event") == "events"


# ---------------------------------------------------------------------------
# SLO burn-rate watchdog
# ---------------------------------------------------------------------------


def test_burn_rates_scale_miss_fraction_by_budget():
    w = BurnRateWatchdog(target=0.99, threshold=2.0)  # budget = 1%
    t0 = 1000.0
    for i in range(90):
        w.observe(True, now=t0 + i * 0.1)
    for i in range(10):
        w.observe(False, now=t0 + 9.0 + i * 0.1)
    rates = w.burn_rates(now=t0 + 10.0)
    assert set(rates) == {"1m", "10m"}
    # 10% misses against a 1% budget -> burn rate ~10 in both windows.
    assert 9.0 < rates["1m"] < 11.0
    assert 9.0 < rates["10m"] < 11.0
    assert w.degraded(now=t0 + 10.0)


def test_degraded_requires_both_windows():
    # A miss burst that has aged out of the fast window is history, not
    # a live problem: the 1m window reads 0 -> not degraded.
    w = BurnRateWatchdog(target=0.99, threshold=2.0)
    t0 = 2000.0
    for i in range(20):
        w.observe(False, now=t0 + i * 0.1)
    later = t0 + 120.0
    w.observe(True, now=later)
    rates = w.burn_rates(now=later)
    assert rates["10m"] > 2.0
    assert rates["1m"] < 2.0
    assert not w.degraded(now=later)


def test_empty_windows_and_zero_threshold():
    w = BurnRateWatchdog(target=0.99, threshold=2.0)
    # No traffic is not an SLO violation.
    assert w.burn_rates(now=50.0) == {"1m": 0.0, "10m": 0.0}
    assert not w.degraded(now=50.0)
    # threshold <= 0 disables the degraded flag entirely.
    off = BurnRateWatchdog(target=0.99, threshold=0.0)
    for i in range(10):
        off.observe(False, now=100.0 + i)
    assert not off.degraded(now=110.0)


def test_bins_prune_past_slow_window():
    w = BurnRateWatchdog(target=0.99, threshold=2.0)
    for i in range(400):
        w.observe(True, now=1000.0 + i * 5.0)
    # O(windows) memory: bins older than the 10m horizon are gone.
    assert len(w._bins) <= int(w._horizon // w.BIN_S) + 2
