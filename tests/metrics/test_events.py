"""Request-lifecycle event recorder + phase stitching + histogram
mechanics (metrics/events.py, metrics/stats.py)."""

import random

from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.stats import (ITL_BUCKETS,
                                                STEP_PHASE_BUCKETS,
                                                TTFT_BUCKETS, Histogram,
                                                merge_histogram_dicts,
                                                render_histogram_lines)

# ---------------------------------------------------------------------------
# EventRecorder
# ---------------------------------------------------------------------------


def test_recorder_record_drain_snapshot():
    r = ev.EventRecorder(enabled=True)
    r.record("r1", ev.QUEUED, {"prompt_tokens": 4})
    r.record("r1", ev.SCHEDULED, None)
    r.record("r2", ev.QUEUED, None)
    assert len(r) == 3
    snap = r.snapshot()
    assert len(snap) == 3 and len(r) == 3  # snapshot does not clear
    drained = r.drain()
    assert [e[1:3] for e in drained] == [["r1", ev.QUEUED],
                                         ["r1", ev.SCHEDULED],
                                         ["r2", ev.QUEUED]]
    assert drained[0][3] == {"prompt_tokens": 4}
    assert len(r) == 0 and r.drain() == []
    # Timestamps are monotonic-clock floats in order.
    assert drained[0][0] <= drained[1][0] <= drained[2][0]


def test_recorder_overflow_drops_oldest():
    r = ev.EventRecorder(maxlen=4, enabled=True)
    for i in range(10):
        r.record(f"r{i}", ev.QUEUED, None)
    assert len(r) == 4
    assert r.num_dropped >= 1
    assert [e[1] for e in r.drain()] == ["r6", "r7", "r8", "r9"]


def test_recorder_disabled_records_nothing():
    r = ev.EventRecorder(enabled=False)
    r.record("r1", ev.QUEUED, None)
    assert len(r) == 0 and r.drain() == []


def test_merge_event_lists_sorts_by_timestamp():
    a = [[2.0, "a", ev.QUEUED, None], [5.0, "a", ev.FINISHED, None]]
    b = [[1.0, "b", ev.QUEUED, None], [3.0, "b", ev.FINISHED, None]]
    merged = ev.merge_event_lists(a, b, None, [])
    assert [e[0] for e in merged] == [1.0, 2.0, 3.0, 5.0]


# ---------------------------------------------------------------------------
# Phase stitching
# ---------------------------------------------------------------------------


def _tl(*entries):
    return [(float(ts), event, detail) for ts, event, detail in entries]


def test_phases_plain_request():
    tl = _tl((10, ev.ARRIVED, None), (12, ev.SCHEDULED, None),
             (14, ev.FIRST_TOKEN, None), (20, ev.FINISHED, None))
    phases = {p["phase"]: (p["start"], p["end"])
              for p in ev.phases_from_timeline(tl)}
    assert phases == {"queue": (10, 12), "prefill": (12, 14),
                      "decode": (14, 20)}


def test_phases_with_kv_pull_and_preemption():
    tl = _tl((0, ev.ARRIVED, None), (1, ev.KV_PULL_WAIT, None),
             (4, ev.KV_PULL_DONE, None), (5, ev.SCHEDULED, None),
             (6, ev.FIRST_TOKEN, None), (8, ev.PREEMPTED, None),
             (9, ev.RESUMED, None), (12, ev.FINISHED, None))
    phases = ev.phases_from_timeline(tl)
    by_name = {p["phase"]: p for p in phases}
    assert by_name["queue"]["end"] == 1  # queue ends at the hold
    assert (by_name["kv_pull"]["start"],
            by_name["kv_pull"]["end"]) == (1, 4)
    assert (by_name["stall"]["start"], by_name["stall"]["end"]) == (8, 9)
    assert by_name["decode"]["end"] == 12


def test_phases_replay_window_is_a_stall():
    tl = _tl((0, ev.ARRIVED, None), (1, ev.SCHEDULED, None),
             (2, ev.FIRST_TOKEN, None), (3, ev.ENGINE_DEATH, None),
             (7, ev.JOURNAL_REPLAY, None), (9, ev.FINISHED, None))
    stalls = [p for p in ev.phases_from_timeline(tl)
              if p["phase"] == "stall"]
    assert len(stalls) == 1
    assert (stalls[0]["start"], stalls[0]["end"]) == (3, 7)


def test_phases_open_request_ends_at_now():
    tl = _tl((0, ev.ARRIVED, None), (1, ev.SCHEDULED, None),
             (2, ev.FIRST_TOKEN, None))
    by_name = {p["phase"]: p for p in ev.phases_from_timeline(tl, now=6)}
    assert by_name["decode"]["end"] == 6
    assert ev.current_phase(tl) == "decode"


def test_current_phase_transitions():
    assert ev.current_phase(_tl((0, ev.ARRIVED, None))) == "queued"
    assert ev.current_phase(_tl(
        (0, ev.ARRIVED, None), (1, ev.KV_PULL_WAIT, None))) == "kv_pull"
    assert ev.current_phase(_tl(
        (0, ev.ARRIVED, None), (1, ev.SCHEDULED, None),
        (2, ev.PREEMPTED, None))) == "preempted"
    assert ev.current_phase(_tl(
        (0, ev.ARRIVED, None), (1, ev.ENGINE_DEATH, None))) == "replaying"
    assert ev.current_phase(_tl(
        (0, ev.ARRIVED, None), (1, ev.FINISHED, None))) == "finished"
    # A decode-stage request resumed after preemption (or replayed) is
    # still DECODING — re-grants must not read as prefill forever.
    assert ev.current_phase(_tl(
        (0, ev.ARRIVED, None), (1, ev.SCHEDULED, None),
        (2, ev.FIRST_TOKEN, None), (3, ev.PREEMPTED, None),
        (4, ev.RESUMED, None))) == "decode"
    assert ev.current_phase(_tl(
        (0, ev.ARRIVED, None), (1, ev.SCHEDULED, None),
        (2, ev.FIRST_TOKEN, None), (3, ev.ENGINE_DEATH, None),
        (4, ev.JOURNAL_REPLAY, None))) == "decode"


def test_phase_durations_sums_stalls():
    phases = [{"phase": "stall", "start": 1.0, "end": 2.0},
              {"phase": "stall", "start": 4.0, "end": 7.0},
              {"phase": "decode", "start": 0.0, "end": 10.0}]
    durs = ev.phase_durations(phases)
    assert durs["stall"] == 4.0 and durs["decode"] == 10.0


# ---------------------------------------------------------------------------
# Histogram: bisect observe parity + serialized round-trip (satellites)
# ---------------------------------------------------------------------------


def _linear_reference(buckets, values):
    counts = [0] * (len(buckets) + 1)
    for v in values:
        for i, b in enumerate(buckets):
            if v <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    return counts


def test_observe_bisect_matches_linear_scan():
    rng = random.Random(0)
    for buckets in (TTFT_BUCKETS, ITL_BUCKETS, STEP_PHASE_BUCKETS):
        h = Histogram(buckets)
        values = [rng.random() * buckets[-1] * 2 for _ in range(500)]
        # Exact bucket edges are the classic off-by-one trap for a
        # bisect rewrite: value == bound must land IN that bucket.
        values += list(buckets)
        for v in values:
            h.observe(v)
        assert h.counts == _linear_reference(buckets, values)
        assert h.count == len(values)


def test_render_round_trip_is_byte_identical():
    """render_histogram_lines over a live Histogram and over its
    serialized-dict stats form (what engines ship over the stats RPC)
    must produce byte-identical exposition."""
    h = Histogram(TTFT_BUCKETS)
    rng = random.Random(1)
    for _ in range(200):
        h.observe(rng.random() * 50)
    live = h.render("vdt:test_seconds", "help text")
    d = h.to_dict()
    wire = render_histogram_lines("vdt:test_seconds", "help text",
                                  d["buckets"], d["counts"], d["sum"],
                                  d["count"])
    assert "\n".join(live) == "\n".join(wire)


def test_merge_histogram_dicts():
    a = Histogram(ITL_BUCKETS)
    b = Histogram(ITL_BUCKETS)
    for i in range(50):
        a.observe(i * 0.01)
        b.observe(i * 0.02)
    merged = merge_histogram_dicts([a.to_dict(), b.to_dict(), None])
    assert merged["count"] == 100
    assert merged["counts"] == [x + y for x, y in zip(a.counts, b.counts)]
    # Mismatched layouts are skipped, not mis-summed.
    other = Histogram(TTFT_BUCKETS)
    other.observe(1.0)
    merged2 = merge_histogram_dicts([a.to_dict(), other.to_dict()])
    assert merged2["count"] == a.count
    assert merge_histogram_dicts([None, {}]) is None
