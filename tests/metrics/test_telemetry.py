"""Cluster telemetry plane (metrics/telemetry.py + the labeled
/metrics families): recorder semantics, exact DP merging (labels
preserved, counters summed exactly once), kill switches, SLO goodput
scoring, and dead-replica aggregation."""

import threading

import pytest

from vllm_distributed_tpu.metrics import prometheus, telemetry
from vllm_distributed_tpu.metrics.stats import (FrontendStats,
                                                RequestTimes)
from vllm_distributed_tpu.metrics.telemetry import (
    TransportRecorder, merge_kv_cache_stats, merge_transport_snapshots,
    merge_worker_telemetry, worker_label)


class _PC:
    def __init__(self, dp=0, host=0):
        self.data_parallel_rank = dp
        self.host_rank = host


# ---------------------------------------------------------------------------
# TransportRecorder
# ---------------------------------------------------------------------------
def test_recorder_records_and_snapshots():
    r = TransportRecorder(enabled=True)
    r.record_transfer("dcn_pull", "rx", 1000, seconds=0.01)
    r.record_transfer("dcn_pull", "rx", 24, seconds=0.02)
    r.record_transfer("dcn_pull", "tx", 512)
    r.record_failure("dcn_pull")
    r.adjust_inflight("dcn_pull", +2)
    r.adjust_inflight("dcn_pull", -1)
    r.record_shm("write", 0.001)
    r.record_shm("read", 0.1, lag=7)
    snap = r.snapshot()
    conn = snap["kv"]["dcn_pull"]
    assert conn["rx_bytes"] == 1024
    assert conn["tx_bytes"] == 512
    assert conn["failures"] == 1
    assert conn["inflight"] == 1
    assert conn["seconds"]["count"] == 2
    assert snap["shm"]["read"]["messages"] == 1
    assert snap["shm_lag_chunks"] == 7
    # Inflight never goes negative (a restart can drop the +1 side).
    r.adjust_inflight("dcn_pull", -10)
    assert r.snapshot()["kv"]["dcn_pull"]["inflight"] == 0


def test_recorder_kill_switch(monkeypatch):
    monkeypatch.setenv("VDT_TRANSPORT_TELEMETRY", "0")
    r = TransportRecorder()  # env-driven
    r.record_transfer("dcn_pull", "rx", 100)
    r.record_shm("write", 0.1)
    r.record_qcomm("dcn_pull", 100)
    assert r.snapshot() == {"kv": {}, "shm": {}, "shm_lag_chunks": 0,
                            "qcomm": {}}
    monkeypatch.setenv("VDT_TRANSPORT_TELEMETRY", "1")
    r.record_transfer("dcn_pull", "rx", 100)
    assert r.snapshot()["kv"]["dcn_pull"]["rx_bytes"] == 100


def test_recorder_thread_safety():
    r = TransportRecorder(enabled=True)

    def work():
        for _ in range(500):
            r.record_transfer("c", "rx", 1, seconds=0.001)
            r.adjust_inflight("c", +1)
            r.adjust_inflight("c", -1)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = r.snapshot()
    assert snap["kv"]["c"]["rx_bytes"] == 2000
    assert snap["kv"]["c"]["seconds"]["count"] == 2000
    assert snap["kv"]["c"]["inflight"] == 0


def test_install_recorder_scopes_current():
    default = telemetry.current_recorder()
    mine = TransportRecorder(enabled=True)
    restore = telemetry.install_recorder(mine)
    try:
        assert telemetry.current_recorder() is mine
    finally:
        restore()
    assert telemetry.current_recorder() is default


# ---------------------------------------------------------------------------
# Merges: labels preserved, counters summed exactly once
# ---------------------------------------------------------------------------
def test_worker_label_is_fleet_unique():
    labels = {worker_label(_PC(dp, h))
              for dp in range(3) for h in range(2)}
    assert len(labels) == 6
    assert worker_label(_PC(1, 0)) == "dp1-h0"


def test_merge_worker_telemetry_union_never_sums():
    a = {"dp0-h0": {"num_recompiles": 2,
                    "device_memory_peak_bytes": 100}}
    b = {"dp1-h0": {"num_recompiles": 5}}
    merged = merge_worker_telemetry([a, b, None, "junk"])
    assert merged == {"dp0-h0": a["dp0-h0"], "dp1-h0": b["dp1-h0"]}
    # A pathological label collision keeps the first — never adds.
    clash = merge_worker_telemetry(
        [a, {"dp0-h0": {"num_recompiles": 99}}])
    assert clash["dp0-h0"]["num_recompiles"] == 2


def test_merge_transport_snapshots_exact():
    r1 = TransportRecorder(enabled=True)
    r2 = TransportRecorder(enabled=True)
    r1.record_transfer("dcn_pull", "rx", 100, seconds=0.01)
    r1.record_shm("read", 0.001, lag=2)
    r2.record_transfer("dcn_pull", "rx", 11, seconds=0.5)
    r2.record_transfer("shared_storage", "tx", 7)
    r2.record_shm("read", 0.2, lag=9)
    merged = merge_transport_snapshots(
        [r1.snapshot(), r2.snapshot(), None])
    assert merged["kv"]["dcn_pull"]["rx_bytes"] == 111
    assert merged["kv"]["dcn_pull"]["seconds"]["count"] == 2
    assert merged["kv"]["shared_storage"]["tx_bytes"] == 7
    assert merged["shm"]["read"]["messages"] == 2
    assert merged["shm_lag_chunks"] == 9  # max, not sum
    assert merge_transport_snapshots([]) is None


def test_merge_kv_cache_stats_counts_sum_ratios_exact():
    """Ratios recompute from the summed tallies: an idle replica
    (zero queries, zero held pages) must not dilute the fleet hit
    rate or fragmentation."""
    merged = merge_kv_cache_stats([
        {"total_blocks": 8, "free_blocks": 4, "used_blocks": 4,
         "held_blocks": 4, "fragmentation_frac": 0.5,
         "window_queries": 10, "window_hits": 10,
         "window_hit_rate": 1.0,
         "preemption_causes": {"capacity": 1}},
        {"total_blocks": 8, "free_blocks": 8, "used_blocks": 0,
         "held_blocks": 0, "fragmentation_frac": 0.0,
         "window_queries": 0, "window_hits": 0,
         "window_hit_rate": 0.0,
         "preemption_causes": {"capacity": 2, "self": 1}},
    ])
    assert merged["total_blocks"] == 16
    assert merged["used_blocks"] == 4
    # All held pages live on replica 0 at fragmentation 0.5; the idle
    # replica holds nothing and must not halve the figure.
    assert merged["fragmentation_frac"] == pytest.approx(0.5)
    # 10/10 hits fleet-wide: the idle replica's 0.0 ratio is ignored.
    assert merged["window_hit_rate"] == pytest.approx(1.0)
    assert merged["window_queries"] == 10
    assert merged["preemption_causes"] == {"capacity": 3, "self": 1}


# ---------------------------------------------------------------------------
# Prometheus rendering of the labeled families
# ---------------------------------------------------------------------------
def _full_stats():
    r = TransportRecorder(enabled=True)
    r.record_transfer("dcn_pull", "rx", 64, seconds=0.01)
    r.record_shm("write", 0.0001)
    return {
        "workers": {
            "dp0-h0": {"num_recompiles": 1,
                       "device_memory_peak_bytes": 2048,
                       "device_memory_in_use_bytes": 1024,
                       "device_wait_seconds": {
                           "buckets": [0.01, 0.1], "counts": [1, 0, 0],
                           "sum": 0.005, "count": 1}},
        },
        "transport": r.snapshot(),
        "kv_cache": {"total_blocks": 8, "free_blocks": 5,
                     "used_blocks": 3, "tombstoned_blocks": 1,
                     "cached_free_blocks": 2,
                     "fragmentation_frac": 0.125,
                     "window_queries": 4, "window_hit_rate": 0.75,
                     "preemption_causes": {"capacity": 2}},
    }


def test_render_metrics_labeled_families():
    text = prometheus.render_metrics(_full_stats())
    for needle in (
        'vdt:recompiles_total{worker="dp0-h0"} 1.0',
        'vdt:device_memory_peak_bytes{worker="dp0-h0"} 2048.0',
        'vdt:device_wait_seconds_bucket{worker="dp0-h0",le="+Inf"} 1',
        'vdt:kv_transfer_bytes_total{connector="dcn_pull",'
        'direction="rx"} 64',
        'vdt:kv_transfer_inflight{connector="dcn_pull"} 0',
        'vdt:kv_transfer_seconds_count{connector="dcn_pull"} 1',
        'vdt:shm_ring_messages_total{side="write"} 1',
        "vdt:shm_ring_lag_chunks 0",
        'vdt:kv_blocks{state="free"} 5',
        'vdt:kv_blocks{state="tombstoned"} 1',
        "vdt:kv_fragmentation_frac 0.125",
        "vdt:prefix_cache_hit_rate_window 0.75",
        'vdt:preemptions_by_cause_total{cause="capacity"} 2',
    ):
        assert needle in text, f"missing {needle!r} in:\n{text}"
    # Every rendered labeled family must be declared in the registry
    # (the lint script cross-checks the registry against the README).
    import re
    for name, label in re.findall(
            r"^(vdt:[a-z0-9_]+?)(?:_bucket|_sum|_count)?"
            r"\{([a-z_]+)=", text, re.M):
        assert name in prometheus.LABELED_METRICS, name
        assert label in prometheus.LABELED_METRICS[name], (name, label)


def test_render_metrics_empty_sections_render_nothing():
    text = prometheus.render_metrics({"num_running_reqs": 0})
    assert "vdt:kv_transfer" not in text
    assert "vdt:recompiles_total" not in text
    assert "vdt:kv_blocks" not in text


# ---------------------------------------------------------------------------
# SLO goodput scoring (FrontendStats.on_slo)
# ---------------------------------------------------------------------------
def _times(ttft_s, tpot_s, n):
    return RequestTimes(arrival=0.0, first_token=ttft_s,
                        last_token=ttft_s + tpot_s * (n - 1))


def test_slo_scoring_and_render():
    fs = FrontendStats()
    fs.slo_ttft_ms = 100.0
    fs.slo_tpot_ms = 10.0
    fs.on_slo(_times(0.05, 0.005, 10), 10)   # both met
    fs.on_slo(_times(0.5, 0.005, 10), 10)    # ttft miss
    fs.on_slo(_times(0.05, 0.5, 10), 10)     # tpot miss
    fs.on_slo(RequestTimes(arrival=0.0), 0)  # no token: not scored
    assert fs.slo_scored == 3 and fs.slo_good == 1
    assert fs.slo_ttft_misses == 1 and fs.slo_tpot_misses == 1
    out = fs.render()
    assert "vdt:slo_goodput_frac 0.333333" in out
    assert "vdt:slo_requests_scored_total 3" in out


def test_slo_single_token_with_only_tpot_is_not_scored():
    """Only TPOT enabled and a 1-token request: no enabled target was
    evaluable, so the request must not count toward goodput (counting
    it as good would read 1.0 on a workload the target never saw)."""
    fs = FrontendStats()
    fs.slo_tpot_ms = 1.0  # 1 ms: any measured tpot would miss
    fs.on_slo(RequestTimes(arrival=0.0, first_token=1.0,
                           last_token=1.0), 1)
    assert fs.slo_scored == 0 and fs.slo_good == 0
    # With TTFT also enabled the same request scores on TTFT alone.
    fs.slo_ttft_ms = 5000.0
    fs.on_slo(RequestTimes(arrival=0.0, first_token=1.0,
                           last_token=1.0), 1)
    assert fs.slo_scored == 1 and fs.slo_good == 1


def test_slo_disabled_renders_nothing():
    fs = FrontendStats()
    fs.on_slo(_times(9.0, 9.0, 5), 5)
    out = fs.render()
    assert fs.slo_scored == 0
    assert "vdt:slo_goodput_frac" not in out


# ---------------------------------------------------------------------------
# DP aggregation: executor fan-in + replica merge, dead replica
# mid-scrape (satellite: labels preserved, counters never
# double-counted)
# ---------------------------------------------------------------------------
class _FakeClient:
    def __init__(self, stats=None, dead=False):
        self._stats = stats or {}
        self._dead = dead

    def get_stats(self):
        if self._dead:
            raise RuntimeError("replica is dead; scrape must not "
                               "touch it")
        return dict(self._stats)


def _dp(clients, down=()):
    from vllm_distributed_tpu.engine.dp_client import DPEngineClient
    dp = DPEngineClient.__new__(DPEngineClient)
    dp.clients = clients
    dp._live = [set() for _ in clients]
    dp._down = set(down)
    dp.replica_failovers = len(down)
    dp.replica_resurrections = 0
    return dp


def _replica_stats(label, recompiles, rx):
    rec = TransportRecorder(enabled=True)
    rec.record_transfer("dcn_pull", "rx", rx, seconds=0.01)
    return {
        "num_preemptions": 1,
        "workers": {label: {"num_recompiles": recompiles}},
        "transport": rec.snapshot(),
        "kv_cache": {"total_blocks": 4, "free_blocks": 2,
                     "used_blocks": 2, "held_blocks": 2,
                     "fragmentation_frac": 0.5,
                     "window_queries": 2, "window_hits": 1,
                     "window_hit_rate": 0.5,
                     "preemption_causes": {"capacity": 1}},
    }


def test_dp_aggregation_preserves_labels_and_sums_once():
    dp = _dp([_FakeClient(_replica_stats("dp0-h0", 1, 100)),
              _FakeClient(_replica_stats("dp1-h0", 2, 11))])
    agg = dp.get_stats()
    # Worker maps union — every replica's series survives unsummed.
    assert agg["workers"]["dp0-h0"]["num_recompiles"] == 1
    assert agg["workers"]["dp1-h0"]["num_recompiles"] == 2
    # Transport sums exactly once per label.
    assert agg["transport"]["kv"]["dcn_pull"]["rx_bytes"] == 111
    assert agg["transport"]["kv"]["dcn_pull"]["seconds"]["count"] == 2
    # Flat counters sum; kv gauges average/sum per kind.
    assert agg["num_preemptions"] == 2
    assert agg["kv_cache"]["total_blocks"] == 8
    assert agg["kv_cache"]["fragmentation_frac"] == pytest.approx(0.5)
    assert agg["kv_cache"]["preemption_causes"] == {"capacity": 2}


def test_dp_aggregation_skips_dead_replica_mid_scrape():
    """A replica failed over mid-scrape: its client must not be
    scraped (the fake raises if touched) and the survivors' stats must
    come through intact, with the failover visible."""
    dp = _dp([_FakeClient(_replica_stats("dp0-h0", 3, 64)),
              _FakeClient(dead=True)], down={1})
    agg = dp.get_stats()
    assert agg["workers"] == {"dp0-h0": {"num_recompiles": 3}}
    assert agg["transport"]["kv"]["dcn_pull"]["rx_bytes"] == 64
    assert agg["dp_replicas_down"] == [1]
    assert agg["replica_failovers"] == 1


# ---------------------------------------------------------------------------
# Quantized communication plane counters
# ---------------------------------------------------------------------------

def test_recorder_qcomm_counters_and_merge():
    rec_a = TransportRecorder(enabled=True)
    rec_a.record_qcomm("dcn_pull", 3000)
    rec_a.record_qcomm("dcn_pull", 1000)
    rec_a.record_qcomm_fallback("dcn_pull")
    rec_b = TransportRecorder(enabled=True)
    rec_b.record_qcomm("shared_storage", 500)
    snap_a, snap_b = rec_a.snapshot(), rec_b.snapshot()
    assert snap_a["qcomm"]["dcn_pull"] == {"bytes_saved": 4000,
                                           "fallbacks": 1}
    merged = telemetry.merge_transport_snapshots([snap_a, snap_b,
                                                  snap_a])
    # Per-path sums are exact (each recorder is disjoint; the repeated
    # snapshot models a second DP replica's identical counters).
    assert merged["qcomm"]["dcn_pull"] == {"bytes_saved": 8000,
                                           "fallbacks": 2}
    assert merged["qcomm"]["shared_storage"] == {"bytes_saved": 500,
                                                 "fallbacks": 0}


def test_qcomm_render_merges_transport_and_traced():
    from vllm_distributed_tpu.parallel import collectives
    collectives.reset_counters()
    collectives._note_saved("tknp", 1234)
    collectives.note_fallback("tp")
    try:
        rec = TransportRecorder(enabled=True)
        rec.record_qcomm("dcn_pull", 4000)
        text = prometheus.render_metrics({"transport": rec.snapshot()})
        assert 'vdt:qcomm_bytes_saved_total{path="dcn_pull"} 4000' \
            in text
        assert 'vdt:qcomm_bytes_saved_total{path="tknp"} 1234' in text
        assert "vdt:qcomm_fallbacks_total 1" in text
    finally:
        collectives.reset_counters()


def test_qcomm_render_silent_when_plane_never_fired():
    from vllm_distributed_tpu.parallel import collectives
    collectives.reset_counters()
    rec = TransportRecorder(enabled=True)
    text = prometheus.render_metrics({"transport": rec.snapshot()})
    assert "qcomm" not in text
