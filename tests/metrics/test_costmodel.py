"""Analytic cost model (metrics/costmodel.py): hand-computed FLOP and
HBM-byte counts for the layouts the attribution plane must price —
dense and GQA llama blocks, a DeepSeek MLA layer under TPLA TP=2 (the
per-rank latent slice is read once per rank, the score psum is counted
ONCE), the fused-block decode path, and an SSM (Mamba) scan — plus the
roofline classifier and the per-chip peak tables bench.py shares."""

import types

import pytest

from vllm_distributed_tpu.metrics.costmodel import (
    HOST_PEAK_FLOPS, HOST_PEAK_HBM, PEAK_FLOPS_PER_CHIP, CostModel,
    classify_roofline, peak_flops_per_chip, peak_hbm_per_chip)


def _arch(**kw):
    a = types.SimpleNamespace(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_layers=2, num_q_heads=4, num_kv_heads=4, head_dim=16,
        dtype="float32", mlp_gated=True)
    for k, v in kw.items():
        setattr(a, k, v)
    return a


# Shared toy dims: H=64, I=128, L=2, V=128, 4 q heads x 16.
H, I, L, V = 64, 128, 2, 128


def test_dense_llama_hand_count():
    """kvh == qh: per-layer proj = QKV (2*H*3*Dq) + O (2*Dq*H), MLP =
    3 gated mats of [H, I]; attention pair = 4 FLOPs per (q head,
    lane)."""
    cm = CostModel.from_arch(_arch(), kv_row_bytes=512.0)
    Dq = 4 * 16  # == H
    per_layer = 2 * H * (Dq + 2 * Dq) + 2 * Dq * H + 3 * 2 * H * I
    assert cm.linear_flops_per_token == L * per_layer
    assert cm.attn_flops_per_token_kv == L * 4 * 4 * 16
    assert cm.lm_head_flops_per_row == 2 * H * V
    # fp32 weights: per-layer mats + 2 norms, + LM head; embed rows
    # ride act_bytes (gather, not a stream).
    w = (L * (H * 3 * Dq + Dq * H + 3 * H * I) + V * H) * 4 \
        + 2 * L * H * 4
    assert cm.dense_weight_bytes == w
    # One decode token at context 9: 10 attended positions.
    c = cm.wave_cost(1, 10.0, 1)
    assert c.flops == (cm.linear_flops_per_token +
                       10 * cm.attn_flops_per_token_kv +
                       cm.lm_head_flops_per_row)
    assert c.kv_read_bytes == 10 * 512.0
    assert c.kv_write_bytes == 512.0
    assert c.act_bytes == (4 * L * H + H) * 4 + V * 4


def test_gqa_hand_count():
    """2 KV heads against 4 q heads: the QKV stream shrinks, the
    attention pair count (per q head) does not."""
    cm = CostModel.from_arch(_arch(num_kv_heads=2),
                             kv_row_bytes=256.0)
    Dq, Dkv = 64, 32
    per_layer = 2 * H * (Dq + 2 * Dkv) + 2 * Dq * H + 3 * 2 * H * I
    assert cm.linear_flops_per_token == L * per_layer
    assert cm.attn_flops_per_token_kv == L * 4 * 4 * 16  # q heads


def test_prefill_wave_composition():
    """A causal prefill chunk of n tokens at context c attends
    n*c + n(n+1)/2 pairs; weights stream once regardless of width."""
    cm = CostModel.from_arch(_arch(), kv_row_bytes=512.0)
    n, ctx = 8, 4
    pairs = n * ctx + n * (n + 1) / 2
    c = cm.wave_cost(n, pairs, 2)
    assert c.flops == (n * cm.linear_flops_per_token +
                       pairs * cm.attn_flops_per_token_kv +
                       2 * cm.lm_head_flops_per_row)
    assert c.weight_bytes == cm.dense_weight_bytes
    wide = cm.wave_cost(4 * n, pairs, 2)
    assert wide.weight_bytes == c.weight_bytes


def test_multi_pass_burst_streams_weights_per_pass():
    cm = CostModel.from_arch(_arch(), kv_row_bytes=512.0)
    c = cm.wave_cost(8, 80.0, 8, passes=4)
    assert c.weight_bytes == 4 * cm.dense_weight_bytes


def test_mla_tpla_hand_count():
    """DeepSeek MLA geometry (no q_lora): Lkv=64, rope 8, nope 16,
    v 16, 4 heads. Attention pair = scores over the latent (psum
    counted ONCE — per-rank slices are disjoint) + rope scores + PV
    over the latent; INDEPENDENT of the TPLA shard count. Per-rank KV
    row bytes: each rank reads its Lkv/TP slice plus its OWN rope
    sidecar copy, so TP=2 total row bytes exceed the replicated row by
    one extra rope sidecar."""
    Lkv, dr, dn, dv, N = 64, 8, 16, 16, 4
    base = dict(mla=True, kv_lora_rank=Lkv, qk_rope_head_dim=dr,
                qk_nope_head_dim=dn, v_head_dim=dv, q_lora_rank=None,
                num_q_heads=N, num_layers=3)
    # CPU storage: no 128-lane padding, float32.
    row_repl = 3 * (Lkv + dr) * 4.0
    row_tpla = 2 * (3 * (Lkv // 2 + dr) * 4.0)  # 2 ranks' slices+rope
    cm1 = CostModel.from_arch(_arch(**base, tpla_shards=1),
                              kv_row_bytes=row_repl)
    cm2 = CostModel.from_arch(_arch(**base, tpla_shards=2),
                              kv_row_bytes=row_tpla)
    pair = 2 * N * (Lkv + dr) + 2 * N * Lkv
    assert cm1.attn_flops_per_token_kv == 3 * pair
    # Exactness of TPLA: useful attention FLOPs identical to the
    # replicated layout — the psum reassembles full scores, counted
    # once, never per rank.
    assert cm2.attn_flops_per_token_kv == cm1.attn_flops_per_token_kv
    assert cm2.linear_flops_per_token == cm1.linear_flops_per_token
    # Projections, hand-counted per layer: q + kv-down + absorbed
    # q*W_UK + out*W_UV + o-proj.
    attn_proj = (2 * H * N * (dn + dr) + 2 * H * (Lkv + dr)
                 + 2 * N * dn * Lkv + 2 * N * Lkv * dv
                 + 2 * N * dv * H)
    mlp = 3 * 2 * H * I
    assert cm1.linear_flops_per_token == 3 * (attn_proj + mlp)
    # The TPLA layout's real HBM trade: +1 rope sidecar per extra rank.
    assert cm2.kv_row_read_bytes - cm1.kv_row_read_bytes == \
        pytest.approx(3 * dr * 4.0)


def test_mla_via_real_deepseek_model():
    """from_model prices the real DeepseekModel page layout: per-rank
    page bytes x shard count, matching the model's own accounting."""
    pytest.importorskip("transformers")
    from transformers import DeepseekV2Config

    from vllm_distributed_tpu.models.llama import LlamaArchConfig
    from vllm_distributed_tpu.models.registry import resolve_architecture
    hf = DeepseekV2Config(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        moe_intermediate_size=48, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=4, q_lora_rank=None,
        kv_lora_rank=64, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16, n_routed_experts=4, num_experts_per_tok=2,
        n_shared_experts=1, first_k_dense_replace=1,
        routed_scaling_factor=1.0, topk_method="greedy", n_group=1,
        topk_group=1, norm_topk_prob=False, max_position_embeddings=64,
        eos_token_id=1, head_dim=8,
        architectures=["DeepseekV2ForCausalLM"])
    model_cls = resolve_architecture(hf)
    import jax.numpy as jnp
    rows = {}
    for shards in (1, 2):
        arch = LlamaArchConfig.from_hf_config(
            model_cls.arch_config_source(hf), dtype=jnp.float32)
        model_cls.configure_arch(arch, hf)
        arch.tpla_shards = shards
        model = model_cls(arch)
        config = types.SimpleNamespace(
            cache_config=types.SimpleNamespace(block_size=4))
        cm = CostModel.from_model(model, config)
        rows[shards] = cm.kv_row_read_bytes
        assert cm.kv_row_read_bytes == pytest.approx(
            model.kv_cache_page_bytes(4) / 4 * shards)
        assert cm.moe_layers == 2 and cm.num_experts == 4
    # TP=2 aggregate row costs one extra replicated rope sidecar.
    assert rows[2] > rows[1]


def test_fused_block_costs_match_per_op_path():
    """The fused decode-block kernel computes the SAME math as the
    per-op path — the cost model prices a fused dispatch identically
    (only the attribution LABEL differs, keyed by the runner)."""
    cm_fused = CostModel.from_arch(_arch(block_fusion=True),
                                   kv_row_bytes=512.0)
    cm_plain = CostModel.from_arch(_arch(), kv_row_bytes=512.0)
    a = cm_fused.wave_cost(8, 100.0, 8)
    b = cm_plain.wave_cost(8, 100.0, 8)
    assert a == b


def test_ssm_scan_hand_count():
    """Pure Mamba: no FFN, no paged KV; per-layer cost = in_proj +
    conv + x_proj + dt_proj + scan + out_proj; state traffic =
    (Di*N + Di*(K-1)) fp32 read+write per token per layer."""
    Di, N, K, R = 128, 16, 4, 4
    cm = CostModel.from_arch(
        _arch(stateful=True, d_inner=Di, ssm_state_size=N,
              conv_kernel=K, dt_rank=R, intermediate_size=Di),
        kv_row_bytes=0.0)
    per_layer = (2 * H * 2 * Di + 2 * Di * K + 2 * Di * (R + 2 * N)
                 + 2 * R * Di + 6 * Di * N + 2 * Di * H)
    assert cm.linear_flops_per_token == L * per_layer
    assert cm.attn_flops_per_token_kv == 0
    state = L * (Di * N + Di * (K - 1)) * 4.0
    assert cm.state_read_bytes_per_token == state
    c = cm.wave_cost(3, 0.0, 3)
    assert c.kv_read_bytes == 3 * state
    assert c.kv_write_bytes == 3 * state


def test_sliding_window_clamps_span():
    cm = CostModel.from_arch(_arch(sliding_window=32),
                             kv_row_bytes=512.0)
    assert cm.attn_window == 32
    assert cm.clamp_span(10) == 10
    assert cm.clamp_span(1000) == 32
    # Closed-form span_sum == the per-token reference, across the
    # regimes: all-under-window, straddling, all-saturated.
    for ctx, n in ((0, 8), (20, 30), (100, 16), (31, 1), (32, 1)):
        ref = sum(cm.clamp_span(ctx + j) for j in range(1, n + 1))
        assert cm.span_sum(ctx, n) == pytest.approx(ref), (ctx, n)
    full = CostModel.from_arch(_arch(), kv_row_bytes=512.0)
    assert full.span_sum(10, 4) == 4 * 10 + 4 * 5 / 2
    # Uniform window pattern resolves; mixed pattern does not.
    cm2 = CostModel.from_arch(_arch(window_pattern=(16, 16)),
                              kv_row_bytes=512.0)
    assert cm2.attn_window == 16
    cm3 = CostModel.from_arch(_arch(window_pattern=(16, 0)),
                              kv_row_bytes=512.0)
    assert cm3.attn_window is None


def test_peak_tables_and_aliases():
    assert peak_flops_per_chip("TPU v5 lite") == \
        PEAK_FLOPS_PER_CHIP["v5e"]
    assert peak_flops_per_chip("TPU v4") == PEAK_FLOPS_PER_CHIP["v4"]
    assert peak_hbm_per_chip("TPU v5p") == 2765e9
    assert peak_flops_per_chip("cpu") == HOST_PEAK_FLOPS
    assert peak_hbm_per_chip("") == HOST_PEAK_HBM


def test_mesh_scales_peaks():
    cm = CostModel.from_arch(_arch(), kv_row_bytes=512.0,
                             num_chips=4, device_kind="TPU v4")
    assert cm.peak_flops == 4 * PEAK_FLOPS_PER_CHIP["v4"]


def test_classify_roofline():
    peaks = {"flops": 100.0, "hbm": 100.0}
    # Device busy, FLOP fraction dominates -> compute.
    assert classify_roofline(
        {"device_seconds": 1.0, "host_seconds": 0.1, "flops": 80.0,
         "bytes": 10.0}, peaks) == "compute"
    # Byte fraction dominates -> bandwidth.
    assert classify_roofline(
        {"device_seconds": 1.0, "host_seconds": 0.1, "flops": 10.0,
         "bytes": 80.0}, peaks) == "bandwidth"
    # Host time above device time -> host-bound regardless of rates.
    assert classify_roofline(
        {"device_seconds": 0.1, "host_seconds": 1.0, "flops": 9.0,
         "bytes": 1.0}, peaks) == "host"
    assert classify_roofline({"device_seconds": 0.0}, peaks) == "host"


def test_mfu_mbu_helpers():
    cm = CostModel.from_arch(_arch(), kv_row_bytes=512.0)
    assert cm.mfu(cm.peak_flops * 2.0, 2.0) == pytest.approx(1.0)
    assert cm.mbu(cm.peak_hbm * 0.5, 1.0) == pytest.approx(0.5)
    assert cm.mfu(1e9, 0.0) == 0.0
    # decode_flops_per_token credits attention at the given context.
    assert cm.decode_flops_per_token(99) == (
        cm.linear_flops_per_token +
        100 * cm.attn_flops_per_token_kv + cm.lm_head_flops_per_row)
