"""Correctness sentinel unit mechanics (correctness_plane.py) and the
Prometheus exposition of its families (metrics/prometheus.py).

The fleet-level drills (fault-injected flip/NaN -> suspect -> fleet
quarantine) live in tests/engine/test_fleet.py; this file pins the
plane's scoring rules in isolation — journal self-seeding, the
vote/reference/logprob/timeout cause ladder, the median-based numerics
drift detector, episode hygiene (forget_replica, clean-round resets) —
and the render contract: per-replica labeled series that are NEVER
numeric-summed across replicas."""

import pytest

from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.correctness_plane import (CANARY_DECODE_TOKENS,
                                                    CorrectnessPlane,
                                                    NumericsTap,
                                                    canary_sampling_params,
                                                    flag_config_fingerprint,
                                                    reference_key)
from vllm_distributed_tpu.metrics.prometheus import render_metrics

GOLD_TOKENS = list(range(100, 100 + CANARY_DECODE_TOKENS))


@pytest.fixture()
def plane(monkeypatch):
    monkeypatch.setenv("VDT_CORRECTNESS", "1")
    monkeypatch.setenv("VDT_CANARY_INTERVAL_S", "30")
    monkeypatch.setenv("VDT_CANARY_QUARANTINE_N", "2")
    monkeypatch.setenv("VDT_NUMERICS_DRIFT_FRAC", "0.5")
    return CorrectnessPlane()


def _finish(plane, rid, tokens, lp=None):
    """Deliver one probe's full output in a single finished delta."""
    logprobs = [{tokens[-1]: lp}] if lp is not None else None
    plane.on_output(EngineCoreOutput(req_id=rid, new_token_ids=tokens,
                                     finish_reason="length",
                                     logprobs=logprobs))


def _run_round(plane, per_replica, now, lp=None):
    """Mint a round for the keyed replicas and resolve it with the
    given token streams ({replica: tokens})."""
    probes = plane.due_probes(sorted(per_replica), now=now)
    assert [i for i, _ in probes] == sorted(per_replica)
    for i, req in probes:
        _finish(plane, req.request_id, per_replica[i],
                lp=lp[i] if isinstance(lp, dict) else lp)
    assert plane._round is None  # resolved


# ---------------------------------------------------------------------------
# Canary round scoring
# ---------------------------------------------------------------------------


def test_first_unanimous_round_self_seeds_journal(plane):
    _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS}, now=0.0, lp=-0.5)
    stats = plane.get_stats()
    assert stats["journal_entries"] == 1
    assert stats["divergences"] == {}
    assert plane.suspects() == {}
    ref = next(iter(plane.journal.values()))
    assert ref["tokens"] == GOLD_TOKENS
    assert ref["lp"] == pytest.approx(-0.5)


def test_interval_gates_next_round(plane):
    _run_round(plane, {0: GOLD_TOKENS}, now=0.0)
    assert plane.due_probes([0], now=10.0) == []  # interval 30s
    assert plane.due_probes([0], now=31.0) != []


def test_two_replica_tie_breaks_on_reference(plane):
    _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS}, now=0.0)
    bad = [t + 1 for t in GOLD_TOKENS]
    # Prompt rotates per round: seed all four golden prompts so the
    # corrupted round has a reference to break the 1-1 tie.
    for r in range(1, 4):
        _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS},
                   now=31.0 * r)
    _run_round(plane, {0: GOLD_TOKENS, 1: bad}, now=31.0 * 4)
    assert plane.divergences == {1: {"reference": 1}}
    assert plane.suspects() == {1: 1}
    # The healthy replica that matched the journal stays clean.
    assert plane._canary_strikes.get(0, 0) == 0


def test_three_replica_vote_needs_no_journal(plane):
    bad = [t + 7 for t in GOLD_TOKENS]
    _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS, 2: bad}, now=0.0)
    assert plane.divergences == {2: {"vote": 1}}
    assert plane.suspects() == {2: 1}
    # A non-unanimous round never seeds the journal.
    assert plane.get_stats()["journal_entries"] == 0


def test_fleet_wide_reference_mismatch_suspects_nobody(plane):
    _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS}, now=0.0)
    drifted = [t + 3 for t in GOLD_TOKENS]
    for r in range(1, 4):  # rotate back to the seeded prompt
        _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS},
                   now=31.0 * r)
    _run_round(plane, {0: drifted, 1: drifted}, now=31.0 * 4)
    # Both replicas strayed from the journal in unison: a divergence
    # per replica for the operator, but no odd one out to suspect.
    assert plane.divergences == {0: {"reference": 1},
                                 1: {"reference": 1}}
    assert plane.suspects() == {}


def test_logprob_fingerprint_divergence(plane):
    _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS}, now=0.0,
               lp=-0.5)
    for r in range(1, 4):
        _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS},
                   now=31.0 * r, lp=-0.5)
    # Same tokens, one replica's final-position logprob drifted past
    # tolerance: quality degradation below the argmax.
    _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS}, now=31.0 * 4,
               lp={0: -0.5, 1: -0.9})
    assert plane.divergences == {1: {"logprob": 1}}
    assert plane.suspects() == {1: 1}


def test_silent_replica_times_out_on_expiry(plane):
    probes = plane.due_probes([0, 1], now=0.0)
    rid0 = probes[0][1].request_id
    _finish(plane, rid0, GOLD_TOKENS)
    # Replica 1 never answers; the NEXT injector pass past the round
    # deadline (4 intervals) expires it and scores the responders.
    assert plane.due_probes([0, 1], now=10.0) == []  # still in flight
    late = plane.due_probes([0, 1], now=500.0)
    assert late != []  # expiry frees the injector for a new round
    assert plane.divergences[1] == {"timeout": 1}


def test_stale_round_output_is_dropped(plane):
    probes = plane.due_probes([0, 1], now=0.0)
    stale_rid = probes[1][1].request_id
    _finish(plane, probes[0][1].request_id, GOLD_TOKENS)
    fresh = plane.due_probes([0, 1], now=500.0)  # expires round 0
    # Round 0's straggler streams in AFTER round 1 opened: it must not
    # pollute replica 1's round-1 slot.
    _finish(plane, stale_rid, [1, 2, 3])
    assert plane._round[1]["tokens"] == []
    for i, req in fresh:
        _finish(plane, req.request_id, GOLD_TOKENS)
    assert plane._round is None
    assert plane.divergences.get(1, {}).get("reference", 0) == 0


def test_quarantine_hint_fires_once_per_episode(plane):
    bad = [t + 1 for t in GOLD_TOKENS]
    for r in range(4):
        _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS}, now=31.0 * r)
    for r in range(4, 8):  # 4 straight corrupted rounds, quarantine_n=2
        _run_round(plane, {0: GOLD_TOKENS, 1: bad}, now=31.0 * r)
    assert plane.quarantine_hints_emitted == 1
    assert plane.quarantine_hints() == {1: "reference"}
    assert plane.quarantine_hints() == {}  # drained
    # A clean round closes the episode and re-arms the hint.
    _run_round(plane, {0: GOLD_TOKENS, 1: GOLD_TOKENS}, now=31.0 * 8)
    assert plane.suspects() == {}
    for r in range(9, 11):
        _run_round(plane, {0: GOLD_TOKENS, 1: bad}, now=31.0 * r)
    assert plane.quarantine_hints_emitted == 2


def test_forget_replica_resolves_round_with_survivors(plane):
    probes = plane.due_probes([0, 1], now=0.0)
    _finish(plane, probes[0][1].request_id, GOLD_TOKENS)
    plane.forget_replica(1)  # quarantined mid-round
    assert plane._round is None  # survivor resolved (and self-seeded)
    assert plane.get_stats()["journal_entries"] == 1
    assert plane.divergences == {}


def test_flag_fingerprint_keys_disjoint_references(monkeypatch):
    sp = canary_sampling_params()
    fp_a = flag_config_fingerprint()
    monkeypatch.setenv("VDT_BLOCK_FUSION", "1")
    fp_b = flag_config_fingerprint()
    assert fp_a != fp_b
    prompt = (11, 29, 7, 3, 17, 23, 5, 13)
    assert reference_key(prompt, sp, fp_a) != reference_key(prompt, sp,
                                                            fp_b)


def test_sentinel_knobs_excluded_from_fingerprint(monkeypatch):
    fp_a = flag_config_fingerprint()
    monkeypatch.setenv("VDT_CANARY_INTERVAL_S", "5")
    monkeypatch.setenv("VDT_NUMERICS_DRIFT_FRAC", "0.9")
    # Tuning the sentinel itself must not re-seed the journal.
    assert flag_config_fingerprint() == fp_a


# ---------------------------------------------------------------------------
# Numerics watch
# ---------------------------------------------------------------------------


def test_numerics_tap_excludes_poisoned_step():
    import numpy as np
    tap = NumericsTap()
    tap.dispatch(np.array([0.0, 1.5, 2.0], dtype=np.float32))
    tap.dispatch(np.array([3.0, float("nan"), 0.0], dtype=np.float32))
    s = tap.stats()  # harvests the pending poisoned step
    assert s["nan_steps"] == 1
    # The clean step landed; the poisoned step's garbage means did not.
    assert s["entropy"]["count"] == 1
    assert s["entropy_window_mean"] == pytest.approx(1.5)
    assert s["window_steps"] == 1


def test_drift_detector_uses_median_not_mean(plane):
    # 3 replicas at 1, 1, 8: the MEAN (3.3) would flag the healthy
    # pair too; the median stays with the majority and isolates the
    # poisoned replica alone.
    snap = lambda m: {"nan_steps": 0, "entropy_window_mean": m}
    plane.observe_numerics({0: snap(1.0), 1: snap(1.0), 2: snap(8.0)})
    assert plane.divergences == {2: {"numerics_drift": 1}}
    assert plane.suspects() == {2: 1}


def test_nan_delta_climbs_ladder_and_clean_poll_resets(plane):
    healthy = {"nan_steps": 0, "entropy_window_mean": 1.0}
    plane.observe_numerics({0: healthy, 1: {"nan_steps": 1,
                                            "entropy_window_mean": 1.0}})
    assert plane.divergences == {1: {"nan_logits": 1}}
    assert plane.suspects() == {1: 1}
    # Same cumulative counter, no NEW NaNs: the poll is clean and the
    # episode resets.
    plane.observe_numerics({0: healthy, 1: {"nan_steps": 1,
                                            "entropy_window_mean": 1.0}})
    assert plane.suspects() == {}


def test_single_replica_never_drifts(plane):
    # Drift is a fleet-relative signal: one replica has no peers to
    # disagree with.
    plane.observe_numerics({0: {"nan_steps": 0,
                                "entropy_window_mean": 42.0}})
    assert plane.divergences == {}


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def _correctness_stats():
    return {
        "probes": {0: 5, 1: 4},
        "divergences": {1: {"vote": 2, "nan_logits": 1}},
        "suspects": {0: 0, 1: 1},
        "quarantine_hints": 1,
        "journal_entries": 4,
    }


def test_render_correctness_families_are_per_replica():
    text = render_metrics({"correctness": _correctness_stats()})
    assert 'vdt:canary_probes_total{replica="0"} 5' in text
    assert 'vdt:canary_probes_total{replica="1"} 4' in text
    assert ('vdt:canary_divergences_total{replica="1",cause="vote"} 2'
            in text)
    assert ('vdt:canary_divergences_total{replica="1",'
            'cause="nan_logits"} 1' in text)
    assert 'vdt:replica_suspect{replica="0"} 0' in text
    assert 'vdt:replica_suspect{replica="1"} 1' in text
    # NEVER numeric-summed: no unlabeled series, no 5+4 rollup.
    for line in text.splitlines():
        if line.startswith("vdt:canary_probes_total"):
            assert line.startswith('vdt:canary_probes_total{replica=')
            assert not line.endswith(" 9")


def test_render_numerics_keyed_and_flat():
    import numpy as np
    tap = NumericsTap()
    tap.dispatch(np.array([0.0, 1.0, 2.0], dtype=np.float32))
    snap = tap.stats()
    # DP shape: {replica: snapshot}.
    text = render_metrics({"numerics": {0: snap, 1: snap}})
    assert 'vdt:logits_nan_steps_total{replica="0"} 0' in text
    assert 'vdt:logits_nan_steps_total{replica="1"} 0' in text
    assert 'vdt:logits_entropy_bucket{replica="1"' in text
    assert 'vdt:logits_top_margin_bucket{replica="0"' in text
    # Single-engine flat snapshot renders as replica 0.
    flat = render_metrics({"numerics": snap})
    assert 'vdt:logits_nan_steps_total{replica="0"} 0' in flat
    assert 'replica="1"' not in flat


def test_render_excludes_dead_replica_mid_scrape():
    # The DP aggregator keys numerics by the ALIVE indices it polled;
    # a replica that died mid-scrape simply has no entry and must not
    # render as a zeroed ghost.
    import numpy as np
    tap = NumericsTap()
    tap.dispatch(np.array([0.0, 1.0, 2.0], dtype=np.float32))
    text = render_metrics({"numerics": {0: tap.stats()},
                           "correctness": {"probes": {0: 3}}})
    assert 'replica="0"' in text
    assert 'replica="1"' not in text


def test_render_off_by_default():
    # VDT_CORRECTNESS=0 ships no correctness/numerics keys at all.
    text = render_metrics({})
    assert "vdt:canary" not in text
    assert "vdt:logits" not in text
    assert "vdt:replica_suspect" not in text
