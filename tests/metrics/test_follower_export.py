"""Follower-process telemetry export (PR 5 named gap): the shm ring's
READ side records in multi-host follower processes with no stats RPC —
followers publish snapshots to VDT_FOLLOWER_STATS_DIR, host 0's
executor folds them into the standard worker/transport merges, and
vdt:shm_ring_*{side="read"} reaches /metrics through the engine core's
existing transport key."""

import json

from vllm_distributed_tpu.metrics import telemetry
from vllm_distributed_tpu.metrics.prometheus import render_metrics


class _FakeWorker:
    def __init__(self, label):
        self._label = label

    def get_stats(self):
        return {"workers": {self._label: {"num_recompiles": 0,
                                          "device_memory_peak_bytes":
                                          123}}}


def _reader_recorder() -> telemetry.TransportRecorder:
    rec = telemetry.TransportRecorder(enabled=True)
    for lag in (0, 2, 5):
        rec.record_shm("read", 0.001, lag=lag)
    return rec


def test_publish_and_collect_round_trip(tmp_path, monkeypatch):
    rec = _reader_recorder()
    monkeypatch.setattr(telemetry, "_current", rec)
    path = telemetry.publish_follower_stats(str(tmp_path), 1,
                                            _FakeWorker("dp0-h1"))
    assert path and path.endswith("follower-h1.json")
    snaps = telemetry.collect_follower_stats(str(tmp_path))
    assert len(snaps) == 1
    snap = snaps[0]
    assert snap["host_rank"] == 1
    assert snap["workers"]["dp0-h1"]["device_memory_peak_bytes"] == 123
    shm = snap["transport"]["shm"]
    assert shm["read"]["messages"] == 3
    assert snap["transport"]["shm_lag_chunks"] == 5
    # Republish overwrites in place (one file per host rank).
    telemetry.publish_follower_stats(str(tmp_path), 1,
                                     _FakeWorker("dp0-h1"))
    assert len(telemetry.collect_follower_stats(str(tmp_path))) == 1


def test_collect_skips_torn_files_and_off(tmp_path):
    assert telemetry.collect_follower_stats("") == []
    assert telemetry.collect_follower_stats(str(tmp_path)) == []
    (tmp_path / "follower-h2.json").write_text("{torn")
    (tmp_path / "follower-h3.json").write_text(
        json.dumps({"host_rank": 3, "workers": {}, "transport":
                    {"kv": {}, "shm": {}, "shm_lag_chunks": 0,
                     "qcomm": {}}}))
    snaps = telemetry.collect_follower_stats(str(tmp_path))
    assert [s["host_rank"] for s in snaps] == [3]


def test_follower_read_side_renders_through_standard_merge(tmp_path,
                                                           monkeypatch):
    """The core's own recorder (write side) + a follower snapshot
    (read side) merge per label and render both sides of
    vdt:shm_ring_* — exactly the DP-merge shape, one level earlier."""
    rec = _reader_recorder()
    monkeypatch.setattr(telemetry, "_current", rec)
    telemetry.publish_follower_stats(str(tmp_path), 1,
                                     _FakeWorker("dp0-h1"))
    host0 = telemetry.TransportRecorder(enabled=True)
    host0.record_shm("write", 0.002)
    snaps = telemetry.collect_follower_stats(str(tmp_path))
    merged = telemetry.merge_transport_snapshots(
        [host0.snapshot()] + [s["transport"] for s in snaps])
    assert merged["shm"]["read"]["messages"] == 3
    assert merged["shm"]["write"]["messages"] == 1
    assert merged["shm_lag_chunks"] == 5
    text = render_metrics({"transport": merged})
    assert 'vdt:shm_ring_messages_total{side="read"} 3' in text
    assert 'vdt:shm_ring_messages_total{side="write"} 1' in text
    assert "vdt:shm_ring_lag_chunks 5" in text
    # Follower worker labels union into the standard per-worker map.
    workers = telemetry.merge_worker_telemetry(
        [{"dp0-h0": {"num_recompiles": 1}}] +
        [s["workers"] for s in snaps])
    assert set(workers) == {"dp0-h0", "dp0-h1"}


# ---------------------------------------------------------------------------
# Fleet-exact process-local counters (PR 19): spawned cores export
# pid-tagged snapshots; the merged remote views fold into /metrics.
# ---------------------------------------------------------------------------


def test_fault_injection_render_folds_remote_counts():
    from vllm_distributed_tpu.metrics.stats import \
        render_fault_injections
    # The fire registry is process-global and clear() keeps cumulative
    # counters: drill suites that ran earlier in this pytest process
    # may already have fired these points, so expectations are
    # local + remote, never bare remote.
    from vllm_distributed_tpu.utils import fault_injection as fi
    stall = fi.counters().get("disagg.handoff_stall", 0)
    corrupt = fi.counters().get("kv.spill_corrupt", 0)
    lines = render_fault_injections(
        {"disagg.handoff_stall": 2, "kv.spill_corrupt": 1})
    text = "\n".join(lines)
    # Remote counts ADD to any local fires at the same point.
    assert (f'vdt:fault_injections_total{{point="disagg.handoff_stall"}}'
            f' {stall + 2}') in text
    assert f'point="kv.spill_corrupt"}} {corrupt + 1}' in text


def test_merged_qcomm_view_folds_remote_snapshot():
    from vllm_distributed_tpu.parallel import collectives
    transport = {"dcn_pull": {"bytes_saved": 100, "fallbacks": 0}}
    remote = {"bytes_saved": {"dcn_pull": 40, "allgather": 7},
              "fallbacks": {"allgather": 1}}
    merged = collectives.merged_qcomm_view(transport, remote)
    assert merged["dcn_pull"]["bytes_saved"] >= 140
    assert merged["allgather"]["bytes_saved"] >= 7
    assert merged["allgather"]["fallbacks"] >= 1
    # Remote None degrades to the old single-process view.
    solo = collectives.merged_qcomm_view(transport, None)
    assert solo["dcn_pull"]["bytes_saved"] >= 100
