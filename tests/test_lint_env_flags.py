"""CI guard: every VDT_* env flag in envs.py stays documented.

Runs scripts/lint_env_flags.py over the real registry + README (the
tier-1 mechanical check that caught the undocumented PR 9-11 flags)
and unit-tests the linter's failure modes on synthetic files."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_env_flags.py"

_ENVS = '''\
environment_variables = {
    "VDT_GOOD_FLAG":
    lambda: "1",
    "VDT_OTHER_FLAG":
    lambda: "x",
}


def unrelated():
    return {"VDT_NOT_A_FLAG": 1}
'''


def _run(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, str(SCRIPT), *args],
                          capture_output=True, text=True, timeout=60)


def _files(tmp_path, envs: str, readme: str):
    envs_path = tmp_path / "envs.py"
    envs_path.write_text(envs)
    readme_path = tmp_path / "README.md"
    readme_path.write_text(readme)
    return envs_path, readme_path


def test_package_env_flags_are_documented():
    res = _run()
    assert res.returncode == 0, (
        f"VDT_* env-flag documentation drifted:\n{res.stderr}")


def test_missing_readme_row_is_caught(tmp_path):
    envs, readme = _files(
        tmp_path, _ENVS, "| `VDT_GOOD_FLAG` | 1 | fine |\n")
    res = _run("--envs", str(envs), "--readme", str(readme))
    assert res.returncode == 1
    assert "VDT_OTHER_FLAG" in res.stderr
    assert "missing from the README" in res.stderr


def test_orphaned_readme_row_is_caught(tmp_path):
    envs, readme = _files(
        tmp_path, _ENVS,
        "| `VDT_GOOD_FLAG` | 1 | fine |\n"
        "| `VDT_OTHER_FLAG` | x | fine |\n"
        "| `VDT_GHOST` | ? | removed long ago |\n")
    res = _run("--envs", str(envs), "--readme", str(readme))
    assert res.returncode == 1
    assert "VDT_GHOST" in res.stderr
    assert "orphaned row" in res.stderr


def test_keys_outside_registry_are_ignored(tmp_path):
    """Only the environment_variables dict counts — stray VDT_* string
    keys elsewhere in the module are not flags."""
    envs, readme = _files(
        tmp_path, _ENVS,
        "| `VDT_GOOD_FLAG` | 1 | fine |\n"
        "| `VDT_OTHER_FLAG` | x | fine |\n")
    res = _run("--envs", str(envs), "--readme", str(readme))
    assert res.returncode == 0, res.stderr


def test_prose_mention_does_not_count_as_documentation(tmp_path):
    envs, readme = _files(
        tmp_path, _ENVS,
        "Set `VDT_GOOD_FLAG` and `VDT_OTHER_FLAG` for fun.\n")
    res = _run("--envs", str(envs), "--readme", str(readme))
    assert res.returncode == 1
    assert "VDT_GOOD_FLAG" in res.stderr


def test_missing_file_is_a_usage_error(tmp_path):
    res = _run("--envs", str(tmp_path / "nope.py"),
               "--readme", str(tmp_path / "nope.md"))
    assert res.returncode == 2
