"""Hierarchical KV/state memory (core/kv_tier.py, ISSUE 15).

Acceptance contract: ``VDT_KV_TIERING=0`` (the default) constructs no
tier state anywhere (byte-identical revert); with tiering ON, demote/
promote round-trips are bit-exact (fp32 + bf16), host/disk budgets
hold, LRU order governs spills, a corrupt spill file degrades to a
clean recompute (fault point ``kv_tier.spill_corrupt``) — never wrong
tokens — SSM snapshot eviction demotes to the checkpoint journal, the
router scores residency by restore cost, and an engine serving a
session working set past its pinned device pool shows a strictly
higher prefix window hit rate with greedy outputs token-identical to
the untiered engine."""

import os

import numpy as np
import pytest

from tests.conftest import make_config, make_request
from vllm_distributed_tpu.core.kv_cache_utils import hash_block_tokens
from vllm_distributed_tpu.core.kv_tier import (TIER_DISK, TIER_GONE,
                                               TIER_HOST, KVTierManager,
                                               maybe_kv_tier)
from vllm_distributed_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


def _chain(n: int, salt: int = 0):
    """n chained BlockHashes (page size 2 tokens)."""
    out, parent = [], None
    for i in range(n):
        bh = hash_block_tokens(parent, (salt * 1000 + 2 * i,
                                        salt * 1000 + 2 * i + 1))
        out.append(bh)
        parent = bh.hash_value
    return out


def _page(seed: int, dtype=np.float32):
    """One wire-layout page pair [L, KVH, PS, D]."""
    rng = np.random.default_rng(seed)
    shape = (2, 2, 4, 8)
    k = rng.standard_normal(shape, np.float32)
    v = rng.standard_normal(shape, np.float32)
    return k.astype(dtype), v.astype(dtype)


def _dtype_params():
    import ml_dtypes
    return [np.float32, ml_dtypes.bfloat16]


PAGE_BYTES = 2 * (2 * 2 * 4 * 8) * 4  # one fp32 page pair


# ---------------------------------------------------------------------------
# Tier-manager units
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", _dtype_params(),
                         ids=["fp32", "bf16"])
def test_host_round_trip_bit_exact(dtype):
    mgr = KVTierManager(host_budget_bytes=1 << 20)
    (bh, ) = _chain(1)
    k, v = _page(0, dtype)
    mgr.insert_host(bh.hash_value, k, v)
    tier, k2, v2 = mgr.lookup(bh)
    assert tier == "host"
    assert k2.dtype == k.dtype and v2.dtype == v.dtype
    assert k2.tobytes() == k.tobytes()
    assert v2.tobytes() == v.tobytes()
    assert mgr.demotions == {"host": 1, "disk": 0}


@pytest.mark.parametrize("dtype", _dtype_params(),
                         ids=["fp32", "bf16"])
def test_disk_round_trip_bit_exact(tmp_path, dtype):
    # Host pool fits ONE page: the second insert spills the first to
    # disk, and the spill file (shared_storage page-file format)
    # restores bit-exact — including bfloat16, which rides the raw-
    # bytes sidecar codec because numpy cannot round-trip it as .npy.
    k0, v0 = _page(0, dtype)
    mgr = KVTierManager(host_budget_bytes=k0.nbytes + v0.nbytes,
                        disk_dir=str(tmp_path))
    a, b = _chain(2)
    mgr.insert_host(a.hash_value, k0, v0)
    mgr.insert_host(b.hash_value, *_page(1, dtype))
    tier, k2, v2 = mgr.lookup(a)
    assert tier == "disk"
    assert k2.dtype == k0.dtype
    assert k2.tobytes() == k0.tobytes()
    assert v2.tobytes() == v0.tobytes()
    assert mgr.demotions["disk"] == 1
    assert os.path.exists(os.path.join(
        str(tmp_path), f"{a.hash_value.hex()}.npz"))


def test_lru_spill_order(tmp_path):
    # Budget holds two pages; A (oldest) spills first. Touching B via
    # lookup moves it most-recently-used, so the next insert spills C.
    mgr = KVTierManager(host_budget_bytes=2 * PAGE_BYTES,
                        disk_dir=str(tmp_path))
    a, b, c, d = _chain(4)
    for i, bh in enumerate((a, b, c)):
        mgr.insert_host(bh.hash_value, *_page(i))
    assert set(mgr._disk) == {a.hash_value}
    assert mgr.lookup(b)[0] == "host"  # touch -> MRU
    mgr.insert_host(d.hash_value, *_page(3))
    assert set(mgr._disk) == {a.hash_value, c.hash_value}
    assert set(mgr._host) == {b.hash_value, d.hash_value}


def test_budget_enforcement(tmp_path):
    host_budget = 2 * PAGE_BYTES
    mgr = KVTierManager(host_budget_bytes=host_budget,
                        disk_dir=str(tmp_path),
                        # Disk fits ~2 compressed pages at most.
                        disk_budget_bytes=2 * PAGE_BYTES)
    hashes = _chain(8)
    for i, bh in enumerate(hashes):
        mgr.insert_host(bh.hash_value, *_page(i))
        assert mgr._host_bytes <= host_budget
    assert mgr._disk_bytes <= 2 * PAGE_BYTES
    # Oldest spill files were deleted past the disk budget.
    on_disk = {n for n in os.listdir(str(tmp_path))
               if n.endswith(".npz")}
    assert len(on_disk) == len(mgr._disk) < 6
    stats = mgr.stats()
    assert stats["pages"]["host"] == 2
    assert stats["bytes"]["host"] == mgr._host_bytes
    # Transitions recorded host demotions, disk spills and evictions.
    codes = {c for _, c in stats["transitions"]}
    assert {TIER_HOST, TIER_DISK, TIER_GONE} <= codes


def test_spill_corrupt_drill_degrades_to_miss(tmp_path):
    k0, v0 = _page(0)
    mgr = KVTierManager(host_budget_bytes=k0.nbytes + v0.nbytes,
                        disk_dir=str(tmp_path))
    a, b = _chain(2)
    mgr.insert_host(a.hash_value, k0, v0)
    mgr.insert_host(b.hash_value, *_page(1))  # spills a to disk
    fi.registry.inject("kv_tier.spill_corrupt", rate=1.0, max_fires=1)
    assert mgr.lookup(a) is None  # clean miss, never bad bytes
    assert mgr.misses["disk"] == 1
    # Quarantined: the corrupt file is gone, later lookups miss fast.
    assert not os.path.exists(os.path.join(
        str(tmp_path), f"{a.hash_value.hex()}.npz"))
    assert mgr.lookup(a) is None
    assert fi.counters().get("kv_tier.spill_corrupt") == 1


def test_shape_foreign_spill_is_miss_not_deleted(tmp_path):
    mgr = KVTierManager(host_budget_bytes=1 << 20,
                        disk_dir=str(tmp_path))
    mgr.wire_shapes = ((2, 2, 4, 8), (2, 2, 4, 8))
    (a, ) = _chain(1)
    # A foreign store's page: same key namespace, different geometry.
    from vllm_distributed_tpu.distributed.kv_transfer import \
        shared_storage
    k = np.zeros((3, 2, 4, 8), np.float32)
    shared_storage.write_page_file(
        os.path.join(str(tmp_path), f"{a.hash_value.hex()}.npz"), k, k)
    assert mgr.lookup(a) is None
    assert mgr.misses["disk"] == 1
    # Someone else's valid page: ignored, never deleted.
    assert os.path.exists(os.path.join(
        str(tmp_path), f"{a.hash_value.hex()}.npz"))


def test_foreign_spill_rejection_keeps_bytes_accounting(tmp_path):
    # De-indexing a shape-foreign file must subtract its bytes: a
    # bare pop would leave phantom bytes that eventually convince the
    # budget sweep to delete the tier's own valid spills.
    mgr = KVTierManager(host_budget_bytes=1 << 20,
                        disk_dir=str(tmp_path))
    mgr.wire_shapes = ((2, 2, 4, 8), (2, 2, 4, 8))
    (a, ) = _chain(1)
    from vllm_distributed_tpu.distributed.kv_transfer import \
        shared_storage
    k = np.zeros((3, 2, 4, 8), np.float32)
    shared_storage.write_page_file(
        os.path.join(str(tmp_path), f"{a.hash_value.hex()}.npz"), k, k)
    # Warm start indexes the foreign file (its shape is unknowable
    # without reading it)...
    fresh = KVTierManager(host_budget_bytes=1 << 20,
                          disk_dir=str(tmp_path))
    fresh.wire_shapes = ((2, 2, 4, 8), (2, 2, 4, 8))
    assert fresh._disk_bytes > 0
    # ...and the rejecting lookup de-indexes it bytes and all.
    assert fresh.lookup(a) is None
    assert fresh._disk_bytes == 0 and not fresh._disk


def test_re_eviction_of_tiered_page_retags_router():
    # Demote -> promote -> evict again: the dedup path must still
    # emit the tier transition or the router scores the page at full
    # HBM credit forever.
    mgr = KVTierManager(host_budget_bytes=1 << 20)
    (a, ) = _chain(1)
    mgr.insert_host(a.hash_value, *_page(0))
    mgr.stats()  # drain the demotion transition
    mgr.note_evicted(7, a)
    assert mgr.take_demotes(True) is None  # content-addressed dedupe
    assert mgr.stats()["transitions"] == [(a.hash_value.hex(),
                                           TIER_HOST)]


def test_match_prefix_stages_and_memoizes(tmp_path):
    k0, v0 = _page(0)
    mgr = KVTierManager(host_budget_bytes=k0.nbytes + v0.nbytes,
                        disk_dir=str(tmp_path))
    hashes = _chain(4)
    mgr.insert_host(hashes[2].hash_value, k0, v0)
    mgr.insert_host(hashes[3].hash_value, *_page(1))  # spills [2]
    # Device holds pages [0, 1]; the tier serves [2, 3]; page size 2,
    # prompt 9 tokens -> max 8 cacheable tokens = all 4 pages.
    n = mgr.match_prefix("r1", hashes, start=2, max_tokens=8,
                         block_size=2)
    assert n == 2
    # Memoized retry: corrupt the spill file under the stash — the
    # blocked-queue-head retry must NOT re-read disk (content-
    # addressed arrays never go stale).
    path = os.path.join(str(tmp_path),
                        f"{hashes[2].hash_value.hex()}.npz")
    with open(path, "wb") as f:
        f.write(b"garbage")
    assert mgr.match_prefix("r1", hashes, start=2, max_tokens=8,
                            block_size=2) == 2
    hits = mgr.take_hits("r1")
    assert [h[0] for h in hits] == [hashes[2].hash_value,
                                    hashes[3].hash_value]
    assert {h[1] for h in hits} == {"host", "disk"}
    assert hits[0][2].tobytes() == k0.tobytes()
    assert mgr.take_hits("r1") is None  # consumed


def test_last_token_never_served_from_tier():
    mgr = KVTierManager(host_budget_bytes=1 << 20)
    hashes = _chain(2)
    for i, bh in enumerate(hashes):
        mgr.insert_host(bh.hash_value, *_page(i))
    # Prompt of exactly 4 tokens (2 pages): the last token must still
    # be computed to produce a logit, so only page 0 may hit.
    assert mgr.match_prefix("r1", hashes, start=0, max_tokens=3,
                            block_size=2) == 1


def test_disk_warm_start(tmp_path):
    k0, v0 = _page(0)
    mgr = KVTierManager(host_budget_bytes=k0.nbytes + v0.nbytes,
                        disk_dir=str(tmp_path))
    a, b = _chain(2)
    mgr.insert_host(a.hash_value, k0, v0)
    mgr.insert_host(b.hash_value, *_page(1))
    # A respawned engine scans the surviving spill files and serves
    # them — fleet-scale session memory across restarts.
    fresh = KVTierManager(host_budget_bytes=1 << 20,
                          disk_dir=str(tmp_path))
    assert a.hash_value in fresh._disk
    tier, k2, _ = fresh.lookup(a)
    assert tier == "disk" and k2.tobytes() == k0.tobytes()


def test_demote_cap_drops_excess():
    mgr = KVTierManager(host_budget_bytes=1 << 20,
                        demote_pages_per_step=2)
    for i, bh in enumerate(_chain(5)):
        mgr.note_evicted(i, bh)
    directive = mgr.take_demotes(True)
    assert len(directive.page_ids) == 2
    assert mgr.demotes_dropped == 3
    # A (defensive) zero-work step drops queued demotes instead of
    # gathering stale device contents.
    for i, bh in enumerate(_chain(2, salt=1)):
        mgr.note_evicted(i, bh)
    assert mgr.take_demotes(False) is None
    assert mgr.demotes_dropped == 5


# ---------------------------------------------------------------------------
# Kill switch / construction gates
# ---------------------------------------------------------------------------
def test_maybe_kv_tier_default_off_and_gates(monkeypatch):
    config = make_config()
    assert maybe_kv_tier(config) is None  # default env: no tier state
    monkeypatch.setenv("VDT_KV_TIERING", "1")
    assert maybe_kv_tier(config) is not None
    assert maybe_kv_tier(config, kv_connector=object()) is None
    config.parallel_config.token_parallel_size = 2
    assert maybe_kv_tier(config) is None


def test_scheduler_off_by_default_constructs_nothing():
    from vllm_distributed_tpu.core.sched.scheduler import Scheduler
    sched = Scheduler(make_config())
    assert sched.kv_tier is None
    assert sched.kv_cache_manager.tier is None
    assert sched.kv_cache_manager.block_pool.on_evict is None
    assert "kv_tier" not in sched.get_stats()


def test_scheduler_tier_wiring(monkeypatch, tmp_path):
    monkeypatch.setenv("VDT_KV_TIERING", "1")
    monkeypatch.setenv("VDT_KV_TIER_DIR", str(tmp_path))
    from vllm_distributed_tpu.core.sched.scheduler import Scheduler
    sched = Scheduler(make_config())
    assert sched.kv_tier is not None
    assert sched.kv_cache_manager.tier is sched.kv_tier
    assert (sched.kv_cache_manager.block_pool.on_evict
            == sched.kv_tier.note_evicted)
    assert "kv_tier" in sched.get_stats()


# ---------------------------------------------------------------------------
# SSM snapshot journal-demotion (state_cache second tier)
# ---------------------------------------------------------------------------
def test_ssm_eviction_demotes_to_journal_and_restores(tmp_path):
    import ml_dtypes

    from vllm_distributed_tpu.core.state_cache import (StateCacheManager,
                                                       write_journal)
    mgr = StateCacheManager(num_slots=1, block_size=4, interval=4,
                            paged_kv=False, journal_dir=str(tmp_path),
                            demote_on_evict=True)
    req1 = make_request(num_tokens=8, token_ids=list(range(10, 18)))
    d1 = mgr.maybe_save(req1, 4)
    assert d1 is not None
    mgr.commit_save(d1, req1)  # committed; journal file NOT yet written

    # Pool full + journal file missing: eviction DEMOTES (owes a
    # persist_only directive, slot pinned) instead of discarding.
    req2 = make_request(num_tokens=8, token_ids=list(range(50, 58)))
    assert mgr.maybe_save(req2, 4) is None  # no slot until it ships
    assert mgr.journal_demotions == 1
    persists = mgr.take_persists()
    assert len(persists) == 1 and persists[0].persist_only
    # Simulate the runner shipping the owed journal write.
    arrays = {"conv": np.arange(12, dtype=np.float32).reshape(3, 4),
              "ssm": np.ones((2, 2), ml_dtypes.bfloat16)}
    write_journal(persists[0].journal, arrays, 4)

    # With the file on disk the LRU victim now evicts normally...
    d2 = mgr.maybe_save(req2, 4)
    assert d2 is not None
    assert mgr.evictions == 1
    mgr.commit_save(d2, req2)

    # ...and a returning session restores the DEMOTED snapshot from
    # the journal, bit-exact (fp32 + bf16 rows).
    req1b = make_request(num_tokens=8, token_ids=list(range(10, 18)))
    blocks, boundary, restore = mgr.get_computed_state(req1b, None)
    assert boundary == 4 and restore is not None
    assert restore.slot == -1 and restore.journal
    got = restore.arrays
    assert got["conv"].tobytes() == arrays["conv"].tobytes()
    assert got["ssm"].tobytes() == arrays["ssm"].tobytes()
    assert got["ssm"].dtype == arrays["ssm"].dtype


def test_ssm_no_demote_without_flag(tmp_path):
    from vllm_distributed_tpu.core.state_cache import StateCacheManager
    mgr = StateCacheManager(num_slots=1, block_size=4, interval=4,
                            paged_kv=False, journal_dir=str(tmp_path))
    req1 = make_request(num_tokens=8, token_ids=list(range(10, 18)))
    d1 = mgr.maybe_save(req1, 4)
    mgr.commit_save(d1, req1)
    req2 = make_request(num_tokens=8, token_ids=list(range(50, 58)))
    # Pre-tiering behavior: the victim is discarded outright.
    assert mgr.maybe_save(req2, 4) is not None
    assert mgr.journal_demotions == 0
    assert mgr.evictions == 1


# ---------------------------------------------------------------------------
# Router tier-aware scoring
# ---------------------------------------------------------------------------
def _router(n=2):
    from vllm_distributed_tpu.engine.router import ReplicaRouter
    return ReplicaRouter(n, make_config())


def test_router_tier_credits_order():
    r = _router(3)
    hashes = [bh.hash_value for bh in _chain(4)]
    for rep in range(3):
        r._register(rep, hashes)
    r.on_demote(1, hashes, 1)  # whole prefix in host RAM
    r.on_demote(2, hashes, 2)  # whole prefix on disk
    a0, a1, a2 = (r._affinity(i, hashes) for i in range(3))
    assert a0 == pytest.approx(1.0)
    assert a0 > a1 > a2 > 0.0  # device > host > disk > nothing
    # Promotion back to HBM restores full credit.
    r.on_demote(2, hashes, 0)
    assert r._affinity(2, hashes) == pytest.approx(1.0)


def test_router_on_evict_drops_and_ignores_unknown():
    r = _router()
    hashes = [bh.hash_value for bh in _chain(2)]
    r._register(0, hashes)
    r.on_evict(0, [hashes[0]])
    assert r._affinity(0, hashes) == 0.0  # leading page gone
    # Demoting a hash we never tracked must not insert it.
    unknown = _chain(1, salt=9)[0].hash_value
    r.on_demote(0, [unknown], 1)
    assert unknown not in r._residency[0]


def test_router_observe_stats_applies_transition_feed():
    r = _router()
    hashes = [bh.hash_value for bh in _chain(2)]
    r._register(0, hashes)
    stats = {"num_running_reqs": 0, "kv_cache_usage": 0.0,
             "kv_tier": {"transitions": [
                 (hashes[0].hex(), 2),
                 (hashes[1].hex(), -1),
                 ("zz-not-hex", 1),  # garbage entries are ignored
             ]}}
    r.observe_stats(0, stats)
    assert r._residency[0][hashes[0]][1] == TIER_DISK
    assert hashes[1] not in r._residency[0]


def test_router_routes_to_cheapest_restore():
    r = _router()
    for i in range(2):
        r.observe_stats(i, {"num_running_reqs": 0,
                            "num_waiting_reqs": 0,
                            "kv_cache_usage": 0.0})
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    from vllm_distributed_tpu.request import EngineCoreRequest
    from vllm_distributed_tpu.sampling_params import SamplingParams
    req = EngineCoreRequest(request_id="r", prompt_token_ids=prompt,
                            sampling_params=SamplingParams())
    hashes = r.request_hashes(req)
    assert hashes
    r._register(0, hashes)
    r._register(1, hashes)
    r.on_demote(0, hashes, 2)  # replica 0 only has it on disk
    assert r.route(req, [0, 0], set()) == 1
    r.on_admit(req, 1)
    assert r.affinity_hits == 1


def test_dp_merge_sums_kv_tier_per_leaf():
    """DP aggregation: per-tier leaves sum, the promotion histogram
    merges element-wise, and the (router-consumed) transition feed
    never reaches the merged view."""
    from vllm_distributed_tpu.engine.dp_client import DPEngineClient
    dp = DPEngineClient.__new__(DPEngineClient)
    dp.clients = [object(), object()]
    dp._live = [set(), set()]
    dp._down = set()
    dp.replica_failovers = 0
    dp.replica_resurrections = 0

    def tier_stats(n):
        return {"pages": {"host": n, "disk": 2 * n},
                "demotions": {"host": 3 * n, "disk": n},
                "demotes_dropped": n,
                "promotion_seconds": {"buckets": [0.01, 0.1],
                                      "counts": [n, 0, 0],
                                      "sum": 0.01 * n, "count": n},
                "transitions": [("ab" * 16, 1)]}

    agg = dp._aggregate_stats([{"kv_tier": tier_stats(1)},
                               {"kv_tier": tier_stats(2)}])
    tier = agg["kv_tier"]
    assert tier["pages"] == {"host": 3, "disk": 6}
    assert tier["demotions"] == {"host": 9, "disk": 3}
    assert tier["demotes_dropped"] == 3
    assert tier["promotion_seconds"]["count"] == 3
    assert "transitions" not in tier


# ---------------------------------------------------------------------------
# Engine-level gate: greedy token parity + strictly better window hit
# rate with the session working set past the pinned device pool, both
# tiers exercised, corrupt-spill drill degrading to recompute.
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    import torch
    from transformers import LlamaConfig
    from transformers import LlamaForCausalLM as HFLlama
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=512, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_tier")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def _make_engine(path):
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    return LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=24, max_model_len=256,
        max_num_batched_tokens=64, max_num_seqs=4,
        skip_tokenizer_init=True).create_engine_config())


def _run_turns(engine, prompts, outs, turn):
    from vllm_distributed_tpu.sampling_params import SamplingParams
    for s in range(len(prompts)):
        engine.add_request(
            f"s{s}t{turn}", list(prompts[s]),
            SamplingParams(temperature=0.0, max_tokens=4,
                           ignore_eos=True))
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                outs[out.request_id] = list(out.outputs[0].token_ids)
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    for s in range(len(prompts)):
        prompts[s] = prompts[s] + outs[f"s{s}t{turn}"] + [90 + s, 91]


def _run_sessions(engine, turns=3):
    """3 sessions x N turns of growing prompts: the combined prefix
    working set runs well past the 24-page (96-token) device pool."""
    outs: dict = {}
    prompts = [[2 + s] * 40 for s in range(3)]
    for turn in range(turns):
        _run_turns(engine, prompts, outs, turn)
    return outs, prompts


def test_engine_parity_and_hit_rate_tiering_on_vs_off(
        checkpoint, monkeypatch, tmp_path):
    monkeypatch.setenv("VDT_KV_TIERING", "0")
    e_off = _make_engine(checkpoint)
    base, base_prompts = _run_sessions(e_off)
    off_stats = e_off.get_stats()
    assert "kv_tier" not in off_stats

    monkeypatch.setenv("VDT_KV_TIERING", "1")
    # Host pool ~10 pages: forces host->disk spills so BOTH tiers
    # serve promotions.
    monkeypatch.setenv("VDT_KV_TIER_HOST_MB", "0.02")
    monkeypatch.setenv("VDT_KV_TIER_DIR", str(tmp_path))
    e_on = _make_engine(checkpoint)
    tiered, on_prompts = _run_sessions(e_on)
    assert tiered == base  # greedy token-identical, tier on vs off
    on_stats = e_on.get_stats()
    tier = on_stats["kv_tier"]
    assert tier["demotions"]["host"] > 0
    assert tier["demotions"]["disk"] > 0
    assert (tier["promotions"]["host"] + tier["promotions"]["disk"]) > 0
    assert tier["promotion_seconds"]["count"] > 0

    # Strictly better prefix window hit rate with tiering on.
    kv_off, kv_on = off_stats["kv_cache"], on_stats["kv_cache"]
    rate_off = kv_off["window_hits"] / max(kv_off["window_queries"], 1)
    rate_on = kv_on["window_hits"] / max(kv_on["window_queries"], 1)
    assert rate_on > rate_off

    # Metrics render end to end.
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    text = render_metrics(on_stats)
    assert 'vdt:kv_tier_pages{tier="host"}' in text
    assert 'vdt:kv_tier_demotions_total{tier="disk"}' in text
    assert "vdt:kv_tier_promotion_seconds_count" in text

    # Corrupt-spill drill: with every disk read corrupted, the next
    # turn DEGRADES to recompute — outputs stay identical to the
    # untiered engine's same turn, never wrong tokens.
    fi.registry.inject("kv_tier.spill_corrupt", rate=1.0)
    outs_off: dict = {}
    outs_on: dict = {}
    _run_turns(e_off, base_prompts, outs_off, 3)
    _run_turns(e_on, on_prompts, outs_on, 3)
    assert outs_on == outs_off
    assert e_on.get_stats()["kv_tier"]["misses"]["disk"] > 0
    e_off.shutdown()
    e_on.shutdown()
