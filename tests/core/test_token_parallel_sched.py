"""TokenParallel scheduler + KV-manager unit tests (device-free).

Model: the fork's TokenParallelScheduler tests — rank assignment is
free-page aware, every page of a request stays inside its rank's pool
partition, and preemption/resume keeps the rank sticky.
(reference: vllm/v1/core/sched/scheduler.py:55-255)
"""

from tests.conftest import make_config, make_request
from vllm_distributed_tpu.config import ParallelConfig
from vllm_distributed_tpu.core.kv_cache_manager import (
    TokenParallelKVCacheManager)
from vllm_distributed_tpu.core.sched.output import ModelRunnerOutput
from vllm_distributed_tpu.core.sched.scheduler import Scheduler


def make_tknp_config(num_ranks=2, **kwargs):
    cfg = make_config(**kwargs)
    cfg.parallel_config = ParallelConfig(token_parallel_size=num_ranks)
    return cfg


def fake_output(scheduler_output, sample_token=7):
    """Answer a SchedulerOutput as the worker would (sample when a
    request's known tokens are fully computed)."""
    req_ids, sampled = [], []
    for req_id in scheduler_output.num_scheduled_tokens:
        req_ids.append(req_id)
        sampled.append([sample_token])
    return ModelRunnerOutput(req_ids=req_ids, sampled_token_ids=sampled)


def rank_range(mgr: TokenParallelKVCacheManager, rank: int):
    lo = rank * mgr.blocks_per_rank
    return range(lo, lo + mgr.blocks_per_rank)


def test_ranks_assigned_and_pages_partitioned():
    sched = Scheduler(make_tknp_config(num_ranks=2, num_blocks=32))
    reqs = [make_request(num_tokens=8, max_tokens=4) for _ in range(4)]
    for r in reqs:
        sched.add_request(r)
    out = sched.schedule()

    assert out.token_parallel_allocation is not None
    alloc = out.token_parallel_allocation
    ranks = [r.tknp_rank for r in reqs]
    assert all(rk is not None for rk in ranks)
    # Free-page-aware assignment balances 4 identical requests 2/2.
    assert sorted(ranks) == [0, 0, 1, 1]
    assert sum(alloc.tokens_per_rank) == out.total_num_scheduled_tokens

    mgr = sched.kv_cache_manager
    for r in reqs:
        ids = mgr.get_block_ids(r.request_id)
        assert ids, r.request_id
        assert all(b in rank_range(mgr, r.tknp_rank) for b in ids), \
            (r.request_id, r.tknp_rank, ids)


def test_pages_stay_in_rank_partition_under_preemption():
    # Tiny pool: 2 ranks x 8 pages; block_size 4 -> each request's 8-token
    # prompt takes 2 pages + grows until the pool churns with preemption.
    # The invariant: at every step, every request's pages sit inside its
    # CURRENT rank's partition (a page-less request may be re-assigned to
    # a less-loaded rank on re-admission; one holding pages never moves).
    sched = Scheduler(make_tknp_config(num_ranks=2, num_blocks=16,
                                       max_num_seqs=8))
    reqs = [make_request(num_tokens=8, max_tokens=40, ignore_eos=True)
            for _ in range(4)]
    for r in reqs:
        sched.add_request(r)
    mgr = sched.kv_cache_manager
    saw_preemption = False
    for _ in range(30):
        out = sched.schedule()
        if not out.num_scheduled_tokens:
            break
        sched.update_from_output(out, fake_output(out))
        saw_preemption |= sched.num_preemptions > 0
        for r in sched.running:
            ids = mgr.get_block_ids(r.request_id)
            assert all(b in rank_range(mgr, r.tknp_rank) for b in ids), \
                (r.request_id, r.tknp_rank, ids)
    assert saw_preemption, "scenario should have preempted something"


def test_abort_waiting_request_without_rank():
    """Aborting a request still in the waiting queue (never assigned a
    rank) must not crash the token-parallel KV manager."""
    from vllm_distributed_tpu.request import RequestStatus
    sched = Scheduler(make_tknp_config(num_ranks=2, num_blocks=32))
    req = make_request(num_tokens=8, max_tokens=4)
    sched.add_request(req)
    assert req.tknp_rank is None
    sched.finish_requests(req.request_id, RequestStatus.FINISHED_ABORTED)
    assert not sched.has_requests()


def test_no_cross_rank_pool_bleed():
    """Exhausting one rank's pool must not consume the other rank's
    pages: the third request lands on the rank with free pages."""
    cfg = make_tknp_config(num_ranks=2, num_blocks=16, max_model_len=64,
                           max_num_batched_tokens=64)
    sched = Scheduler(cfg)
    # Request 0 eats most of one rank's 8 pages (24 tokens = 6 pages).
    big = make_request(num_tokens=24, max_tokens=2)
    sched.add_request(big)
    out = sched.schedule()
    sched.update_from_output(out, fake_output(out))
    rank_of_big = big.tknp_rank
    # Next request must go to the other rank (more free pages there).
    small = make_request(num_tokens=8, max_tokens=2)
    sched.add_request(small)
    out = sched.schedule()
    assert small.tknp_rank == 1 - rank_of_big
    mgr = sched.kv_cache_manager
    assert all(b in rank_range(mgr, small.tknp_rank)
               for b in mgr.get_block_ids(small.request_id))


def test_prefix_cache_is_per_rank():
    """A prefix cached on one rank serves only same-rank requests."""
    cfg = make_tknp_config(num_ranks=2, num_blocks=32)
    sched = Scheduler(cfg)
    shared = list(range(1, 9))
    a = make_request(token_ids=shared, max_tokens=2)
    sched.add_request(a)
    out = sched.schedule()
    sched.update_from_output(out, fake_output(out, sample_token=3))
    # Finish request a -> its pages become evictable-but-cached.
    out2 = sched.schedule()
    sched.update_from_output(out2, fake_output(out2, sample_token=2))
    # Request b, identical prompt: assignment is free-page-aware, and
    # whatever rank it lands on must produce pages in that rank's range.
    b = make_request(token_ids=shared, max_tokens=2)
    sched.add_request(b)
    out3 = sched.schedule()
    assert b.request_id in out3.num_scheduled_tokens
    mgr = sched.kv_cache_manager
    assert all(blk in rank_range(mgr, b.tknp_rank)
               for blk in mgr.get_block_ids(b.request_id))
