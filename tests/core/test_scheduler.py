"""Scheduler unit tests (model: reference tests/v1/core/test_scheduler.py —
construct the Scheduler directly with synthetic requests, no model/device)."""

from vllm_distributed_tpu.core.sched.output import ModelRunnerOutput
from vllm_distributed_tpu.core.sched.scheduler import Scheduler
from vllm_distributed_tpu.request import RequestStatus
from tests.conftest import make_config, make_request


def make_scheduler(**kwargs):
    return Scheduler(make_config(**kwargs))


def fake_output(scheduler_output, sample_token=42):
    """Simulate the workers: one sampled token for every request whose
    scheduled tokens reached the end of its known tokens."""
    req_ids, sampled = [], []
    for req_id, _ in scheduler_output.num_scheduled_tokens.items():
        req_ids.append(req_id)
        sampled.append([sample_token])
    return ModelRunnerOutput(req_ids=req_ids, sampled_token_ids=sampled)


def step(scheduler, sample_token=42):
    out = scheduler.schedule()
    if out.total_num_scheduled_tokens == 0:
        return out, []
    # Partial-prefill requests produce no sample.
    req_ids, sampled = [], []
    for req_id, n in out.num_scheduled_tokens.items():
        req = scheduler.requests[req_id]
        req_ids.append(req_id)
        done_prefill = req.num_computed_tokens + n >= req.num_tokens
        sampled.append([sample_token] if done_prefill else [])
    mro = ModelRunnerOutput(req_ids=req_ids, sampled_token_ids=sampled)
    eco = scheduler.update_from_output(out, mro)
    return out, eco


def test_basic_prefill_then_decode():
    scheduler = make_scheduler()
    req = make_request(num_tokens=8, max_tokens=4)
    scheduler.add_request(req)

    out, _ = step(scheduler)
    assert out.num_scheduled_tokens[req.request_id] == 8
    assert len(out.scheduled_new_reqs) == 1
    assert req.num_computed_tokens == 8
    assert req.output_token_ids == [42]

    out, _ = step(scheduler)
    assert out.num_scheduled_tokens[req.request_id] == 1
    assert len(out.scheduled_new_reqs) == 0
    assert out.scheduled_cached_reqs.req_ids == [req.request_id]


def test_max_tokens_finishes_request():
    scheduler = make_scheduler()
    req = make_request(num_tokens=4, max_tokens=3)
    scheduler.add_request(req)
    for _ in range(3):
        step(scheduler)
    assert req.status == RequestStatus.FINISHED_LENGTH_CAPPED
    assert not scheduler.has_requests()
    # Pages returned.
    assert scheduler.kv_cache_manager.get_num_free_blocks() == 64


def test_eos_stops_request():
    scheduler = make_scheduler()
    req = make_request(num_tokens=4, max_tokens=10)
    scheduler.add_request(req)
    step(scheduler, sample_token=2)  # eos_token_id=2 in conftest
    assert req.status == RequestStatus.FINISHED_STOPPED
    assert req.get_finished_reason() == "stop"


def test_stop_token_ids():
    scheduler = make_scheduler()
    req = make_request(num_tokens=4, max_tokens=10, stop_token_ids=[77])
    scheduler.add_request(req)
    step(scheduler, sample_token=77)
    assert req.status == RequestStatus.FINISHED_STOPPED
    assert req.stop_reason == 77


def test_chunked_prefill_respects_token_budget():
    scheduler = make_scheduler(max_num_batched_tokens=16)
    req = make_request(num_tokens=40, max_tokens=2)
    scheduler.add_request(req)

    out, eco = step(scheduler)
    assert out.num_scheduled_tokens[req.request_id] == 16
    assert req.num_computed_tokens == 16
    assert not eco  # no token sampled mid-prefill

    step(scheduler)
    assert req.num_computed_tokens == 32
    out, eco = step(scheduler)
    assert out.num_scheduled_tokens[req.request_id] == 8
    assert req.output_token_ids == [42]


def test_budget_shared_across_requests():
    scheduler = make_scheduler(max_num_batched_tokens=16)
    reqs = [make_request(num_tokens=10, max_tokens=2) for _ in range(3)]
    for r in reqs:
        scheduler.add_request(r)
    out, _ = step(scheduler)
    # First request fits (10), second chunked to 6, third not scheduled.
    assert out.num_scheduled_tokens[reqs[0].request_id] == 10
    assert out.num_scheduled_tokens[reqs[1].request_id] == 6
    assert reqs[2].request_id not in out.num_scheduled_tokens


def test_max_num_seqs_limit():
    scheduler = make_scheduler(max_num_seqs=2)
    reqs = [make_request(num_tokens=4) for _ in range(4)]
    for r in reqs:
        scheduler.add_request(r)
    out, _ = step(scheduler)
    assert len(out.num_scheduled_tokens) == 2
    assert len(scheduler.running) == 2
    assert len(scheduler.waiting) == 2


def test_decode_batch_mixed_with_prefill():
    scheduler = make_scheduler()
    req_a = make_request(num_tokens=8, max_tokens=8)
    scheduler.add_request(req_a)
    step(scheduler)
    req_b = make_request(num_tokens=8, max_tokens=8)
    scheduler.add_request(req_b)
    out, _ = step(scheduler)
    # a decodes 1 token while b prefills 8 in the same step.
    assert out.num_scheduled_tokens[req_a.request_id] == 1
    assert out.num_scheduled_tokens[req_b.request_id] == 8


def test_preemption_on_memory_pressure():
    # 8 pages of 4 tokens = 32 token slots.
    scheduler = make_scheduler(num_blocks=8, max_num_batched_tokens=32)
    req_a = make_request(num_tokens=15, max_tokens=30)
    req_b = make_request(num_tokens=15, max_tokens=30)
    scheduler.add_request(req_a)
    scheduler.add_request(req_b)
    step(scheduler)  # both prefill: 4 pages each
    # Decode until the pool is exhausted; the scheduler must preempt b
    # (last in running) rather than deadlock.
    for _ in range(10):
        out, _ = step(scheduler)
        if req_b.num_preemptions > 0:
            break
    assert req_b.num_preemptions == 1
    assert req_b.status == RequestStatus.PREEMPTED
    assert req_b in scheduler.waiting
    # a keeps making progress.
    assert req_a.status == RequestStatus.RUNNING

    # Finish a -> b resumes and its re-prefill re-runs from scratch.
    scheduler.finish_requests(req_a.request_id,
                              RequestStatus.FINISHED_ABORTED)
    out, _ = step(scheduler)
    assert req_b.status == RequestStatus.RUNNING
    assert out.scheduled_cached_reqs.resumed_from_preemption == [True]


def test_prefix_cache_reduces_prefill():
    scheduler = make_scheduler(block_size=4)
    req_a = make_request(token_ids=list(range(100, 116)), max_tokens=1)
    scheduler.add_request(req_a)
    step(scheduler)  # prefill 16 + sample -> finished (max_tokens=1)
    assert req_a.is_finished

    req_b = make_request(token_ids=list(range(100, 116)) + [7, 8],
                         max_tokens=1)
    scheduler.add_request(req_b)
    out, _ = step(scheduler)
    # First 16 tokens cached -> only 2 new tokens scheduled.
    assert out.num_scheduled_tokens[req_b.request_id] == 2
    assert out.scheduled_new_reqs[0].num_computed_tokens == 16


def test_priority_policy_orders_waiting():
    scheduler = make_scheduler(policy="priority", max_num_seqs=1)
    req_low = make_request(num_tokens=4, priority=10)
    req_high = make_request(num_tokens=4, priority=0)
    scheduler.add_request(req_low)
    scheduler.add_request(req_high)
    out, _ = step(scheduler)
    assert list(out.num_scheduled_tokens) == [req_high.request_id]


def test_abort_frees_blocks():
    scheduler = make_scheduler()
    req = make_request(num_tokens=8)
    scheduler.add_request(req)
    step(scheduler)
    free_before = scheduler.kv_cache_manager.get_num_free_blocks()
    scheduler.finish_requests(req.request_id, RequestStatus.FINISHED_ABORTED)
    assert scheduler.kv_cache_manager.get_num_free_blocks() > free_before
    out = scheduler.schedule()
    assert req.request_id in out.finished_req_ids


def test_finished_req_ids_propagated_once():
    scheduler = make_scheduler()
    req = make_request(num_tokens=4, max_tokens=1)
    scheduler.add_request(req)
    step(scheduler)
    out = scheduler.schedule()
    assert req.request_id in out.finished_req_ids
    out2 = scheduler.schedule()
    assert req.request_id not in out2.finished_req_ids


def test_context_window_cap():
    scheduler = make_scheduler(max_model_len=16)
    req = make_request(num_tokens=12, max_tokens=100)
    scheduler.add_request(req)
    for _ in range(10):
        step(scheduler)
        if req.is_finished:
            break
    assert req.status == RequestStatus.FINISHED_LENGTH_CAPPED
    assert req.num_tokens <= 16


def test_overlong_prompt_rejected():
    scheduler = make_scheduler(max_model_len=16)
    req = make_request(num_tokens=20, max_tokens=4)
    scheduler.add_request(req)
    out, _ = step(scheduler)
    assert req.status == RequestStatus.FINISHED_IGNORED
    assert req.request_id not in out.num_scheduled_tokens
    assert not scheduler.has_requests()


def test_shared_sampling_params_not_mutated():
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.request import Request
    sp = SamplingParams(temperature=0.0, max_tokens=None)
    req_a = Request("sa", [1, 2, 3], sp, eos_token_id=5)
    req_b = Request("sb", [1, 2, 3], sp, eos_token_id=9)
    assert sp.max_tokens is None  # caller's object untouched
    assert req_a.sampling_params.all_stop_token_ids == {5}
    assert req_b.sampling_params.all_stop_token_ids == {9}


def test_priority_preemption_never_evicts_scheduled():
    # Pool sized so the second decode allocation fails while the
    # high-priority request was already scheduled this step.
    scheduler = make_scheduler(policy="priority", num_blocks=8,
                               max_num_batched_tokens=32)
    req_high = make_request(num_tokens=15, max_tokens=30, priority=0)
    req_low = make_request(num_tokens=15, max_tokens=30, priority=5)
    scheduler.add_request(req_high)
    scheduler.add_request(req_low)
    step(scheduler)
    for _ in range(10):
        out, _ = step(scheduler)
        # Invariant: no request in the output was preempted.
        for rid in out.num_scheduled_tokens:
            assert scheduler.requests[rid].status == RequestStatus.RUNNING
        if req_low.num_preemptions:
            break
    assert req_low.num_preemptions == 1
    assert req_high.status == RequestStatus.RUNNING


def test_spec_tokens_trimmed_to_budget():
    scheduler = make_scheduler(max_num_batched_tokens=64)
    req = make_request(num_tokens=8, max_tokens=20)
    scheduler.add_request(req)
    step(scheduler)  # prefill + first token
    # Pretend the worker proposed 4 draft tokens but shrink the budget so
    # only 2 tokens (1 committed + 1 draft) can run.
    req.spec_token_ids = [201, 202, 203, 204]
    scheduler.max_num_batched_tokens = 2
    out = scheduler.schedule()
    assert out.num_scheduled_tokens[req.request_id] == 2
    assert out.scheduled_spec_decode_tokens[req.request_id] == [201]
    # Worker accepts the draft: returns committed + accepted draft.
    mro = ModelRunnerOutput(req_ids=[req.request_id],
                            sampled_token_ids=[[42, 43]])
    scheduler.update_from_output(out, mro)
    assert req.num_computed_tokens == 10  # 8 prefill + 2 this step
    assert req.output_token_ids[-2:] == [42, 43]


def test_sliding_window_frees_dead_pages():
    """Uniform-window models free pages that leave every future query's
    window (reference: SlidingWindowManager null-block replacement,
    v1/core/single_type_kv_cache_manager.py:444): steady-state page usage
    is bounded by the window, not the generated length."""
    from transformers import MistralConfig
    cfg = make_config(num_blocks=64, max_model_len=128,
                      max_num_batched_tokens=128)
    cfg.model_config.hf_config = MistralConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        sliding_window=8, max_position_embeddings=128)
    scheduler = Scheduler(cfg)
    assert scheduler.kv_cache_manager.free_window == 8

    req = make_request(num_tokens=16, max_tokens=60)
    scheduler.add_request(req)
    step(scheduler)  # prefill
    peak_used = 0
    for _ in range(59):
        step(scheduler)
        used = 64 - scheduler.kv_cache_manager.get_num_free_blocks()
        peak_used = max(peak_used, used)
    # Window 8 + current page + allocation slack: never the 19 pages a
    # 76-token history would need.
    assert peak_used <= 4, peak_used
    assert req.status == RequestStatus.FINISHED_LENGTH_CAPPED
    # Everything returns to the pool (no double-free of nulled slots).
    assert scheduler.kv_cache_manager.get_num_free_blocks() == 64


def test_full_attention_models_do_not_window_free():
    from transformers import LlamaConfig
    cfg = make_config()
    cfg.model_config.hf_config = LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2)
    scheduler = Scheduler(cfg)
    assert scheduler.kv_cache_manager.free_window is None


def test_mixed_window_layout_does_not_free():
    """Any full-attention layer needs the whole history: Gemma2-style
    alternating layouts must not free (per-group freeing needs hybrid
    cache groups — not wired)."""
    from transformers import Qwen2Config
    cfg = make_config()
    cfg.model_config.hf_config = Qwen2Config(
        vocab_size=128, hidden_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2,
        sliding_window=8, use_sliding_window=True, max_window_layers=2,
        max_position_embeddings=128)
    scheduler = Scheduler(cfg)
    assert scheduler.kv_cache_manager.free_window is None
