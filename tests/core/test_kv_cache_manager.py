"""KVCacheManager unit tests (model: reference tests/v1/core/test_prefix_caching.py)."""

from vllm_distributed_tpu.core.kv_cache_manager import KVCacheManager
from tests.conftest import make_request


def make_manager(block_size=4, num_blocks=16, caching=True):
    return KVCacheManager(block_size=block_size, num_blocks=num_blocks,
                          enable_caching=caching)


def test_allocate_and_free():
    mgr = make_manager()
    req = make_request(num_tokens=10)
    blocks = mgr.allocate_slots(req, 10)
    assert blocks is not None
    # 10 tokens / block_size 4 -> 3 pages.
    assert len(blocks.blocks) == 3
    assert mgr.get_num_free_blocks() == 13
    req.status = 3  # FINISHED_STOPPED (free requires finished? manager doesn't check)
    mgr.free(req)
    assert mgr.get_num_free_blocks() == 16


def test_allocation_failure_returns_none():
    mgr = make_manager(num_blocks=2)
    req = make_request(num_tokens=12)
    assert mgr.allocate_slots(req, 12) is None
    # Failed allocation must not leak blocks.
    assert mgr.get_num_free_blocks() == 2


def test_incremental_decode_allocation():
    mgr = make_manager(block_size=4)
    req = make_request(num_tokens=7)
    first = mgr.allocate_slots(req, 7)
    assert len(first.blocks) == 2  # 7 tokens -> 2 pages
    req.num_computed_tokens = 7
    req.append_output_token_ids(100)
    # Token 8 fits in page 2 (slot 7).
    more = mgr.allocate_slots(req, 1)
    assert len(more.blocks) == 0
    req.num_computed_tokens = 8
    req.append_output_token_ids(101)
    # Token 9 needs a third page.
    more = mgr.allocate_slots(req, 1)
    assert len(more.blocks) == 1


def test_prefix_cache_hit_across_requests():
    mgr = make_manager(block_size=4)
    req_a = make_request(token_ids=list(range(100, 112)))  # 12 tokens
    blocks = mgr.allocate_slots(req_a, 12)
    assert blocks is not None
    req_a.num_computed_tokens = 12

    # Same first 8 tokens, different tail.
    req_b = make_request(token_ids=list(range(100, 108)) + [7, 8, 9, 10])
    cached, num_computed = mgr.get_computed_blocks(req_b)
    assert num_computed == 8
    assert len(cached.blocks) == 2
    assert cached.blocks[0] is mgr.req_to_blocks[req_a.request_id][0]

    new_blocks = mgr.allocate_slots(req_b, 12 - num_computed, cached)
    assert new_blocks is not None
    # Shared pages are ref-counted, not copied.
    assert cached.blocks[0].ref_cnt == 2


def test_full_prompt_cached_leaves_last_token():
    mgr = make_manager(block_size=4)
    req_a = make_request(token_ids=list(range(100, 108)))
    mgr.allocate_slots(req_a, 8)

    # Identical prompt: hit is capped below the full prompt so the last
    # token still produces a logit.
    req_b = make_request(token_ids=list(range(100, 108)))
    cached, num_computed = mgr.get_computed_blocks(req_b)
    assert num_computed == 4  # capped to < 8 at page granularity


def test_no_caching_mode():
    mgr = make_manager(caching=False)
    req_a = make_request(num_tokens=8)
    mgr.allocate_slots(req_a, 8)
    req_b = make_request(num_tokens=8)
    cached, num_computed = mgr.get_computed_blocks(req_b)
    assert num_computed == 0 and not cached.blocks


def test_freed_blocks_reusable_as_prefix():
    mgr = make_manager(block_size=4, num_blocks=4)
    req_a = make_request(token_ids=[5, 6, 7, 8, 9])
    mgr.allocate_slots(req_a, 5)
    req_a.num_computed_tokens = 5
    mgr.free(req_a)
    mgr.free_block_hashes(req_a)
    assert mgr.get_num_free_blocks() == 4

    # New request with the same first page hits the dangling cached page.
    req_b = make_request(token_ids=[5, 6, 7, 8, 1, 2])
    cached, num_computed = mgr.get_computed_blocks(req_b)
    assert num_computed == 4
