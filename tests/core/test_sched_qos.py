"""Per-tenant QoS (core/sched/qos.py + the scheduler wiring): DRR
weighted fair queueing over granted tokens, soft KV page quotas with
quota-aware preemption and cooldown hysteresis, bounded tenant
bucketing, per-tenant stats/DP merge/render, and the ``VDT_QOS=0``
no-state revert.

The adversarial-flood drill here is the deterministic, scheduler-level
form of the acceptance criterion: gaps are measured in SCHEDULER STEPS
(each step is one decode iteration, so an interactive request's
inter-grant step gap IS its TPOT in step units) instead of flaky wall
clock — bench.py's QoS leg carries the wall-clock version."""

import pytest

from tests.conftest import make_config, make_request
from vllm_distributed_tpu.core.sched import qos as qm
from vllm_distributed_tpu.core.sched.output import ModelRunnerOutput
from vllm_distributed_tpu.core.sched.scheduler import Scheduler
from vllm_distributed_tpu.request import RequestStatus
from vllm_distributed_tpu.utils import fault_injection as fi


def make_scheduler(monkeypatch=None, *, qos=True, weights="",
                   quota=None, **cfg):
    if monkeypatch is not None and qos:
        monkeypatch.setenv("VDT_QOS", "1")
        if weights:
            monkeypatch.setenv("VDT_QOS_WEIGHTS", weights)
        if quota is not None:
            monkeypatch.setenv("VDT_QOS_KV_QUOTA_FRAC", str(quota))
    return Scheduler(make_config(**cfg))


def tagged(tenant, num_tokens, **kw):
    r = make_request(num_tokens=num_tokens, **kw)
    r.tenant = tenant
    return r


def step(scheduler, sample_token=42):
    """One schedule + reconcile round (tests/core/test_scheduler.py
    idiom): requests whose grant completes their known tokens sample
    one token, partial prefill chunks sample nothing."""
    out = scheduler.schedule()
    if out.total_num_scheduled_tokens == 0:
        return out, []
    req_ids, sampled = [], []
    for req_id, n in out.num_scheduled_tokens.items():
        req = scheduler.requests[req_id]
        req_ids.append(req_id)
        done = req.num_computed_tokens + n >= req.num_tokens
        # Async-off: num_computed is pre-advance at this point.
        sampled.append([sample_token] if done else [])
    mro = ModelRunnerOutput(req_ids=req_ids, sampled_token_ids=sampled)
    return out, scheduler.update_from_output(out, mro)


# ---------------------------------------------------------------------------
# VDT_QOS=0 (default): no state, byte-identical scheduling
# ---------------------------------------------------------------------------
def test_qos_off_by_default_constructs_no_state():
    s = make_scheduler()
    assert s.qos is None
    assert "tenants" not in s.get_stats()
    assert s.get_debug_state()["qos"] is None


def test_single_tenant_qos_on_matches_qos_off(monkeypatch):
    """Work-conserving gate: with one tenant and no pool pressure the
    DRR clips are all waived, so QoS on grants exactly what the
    pre-QoS scheduler (QoS off, the byte-identical default path)
    grants, step for step."""
    traces = {}
    for mode in ("off", "on"):
        if mode == "on":
            monkeypatch.setenv("VDT_QOS", "1")
        s = Scheduler(make_config(max_num_batched_tokens=32,
                                  num_blocks=128, max_model_len=512))
        reqs = [make_request(num_tokens=n, max_tokens=4, req_id=f"r{i}",
                             token_ids=list(range(501 + 100 * i,
                                                  501 + 100 * i + n)))
                for i, n in enumerate((70, 9, 33))]
        for r in reqs:
            s.add_request(r)
        trace = []
        for _ in range(30):
            out, _ = step(s)
            trace.append(sorted(out.num_scheduled_tokens.items()))
            if not s.has_requests():
                break
        traces[mode] = (trace, [list(r.output_token_ids) for r in reqs])
    assert traces["on"] == traces["off"]


# ---------------------------------------------------------------------------
# Units: weight spec, tenant bucketing, deficit carry-over
# ---------------------------------------------------------------------------
def test_parse_weights_drops_malformed_entries():
    w = qm.parse_weights("gold:3, bronze:1.5,,bad,neg:-2,zero:0,:7,"
                         "interactive:2")
    assert w == {"gold": 3.0, "bronze": 1.5, "interactive": 2.0}


def test_bucket_tenant_bounds_cardinality():
    tracked = set()
    assert qm.bucket_tenant(None, tracked, 2) == qm.DEFAULT_KEY
    assert qm.bucket_tenant("a", tracked, 2) == "a"
    assert qm.bucket_tenant("b", tracked, 2) == "b"
    # Past the cap: stable hash buckets, never new tracked ids.
    over = {qm.bucket_tenant(f"t{i}", tracked, 2) for i in range(100)}
    assert tracked == {"a", "b"}
    assert all(k.startswith("~") for k in over)
    assert len(over) <= qm.OVERFLOW_BUCKETS
    # Deterministic: the same tenant always lands in the same bucket.
    assert (qm.bucket_tenant("t7", tracked, 2)
            == qm.bucket_tenant("t7", tracked, 2))
    # Tracked ids keep resolving to themselves.
    assert qm.bucket_tenant("a", tracked, 2) == "a"


def test_deficit_carry_over_is_bounded():
    state = qm.QosState(64, 64, weights_spec="", quota_frac=0.5,
                        max_tracked=8)
    idle = tagged("idle", 8)
    for _ in range(10):  # replenished but never charged
        state.begin_step([idle], [], {})
    assert state.deficit["idle"] == qm.DEFICIT_CARRY_STEPS * 64
    # Debt from work-conserving over-grants floors symmetrically.
    for _ in range(20):
        state.charge("idle", 64)
    assert state.deficit["idle"] == -qm.DEFICIT_CARRY_STEPS * 64


def test_tenantless_requests_share_the_anon_bucket(monkeypatch):
    s = make_scheduler(monkeypatch)
    for n in (8, 12):
        s.add_request(make_request(num_tokens=n, max_tokens=2))
    step(s)
    tenants = s.get_stats()["tenants"]
    assert set(tenants) == {qm.DEFAULT_KEY}
    assert tenants[qm.DEFAULT_KEY]["granted_tokens"] == 20


# ---------------------------------------------------------------------------
# DRR grant loop
# ---------------------------------------------------------------------------
def test_drr_weights_split_prefill_bandwidth(monkeypatch):
    """Two tenants chunk-prefilling long prompts through a 64-token
    budget: granted tokens must track the 3:1 weight spec, not the
    arrival order."""
    s = make_scheduler(monkeypatch, weights="gold:3,bronze:1",
                       max_num_batched_tokens=64, num_blocks=256,
                       max_model_len=1024)
    s.add_request(tagged("bronze", 320, max_tokens=2))  # arrives first
    s.add_request(tagged("gold", 320, max_tokens=2))
    for _ in range(4):
        step(s)
    granted = s.qos.granted_tokens
    ratio = granted["gold"] / granted["bronze"]
    assert 2.5 <= ratio <= 3.5, granted
    # Weighted split of every full budget: nothing left idle.
    assert granted["gold"] + granted["bronze"] == 4 * 64


def test_class_weights_map_through_priority(monkeypatch):
    """best_effort/interactive class keys (PR 7's priority classes)
    resolve weights for tenants with no explicit entry."""
    s = make_scheduler(monkeypatch, weights="best_effort:1,interactive:3",
                      max_num_batched_tokens=64, num_blocks=256,
                      max_model_len=1024)
    flood = tagged("flood", 320, max_tokens=2, priority=1)  # best_effort
    chat = tagged("chat", 320, max_tokens=2, priority=0)    # interactive
    s.add_request(flood)
    s.add_request(chat)
    for _ in range(4):
        step(s)
    granted = s.qos.granted_tokens
    assert 2.5 <= granted["chat"] / granted["flood"] <= 3.5, granted


def test_adversarial_flood_bounded_interactive_gaps(monkeypatch):
    """The acceptance drill, in deterministic step units: a flood
    tenant chunk-prefilling a huge prompt ahead of an interactive
    tenant in the running list. QoS ON: the interactive request admits
    within a few steps and then receives its decode token EVERY step
    (max inter-grant gap 1 — the decode-headroom reservation). QoS
    OFF: the flood's chunks consume the whole budget and the
    interactive request starves for the length of the flood prefill."""
    for mode in ("on", "off"):
        if mode == "on":
            monkeypatch.setenv("VDT_QOS", "1")
        else:
            monkeypatch.delenv("VDT_QOS", raising=False)
        s = Scheduler(make_config(max_num_batched_tokens=16,
                                  num_blocks=512, max_model_len=2048))
        flood = tagged("flood", 960, max_tokens=4)
        s.add_request(flood)
        step(s)  # flood alone: work-conserving full budget
        assert s.qos is None or \
            s.qos.granted_tokens["flood"] == 16
        inter = tagged("chat", 8, max_tokens=40)
        s.add_request(inter)
        grant_steps = []
        for i in range(40):
            out, _ = step(s)
            if inter.request_id in out.num_scheduled_tokens:
                grant_steps.append(i)
        if mode == "on":
            # Admitted immediately; decode served every step after.
            assert grant_steps[0] <= 1
            gaps = [b - a for a, b in zip(grant_steps, grant_steps[1:])]
            assert max(gaps) <= 1, gaps
            # The flood still progresses (work stays conserved).
            assert flood.num_computed_tokens > 200
        else:
            # Pre-QoS behavior: the 960-token prefill walls off the
            # budget for ~960/16 = 60 steps — chat sees NOTHING in the
            # 40-step observation window.
            assert not grant_steps


# ---------------------------------------------------------------------------
# Quota-aware preemption
# ---------------------------------------------------------------------------
def test_quota_preemption_evicts_over_quota_lowest_priority(monkeypatch):
    """Pages run out while tenant "hog" is far over its soft quota:
    the victim must be hog's lowest-priority request (not the last
    running request, which is the capacity policy's pick), attributed
    cause "quota" and counted per tenant."""
    s = make_scheduler(monkeypatch, quota=0.4, policy="priority",
                       num_blocks=16, max_num_batched_tokens=64,
                       max_model_len=256)
    small = tagged("small", 7, max_tokens=30, priority=0)
    hog_hi = tagged("hog", 15, max_tokens=30, priority=1)
    hog_lo = tagged("hog", 15, max_tokens=30, priority=5)
    for r in (small, hog_hi, hog_lo):
        s.add_request(r)
    step(s)  # all prefill: 2 + 4 + 4 pages of 16; quota = 6
    for _ in range(12):
        step(s)
        if s.num_preemptions:
            break
    assert s.preemption_causes.get("quota", 0) >= 1
    assert hog_lo.num_preemptions == 1
    assert hog_hi.num_preemptions == 0
    assert small.num_preemptions == 0
    assert s.get_stats()["tenants"]["hog"]["preemptions"] >= 1


def test_quota_thrash_drill_hysteresis_bounds_the_storm(monkeypatch):
    """sched.quota_thrash forces every page-holding tenant over quota,
    so each allocation failure WANTS a quota eviction — the cooldown
    must space quota preemptions out per tenant and the scheduler must
    keep making progress (no evict/resume livelock)."""
    s = make_scheduler(monkeypatch, quota=0.5, num_blocks=12,
                       max_num_batched_tokens=64, max_model_len=256)
    fi.clear()
    fi.inject("sched.quota_thrash")
    try:
        a = tagged("osc", 15, max_tokens=25)
        b = tagged("osc", 15, max_tokens=25)
        c = tagged("other", 7, max_tokens=25)
        for r in (a, b, c):
            s.add_request(r)
        steps = 0
        while s.has_requests() and steps < 200:
            step(s)
            steps += 1
        # Progress: everything finished despite the forced storm.
        assert not s.has_requests(), (steps, s.preemption_causes)
        quota_evictions = s.preemption_causes.get("quota", 0)
        assert quota_evictions >= 1  # the drill actually fired
        # Hysteresis bound: per tenant, at most one quota eviction per
        # cooldown window (2 tenants share the storm).
        assert quota_evictions <= 2 * (steps // qm.QUOTA_COOLDOWN_STEPS
                                       + 1), (quota_evictions, steps)
    finally:
        fi.clear()


def test_over_quota_tenant_waits_at_admission_under_pressure():
    """pick_waiting_tenant passes over an over-quota tenant while an
    under-quota tenant has waiting work — but only at pool pressure,
    and never when every waiting tenant is over (work conserving)."""
    state = qm.QosState(64, 100, weights_spec="", quota_frac=0.1,
                        max_tracked=8)
    state.held = {"hog": 50, "small": 2}
    state.deficit = {"hog": 64.0, "small": 1.0}
    # Pressured: the under-quota tenant wins despite the deficit gap.
    assert state.pick_waiting_tenant(["hog", "small"], 0.95) == "small"
    # Unpressured: quota is soft — deficit order stands.
    assert state.pick_waiting_tenant(["hog", "small"], 0.5) == "hog"
    # Every candidate over quota: deficit order again (no starvation).
    state.held["small"] = 40
    assert state.pick_waiting_tenant(["hog", "small"], 0.95) == "hog"


# ---------------------------------------------------------------------------
# Stats plumbing: scheduler -> DP merge -> /metrics render
# ---------------------------------------------------------------------------
def test_tenant_stats_dp_merge_and_render():
    from vllm_distributed_tpu.engine.dp_client import DPEngineClient
    from vllm_distributed_tpu.metrics.prometheus import render_metrics

    class _FakeClient:
        def __init__(self, stats):
            self._stats = stats

        def get_stats(self):
            return dict(self._stats)

    per = [
        {"tenants": {"a": {"granted_tokens": 100, "kv_blocks": 4,
                           "preemptions": 1}}},
        {"tenants": {"a": {"granted_tokens": 50, "kv_blocks": 2,
                           "preemptions": 0},
                     "_anon": {"granted_tokens": 7, "kv_blocks": 1,
                               "preemptions": 0}}},
    ]
    dp = DPEngineClient.__new__(DPEngineClient)
    dp.clients = [_FakeClient(s) for s in per]
    dp._live = [set(), set()]
    dp._down = set()
    dp.replica_failovers = 0
    dp.replica_resurrections = 0
    agg = dp.get_stats()
    assert agg["tenants"]["a"] == {"granted_tokens": 150, "kv_blocks": 6,
                                   "preemptions": 1}
    assert agg["tenants"]["_anon"]["granted_tokens"] == 7
    text = render_metrics(agg)
    assert 'vdt:tenant_granted_tokens_total{tenant="a"} 150' in text
    assert 'vdt:tenant_kv_blocks{tenant="a"} 6' in text
    assert 'vdt:tenant_preemptions_total{tenant="a"} 1' in text
    assert 'vdt:tenant_granted_tokens_total{tenant="_anon"} 7' in text


def test_tenant_goodput_scored_and_rendered():
    from vllm_distributed_tpu.metrics.stats import (FrontendStats,
                                                    RequestTimes)
    fe = FrontendStats()
    fe.slo_ttft_ms = 100.0
    good = RequestTimes(arrival=0.0, first_token=0.05, last_token=0.2)
    bad = RequestTimes(arrival=0.0, first_token=0.5, last_token=0.9)
    fe.on_slo(good, 8, tenant="chat")
    fe.on_slo(bad, 8, tenant="flood")
    fe.on_slo(good, 8, tenant="flood")
    text = fe.render()
    assert 'vdt:tenant_goodput_frac{tenant="chat"} 1.0' in text
    assert 'vdt:tenant_goodput_frac{tenant="flood"} 0.5' in text
    # Tenantless scoring (QoS off) renders no per-tenant series.
    fe2 = FrontendStats()
    fe2.slo_ttft_ms = 100.0
    fe2.on_slo(good, 8)
    assert "tenant_goodput" not in fe2.render()
