"""BlockPool unit tests (model: reference tests/v1/core/)."""

import pytest

from vllm_distributed_tpu.core.block_pool import BlockPool
from vllm_distributed_tpu.core.kv_cache_utils import hash_block_tokens


def test_alloc_free_roundtrip():
    pool = BlockPool(num_blocks=8)
    assert pool.get_num_free_blocks() == 8
    blocks = pool.get_new_blocks(3)
    assert pool.get_num_free_blocks() == 5
    assert len({b.block_id for b in blocks}) == 3
    pool.free_blocks(list(reversed(blocks)))
    assert pool.get_num_free_blocks() == 8


def test_overallocation_raises():
    pool = BlockPool(num_blocks=2)
    with pytest.raises(ValueError):
        pool.get_new_blocks(3)


def test_prefix_cache_hit_and_touch():
    pool = BlockPool(num_blocks=4)
    blocks = pool.get_new_blocks(2)
    h0 = hash_block_tokens(None, (1, 2, 3, 4))
    h1 = hash_block_tokens(h0.hash_value, (5, 6, 7, 8))
    pool.cache_full_blocks(blocks, [h0, h1], 0, 2)

    # Free the blocks: they stay in the cache index until evicted.
    pool.free_blocks(list(reversed(blocks)))
    hit = pool.get_cached_block(h0)
    assert hit is blocks[0]

    # touch() takes a ref and removes from the free queue.
    pool.touch([hit])
    assert pool.get_num_free_blocks() == 3
    assert hit.ref_cnt == 1
    pool.free_blocks([hit])


def test_eviction_removes_hash():
    pool = BlockPool(num_blocks=2)
    blocks = pool.get_new_blocks(2)
    h0 = hash_block_tokens(None, (1, 2))
    pool.cache_full_blocks(blocks, [h0], 0, 1)
    pool.free_blocks(list(reversed(blocks)))

    # Allocating all blocks evicts the cached one (LRU order: blocks[1]
    # freed first, then blocks[0] — eviction pops blocks[1] first).
    newly = pool.get_new_blocks(2)
    assert pool.get_cached_block(h0) is None
    assert {b.block_id for b in newly} == {0, 1}


def test_lru_eviction_order_prefers_prefix():
    pool = BlockPool(num_blocks=3)
    blocks = pool.get_new_blocks(3)
    # Freed tail-first: eviction order is tail, middle, head.
    pool.free_blocks(list(reversed(blocks)))
    popped = pool.get_new_blocks(3)
    assert [b.block_id for b in popped] == \
        [blocks[2].block_id, blocks[1].block_id, blocks[0].block_id]


def test_reset_prefix_cache():
    pool = BlockPool(num_blocks=2)
    blocks = pool.get_new_blocks(1)
    h0 = hash_block_tokens(None, (9,))
    pool.cache_full_blocks(blocks, [h0], 0, 1)
    # In use -> refuse.
    assert not pool.reset_prefix_cache()
    pool.free_blocks(blocks)
    assert pool.reset_prefix_cache()
    assert pool.get_cached_block(h0) is None
