"""EPLB: placement algorithm invariants + end-to-end redundant-expert
routing parity (model: reference tests/distributed/test_eplb_algo.py /
test_eplb_execute.py, pure-CPU)."""

import numpy as np
import pytest
import torch
from transformers import MixtralConfig
from transformers import MixtralForCausalLM as HFMixtral

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.parallel.eplb import (EplbState, rank_loads,
                                                rebalance_experts)
from vllm_distributed_tpu.sampling_params import SamplingParams


def test_placement_invariants():
    rng = np.random.default_rng(0)
    loads = rng.gamma(1.0, 1.0, size=(3, 8))
    p = rebalance_experts(loads, num_physical=12, num_ranks=4)
    L, P = p.phys_to_logical.shape
    assert (L, P) == (3, 12)
    for layer in range(L):
        # Every logical expert hosted at least once; replica counts match.
        counts = np.bincount(p.phys_to_logical[layer], minlength=8)
        assert (counts >= 1).all()
        assert (counts == p.logical_replicas[layer]).all()
        # logical_to_phys inverts phys_to_logical.
        for e in range(8):
            ids = p.logical_to_phys[layer, e]
            ids = ids[ids >= 0]
            assert len(ids) == counts[e]
            assert all(p.phys_to_logical[layer, i] == e for i in ids)


def test_replicas_go_to_hot_experts_and_balance_ranks():
    # One extremely hot expert: it must get the spare slots, and the
    # packed per-rank load must beat the naive contiguous layout.
    loads = np.asarray([[100.0, 1, 1, 1, 1, 1, 1, 2]])
    p = rebalance_experts(loads, num_physical=12, num_ranks=4)
    assert p.logical_replicas[0, 0] == 5  # all 4 spares + original
    balanced = rank_loads(p, loads, 4)[0]
    naive = np.asarray(
        [loads[0, 0] + loads[0, 1], loads[0, 2] + loads[0, 3],
         loads[0, 4] + loads[0, 5], loads[0, 6] + loads[0, 7]])
    assert balanced.max() < naive.max() / 2
    # Replicas of the hot expert spread across ranks.
    hot_ranks = {i // 3 for i in p.logical_to_phys[0, 0] if i >= 0}
    assert len(hot_ranks) >= 3


def test_eplb_state_ema_and_cadence():
    st = EplbState(num_layers=1, num_experts=4, ema_decay=0.5,
                   rebalance_interval=3)
    for _ in range(3):
        st.record(np.asarray([[8.0, 0, 0, 0]]))
    assert st.should_rebalance()
    assert st.loads[0, 0] > st.loads[0, 1]
    p = st.make_placement(num_physical=6, num_ranks=2)
    assert not st.should_rebalance()
    assert p.logical_replicas[0, 0] == 3  # hot expert got both spares


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = MixtralConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=96, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        num_local_experts=4, num_experts_per_tok=2,
                        max_position_embeddings=64, eos_token_id=1)
    hf = HFMixtral(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_mixtral_eplb")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def run(engine, prompts, tag):
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(200):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


PROMPTS = [[3, 17, 92, 45, 8, 21, 33], [5, 9, 33, 71, 14]]


def test_redundant_experts_preserve_hf_parity(checkpoint):
    """Physical replicas + routing indirection must be numerically
    invisible: redundant-expert engines match the plain engine exactly
    (replica weights are copies; per-token replica choice is arbitrary
    but the weights are identical)."""
    path, _hf = checkpoint

    def make(**overrides):
        args = dict(model=path, dtype="float32", block_size=4,
                    num_gpu_blocks_override=128, max_model_len=64,
                    max_num_batched_tokens=64, max_num_seqs=8,
                    skip_tokenizer_init=True)
        args.update(overrides)
        return LLMEngine(EngineArgs(**args).create_engine_config())

    base = run(make(), PROMPTS, "b")
    redundant = run(make(num_redundant_experts=2), PROMPTS, "r")
    assert redundant == base
    # And under expert parallelism over the padded physical count
    # (6 physical experts NOT divisible by tp=2? use 4+4=8 phys, tp=4).
    ep = run(make(num_redundant_experts=4, enable_expert_parallel=True,
                  tensor_parallel_size=4), PROMPTS, "e")
    assert ep == base


def test_live_rebalance_keeps_outputs(checkpoint):
    """apply_rebalance moves weights to a new placement mid-flight;
    outputs after the move stay identical to before."""
    path, _hf = checkpoint
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True, num_redundant_experts=2)
    engine = LLMEngine(EngineArgs(**args).create_engine_config())
    first = run(engine, PROMPTS, "a")

    runner = engine.engine_core.engine_core.executor.worker.model_runner
    model = runner.model
    loads = np.asarray([[5.0, 1.0, 9.0, 2.0]] * 2)
    placement = rebalance_experts(loads, model.num_physical, 1)
    runner.params = model.apply_rebalance(runner.params, placement)

    second = run(engine, PROMPTS, "b")
    assert second == first
