"""HTTP tests for the encoder-model serving surface: /v1/embeddings on a
BERT checkpoint, /v1/score and /v1/rerank on a cross-encoder (reference:
serving_embedding.py + serving_score.py of the reference's OpenAI
server)."""

import asyncio
import threading

import httpx
import numpy as np
import pytest
import torch
import transformers

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.utils import get_open_port

VOCAB = 96


def _save_tokenizer(path):
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast
    vocab = {f"w{i}": i for i in range(VOCAB - 2)}
    vocab["<unk>"] = VOCAB - 2
    vocab["</s>"] = VOCAB - 1
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    fast = PreTrainedTokenizerFast(tokenizer_object=tok,
                                   unk_token="<unk>", eos_token="</s>")
    fast.save_pretrained(path)
    return fast


def _serve(path):
    engine_args = EngineArgs(model=path, dtype="float32", block_size=4,
                             max_model_len=32, max_num_batched_tokens=64,
                             max_num_seqs=8)
    engine = AsyncLLM(engine_args.create_engine_config())
    port = get_open_port()
    ready = threading.Event()
    holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        holder["stop"], holder["loop"] = stop, loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready, stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=120), "server did not start"
    return f"http://127.0.0.1:{port}", holder, t


@pytest.fixture(scope="module")
def cross_server(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tiny_cross_served"))
    cfg = transformers.BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, num_labels=1)
    torch.manual_seed(7)
    hf = transformers.BertForSequenceClassification(cfg).eval()
    hf.save_pretrained(path, safe_serialization=True)
    tok = _save_tokenizer(path)
    base, holder, t = _serve(path)
    yield base, hf, tok
    holder["loop"].call_soon_threadsafe(holder["stop"].set)
    t.join(timeout=30)


def _hf_score(hf, tok, q, d):
    enc = tok(q, d)
    ids = torch.tensor([enc["input_ids"]], dtype=torch.long)
    tt = enc.get("token_type_ids")
    tt = torch.tensor([tt if tt else [0] * ids.shape[1]], dtype=torch.long)
    with torch.no_grad():
        # Single-logit cross-encoders score through sigmoid (HF's
        # get_cross_encoder_activation_function for num_labels == 1).
        return float(torch.sigmoid(
            hf(input_ids=ids, token_type_ids=tt).logits[0, 0]))


def test_score_endpoint_matches_hf(cross_server):
    base, hf, tok = cross_server
    r = httpx.post(f"{base}/v1/score", timeout=300, json={
        "text_1": "w3 w17 w45",
        "text_2": ["w8 w21 w5", "w60 w2"],
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert [d["index"] for d in body["data"]] == [0, 1]
    for d, doc in zip(body["data"], ["w8 w21 w5", "w60 w2"]):
        ref = _hf_score(hf, tok, "w3 w17 w45", doc)
        np.testing.assert_allclose(d["score"], ref, atol=5e-4, rtol=5e-3)
    assert body["usage"]["prompt_tokens"] > 0


def test_rerank_endpoint_orders_by_score(cross_server):
    base, hf, tok = cross_server
    docs = ["w8 w21 w5", "w60 w2", "w11 w12 w13"]
    r = httpx.post(f"{base}/v1/rerank", timeout=300, json={
        "query": "w3 w17 w45",
        "documents": docs,
        "top_n": 2,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert len(body["results"]) == 2
    refs = sorted(((_hf_score(hf, tok, "w3 w17 w45", d), i)
                   for i, d in enumerate(docs)), reverse=True)
    got = [res["index"] for res in body["results"]]
    assert got == [i for _, i in refs[:2]]
    scores = [res["relevance_score"] for res in body["results"]]
    assert scores == sorted(scores, reverse=True)


def test_rerank_accepts_bare_string_document(cross_server):
    base, _, _ = cross_server
    r = httpx.post(f"{base}/v1/rerank", timeout=300, json={
        "query": "w3 w17", "documents": "w8 w21 w5",
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert len(body["results"]) == 1
    assert body["results"][0]["document"]["text"] == "w8 w21 w5"


def test_embeddings_endpoint_on_encoder_model(cross_server):
    base, _, _ = cross_server
    r = httpx.post(f"{base}/v1/embeddings", timeout=300, json={
        "input": ["w3 w17 w45", "w8 w21"],
    })
    assert r.status_code == 200, r.text
    data = r.json()["data"]
    assert len(data) == 2 and len(data[0]["embedding"]) == 32


def test_completions_rejected_on_encoder_model(cross_server):
    base, _, _ = cross_server
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "prompt": "w3 w17", "max_tokens": 4,
    })
    assert r.status_code == 400
    assert "encoder-only" in r.text
