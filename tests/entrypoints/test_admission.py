"""API admission control: watermark shedding, KV pressure, per-request
deadlines, drain mode — controller units plus a live-server overload
drill (429 + Retry-After, counters on /metrics)."""

import asyncio
import threading
import types

import httpx
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.entrypoints.openai.admission import (
    AdmissionController, AdmissionRejected)
from vllm_distributed_tpu.metrics.stats import FrontendStats
from vllm_distributed_tpu.utils import fault_injection as fi
from vllm_distributed_tpu.utils import get_open_port

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


def _stub_engine(kv_usage: float = 0.0):
    async def get_stats(include_events=True):
        return {"kv_cache_usage": kv_usage}

    return types.SimpleNamespace(
        output_processor=types.SimpleNamespace(stats=FrontendStats()),
        get_stats=get_stats)


def _controller(engine=None, high=4, low=0, kv_high=0.0):
    return AdmissionController(engine or _stub_engine(),
                               high_watermark=high, low_watermark=low,
                               kv_high=kv_high, retry_after_s=7)


# ---------------------------------------------------------------------------
# Controller units
# ---------------------------------------------------------------------------

def test_watermark_shed_with_hysteresis():
    engine = _stub_engine()
    ctrl = _controller(engine, high=4, low=2)

    async def run():
        for _ in range(4):
            await ctrl.acquire()
        with pytest.raises(AdmissionRejected) as ei:
            await ctrl.acquire()  # depth 4 >= high -> shed
        assert ei.value.status == 429
        assert ei.value.retry_after_s == 7
        ctrl.release()  # depth 3: still above low -> keep shedding
        with pytest.raises(AdmissionRejected):
            await ctrl.acquire()
        ctrl.release()  # depth 2 == low -> recovered
        await ctrl.acquire()
        assert ctrl.depth == 3

    asyncio.run(run())
    assert engine.output_processor.stats.num_requests_shed == 2


def test_kv_pressure_sheds():
    ctrl = _controller(_stub_engine(kv_usage=0.97), high=100,
                       kv_high=0.9)

    async def run():
        with pytest.raises(AdmissionRejected) as ei:
            await ctrl.acquire()
        assert "KV cache pressure" in str(ei.value)

    asyncio.run(run())


def test_admission_stall_fault_builds_pressure():
    ctrl = _controller(high=2, low=1)
    fi.inject("admission.stall")

    async def run():
        await ctrl.acquire()  # stall leaks a slot: depth 2 after admit
        assert ctrl.depth == 2
        with pytest.raises(AdmissionRejected):
            await ctrl.acquire()  # leaked slot pushed depth to the high

    asyncio.run(run())
    assert fi.counters().get("admission.stall", 0) >= 2


def test_drain_mode_refuses_and_completes():
    engine = _stub_engine()
    ctrl = _controller(engine, high=4)

    async def run():
        await ctrl.acquire()
        ctrl.begin_drain()
        with pytest.raises(AdmissionRejected) as ei:
            await ctrl.acquire()
        assert ei.value.status == 503
        ctrl.release()  # last in-flight request finishes
        duration = await ctrl.wait_drained(timeout_s=5.0)
        assert duration < 5.0

    asyncio.run(run())
    assert engine.output_processor.stats.drain_duration_seconds > 0


def test_disabled_controller_admits_everything():
    ctrl = _controller(high=0)

    async def run():
        for _ in range(100):
            await ctrl.acquire()
        # Depth still tracked (drain needs it); nothing is ever shed.
        assert ctrl.depth == 100
        for _ in range(100):
            ctrl.release()
        assert ctrl.depth == 0

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Live server: overload 429 + Retry-After, deadline 408, /metrics
# ---------------------------------------------------------------------------

VOCAB = 128


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    import os

    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM

    path = str(tmp_path_factory.mktemp("tiny_admission"))
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=VOCAB, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    HFLlama(cfg).eval().save_pretrained(path, safe_serialization=True)

    saved = {k: os.environ.get(k) for k in
             ("VDT_ADMISSION_HIGH_WATERMARK",
              "VDT_ADMISSION_LOW_WATERMARK")}
    os.environ["VDT_ADMISSION_HIGH_WATERMARK"] = "2"
    os.environ["VDT_ADMISSION_LOW_WATERMARK"] = "1"

    engine = AsyncLLM(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True).create_engine_config(),
        load_tokenizer=False)
    port = get_open_port()
    ready = threading.Event()
    stop_holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import \
            serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        stop_holder["stop"] = stop
        stop_holder["loop"] = loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready,
                                      stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=120), "server did not start"
    yield f"http://127.0.0.1:{port}"
    stop_holder["loop"].call_soon_threadsafe(stop_holder["stop"].set)
    t.join(timeout=30)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


BODY = {"model": "tiny", "prompt": [3, 17, 92], "max_tokens": 4,
        "temperature": 0.0, "ignore_eos": True}


def test_per_request_deadline_aborts_with_408(server):
    # (Runs before the overload drill: admission.stall leaks slots into
    # the module-scoped server's gate, shedding everything after it.)
    body = dict(BODY, max_tokens=48, timeout_s=0.001)
    r = httpx.post(f"{server}/v1/completions", timeout=300, json=body)
    assert r.status_code == 408, r.text
    assert r.json()["error"]["type"] == "timeout_error"
    # The aborted request released its slot and the engine still serves.
    r = httpx.post(f"{server}/v1/completions", timeout=300, json=BODY)
    assert r.status_code == 200, r.text


def test_overload_sheds_429_with_retry_after(server):
    # Warm path: under the watermark everything is served.
    r = httpx.post(f"{server}/v1/completions", timeout=300, json=BODY)
    assert r.status_code == 200, r.text

    # admission.stall leaks one slot per request: the second request
    # finds the queue at the high watermark and is shed.
    fi.inject("admission.stall")
    r1 = httpx.post(f"{server}/v1/completions", timeout=300, json=BODY)
    assert r1.status_code == 200, r1.text
    r2 = httpx.post(f"{server}/v1/completions", timeout=300, json=BODY)
    assert r2.status_code == 429, r2.text
    assert "Retry-After" in r2.headers
    assert int(r2.headers["Retry-After"]) >= 1
    assert r2.json()["error"]["type"] == "overloaded"
    fi.clear()

    # Shed + queue metrics are on /metrics.
    scrape = httpx.get(f"{server}/metrics", timeout=60).text
    assert "vdt:requests_shed_total" in scrape
    shed = [ln for ln in scrape.splitlines()
            if ln.startswith("vdt:requests_shed_total")]
    assert float(shed[0].split()[-1]) >= 1
    assert "vdt:admission_queue_depth" in scrape
    assert "vdt:requests_replayed_total" in scrape
    assert "vdt:drain_duration_seconds" in scrape


# ---------------------------------------------------------------------------
# Weighted per-class shedding (tenant fairness)
# ---------------------------------------------------------------------------

def test_weighted_shed_evicts_best_effort_first():
    """Overload must 429 best-effort traffic (priority > 0) while
    interactive traffic still admits, with the same Retry-After
    contract."""
    engine = _stub_engine()
    ctrl = AdmissionController(engine, high_watermark=4, low_watermark=3,
                               retry_after_s=7, best_effort_frac=0.5)

    async def run():
        for _ in range(2):
            await ctrl.acquire()  # interactive, depth -> 2
        # Best-effort watermark is 4*0.5 = 2: shed, Retry-After intact.
        with pytest.raises(AdmissionRejected) as ei:
            await ctrl.acquire(priority=5)
        assert ei.value.status == 429
        assert ei.value.retry_after_s == 7
        # Interactive traffic is NOT in shedding mode: still admits.
        await ctrl.acquire()
        assert ctrl.depth == 3
        # Best-effort hysteresis: keeps shedding until depth <= its
        # low watermark (3*0.5 = 1).
        with pytest.raises(AdmissionRejected):
            await ctrl.acquire(priority=1)
        for _ in range(2):
            ctrl.release()  # depth 1 == best-effort low
        await ctrl.acquire(priority=1)  # recovered

    asyncio.run(run())
    assert ctrl.shed_by_class == {"best_effort": 2}
    assert engine.output_processor.stats.num_requests_shed == 2


def test_interactive_shed_counts_by_class():
    ctrl = _controller(high=2, low=1)

    async def run():
        await ctrl.acquire()
        await ctrl.acquire(priority=-3)  # negative = still interactive
        with pytest.raises(AdmissionRejected):
            await ctrl.acquire()

    asyncio.run(run())
    assert ctrl.shed_by_class == {"interactive": 1}


def test_request_class_boundaries():
    assert AdmissionController.request_class(0) == "interactive"
    assert AdmissionController.request_class(-1) == "interactive"
    assert AdmissionController.request_class(1) == "best_effort"


def test_best_effort_frac_one_disables_weighting():
    ctrl = AdmissionController(_stub_engine(), high_watermark=4,
                               best_effort_frac=1.0)
    assert ctrl._thresholds("best_effort") == ctrl._thresholds(
        "interactive")


# ---------------------------------------------------------------------------
# Tenant/priority plumbing: OpenAI body -> EngineCoreRequest -> msgpack
# ---------------------------------------------------------------------------

def test_priority_tenant_from_openai_body():
    from vllm_distributed_tpu.entrypoints.openai.api_server import \
        _priority_tenant
    assert _priority_tenant({}) == (0, None)
    assert _priority_tenant({"priority": 3, "tenant": "acme"}) == \
        (3, "acme")
    # The standard OpenAI "user" field doubles as tenant identity.
    assert _priority_tenant({"user": "u-17"}) == (0, "u-17")
    assert _priority_tenant({"tenant": "t", "user": "u"}) == (0, "t")
    from vllm_distributed_tpu.entrypoints.openai.protocol import \
        RequestError
    with pytest.raises(RequestError):
        _priority_tenant({"priority": "not-an-int"})


def test_priority_tenant_serial_round_trip():
    """EngineCoreRequest carries priority/tenant across the msgpack
    engine-core boundary byte-exactly, and a decoder missing the tenant
    key (old wire) degrades to None."""
    from vllm_distributed_tpu.engine.serial import (decode_request,
                                                    encode_request, pack,
                                                    unpack)
    from vllm_distributed_tpu.request import EngineCoreRequest, Request
    from vllm_distributed_tpu.sampling_params import SamplingParams
    req = EngineCoreRequest(
        request_id="rt-1", prompt_token_ids=[1, 2, 3],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4),
        priority=7, tenant="tenant-a")
    back = decode_request(unpack(pack(encode_request(req))))
    assert back.priority == 7
    assert back.tenant == "tenant-a"
    # Scheduler-side record keeps both.
    sched_req = Request.from_engine_core_request(back)
    assert sched_req.priority == 7 and sched_req.tenant == "tenant-a"
    # Old wire format without the tenant key.
    d = encode_request(req)
    d.pop("tenant")
    assert decode_request(unpack(pack(d))).tenant is None


def test_shed_by_class_metrics_block():
    """/metrics renders vdt:requests_shed_by_class_total{class} with
    exact per-class counts once any shed happened."""
    ctrl = _controller(high=1, low=1)

    async def run():
        await ctrl.acquire()
        for priority in (2, 0):
            with pytest.raises(AdmissionRejected):
                await ctrl.acquire(priority=priority)

    asyncio.run(run())
    assert ctrl.shed_by_class == {"best_effort": 1, "interactive": 1}


def test_best_effort_inherits_interactive_shedding():
    """Drain-down must never invert priority: while interactive traffic
    is still in shedding hysteresis, best-effort requests stay shed
    even though their own class never tripped."""
    ctrl = AdmissionController(_stub_engine(), high_watermark=4,
                               low_watermark=1, best_effort_frac=0.75)

    async def run():
        for _ in range(4):
            await ctrl.acquire()  # interactive, depth -> 4
        with pytest.raises(AdmissionRejected):
            await ctrl.acquire()  # trips ONLY the interactive class
        ctrl.release()
        ctrl.release()  # depth 2: above low=1, interactive still shed
        with pytest.raises(AdmissionRejected):
            await ctrl.acquire()
        # A best-effort request at the same depth must NOT slip in
        # ahead of the interactive traffic being drained.
        with pytest.raises(AdmissionRejected):
            await ctrl.acquire(priority=9)
        ctrl.release()  # depth 1 == low: both classes recover
        await ctrl.acquire(priority=9)

    asyncio.run(run())
    assert ctrl.shed_by_class == {"interactive": 2, "best_effort": 1}
