"""OpenAI-server HTTP tests over a live aiohttp server + tiny checkpoint
(reference pattern: tests/utils.py:74 RemoteOpenAIServer speaking real
HTTP to a served model)."""

import asyncio
import json
import threading

import httpx
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.utils import get_open_port

VOCAB = 128


def _save_checkpoint_with_tokenizer(path) -> HFLlama:
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=VOCAB, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    hf.save_pretrained(path, safe_serialization=True)

    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast
    # Ids 110-117 carry JSON-ish / choice words so structured-output
    # grammars have something to allow.
    special_words = {"{": 110, "}": 111, '"a"': 112, ":": 113,
                     "true": 114, "false": 115, "yes": 116, "no": 117}
    vocab = {f"w{i}": i for i in range(VOCAB - 2)
             if i not in special_words.values()}
    vocab.update(special_words)
    vocab["<unk>"] = VOCAB - 2
    vocab["</s>"] = 1
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    fast = PreTrainedTokenizerFast(tokenizer_object=tok,
                                   unk_token="<unk>", eos_token="</s>")
    fast.save_pretrained(path)
    return hf


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tiny_served"))
    hf = _save_checkpoint_with_tokenizer(path)

    engine_args = EngineArgs(model=path, dtype="float32", block_size=4,
                             num_gpu_blocks_override=128, max_model_len=64,
                             max_num_batched_tokens=64, max_num_seqs=8)
    engine = AsyncLLM(engine_args.create_engine_config())
    port = get_open_port()
    ready = threading.Event()
    stop_holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        stop_holder["stop"] = stop
        stop_holder["loop"] = loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready, stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=120), "server did not start"
    yield f"http://127.0.0.1:{port}", hf
    stop_holder["loop"].call_soon_threadsafe(stop_holder["stop"].set)
    t.join(timeout=30)


def hf_greedy(hf, prompt_ids, n):
    with torch.no_grad():
        out = hf.generate(torch.tensor([prompt_ids]), max_new_tokens=n,
                          do_sample=False, eos_token_id=None)
    return out[0].tolist()[len(prompt_ids):]


def test_health_and_models(server):
    base, _ = server
    assert httpx.get(f"{base}/health", timeout=30).status_code == 200
    models = httpx.get(f"{base}/v1/models", timeout=30).json()
    assert models["object"] == "list" and len(models["data"]) == 1


def test_completion_token_parity(server):
    base, hf = server
    prompt = "w3 w17 w92 w45 w8"
    prompt_ids = [3, 17, 92, 45, 8]
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": prompt, "max_tokens": 6,
        "temperature": 0.0, "ignore_eos": True,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    want = hf_greedy(hf, prompt_ids, 6)
    got_text = body["choices"][0]["text"]
    assert got_text.split() == [f"w{t}" for t in want]
    assert body["usage"]["prompt_tokens"] == 5
    assert body["usage"]["completion_tokens"] == 6
    assert body["choices"][0]["finish_reason"] == "length"


def test_completion_streaming_matches_nonstream(server):
    base, _ = server
    req = {"model": "tiny", "prompt": "w9 w8 w7", "max_tokens": 8,
           "temperature": 0.0, "ignore_eos": True}
    full = httpx.post(f"{base}/v1/completions", timeout=300,
                      json=req).json()["choices"][0]["text"]
    chunks = []
    with httpx.stream("POST", f"{base}/v1/completions", timeout=300,
                      json=dict(req, stream=True)) as r:
        assert r.headers["content-type"].startswith("text/event-stream")
        for line in r.iter_lines():
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            chunks.append(json.loads(payload))
    text = "".join(c["choices"][0]["text"] for c in chunks)
    assert text == full
    assert len(chunks) >= 2, "streaming must deliver incremental chunks"
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"


def test_metrics_expose_latency_histograms(server):
    """After at least one completion, /metrics must expose the TTFT /
    ITL / e2e histograms with real observations (reference:
    v1/metrics/loggers.py:143 histogram families)."""
    base, _ = server
    httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": "w2 w3", "max_tokens": 4,
        "temperature": 0.0, "ignore_eos": True,
    })
    text = httpx.get(f"{base}/metrics", timeout=30).text
    assert "vdt:time_to_first_token_seconds_bucket" in text
    assert "vdt:inter_token_latency_seconds_bucket" in text
    assert "vdt:e2e_request_latency_seconds_count" in text
    count = [line for line in text.splitlines()
             if line.startswith("vdt:time_to_first_token_seconds_count")]
    assert count and float(count[0].split()[-1]) >= 1
    gen = [line for line in text.splitlines()
           if line.startswith("vdt:generation_tokens_total ")]
    assert gen and float(gen[0].split()[-1]) >= 4


def test_profile_rpc_produces_trace(server, tmp_path, monkeypatch):
    """start/stop profile RPC drives jax.profiler on the core
    (reference: tpu_worker.py:246-256 profile RPC)."""
    import os
    base, _ = server
    r = httpx.post(f"{base}/start_profile", timeout=60)
    assert r.status_code == 200, r.text
    httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": "w4", "max_tokens": 2,
        "temperature": 0.0, "ignore_eos": True,
    })
    r = httpx.post(f"{base}/stop_profile", timeout=60)
    assert r.status_code == 200, r.text
    trace_dir = r.json()["dir"]
    assert os.path.isdir(trace_dir)
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found += [f for f in files if "trace" in f or f.endswith(".pb")]
    assert found, f"no trace artifacts under {trace_dir}"


def test_json_mode_always_parses(server):
    """Served structured output: response_format json_object makes the
    (random-weight) model emit valid JSON, every time."""
    base, _ = server
    for seed in range(3):
        r = httpx.post(f"{base}/v1/chat/completions", timeout=300, json={
            "model": "tiny",
            "messages": [{"role": "user", "content": "w1 w2"}],
            "max_tokens": 40, "temperature": 1.0, "seed": seed,
            "response_format": {"type": "json_object"},
        })
        assert r.status_code == 200, r.text
        content = r.json()["choices"][0]["message"]["content"]
        parsed = json.loads(content)
        assert isinstance(parsed, dict), content


def test_guided_choice_served(server):
    base, _ = server
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": "w3 w4", "max_tokens": 10,
        "temperature": 1.0, "seed": 5, "guided_choice": ["yes", "no"],
    })
    assert r.status_code == 200, r.text
    text = r.json()["choices"][0]["text"].strip()
    assert text in ("yes", "no"), text


def test_completion_n_gt_1(server):
    base, _ = server
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": "w5 w6", "n": 2, "max_tokens": 4,
        "temperature": 0.0, "ignore_eos": True,
    }).json()
    assert len(r["choices"]) == 2
    assert [c["index"] for c in r["choices"]] == [0, 1]
    # Greedy: both samples identical.
    assert r["choices"][0]["text"] == r["choices"][1]["text"]


def test_chat_completion(server):
    base, _ = server
    req = {"model": "tiny",
           "messages": [{"role": "user", "content": "w11 w12"}],
           "max_tokens": 4, "temperature": 0.0, "ignore_eos": True}
    r = httpx.post(f"{base}/v1/chat/completions", timeout=300, json=req)
    assert r.status_code == 200, r.text
    body = r.json()
    msg = body["choices"][0]["message"]
    assert msg["role"] == "assistant"
    assert msg["content"]
    # Streaming variant assembles to the same content.
    deltas = []
    with httpx.stream("POST", f"{base}/v1/chat/completions", timeout=300,
                      json=dict(req, stream=True)) as s:
        for line in s.iter_lines():
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            c = json.loads(payload)["choices"][0]
            deltas.append(c["delta"].get("content", ""))
    assert "".join(deltas) == msg["content"]


def test_validation_errors(server):
    base, _ = server
    r = httpx.post(f"{base}/v1/completions", timeout=30, json={
        "model": "tiny", "max_tokens": 4})
    assert r.status_code == 400
    assert r.json()["error"]["type"] == "invalid_request_error"
    r = httpx.post(f"{base}/v1/completions", timeout=30, json={
        "model": "tiny", "prompt": "w1", "temperature": -1.0})
    assert r.status_code == 400


def test_metrics_endpoint(server):
    base, _ = server
    r = httpx.get(f"{base}/metrics", timeout=60)
    assert r.status_code == 200
    assert "vdt:num_requests_running" in r.text
    assert "vdt:prefix_cache_hits_total" in r.text


def test_logit_bias_over_api(server):
    """OpenAI-style logit_bias (string token-id keys) is honored."""
    base, _ = server
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": "w3 w17 w92", "max_tokens": 3,
        "temperature": 0.0, "ignore_eos": True,
        "logit_bias": {"77": 100.0},
    })
    assert r.status_code == 200, r.text
    assert r.json()["choices"][0]["text"].split() == ["w77"] * 3


def test_logprobs_over_api(server):
    base, _ = server
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": "w3 w17 w92 w45", "max_tokens": 3,
        "temperature": 0.0, "ignore_eos": True, "logprobs": 4,
    })
    assert r.status_code == 200, r.text
    lp = r.json()["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == 3
    assert all(len(d) >= 4 for d in lp["top_logprobs"])


def test_embeddings_endpoint(server):
    """/v1/embeddings over the pooling path (reference:
    serving_embedding.py)."""
    base, _ = server
    r = httpx.post(f"{base}/v1/embeddings", timeout=300, json={
        "model": "tiny", "input": ["w1 w2 w3", "w4 w5"],
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["object"] == "list"
    assert len(body["data"]) == 2
    assert all(len(d["embedding"]) == 64 for d in body["data"])
    assert body["usage"]["prompt_tokens"] == 5


def test_tool_calls_forced_function(server):
    """Forced tool choice rides structured output: arguments ALWAYS
    parse against the function schema (reference: serving_chat tool
    handling + tool parsers)."""
    base, _ = server
    r = httpx.post(f"{base}/v1/chat/completions", timeout=300, json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "w1 w2"}],
        "max_tokens": 30, "temperature": 1.0, "seed": 11,
        "tools": [{"type": "function", "function": {
            "name": "set_flag",
            "parameters": {"type": "object",
                           "properties": {"a": {"type": "boolean"}},
                           "required": ["a"]}}}],
        "tool_choice": {"type": "function",
                        "function": {"name": "set_flag"}},
    })
    assert r.status_code == 200, r.text
    choice = r.json()["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    (call, ) = choice["message"]["tool_calls"]
    assert call["function"]["name"] == "set_flag"
    args = json.loads(call["function"]["arguments"])
    assert isinstance(args.get("a"), bool)


def test_completion_echo_with_logprobs(server):
    """echo=true returns prompt + completion text and leads the
    logprobs arrays with the scored prompt positions (first None)."""
    base, hf = server
    prompt = "w3 w17 w92 w45 w8"
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": prompt, "max_tokens": 3,
        "temperature": 0.0, "ignore_eos": True, "echo": True,
        "logprobs": 3,
    })
    assert r.status_code == 200, r.text
    choice = r.json()["choices"][0]
    assert choice["text"].startswith(prompt)
    lp = choice["logprobs"]
    # 5 prompt tokens + 3 completion tokens; first prompt entry None.
    assert len(lp["tokens"]) == 8
    assert lp["token_logprobs"][0] is None
    assert all(isinstance(v, float) for v in lp["token_logprobs"][1:])
    import torch as _torch
    ids = [3, 17, 92, 45, 8]
    with _torch.no_grad():
        ref = _torch.log_softmax(
            hf(_torch.tensor([ids])).logits[0].float(), -1).numpy()
    for i in range(1, 5):
        assert abs(lp["token_logprobs"][i] - float(ref[i - 1, ids[i]])) \
            < 1e-3


def test_completion_echo_stream_rejected(server):
    base, _ = server
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "model": "tiny", "prompt": "w1 w2", "max_tokens": 2,
        "stream": True, "echo": True,
    })
    assert r.status_code == 400


def test_tokenize_detokenize_roundtrip(server):
    base, _ = server
    r = httpx.post(f"{base}/tokenize", timeout=60,
                   json={"prompt": "w3 w17 w92"})
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["tokens"] == [3, 17, 92] and body["count"] == 3
    r2 = httpx.post(f"{base}/detokenize", timeout=60,
                    json={"tokens": body["tokens"]})
    assert r2.status_code == 200
    assert r2.json()["prompt"].split() == ["w3", "w17", "w92"]


def test_responses_api_minimal(server):
    """/v1/responses wraps a chat completion in the Responses item
    shape (reference: serving_responses.py)."""
    base, _ = server
    r = httpx.post(f"{base}/v1/responses", timeout=300, json={
        "input": "w3 w17 w92", "max_output_tokens": 4,
        "temperature": 0.0, "ignore_eos": True,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["object"] == "response"
    assert body["status"] == "completed"
    item = body["output"][0]
    assert item["role"] == "assistant"
    text = item["content"][0]["text"]
    assert text and body["output_text"] == text
    assert body["usage"]["output_tokens"] == 4
    # background mode refuses honestly
    r2 = httpx.post(f"{base}/v1/responses", timeout=60, json={
        "input": "w3", "background": True})
    assert r2.status_code == 400


def test_responses_typed_input_and_stream_rejection(server):
    base, _ = server
    r = httpx.post(f"{base}/v1/responses", timeout=300, json={
        "input": [{"role": "user", "content": [
            {"type": "input_text", "text": "w3 w17"}]}],
        "max_output_tokens": 3, "temperature": 0.0, "ignore_eos": True,
    })
    assert r.status_code == 200, r.text
    assert r.json()["output_text"]
    r2 = httpx.post(f"{base}/v1/responses", timeout=60, json={
        "input": "w3", "stream": True})
    assert r2.status_code == 400


def test_detokenize_rejects_string_tokens(server):
    base, _ = server
    r = httpx.post(f"{base}/detokenize", timeout=60,
                   json={"tokens": "123"})
    assert r.status_code == 400


def test_tokenize_messages_path(server):
    base, _ = server
    r = httpx.post(f"{base}/tokenize", timeout=60, json={
        "messages": [{"role": "user", "content": "w3 w17"}]})
    assert r.status_code == 200, r.text
    body = r.json()
    # Template-less fallback: role-prefixed prompt, same path chat
    # generation uses; the real ids 3 and 17 appear in the encoding.
    assert body["count"] == len(body["tokens"]) > 0


def test_spec_stats_render_in_metrics():
    """Spec-decode counters surface in the Prometheus text (reference:
    the vllm:spec_decode_* family of v1/metrics)."""
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    text = render_metrics({
        "spec_num_draft_tokens": 30,
        "spec_num_accepted_tokens": 21,
        "spec_num_drafts": 10,
        "spec_acceptance_rate": 0.7,
    })
    assert "vdt:spec_decode_num_draft_tokens_total 30.0" in text
    assert "vdt:spec_decode_num_accepted_tokens_total 21.0" in text
    assert "vdt:spec_decode_acceptance_rate 0.7" in text
