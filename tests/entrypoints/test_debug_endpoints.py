"""Debug introspection: GET /debug/requests and /debug/engine serve
live JSON during an in-flight request and are exempt from the admission
gate; the SIGUSR1 dump logs the same state plus thread stacks without
disturbing serving."""

import asyncio
import json
import logging
import threading

import httpx
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.utils import get_open_port

VOCAB = 128


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    import os

    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM

    path = str(tmp_path_factory.mktemp("tiny_debug"))
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=VOCAB, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=512, eos_token_id=1)
    HFLlama(cfg).eval().save_pretrained(path, safe_serialization=True)

    saved = {k: os.environ.get(k) for k in
             ("VDT_ADMISSION_HIGH_WATERMARK",
              "VDT_ADMISSION_LOW_WATERMARK")}
    # Watermark 1: one in-flight generation fills the gate, so the
    # exemption of the GET /debug routes is directly observable.
    os.environ["VDT_ADMISSION_HIGH_WATERMARK"] = "1"
    os.environ["VDT_ADMISSION_LOW_WATERMARK"] = "1"

    engine = AsyncLLM(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=256, max_model_len=512,
        max_num_batched_tokens=512, max_num_seqs=8,
        skip_tokenizer_init=True).create_engine_config(),
        load_tokenizer=False)
    port = get_open_port()
    ready = threading.Event()
    stop_holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import \
            serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        stop_holder["stop"] = stop
        stop_holder["loop"] = loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready,
                                      stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=120), "server did not start"
    yield f"http://127.0.0.1:{port}", engine
    stop_holder["loop"].call_soon_threadsafe(stop_holder["stop"].set)
    t.join(timeout=30)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


BODY = {"model": "tiny", "prompt": [3, 17, 92], "max_tokens": 4,
        "temperature": 0.0, "ignore_eos": True}


class _InflightStream:
    """Holds one long streaming completion open (first chunk consumed,
    the rest drained on close) so the admission slot stays occupied
    while the test pokes the debug endpoints."""

    def __init__(self, url: str):
        self.url = url
        self.started = threading.Event()
        self.finished = threading.Event()
        self._thread = threading.Thread(target=self._consume, daemon=True)

    def _consume(self):
        body = dict(BODY, max_tokens=400, stream=True)
        try:
            with httpx.stream("POST", f"{self.url}/v1/completions",
                              json=body, timeout=300) as r:
                # Headers arrive once the stream response is prepared
                # (admission slot held, generation submitted); the
                # token-less tiny server writes no delta chunks until
                # finish, so first-line would mean "already done".
                assert r.status_code == 200, r.status_code
                self.started.set()
                for _line in r.iter_lines():
                    pass
        finally:
            self.started.set()
            self.finished.set()

    def __enter__(self):
        self._thread.start()
        assert self.started.wait(timeout=120), "stream never started"
        return self

    def __exit__(self, *exc):
        self.finished.wait(timeout=300)
        self._thread.join(timeout=30)


def test_debug_endpoints_live_json_and_admission_exempt(server):
    url, _engine = server
    with _InflightStream(url) as stream:
        assert not stream.finished.is_set()
        # The single admission slot is held: generation is shed...
        shed = httpx.post(f"{url}/v1/completions", timeout=60, json=BODY)
        assert shed.status_code == 429, shed.text
        # ...while the GET debug routes stay exempt and serve live
        # JSON. (Headers land just before the generate submission;
        # poll briefly for the request to appear.)
        import time as _time
        for _ in range(100):
            dr = httpx.get(f"{url}/debug/requests", timeout=60)
            assert dr.status_code == 200, dr.text
            data = dr.json()
            if data["num_requests"] >= 1:
                break
            _time.sleep(0.1)
        de = httpx.get(f"{url}/debug/engine", timeout=60)
        assert data["num_requests"] >= 1
        req = next(r for r in data["requests"]
                   if r.get("phase") is not None)
        assert req["phase"] in ("queued", "prefill", "decode",
                                "preempted", "kv_pull")
        assert req["prompt_tokens"] == 3
        assert isinstance(req["phase_age_s"], dict)
        # Core-side enrichment: scheduler status + progress counters.
        assert req.get("status") in ("WAITING", "RUNNING", "PREEMPTED",
                                     "WAITING_FOR_REMOTE_KVS", None)
        assert de.status_code == 200, de.text
        eng = de.json()
        assert eng["supervisor"]["core"] == "BackgroundEngineCore"
        assert eng["supervisor"]["errored"] is False
        assert eng["admission"]["enabled"] is True
        assert eng["admission"]["depth"] >= 1
        assert eng["admission"]["high_watermark"] == 1
        assert len(eng["engine_cores"]) == 1
        sched = eng["engine_cores"][0]["scheduler"]
        assert sched["num_running"] + sched["num_waiting"] >= 1
        assert "requests" not in sched  # summary endpoint stays lean
    # Gate released: generation serves again.
    ok = httpx.post(f"{url}/v1/completions", timeout=300, json=BODY)
    assert ok.status_code == 200, ok.text


def test_debug_kv_cache_live_mid_request(server):
    """GET /debug/kv_cache serves live block-pool state while a
    request is in flight (and, like the other debug GETs, bypasses the
    admission gate — the stream below holds the single slot)."""
    import time as _time
    url, _engine = server
    with _InflightStream(url):
        data = {}
        for _ in range(100):
            r = httpx.get(f"{url}/debug/kv_cache", timeout=60)
            assert r.status_code == 200, r.text
            data = r.json()
            cores = data.get("engine_cores") or []
            if cores and any(req.get("kv_blocks")
                             for req in cores[0]["requests"]):
                break
            _time.sleep(0.1)
        assert cores, data
        kv = cores[0]["kv_cache"]
        assert kv["total_blocks"] > 0
        assert kv["free_blocks"] + kv["used_blocks"] == \
            kv["total_blocks"]
        assert kv["used_blocks"] >= 1  # the in-flight request's pages
        assert 0.0 <= kv["fragmentation_frac"] <= 1.0
        assert 0.0 <= kv["window_hit_rate"] <= 1.0
        assert isinstance(kv["preemption_causes"], dict)
        req = next(r for r in cores[0]["requests"]
                   if r.get("kv_blocks"))
        assert req["kv_blocks"] >= 1
        assert req["status"] in ("WAITING", "RUNNING", "PREEMPTED",
                                 "WAITING_FOR_REMOTE_KVS")


def test_debug_endpoints_idle_shapes(server):
    url, _engine = server
    data = httpx.get(f"{url}/debug/requests", timeout=60).json()
    assert "requests" in data and "num_requests" in data
    eng = httpx.get(f"{url}/debug/engine", timeout=60).json()
    assert "recent_events" in eng
    # The lifecycle ledger saw earlier arrivals/finishes.
    names = {e[2] for e in eng["recent_events"]}
    assert names & {"arrived", "finished", "aborted", "shed"}


def test_debug_trace_404_when_plane_disabled(server):
    # This server runs with VDT_TRACE_PLANE unset (the default): the
    # endpoint must refuse with a hint, not serve an empty trace.
    url, _engine = server
    r = httpx.get(f"{url}/debug/trace", timeout=60)
    assert r.status_code == 404
    assert "VDT_TRACE_PLANE" in r.json()["error"]


def test_debug_perf_attribution_mid_request(server):
    """GET /debug/perf serves the performance-attribution table —
    non-empty once waves dispatched, totals self-consistent with its
    own rows — and, like the other debug GETs, bypasses the admission
    gate (the stream below holds the single slot)."""
    import time as _time
    url, _engine = server
    with _InflightStream(url):
        perf = {}
        for _ in range(100):
            r = httpx.get(f"{url}/debug/perf", timeout=60)
            assert r.status_code == 200, r.text
            perf = r.json()
            if perf.get("attribution"):
                break
            _time.sleep(0.1)
    rows = perf["attribution"]
    assert rows, perf
    table_flops = sum(r["flops"] for r in rows)
    assert table_flops > 0
    if not perf["rows_dropped"]:
        assert table_flops == pytest.approx(
            perf["totals"]["model_flops"], rel=0.02)
    assert perf["utilization"], "per-worker mfu/mbu expected"
    for w in perf["utilization"].values():
        assert w["mfu"] > 0 and w["mbu"] > 0
    assert set(perf["roofline_bound"]) <= {"prefill", "decode",
                                           "mixed"}
    assert perf["peaks"].get("flops", 0) > 0


def test_sigusr1_dump_logs_without_disturbing_serving(server):
    """The SIGUSR1 path (exercised directly — the test server's loop
    runs off the main thread, where signal handlers cannot register)
    logs the /debug state and every thread's stack, and serving
    continues untouched."""
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        _dump_debug_to_log, _thread_stacks, build_app)
    url, engine = server

    stacks = _thread_stacks()
    assert "--- thread" in stacks and "MainThread" in stacks

    app = build_app(engine, "tiny")
    records: list[logging.LogRecord] = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    root = logging.getLogger("vllm_distributed_tpu")
    handler = _Capture()
    root.addHandler(handler)
    try:
        asyncio.run(_dump_debug_to_log(app))
    finally:
        root.removeHandler(handler)
    dump = [r for r in records if "SIGUSR1 debug dump" in r.getMessage()]
    assert len(dump) == 1
    message = dump[0].getMessage()
    assert "/debug/engine" in message and "thread stacks" in message
    # The KV summary rides the same dump.
    assert "/debug/kv_cache" in message
    kv_payload = message.split("/debug/kv_cache: ", 1)[1].split(
        "\nthread stacks", 1)[0]
    assert "engine_cores" in json.loads(kv_payload)
    # The dumped engine state is valid JSON with supervisor detail.
    payload = message.split("/debug/engine: ", 1)[1].split(
        "\n/debug/requests:", 1)[0]
    assert json.loads(payload)["supervisor"]["core"] == \
        "BackgroundEngineCore"
    # Serving is undisturbed.
    ok = httpx.post(f"{url}/v1/completions", timeout=300, json=BODY)
    assert ok.status_code == 200, ok.text
