"""/v1/audio/transcriptions over a live server + tiny Whisper
checkpoint (reference: serving_transcription.py)."""

import asyncio
import base64
import io
import threading
import wave

import httpx
import numpy as np
import pytest
import torch
import transformers

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.utils import get_open_port


def _wav_bytes(wav: np.ndarray, rate: int = 16000) -> bytes:
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes((np.clip(wav, -1, 1) * 32767).astype("<i2")
                      .tobytes())
    return buf.getvalue()


@pytest.fixture(scope="module")
def whisper_server(tmp_path_factory):
    cfg = transformers.WhisperConfig(
        vocab_size=96, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, num_mel_bins=8,
        max_source_positions=16, max_target_positions=64,
        decoder_start_token_id=2, eos_token_id=1, pad_token_id=0)
    torch.manual_seed(0)
    hf = transformers.WhisperForConditionalGeneration(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_whisper_served"))
    hf.save_pretrained(path, safe_serialization=True)
    # 0.32 s chunks -> 32 mel frames, matching max_source_positions=16
    # after the stride-2 conv.
    transformers.WhisperFeatureExtractor(
        feature_size=8, chunk_length=1).save_pretrained(path)
    import json
    import os
    with open(os.path.join(path, "preprocessor_config.json")) as f:
        pc = json.load(f)
    pc["chunk_length"] = 0.32
    pc["n_samples"] = 5120
    pc["nb_max_frames"] = 32
    with open(os.path.join(path, "preprocessor_config.json"), "w") as f:
        json.dump(pc, f)
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast
    vocab = {f"w{i}": i for i in range(94)}
    vocab["<unk>"] = 94
    vocab["</s>"] = 95
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    PreTrainedTokenizerFast(tokenizer_object=tok, unk_token="<unk>",
                            eos_token="</s>").save_pretrained(path)

    engine = AsyncLLM(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64,
        max_num_seqs=8).create_engine_config())
    port = get_open_port()
    ready = threading.Event()
    holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import \
            serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        holder["stop"], holder["loop"] = stop, loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready,
                                      stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=120)
    yield f"http://127.0.0.1:{port}"
    holder["loop"].call_soon_threadsafe(holder["stop"].set)
    t.join(timeout=30)


def test_transcription_multipart_and_b64(whisper_server):
    base = whisper_server
    rng = np.random.default_rng(0)
    wav = (0.1 * rng.standard_normal(5120)).astype(np.float32)
    data = _wav_bytes(wav)
    r = httpx.post(f"{base}/v1/audio/transcriptions", timeout=300,
                   files={"file": ("a.wav", data, "audio/wav")})
    assert r.status_code == 200, r.text
    text1 = r.json()["text"]
    assert isinstance(text1, str) and text1
    # Same audio via JSON base64 gives the same transcription.
    r2 = httpx.post(f"{base}/v1/audio/transcriptions", timeout=300,
                    json={"audio": base64.b64encode(data).decode()})
    assert r2.status_code == 200, r2.text
    assert r2.json()["text"] == text1


def test_transcription_rejects_wrong_rate(whisper_server):
    base = whisper_server
    wav = np.zeros(4000, np.float32)
    r = httpx.post(f"{base}/v1/audio/transcriptions", timeout=60,
                   files={"file": ("a.wav",
                                   _wav_bytes(wav, rate=8000),
                                   "audio/wav")})
    assert r.status_code == 400
    assert "16 kHz" in r.text


@pytest.fixture(scope="module")
def bart_server(tmp_path_factory):
    from tests.entrypoints.test_encoder_server import (_save_tokenizer,
                                                       _serve)
    cfg = transformers.BartConfig(
        vocab_size=96, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, scale_embedding=True,
        activation_function="gelu", decoder_start_token_id=2,
        eos_token_id=1, pad_token_id=0, bos_token_id=3,
        forced_eos_token_id=None)
    torch.manual_seed(1)
    hf = transformers.BartForConditionalGeneration(cfg).eval()
    path = str(tmp_path_factory.mktemp("tiny_bart_served"))
    hf.save_pretrained(path, safe_serialization=True)
    _save_tokenizer(path)
    base, holder, t = _serve(path)
    yield base, hf
    holder["loop"].call_soon_threadsafe(holder["stop"].set)
    t.join(timeout=30)


def test_completions_with_encoder_text(bart_server):
    """encoder-decoder text over HTTP: the source document rides the
    encoder_text body field (BART summarization-style serving)."""
    base, hf = bart_server
    r = httpx.post(f"{base}/v1/completions", timeout=300, json={
        "prompt": [2, 3], "max_tokens": 5, "temperature": 0.0,
        "ignore_eos": True, "encoder_text": "w3 w17 w45",
    })
    assert r.status_code == 200, r.text
    text = r.json()["choices"][0]["text"]
    assert text.strip(), r.text
    # Parity with HF forced on the same source ids.
    src = [3, 17, 45]
    ids = [2, 3]
    with torch.no_grad():
        for _ in range(5):
            out = hf(input_ids=torch.tensor([src]),
                     decoder_input_ids=torch.tensor([ids]))
            ids.append(int(out.logits[0, -1].argmax()))
    want = " ".join(f"w{t}" for t in ids[2:])
    assert text.strip() == want
