"""Qwen2-VL over the chat API: image_url and video_url (frame-list)
content parts through the dynamic-resolution tower + M-RoPE decoder
(reference: chat_utils media parts + multimodal/video.py)."""

import asyncio
import base64
import io
import json
import threading

import httpx
import numpy as np
import pytest
import torch
from transformers import Qwen2VLConfig, Qwen2VLForConditionalGeneration

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.utils import get_open_port

VOCAB = 160
IMG_TOK, VID_TOK = 151, 152


def _save_ckpt(path):
    torch.manual_seed(0)
    cfg = Qwen2VLConfig(
        text_config=dict(
            vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            rope_scaling={"type": "mrope", "mrope_section": [2, 3, 3]},
            rope_theta=10000.0, eos_token_id=1),
        vision_config=dict(depth=2, embed_dim=32, hidden_size=64,
                           num_heads=2, in_channels=3, patch_size=4,
                           spatial_merge_size=2, temporal_patch_size=2),
        image_token_id=IMG_TOK, video_token_id=VID_TOK,
        vision_start_token_id=153, vision_end_token_id=154)
    hf = Qwen2VLForConditionalGeneration(cfg).eval()
    hf.save_pretrained(path, safe_serialization=True)

    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast
    vocab = {f"w{i}": i for i in range(140)}
    vocab.update({"<|image_pad|>": IMG_TOK, "<|video_pad|>": VID_TOK,
                  "<|vision_start|>": 153, "<|vision_end|>": 154,
                  "<unk>": 158, "</s>": 1})
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.WhitespaceSplit()
    fast = PreTrainedTokenizerFast(tokenizer_object=tok,
                                   unk_token="<unk>", eos_token="</s>")
    fast.save_pretrained(path)
    return hf


def _data_url(rng, w=8, h=8):
    from PIL import Image
    arr = rng.integers(0, 255, size=(h, w, 3)).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return ("data:image/png;base64," +
            base64.b64encode(buf.getvalue()).decode())


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tiny_qwen2vl_served"))
    _save_ckpt(path)
    engine_args = EngineArgs(model=path, dtype="float32", block_size=4,
                             num_gpu_blocks_override=128,
                             max_model_len=128,
                             max_num_batched_tokens=128, max_num_seqs=8)
    engine = AsyncLLM(engine_args.create_engine_config())
    port = get_open_port()
    ready = threading.Event()
    holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import \
            serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        holder["stop"], holder["loop"] = stop, loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready,
                                      stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=180), "server did not start"
    yield f"http://127.0.0.1:{port}"
    holder["loop"].call_soon_threadsafe(holder["stop"].set)
    t.join(timeout=30)


def _chat(base, content, max_tokens=5):
    r = httpx.post(f"{base}/v1/chat/completions", timeout=300, json={
        "model": "m",
        "messages": [{"role": "user", "content": content}],
        "max_tokens": max_tokens,
        "temperature": 0,
    })
    return r


def test_chat_image_url(server):
    rng = np.random.default_rng(0)
    content = [
        {"type": "text", "text": "w5 w6 "},
        {"type": "image_url", "image_url": {"url": _data_url(rng)}},
        {"type": "text", "text": " w7"},
    ]
    r1 = _chat(server, content)
    assert r1.status_code == 200, r1.text
    msg = r1.json()["choices"][0]["message"]["content"]
    assert msg
    # Deterministic: the same request reproduces (the tower ran, the
    # placeholder expanded, M-RoPE ids applied — same everything).
    r2 = _chat(server, content)
    assert r2.json()["choices"][0]["message"]["content"] == msg


def test_chat_video_frames(server):
    rng = np.random.default_rng(1)
    frames = [_data_url(rng) for _ in range(2)]
    content = [
        {"type": "text", "text": "w9 "},
        {"type": "video_url", "video_url": {"url": frames}},
        {"type": "text", "text": " w11"},
    ]
    r = _chat(server, content)
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["choices"][0]["message"]["content"]
    # Video and image requests see different media -> generally
    # different continuations; at minimum the server accepted and
    # generated under the video placeholder.
    assert body["usage"]["completion_tokens"] > 0


def test_video_rejected_on_non_vl_model(tmp_path_factory):
    """A llama-served chat must 400 on video parts, not crash."""
    from transformers import LlamaConfig
    from transformers import LlamaForCausalLM as HFLlama
    path = str(tmp_path_factory.mktemp("tiny_novideo"))
    torch.manual_seed(0)
    HFLlama(LlamaConfig(vocab_size=VOCAB, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=1,
                        num_attention_heads=4, num_key_value_heads=2,
                        max_position_embeddings=64,
                        eos_token_id=1)).save_pretrained(
        path, safe_serialization=True)
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast
    vocab = {f"w{i}": i for i in range(VOCAB - 2)}
    vocab["<unk>"] = VOCAB - 2
    vocab["</s>"] = 1
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.WhitespaceSplit()
    PreTrainedTokenizerFast(tokenizer_object=tok, unk_token="<unk>",
                            eos_token="</s>").save_pretrained(path)

    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        RequestError, _chat_prompt)
    engine_args = EngineArgs(model=path, dtype="float32", block_size=4,
                             num_gpu_blocks_override=64,
                             max_model_len=64,
                             max_num_batched_tokens=64, max_num_seqs=4)
    engine = AsyncLLM(engine_args.create_engine_config())
    with pytest.raises(RequestError, match="video"):
        _chat_prompt(engine, [{
            "role": "user",
            "content": [{"type": "video_url",
                         "video_url": {"url": ["data:,x"]}}],
        }])
