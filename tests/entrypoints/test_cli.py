"""CLI smoke tests (reference: vllm/entrypoints/cli/main.py:23 `vllm
serve|bench`)."""

import json

from tests.engine.test_llm_engine import checkpoint  # noqa: F401
from vllm_distributed_tpu.entrypoints.cli.main import main


def test_bench_latency_smoke(checkpoint, capsys):
    path, _ = checkpoint
    rc = main(["bench", "latency", "--model", path, "--dtype", "float32",
               "--block-size", "4", "--num-gpu-blocks-override", "128",
               "--max-model-len", "64", "--max-num-batched-tokens", "64",
               "--max-num-seqs", "8", "--input-len", "4",
               "--output-len", "4", "--num-prompts", "2", "--warmup", "0"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(out)
    assert result["generated_tokens"] == 8
    assert result["tokens_per_s"] > 0
