"""CLI smoke tests (reference: vllm/entrypoints/cli/main.py:23 `vllm
serve|bench`)."""

import json

from tests.engine.test_llm_engine import checkpoint  # noqa: F401
from vllm_distributed_tpu.entrypoints.cli.main import main


def test_bench_latency_smoke(checkpoint, capsys):
    path, _ = checkpoint
    rc = main(["bench", "latency", "--model", path, "--dtype", "float32",
               "--block-size", "4", "--num-gpu-blocks-override", "128",
               "--max-model-len", "64", "--max-num-batched-tokens", "64",
               "--max-num-seqs", "8", "--input-len", "4",
               "--output-len", "4", "--num-prompts", "2", "--warmup", "0"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(out)
    assert result["generated_tokens"] == 8
    assert result["tokens_per_s"] > 0


def test_run_batch_completions(tmp_path, capsys):
    """run-batch processes an OpenAI batch JSONL offline (reference:
    entrypoints/openai/run_batch.py)."""
    from tests.entrypoints.test_openai_server import \
        _save_checkpoint_with_tokenizer
    path = str(tmp_path / "model")
    _save_checkpoint_with_tokenizer(path)

    inp = tmp_path / "batch.jsonl"
    out_path = tmp_path / "out.jsonl"
    reqs = [
        {"custom_id": "a", "method": "POST", "url": "/v1/completions",
         "body": {"model": "tiny", "prompt": "w3 w17 w92",
                  "max_tokens": 4, "temperature": 0.0,
                  "ignore_eos": True}},
        {"custom_id": "b", "method": "POST", "url": "/v1/completions",
         "body": {"model": "tiny", "prompt": "w5 w6",
                  "max_tokens": 3, "temperature": 0.0,
                  "ignore_eos": True}},
    ]
    inp.write_text("\n".join(json.dumps(r) for r in reqs) + "\n")

    rc = main(["run-batch", "-i", str(inp), "-o", str(out_path),
               "--model", path, "--dtype", "float32", "--block-size", "4",
               "--num-gpu-blocks-override", "128", "--max-model-len", "64",
               "--max-num-batched-tokens", "64", "--max-num-seqs", "8"])
    assert rc == 0
    lines = [json.loads(line)
             for line in out_path.read_text().splitlines()]
    assert [r["custom_id"] for r in lines] == ["a", "b"]
    for rec, want_tokens in zip(lines, (4, 3)):
        assert rec["error"] is None
        body = rec["response"]["body"]
        assert body["object"] == "text_completion"
        assert body["usage"]["completion_tokens"] == want_tokens
        assert body["choices"][0]["text"].strip()


def test_collect_env_smoke(capsys):
    rc = main(["collect-env"])
    assert rc == 0
    info = json.loads(capsys.readouterr().out)
    assert info["jax"] and info["framework_version"]


def test_chat_and_complete_clients(tmp_path, capsys):
    """`vdt chat -q` / `vdt complete -q` drive a live server over HTTP
    (reference: vllm/entrypoints/cli/openai.py)."""
    import asyncio
    import threading

    from tests.entrypoints.test_openai_server import \
        _save_checkpoint_with_tokenizer
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.utils import get_open_port

    path = str(tmp_path / "model")
    _save_checkpoint_with_tokenizer(path)
    engine = AsyncLLM(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8).create_engine_config())
    port = get_open_port()
    ready = threading.Event()
    holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        holder["stop"], holder["loop"] = stop, loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready, stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=120)
    try:
        url = f"http://127.0.0.1:{port}/v1"
        rc = main(["complete", "--url", url, "-q", "w3 w17 w92",
                   "--max-tokens", "4", "--temperature", "0"])
        assert rc == 0
        text = capsys.readouterr().out.strip()
        assert text  # greedy tokens detokenized as wNN words
        rc = main(["chat", "--url", url, "-q", "w3 w17",
                   "--max-tokens", "4", "--temperature", "0"])
        assert rc == 0
        assert capsys.readouterr().out.strip()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=30)


def test_bench_serve_against_live_server(tmp_path, capsys):
    """`vdt bench serve` drives a running server over streaming HTTP
    and reports TTFT/ITL percentiles (reference:
    benchmarks/benchmark_serving.py fixed-QPS mode)."""
    import asyncio
    import threading

    from tests.entrypoints.test_openai_server import \
        _save_checkpoint_with_tokenizer
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.utils import get_open_port

    path = str(tmp_path / "model")
    _save_checkpoint_with_tokenizer(path)
    engine = AsyncLLM(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8).create_engine_config())
    port = get_open_port()
    ready = threading.Event()
    holder = {}

    def run():
        from vllm_distributed_tpu.entrypoints.openai.api_server import serve
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        stop = asyncio.Event()
        holder["stop"], holder["loop"] = stop, loop
        loop.run_until_complete(serve(engine, path, "127.0.0.1", port,
                                      ready_event=ready, stop_event=stop))
        loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(timeout=120)
    try:
        rc = main(["bench", "serve", "--url",
                   f"http://127.0.0.1:{port}/v1", "--model", path,
                   "--num-prompts", "4", "--input-len", "8",
                   "--output-len", "4", "--request-rate", "50",
                   "--prompt-vocab", "120"])
        assert rc == 0
        result = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        assert result["completed"] == 4 and result["errors"] == 0
        assert result["output_tokens"] == 16
        assert result["ttft_ms"]["p50"] > 0
        assert result["itl_ms"]["p50"] is not None
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=30)
