"""Tool-call dialect parsers (reference: the per-model parsers under
vllm/entrypoints/openai/tool_parsers/ and their unit tests)."""

import json

import pytest

from vllm_distributed_tpu.entrypoints.openai.tool_parsers import (
    get_tool_parser)


def test_hermes_blocks_with_content():
    p = get_tool_parser("hermes")
    text = ('I will check the weather.\n<tool_call>\n'
            '{"name": "get_weather", "arguments": {"city": "SF"}}\n'
            '</tool_call>\n<tool_call>\n'
            '{"name": "get_time", "arguments": {"tz": "PST"}}\n'
            '</tool_call>')
    content, calls = p.parse(text)
    assert content == "I will check the weather."
    assert calls == [
        {"name": "get_weather", "arguments": {"city": "SF"}},
        {"name": "get_time", "arguments": {"tz": "PST"}},
    ]


def test_hermes_no_markers_passthrough():
    p = get_tool_parser("hermes")
    content, calls = p.parse("just an answer")
    assert content == "just an answer" and calls is None


def test_mistral_array():
    p = get_tool_parser("mistral")
    text = ('[TOOL_CALLS] [{"name": "f", "arguments": {"x": 1}}, '
            '{"name": "g", "arguments": {}}]')
    content, calls = p.parse(text)
    assert content == ""
    assert calls == [{"name": "f", "arguments": {"x": 1}},
                     {"name": "g", "arguments": {}}]


def test_mistral_content_before_marker():
    p = get_tool_parser("mistral")
    content, calls = p.parse(
        'Sure. [TOOL_CALLS] [{"name": "f", "arguments": {"x": 1}}]')
    assert content == "Sure."
    assert calls[0]["name"] == "f"


def test_llama3_json_with_python_tag_and_semicolons():
    p = get_tool_parser("llama3_json")
    text = ('<|python_tag|>{"name": "a", "parameters": {"q": "x"}}; '
            '{"name": "b", "parameters": {}}')
    content, calls = p.parse(text)
    assert content == ""
    assert calls == [{"name": "a", "arguments": {"q": "x"}},
                     {"name": "b", "arguments": {}}]


def test_llama3_json_plain_text_passthrough():
    p = get_tool_parser("llama3_json")
    content, calls = p.parse("The answer is 4.")
    assert calls is None and content == "The answer is 4."


def test_pythonic_calls():
    p = get_tool_parser("pythonic")
    content, calls = p.parse(
        "[get_weather(city='SF', units=2), noop()]")
    assert content == ""
    assert calls == [
        {"name": "get_weather", "arguments": {"city": "SF", "units": 2}},
        {"name": "noop", "arguments": {}},
    ]


def test_pythonic_rejects_non_literal_args():
    p = get_tool_parser("pythonic")
    content, calls = p.parse("[f(x=os.system('rm'))]")
    assert calls is None  # non-literal arguments never evaluate


def test_json_default_dialect():
    p = get_tool_parser(None)
    content, calls = p.parse(
        '{"name": "f", "arguments": {"a": true}}')
    assert content == "" and calls == [{"name": "f",
                                        "arguments": {"a": True}}]


def test_unknown_parser_rejected():
    with pytest.raises(ValueError, match="unknown tool-call parser"):
        get_tool_parser("clippy")


def test_wire_wrapping():
    from vllm_distributed_tpu.entrypoints.openai import protocol
    wire = protocol.wrap_tool_calls(
        [{"name": "f", "arguments": {"x": 1}}])
    assert wire[0]["type"] == "function"
    assert wire[0]["function"]["name"] == "f"
    assert json.loads(wire[0]["function"]["arguments"]) == {"x": 1}
