"""LLM offline API smoke tests (token-id prompts; no tokenizer on disk)."""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu import LLM, SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_api")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def test_generate_batch(checkpoint):
    path, hf = checkpoint
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=64, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=4)
    prompts = [[3, 17, 92], [5, 6, 7, 8, 9]]
    outs = llm.generate(prompts,
                        SamplingParams(temperature=0.0, max_tokens=5,
                                       ignore_eos=True))
    assert len(outs) == 2
    for p, o in zip(prompts, outs):
        with torch.no_grad():
            hf_out = hf.generate(torch.tensor([p]), max_new_tokens=5,
                                 do_sample=False, eos_token_id=None)
        assert o.outputs[0].token_ids == hf_out[0].tolist()[len(p):]
        assert o.finished
        assert o.prompt_token_ids == p


def test_single_prompt_token_ids(checkpoint):
    path, _ = checkpoint
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=64, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=4)
    outs = llm.generate([1, 2, 3],
                        SamplingParams(temperature=0.0, max_tokens=3,
                                       ignore_eos=True))
    assert len(outs) == 1
    assert len(outs[0].outputs[0].token_ids) == 3
