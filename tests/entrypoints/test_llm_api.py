"""LLM offline API smoke tests (token-id prompts; no tokenizer on disk)."""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu import LLM, SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_api")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path), hf


def test_generate_batch(checkpoint):
    path, hf = checkpoint
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=64, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=4)
    prompts = [[3, 17, 92], [5, 6, 7, 8, 9]]
    outs = llm.generate(prompts,
                        SamplingParams(temperature=0.0, max_tokens=5,
                                       ignore_eos=True))
    assert len(outs) == 2
    for p, o in zip(prompts, outs):
        with torch.no_grad():
            hf_out = hf.generate(torch.tensor([p]), max_new_tokens=5,
                                 do_sample=False, eos_token_id=None)
        assert o.outputs[0].token_ids == hf_out[0].tolist()[len(p):]
        assert o.finished
        assert o.prompt_token_ids == p


def test_single_prompt_token_ids(checkpoint):
    path, _ = checkpoint
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=64, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=4)
    outs = llm.generate([1, 2, 3],
                        SamplingParams(temperature=0.0, max_tokens=3,
                                       ignore_eos=True))
    assert len(outs) == 1
    assert len(outs[0].outputs[0].token_ids) == 3


def test_beam_search_beats_greedy_cumlogprob(checkpoint):
    """Beam search's best beam must score at least greedy's cumulative
    logprob (reference: LLM.beam_search semantics)."""
    from vllm_distributed_tpu.entrypoints.llm import LLM
    from vllm_distributed_tpu.sampling_params import SamplingParams
    path, _ = checkpoint
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=128, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=8,
              skip_tokenizer_init=True)
    prompt = [3, 17, 92, 45]
    beams = llm.beam_search(prompt, beam_width=3, max_tokens=4)
    assert len(beams) == 3
    assert all(len(b["token_ids"]) >= 1 for b in beams)
    # Greedy = beam_width 1; wider beams can only match or improve.
    greedy = llm.beam_search(prompt, beam_width=1, max_tokens=4)
    assert beams[0]["cum_logprob"] >= greedy[0]["cum_logprob"] - 1e-6


def test_score_ranks_identical_higher(checkpoint):
    from vllm_distributed_tpu.entrypoints.llm import LLM
    path, _ = checkpoint
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=128, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=8,
              skip_tokenizer_init=True)
    q = [3, 17, 92, 45, 8]
    same = [3, 17, 92, 45, 8]
    other = [90, 81, 72, 63, 54]
    scores = llm.score([q, q], [same, other])
    assert scores[0] > scores[1]
    assert abs(scores[0] - 1.0) < 1e-5  # identical prompts -> cosine 1


def test_generate_parallel_sampling_n(checkpoint):
    """n > 1 fans out child requests and merges n CompletionOutputs
    (reference: v1 parallel sampling via ParentRequest)."""
    path, hf = checkpoint
    llm = LLM(model=path, dtype="float32", block_size=4,
              num_gpu_blocks_override=64, max_model_len=64,
              max_num_batched_tokens=64, max_num_seqs=8)
    prompt = [3, 17, 92]
    outs = llm.generate([prompt],
                        SamplingParams(temperature=0.0, n=3, max_tokens=4,
                                       ignore_eos=True))
    assert len(outs) == 1
    comps = outs[0].outputs
    assert [c.index for c in comps] == [0, 1, 2]
    # Greedy: all three children agree and match HF.
    with torch.no_grad():
        ref = hf.generate(torch.tensor([prompt]), max_new_tokens=4,
                          do_sample=False,
                          eos_token_id=None)[0].tolist()[len(prompt):]
    for c in comps:
        assert c.token_ids == ref

    # Seeded sampling: children get distinct seeds (and so can differ).
    outs = llm.generate([prompt],
                        SamplingParams(temperature=5.0, n=3, seed=7,
                                       max_tokens=4, ignore_eos=True))
    texts = [tuple(c.token_ids) for c in outs[0].outputs]
    assert len(texts) == 3
    assert len(set(texts)) > 1, "children must not share one seed"
