"""P2P dynamic-membership disaggregation (reference:
kv_transfer/kv_connector/v1/p2p/p2p_nccl_connector.py): instances
register with a TTL'd registry, a decode instance joins MID-RUN with
zero static peer config, pulls KV by producer instance id, serves, and
leaves cleanly."""

import time

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.distributed.kv_transfer.p2p_registry import (
    P2PRegistryClient, P2PRegistryServer)
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_p2p")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


@pytest.fixture()
def registry():
    srv = P2PRegistryServer()
    yield srv
    srv.shutdown()


def make_engine(path, registry, role, instance_id, **overrides):
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True,
                kv_connector="P2PDcnConnector", kv_role=role,
                kv_connector_extra_config={
                    "pull_port": 0,
                    "registry_addr": registry.address,
                    "instance_id": instance_id,
                    "registry_ttl": 3.0,
                })
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run(engine, prompts, tag, max_tokens=6, kv_params=None):
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp,
                           kv_transfer_params=(kv_params[i]
                                               if kv_params else None))
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    return [done[k] for k in sorted(done,
                                    key=lambda s: int(s.split("-")[-1]))]


def _pump_until(consumer, producer, n, max_iters=2000):
    done = {}
    for _ in range(max_iters):
        for out in consumer.step():
            if out.finished:
                done[out.request_id] = out
        producer.step()
        if len(done) == n:
            break
    assert len(done) == n
    return [done[k] for k in sorted(done,
                                    key=lambda s: int(s.split("-")[-1]))]


PROMPTS = [
    [3, 17, 92, 45, 8, 21, 33, 64, 90],
    [5, 9, 33, 71, 14, 62, 77, 80, 6, 41, 93, 2, 54],
]


def test_registry_register_expire_and_leave():
    srv = P2PRegistryServer()
    try:
        a = P2PRegistryClient(srv.address, "inst-a", "producer",
                              ttl=0.5)
        a.register(("127.0.0.1", 1234), heartbeat=False)
        b = P2PRegistryClient(srv.address, "inst-b", "consumer",
                              ttl=30.0)
        b.register(("0.0.0.0", 0), heartbeat=False)
        members = b.list()
        assert set(members) == {"inst-a", "inst-b"}
        assert b.resolve("inst-a") == ("127.0.0.1", 1234)
        assert set(b.list("producer")) == {"inst-a"}
        # TTL expiry drops a dead instance.
        time.sleep(0.8)
        assert "inst-a" not in b.list()
        # Explicit leave.
        b.leave()
        assert b.list() == {}
    finally:
        srv.shutdown()


@pytest.mark.faults
def test_heartbeat_stall_fault_expires_then_recovers():
    """Armed ``heartbeat.stall`` skips every beat: the registration
    ages out of the registry while the client still lives (consumers
    stop routing to it). Clearing the fault lets the next beat
    re-register — the loop must survive the stall, not exit."""
    from vllm_distributed_tpu.utils import fault_injection as fi
    srv = P2PRegistryServer()
    a = P2PRegistryClient(srv.address, "inst-a", "producer", ttl=0.6)
    before = fi.counters().get("heartbeat.stall", 0)
    try:
        fi.inject("heartbeat.stall")
        a.register(("127.0.0.1", 1234), heartbeat=True)
        b = P2PRegistryClient(srv.address, "inst-b", "consumer",
                              ttl=30.0)
        b.register(("0.0.0.0", 0), heartbeat=False)
        assert "inst-a" in b.list()
        # Every beat stalled -> the initial registration expires.
        deadline = time.monotonic() + 10.0
        while "inst-a" in b.list() and time.monotonic() < deadline:
            time.sleep(0.1)
        assert "inst-a" not in b.list()
        assert fi.counters().get("heartbeat.stall", 0) > before
        # Stall ends: the surviving loop re-registers the instance.
        fi.clear("heartbeat.stall")
        deadline = time.monotonic() + 10.0
        while "inst-a" not in b.list() and time.monotonic() < deadline:
            time.sleep(0.1)
        assert b.resolve("inst-a") == ("127.0.0.1", 1234)
    finally:
        fi.clear("heartbeat.stall")
        a.leave()
        srv.shutdown()


def test_decode_instance_joins_pulls_serves_leaves(checkpoint, registry):
    baseline_engine = LLMEngine(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True).create_engine_config())
    baseline = [o.outputs[0].token_ids
                for o in run(baseline_engine, PROMPTS, "base")]

    # Producer joins the deployment.
    producer = make_engine(checkpoint, registry, "kv_producer", "prefill-0")
    assert "prefill-0" in registry.members("producer")

    # Prefill both prompts; the finished params route by INSTANCE id.
    prod_outs = run(producer, PROMPTS, "prod", max_tokens=1)
    params = [dict(o.kv_transfer_params) for o in prod_outs]
    assert all(p["remote_instance"] == "prefill-0" for p in params)
    for p in params:
        # Dynamic membership is the point: drop the static coordinates,
        # the consumer must resolve them through the registry.
        p.pop("pull_host", None)
        p.pop("pull_port", None)

    # Decode instance A joins and serves the first prompt.
    cons_a = make_engine(checkpoint, registry, "kv_consumer", "decode-a")
    assert "decode-a" in registry.members("consumer")
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    cons_a.add_request("a-0", PROMPTS[0], sp, kv_transfer_params=params[0])
    out_a = _pump_until(cons_a, producer, 1)
    assert out_a[0].outputs[0].token_ids == baseline[0]
    assert out_a[0].num_cached_tokens == 8  # pulled, not recomputed

    # Decode instance B joins MID-RUN and serves the second prompt.
    cons_b = make_engine(checkpoint, registry, "kv_consumer", "decode-b")
    assert set(registry.members("consumer")) == {"decode-a", "decode-b"}
    cons_b.add_request("b-0", PROMPTS[1], sp, kv_transfer_params=params[1])
    out_b = _pump_until(cons_b, producer, 1)
    assert out_b[0].outputs[0].token_ids == baseline[1]
    assert out_b[0].num_cached_tokens == 12

    # B leaves cleanly; membership reflects it immediately.
    sched_conn = cons_b.engine_core.engine_core.scheduler.kv_connector
    assert sched_conn is not None
    sched_conn.shutdown()
    assert "decode-b" not in registry.members("consumer")
    assert "decode-a" in registry.members("consumer")


def test_unknown_producer_falls_back_to_local_prefill(checkpoint,
                                                      registry):
    baseline_engine = LLMEngine(EngineArgs(
        model=checkpoint, dtype="float32", block_size=4,
        num_gpu_blocks_override=64, max_model_len=64,
        max_num_batched_tokens=64, max_num_seqs=8,
        skip_tokenizer_init=True).create_engine_config())
    baseline = [o.outputs[0].token_ids
                for o in run(baseline_engine, [PROMPTS[0]], "base")]

    consumer = make_engine(checkpoint, registry, "kv_consumer",
                           "decode-x")
    params = {"remote_req_id": "ghost", "num_tokens": 8,
              "remote_instance": "prefill-gone"}
    outs = run(consumer, [PROMPTS[0]], "solo", kv_params=[params])
    assert outs[0].outputs[0].token_ids == baseline[0]
