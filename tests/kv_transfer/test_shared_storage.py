"""Disaggregated prefill via the SharedStorage KV connector: a producer
engine saves prompt-page KV to a shared directory; a consumer engine
loads it, skips the matched prefill compute, and produces IDENTICAL
tokens (model: reference tests/v1/kv_connector/unit/ +
nixl_integration accuracy harness, on the filesystem connector)."""

import os

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_kvt")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, storage=None, role=None, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    if storage is not None:
        args.update(
            kv_connector="SharedStorageConnector", kv_role=role,
            kv_connector_extra_config={"shared_storage_path": storage})
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run(engine, prompts, tag, max_tokens=6):
    sps = [SamplingParams(temperature=0.0, max_tokens=max_tokens,
                          ignore_eos=True) for _ in prompts]
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


def sched_connector(engine):
    return engine.engine_core.engine_core.scheduler.kv_connector


def worker_connector(engine):
    core = engine.engine_core.engine_core
    return core.executor.worker.model_runner.kv_connector


PROMPTS = [
    [3, 17, 92, 45, 8, 21, 33, 64, 90],   # 9 tokens -> 2 full pages
    [5, 9, 33, 71, 14, 62, 77, 80, 6, 41, 93, 2, 54],  # 13 -> 3 pages
]


def test_producer_saves_consumer_skips_and_matches(checkpoint, tmp_path):
    storage = str(tmp_path / "kv")

    baseline = run(make_engine(checkpoint), PROMPTS, "base")

    producer = make_engine(checkpoint, storage=storage, role="kv_producer")
    prod_out = run(producer, PROMPTS, "prod")
    assert prod_out == baseline
    wc = worker_connector(producer)
    assert wc.num_pages_saved == 5  # 2 + 3 full prompt pages
    assert len(os.listdir(storage)) == 5

    consumer = make_engine(checkpoint, storage=storage, role="kv_consumer")
    cons_out = run(consumer, PROMPTS, "cons")
    assert cons_out == baseline

    sc = sched_connector(consumer)
    wc = worker_connector(consumer)
    assert sc.num_lookup_hits == 2      # both prompts hit
    assert wc.num_pages_loaded == 5     # all full prompt pages loaded
    # And the consumer really skipped prefill compute for the matched
    # span: its scheduler only scheduled the tail tokens. 9->2 pages(8tok)
    # leaves 1; 13->3 pages(12tok) leaves 1.
    stats = consumer.get_stats()
    assert stats is not None


def test_consumer_prefix_extension_hits_shared_pages(checkpoint, tmp_path):
    """A consumer prompt extending a producer prompt hits on the shared
    page prefix (content-hash keying is position-independent)."""
    storage = str(tmp_path / "kv")
    base_prompt = [3, 17, 92, 45, 8, 21, 33, 64]  # exactly 2 pages
    producer = make_engine(checkpoint, storage=storage, role="kv_producer")
    run(producer, [base_prompt], "prod")

    longer = base_prompt + [55, 66, 77]
    baseline = run(make_engine(checkpoint), [longer], "base")
    consumer = make_engine(checkpoint, storage=storage, role="kv_consumer")
    got = run(consumer, [longer], "cons")
    assert got == baseline
    assert worker_connector(consumer).num_pages_loaded == 2


def test_consumer_miss_falls_back_to_local_prefill(checkpoint, tmp_path):
    storage = str(tmp_path / "kv_empty")
    baseline = run(make_engine(checkpoint), PROMPTS, "base")
    consumer = make_engine(checkpoint, storage=storage, role="kv_consumer")
    got = run(consumer, PROMPTS, "cons")
    assert got == baseline
    assert worker_connector(consumer).num_pages_loaded == 0


def test_kv_both_round_trip(checkpoint, tmp_path):
    """kv_both: first engine run populates the store AND consumes its own
    saves on a repeated prompt (second request loads instead of hitting
    only the local prefix cache if caching is off)."""
    storage = str(tmp_path / "kv")
    engine = make_engine(checkpoint, storage=storage, role="kv_both",
                         enable_prefix_caching=False)
    first = run(engine, [PROMPTS[0]], "a")
    second = run(engine, [PROMPTS[0]], "b")
    assert first == second
    assert worker_connector(engine).num_pages_saved == 2
    assert worker_connector(engine).num_pages_loaded == 2


def test_multi_connector_storage_plus_pull(checkpoint, tmp_path):
    """MultiConnector composes children: the SharedStorage child serves
    the hit; lifecycle hooks fan out without interference (reference:
    v1/multi_connector.py)."""
    storage = str(tmp_path / "kv_multi")

    producer = make_engine(
        checkpoint, kv_connector="MultiConnector", kv_role="kv_producer",
        kv_connector_extra_config={
            "connectors": ["SharedStorageConnector", "DCNPullConnector"],
            "shared_storage_path": storage, "pull_port": 0,
        })
    baseline = run(make_engine(checkpoint), PROMPTS, "base")
    prod_out = run(producer, PROMPTS, "prod")
    assert prod_out == baseline
    assert len(os.listdir(storage)) == 5  # storage child saved pages

    consumer = make_engine(
        checkpoint, kv_connector="MultiConnector", kv_role="kv_consumer",
        kv_connector_extra_config={
            "connectors": ["SharedStorageConnector", "DCNPullConnector"],
            "shared_storage_path": storage, "pull_port": 0,
        })
    cons_out = run(consumer, PROMPTS, "cons")
    assert cons_out == baseline
    wc = worker_connector(consumer)
    # The storage child (first in order) owned the loads.
    assert wc.children[0].num_pages_loaded == 5


def test_shared_storage_under_token_parallelism(checkpoint, tmp_path):
    """Disaggregated prefill composes with TKNP: the consumer's pages
    live in per-rank pool partitions (global ids), and the connector's
    gather/scatter addresses the token-axis-sharded cache directly."""
    storage = str(tmp_path / "kv_tknp")
    baseline = run(make_engine(checkpoint), PROMPTS, "base")

    producer = make_engine(checkpoint, storage=storage, role="kv_producer",
                           token_parallel_size=2)
    assert run(producer, PROMPTS, "prod") == baseline

    consumer = make_engine(checkpoint, storage=storage, role="kv_consumer",
                           token_parallel_size=2)
    assert run(consumer, PROMPTS, "cons") == baseline
    assert worker_connector(consumer).num_pages_loaded == 5
