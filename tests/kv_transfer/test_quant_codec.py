"""Quantized KV-transfer payloads (kv_transfer/quant.py): codec
round-trips per cache dtype, the dcn_pull quantized wire format with its
corrupt-scale raw-precision fallback drill, and the shared_storage codec
page files (plus the compressed raw format the plane-off writer uses)."""

import glob
import os

import ml_dtypes
import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.distributed.kv_transfer import quant
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.parallel import collectives
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi


@pytest.fixture(autouse=True)
def _clean_gating(monkeypatch):
    yield
    fi.clear()
    collectives.refresh()


# ---------------------------------------------------------------------------
# Codec units
# ---------------------------------------------------------------------------

def _pages(dtype, rng=None, shape=(2, 3, 2, 4, 16)):
    rng = rng or np.random.default_rng(0)
    k = rng.normal(size=shape).astype(dtype)
    v = rng.normal(size=shape).astype(dtype)
    return k, v


@pytest.mark.parametrize("dtype", [np.float32, ml_dtypes.bfloat16])
def test_codec_roundtrip_geometry_bit_exact(dtype):
    k, v = _pages(dtype)
    payload = quant.encode_pages(k, v)
    k2, v2 = quant.decode_pages(payload)
    assert k2.shape == k.shape and v2.shape == v.shape
    assert k2.dtype == k.dtype and v2.dtype == v.dtype
    # Dequantization error bounded by half an int8 step per block.
    amax = np.max(np.abs(k.astype(np.float32)))
    assert np.max(np.abs(k.astype(np.float32)
                         - k2.astype(np.float32))) <= amax / 127.0


def test_codec_fp32_payload_at_least_3p5x_smaller():
    k, v = _pages(np.float32)
    payload = quant.encode_pages(k, v)
    assert quant.raw_nbytes(payload) / quant.encoded_nbytes(payload) \
        >= 3.5


def test_codec_block_never_crosses_page_head_span():
    # span = page_size * head_dim = 4 * 16 = 64 < default block 256:
    # the block clips to the span so any page subset dequantizes alone.
    k, v = _pages(np.float32)
    payload = quant.encode_pages(k, v)
    assert payload["block"] == 64
    assert 64 % payload["block"] == 0


def test_codec_rejects_corrupt_scale():
    k, v = _pages(np.float32)
    payload = quant.encode_pages(k, v)
    payload["ks"] = bytes([payload["ks"][0] ^ 0xFF]) + payload["ks"][1:]
    with pytest.raises(quant.QuantCodecError):
        quant.decode_pages(payload)


def test_codec_rejects_corrupt_geometry():
    k, v = _pages(np.float32)
    payload = quant.encode_pages(k, v)
    payload["k_shape"] = list(payload["k_shape"])
    payload["k_shape"][1] += 1
    with pytest.raises(quant.QuantCodecError):
        quant.decode_pages(payload)


def test_codec_rejects_newer_version():
    k, v = _pages(np.float32)
    payload = quant.encode_pages(k, v)
    payload["version"] = quant.WIRE_VERSION + 1
    with pytest.raises(quant.QuantCodecError):
        quant.decode_pages(payload)


def test_scale_corrupt_fault_point_trips_decode():
    fi.inject("qcomm.scale_corrupt", max_fires=1)
    k, v = _pages(np.float32)
    payload = quant.encode_pages(k, v)
    with pytest.raises(quant.QuantCodecError):
        quant.decode_pages(payload)
    assert fi.counters().get("qcomm.scale_corrupt") == 1
    # The next encode is clean again (max_fires).
    k2, v2 = quant.decode_pages(quant.encode_pages(k, v))
    assert k2.shape == k.shape


# ---------------------------------------------------------------------------
# Latent (MLA/TPLA) wire format: versioned geometry, old-decoder
# rejection, and the shard/unshard transform round-trip
# ---------------------------------------------------------------------------

_LATENT_META = {"kv_lora_rank": 32, "rope_dim": 8, "tp_shard": 2}


def _latent_pages(dtype=np.float32, pages=3):
    rng = np.random.default_rng(7)
    kv = rng.normal(size=(2, pages, 4, 32)).astype(dtype)
    pe = rng.normal(size=(2, pages, 4, 8)).astype(dtype)
    return kv, pe


def test_latent_codec_roundtrip_carries_geometry():
    kv, pe = _latent_pages()
    payload = quant.encode_pages(kv, pe, latent=_LATENT_META)
    assert payload["version"] == quant.LATENT_WIRE_VERSION
    assert quant.latent_meta(payload) == _LATENT_META
    k2, v2 = quant.decode_pages(payload)
    assert k2.shape == kv.shape and v2.shape == pe.shape
    amax = np.max(np.abs(kv))
    assert np.max(np.abs(kv - k2)) <= amax / 127.0
    # The scale block divides BOTH stacks' per-page spans (the rope
    # sidecar span, 4*8=32, is the binding one here).
    assert (4 * 32) % payload["block"] == 0
    assert (4 * 8) % payload["block"] == 0


def test_latent_payload_rejected_by_pre_tpla_decoder(monkeypatch):
    # An old engine's decoder (MAX_DECODE_VERSION=1) must REJECT a
    # latent payload — degrade to rejection, never silent corruption.
    kv, pe = _latent_pages()
    payload = quant.encode_pages(kv, pe, latent=_LATENT_META)
    monkeypatch.setattr(quant, "MAX_DECODE_VERSION", 1)
    with pytest.raises(quant.QuantCodecError):
        quant.decode_pages(payload)


def test_latent_geometry_in_crc():
    kv, pe = _latent_pages()
    payload = quant.encode_pages(kv, pe, latent=_LATENT_META)
    payload["kv_lora_rank"] = 64  # header tamper must fail the CRC
    with pytest.raises(quant.QuantCodecError):
        quant.decode_pages(payload)


def test_standard_payloads_keep_wire_version_1():
    # Old consumers must keep decoding standard payloads unchanged.
    k, v = _pages(np.float32)
    payload = quant.encode_pages(k, v)
    assert payload["version"] == quant.WIRE_VERSION == 1
    assert quant.latent_meta(payload) is None


@pytest.mark.parametrize("producer_shards,consumer_shards",
                         [(1, 2), (2, 4), (4, 1), (2, 2)])
def test_latent_shard_transform_roundtrip_bit_exact(producer_shards,
                                                    consumer_shards):
    """A producer mesh's cache layout -> wire -> a DIFFERENT TP
    degree's cache layout -> wire again: the full latent rows survive
    bit-exactly (the acceptance criterion for cross-degree transfer)."""
    from vllm_distributed_tpu.distributed.kv_transfer.page_io import (
        _latent_to_wire, _wire_to_latent)
    lkv, rope = 32, 8
    kv, pe = _latent_pages()

    def cache_of(shards):
        if shards == 1:
            # Replicated layout: one concatenated row, no sidecar.
            return _wire_to_latent(kv, pe, lkv, rope, 1, lkv + rope,
                                   None)
        return _wire_to_latent(kv, pe, lkv, rope, shards, lkv, rope)

    c_p, pe_p = cache_of(producer_shards)
    k_w, v_w = _latent_to_wire(c_p, pe_p, lkv, rope, producer_shards)
    assert np.array_equal(k_w, kv) and np.array_equal(v_w, pe)
    c_c, pe_c = _wire_to_latent(
        k_w, v_w, lkv, rope, consumer_shards,
        lkv + rope if consumer_shards == 1 else lkv,
        None if consumer_shards == 1 else rope)
    k2, v2 = _latent_to_wire(c_c, pe_c, lkv, rope, consumer_shards)
    assert np.array_equal(k2, kv) and np.array_equal(v2, pe)


def test_payload_enabled_gating(monkeypatch):
    monkeypatch.setenv("VDT_QCOMM", "1")
    collectives.refresh()
    assert quant.payload_enabled("dcn_pull", np.float32)
    # Sub-byte caches are already small: stay raw.
    assert not quant.payload_enabled("dcn_pull",
                                     ml_dtypes.float8_e4m3fn)
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp")
    collectives.refresh()
    assert not quant.payload_enabled("dcn_pull", np.float32)


# ---------------------------------------------------------------------------
# Engine harness (same tiny checkpoint the other connector tests use)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_qcodec")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, connector=None, role=None, extra=None,
                **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    if connector is not None:
        args.update(kv_connector=connector, kv_role=role,
                    kv_connector_extra_config=extra or {})
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run(engine, prompts, tag, max_tokens=6):
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k] for k in order]


def _pump(consumer, producer, n, max_iters=2000):
    done = {}
    for _ in range(max_iters):
        for out in consumer.step():
            if out.finished:
                done[out.request_id] = out
        producer.step()
        if len(done) == n:
            break
    assert len(done) == n, f"consumer finished {len(done)}/{n}"
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k] for k in order]


def _transfer_bytes(engine) -> int:
    kv = (engine.get_stats().get("transport") or {}).get("kv") or {}
    return sum(int(e.get("tx_bytes", 0)) + int(e.get("rx_bytes", 0))
               for conn, e in kv.items()
               if isinstance(e, dict) and conn != "page_io")


def _qcomm_stats(engine) -> dict:
    return (engine.get_stats().get("transport") or {}).get("qcomm") or {}


PROMPTS = [
    [3, 17, 92, 45, 8, 21, 33, 64, 90],
    [5, 9, 33, 71, 14, 62, 77, 80, 6, 41, 93, 2, 54],
]


def _dcn_leg(checkpoint, tag):
    producer = make_engine(checkpoint, connector="DCNPullConnector",
                           role="kv_producer", extra={"pull_port": 0})
    prod_outs = run(producer, PROMPTS, f"prod-{tag}", max_tokens=1)
    params = [o.kv_transfer_params for o in prod_outs]
    consumer = make_engine(checkpoint, connector="DCNPullConnector",
                           role="kv_consumer", extra={"pull_port": 0})
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, (p, kvp) in enumerate(zip(PROMPTS, params)):
        consumer.add_request(f"cons-{tag}-{i}", p, sp,
                             kv_transfer_params=kvp)
    outs = _pump(consumer, producer, len(PROMPTS))
    toks = [o.outputs[0].token_ids for o in outs]
    nbytes = _transfer_bytes(producer) + _transfer_bytes(consumer)
    qcomm = _qcomm_stats(producer)
    qcomm_cons = _qcomm_stats(consumer)
    producer.engine_core.shutdown()
    consumer.engine_core.shutdown()
    return toks, nbytes, qcomm, qcomm_cons


def test_dcn_pull_quantized_parity_and_bytes(checkpoint, monkeypatch):
    monkeypatch.setenv("VDT_QCOMM", "0")
    collectives.refresh()
    toks_off, bytes_off, _, _ = _dcn_leg(checkpoint, "off")

    monkeypatch.setenv("VDT_QCOMM", "1")
    collectives.refresh()
    toks_on, bytes_on, qcomm_prod, qcomm_cons = _dcn_leg(checkpoint,
                                                         "on")

    # Token-identical greedy with the plane on, >= 3.5x fewer wire
    # bytes, and the CONSUMER accounts the exact savings (credited
    # after a successful decode, so a degraded pull never counts).
    assert toks_on == toks_off
    assert bytes_off / bytes_on >= 3.5
    assert qcomm_cons.get("dcn_pull", {}).get("bytes_saved", 0) > 0
    assert qcomm_cons.get("dcn_pull", {}).get("fallbacks", 0) == 0
    assert qcomm_prod.get("dcn_pull", {}).get("bytes_saved", 0) == 0


def test_dcn_pull_scale_corrupt_degrades_to_raw(checkpoint,
                                                monkeypatch):
    """The PR1/2 recovery ladder under the codec: a corrupted scale
    header fails the consumer's checksum and the pull re-requests the
    raw-precision payload — outputs stay correct, the fallback and the
    fault fire are both counted."""
    monkeypatch.setenv("VDT_QCOMM", "0")
    collectives.refresh()
    toks_off, _, _, _ = _dcn_leg(checkpoint, "fboff")

    monkeypatch.setenv("VDT_QCOMM", "1")
    collectives.refresh()
    before = fi.counters().get("qcomm.scale_corrupt", 0)
    fi.inject("qcomm.scale_corrupt", max_fires=1)
    toks_fb, _, _, qcomm_cons = _dcn_leg(checkpoint, "fb")

    assert toks_fb == toks_off
    assert fi.counters().get("qcomm.scale_corrupt", 0) == before + 1
    assert qcomm_cons.get("dcn_pull", {}).get("fallbacks", 0) == 1


def test_shared_storage_quantized_files_and_parity(checkpoint,
                                                   tmp_path,
                                                   monkeypatch):
    storage = str(tmp_path / "kvq")
    monkeypatch.setenv("VDT_QCOMM", "0")
    collectives.refresh()
    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint), PROMPTS, "base")]

    monkeypatch.setenv("VDT_QCOMM", "1")
    collectives.refresh()
    producer = make_engine(checkpoint, connector="SharedStorageConnector",
                           role="kv_producer",
                           extra={"shared_storage_path": storage})
    run(producer, PROMPTS, "sprod", max_tokens=1)
    files = glob.glob(os.path.join(storage, "*.npz"))
    assert files
    # Files hold the codec fields, at a fraction of the raw bytes.
    with np.load(files[0]) as f:
        assert "qcomm_meta" in f and "qk" in f
    # Smaller than the raw k+v payload it replaces even at this tiny
    # smoke geometry (k+v = 2 * [L=2, KVH=2, PS=4, D=16] * fp32 =
    # 2048 B/page; npz container overhead amortizes at real page
    # sizes).
    raw_page = 2 * 2 * 2 * 4 * 16 * 4
    assert all(os.path.getsize(f) < raw_page for f in files)
    assert _qcomm_stats(producer).get("shared_storage",
                                      {}).get("bytes_saved", 0) > 0

    consumer = make_engine(checkpoint, connector="SharedStorageConnector",
                           role="kv_consumer",
                           extra={"shared_storage_path": storage})
    got = [o.outputs[0].token_ids
           for o in run(consumer, PROMPTS, "scons")]
    assert got == baseline


def test_shared_storage_plane_off_writes_compressed(checkpoint,
                                                    tmp_path,
                                                    monkeypatch):
    """VDT_QCOMM=0 writers still shrink on-disk artifacts (zlib) and
    loads stay token-identical — the uncompressed-journal fix."""
    storage = str(tmp_path / "kvc")
    monkeypatch.setenv("VDT_QCOMM", "0")
    collectives.refresh()
    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint), PROMPTS, "cbase")]
    producer = make_engine(checkpoint, connector="SharedStorageConnector",
                           role="kv_producer",
                           extra={"shared_storage_path": storage})
    run(producer, PROMPTS, "cprod", max_tokens=1)
    files = glob.glob(os.path.join(storage, "*.npz"))
    assert files
    with np.load(files[0]) as f:
        assert "k" in f and "qcomm_meta" not in f
    consumer = make_engine(checkpoint, connector="SharedStorageConnector",
                           role="kv_consumer",
                           extra={"shared_storage_path": storage})
    got = [o.outputs[0].token_ids
           for o in run(consumer, PROMPTS, "ccons")]
    assert got == baseline


def test_shared_storage_legacy_format_still_loads(tmp_path, monkeypatch):
    """A pre-codec (uncompressed np.savez) page file keeps decoding —
    old stores survive the wire-format version bump."""
    from vllm_distributed_tpu.distributed.kv_transfer.shared_storage \
        import SharedStorageConnector
    conn = SharedStorageConnector.__new__(SharedStorageConnector)
    conn.path = str(tmp_path)
    rng = np.random.default_rng(3)
    k = rng.normal(size=(2, 2, 4, 16)).astype(np.float32)
    v = rng.normal(size=(2, 2, 4, 16)).astype(np.float32)
    with open(conn._file("deadbeef"), "wb") as f:
        np.savez(f, k=k, v=v)
    k2, v2, latent = conn._read_page_file("deadbeef")
    np.testing.assert_array_equal(k2, k)
    np.testing.assert_array_equal(v2, v)
    assert latent is None
