"""Async disaggregated prefill via the DCN pull connector: the decode
engine pulls KV pages from the prefill engine over a socket side-channel
while both engines keep stepping; producer pages are freed only after the
pull completes (model: reference nixl_connector lifecycle tests,
tests/v1/kv_connector/unit/test_remote_prefill_lifecycle.py)."""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.request import RequestStatus
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_dcn")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, role=None, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=64, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    if role is not None:
        args.update(kv_connector="DCNPullConnector", kv_role=role,
                    kv_connector_extra_config={"pull_port": 0})
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def scheduler(engine):
    return engine.engine_core.engine_core.scheduler


def run(engine, prompts, tag, max_tokens=6):
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k] for k in order]


PROMPTS = [
    [3, 17, 92, 45, 8, 21, 33, 64, 90],               # 9 tokens, 2 pages
    [5, 9, 33, 71, 14, 62, 77, 80, 6, 41, 93, 2, 54],  # 13 tokens, 3 pages
]


def _pump_until(consumer, producer, tag, n_requests, max_iters=2000):
    """Step both engines until the consumer finishes its requests (the
    pull needs the producer's step-poll to serve pages)."""
    done = {}
    for _ in range(max_iters):
        for out in consumer.step():
            if out.finished:
                done[out.request_id] = out
        producer.step()
        if len(done) == n_requests:
            break
    assert len(done) == n_requests, \
        f"consumer finished {len(done)}/{n_requests}"
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k] for k in order]


def test_async_pull_lifecycle_and_parity(checkpoint):
    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint), PROMPTS, "base")]

    # --- producer: prefill-only requests hand back pull coordinates ---
    producer = make_engine(checkpoint, role="kv_producer")
    prod_outs = run(producer, PROMPTS, "prod", max_tokens=1)
    params = [o.kv_transfer_params for o in prod_outs]
    assert all(p is not None and "pull_port" in p and p["pull_port"] > 0
               for p in params)
    assert [len(p["remote_page_ids"]) for p in params] == [2, 3]

    # Deferred free: the producer's pages are still alive.
    psched = scheduler(producer)
    assert len(psched.reqs_pending_send) == 2
    free_before = psched.kv_cache_manager.block_pool.get_num_free_blocks()

    # --- consumer: requests arrive with the pull coordinates ---
    consumer = make_engine(checkpoint, role="kv_consumer")
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, (p, kvp) in enumerate(zip(PROMPTS, params)):
        consumer.add_request(f"cons-{i}", p, sp, kv_transfer_params=kvp)

    # First consumer step: requests go into WAITING_FOR_REMOTE_KVS.
    consumer.step()
    csched = scheduler(consumer)
    held = dict(csched.waiting_for_remote_kv)
    assert len(held) == 2
    assert all(r.status == RequestStatus.WAITING_FOR_REMOTE_KVS
               for r in held.values())

    outs = _pump_until(consumer, producer, "cons", len(PROMPTS))
    got = [o.outputs[0].token_ids for o in outs]
    assert got == baseline
    assert not csched.waiting_for_remote_kv

    # The pulled span skipped local prefill: only the last page's tail
    # tokens were computed locally (9 -> 2 pages pulled = 8 external).
    assert [o.num_cached_tokens for o in outs] == [8, 12]

    # Producer side: DONE notifications landed, deferred pages freed.
    for _ in range(50):
        producer.step()
        if not psched.reqs_pending_send:
            break
    assert not psched.reqs_pending_send
    free_after = psched.kv_cache_manager.block_pool.get_num_free_blocks()
    assert free_after > free_before


@pytest.mark.faults
def test_delayed_pull_keeps_token_parity(checkpoint):
    """Armed ``kv_pull.delay`` stalls every pull worker at entry (the
    slow-WAN drill): requests sit in WAITING_FOR_REMOTE_KVS longer but
    the async-pull lifecycle must absorb the latency — same tokens as
    the local baseline, no local-recompute fallback."""
    from vllm_distributed_tpu.utils import fault_injection as fi
    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint), PROMPTS, "base")]
    producer = make_engine(checkpoint, role="kv_producer")
    prod_outs = run(producer, PROMPTS, "prod", max_tokens=1)
    params = [o.kv_transfer_params for o in prod_outs]

    consumer = make_engine(checkpoint, role="kv_consumer")
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    before = fi.counters().get("kv_pull.delay", 0)
    fi.inject("kv_pull.delay", delay_s=0.05)
    try:
        for i, (p, kvp) in enumerate(zip(PROMPTS, params)):
            consumer.add_request(f"cons-{i}", p, sp,
                                 kv_transfer_params=kvp)
        outs = _pump_until(consumer, producer, "cons", len(PROMPTS))
    finally:
        fi.clear("kv_pull.delay")
    got = [o.outputs[0].token_ids for o in outs]
    assert got == baseline
    # One delay per pull worker fired; the pulled spans still skipped
    # local prefill (no degraded local-recompute path).
    assert fi.counters().get("kv_pull.delay", 0) >= before + 2
    assert [o.num_cached_tokens for o in outs] == [8, 12]
    csched = scheduler(consumer)
    assert not csched.waiting_for_remote_kv


def test_other_requests_progress_while_pull_held(checkpoint):
    """The hold-until-loaded state must not stall the engine: a local
    request keeps decoding while another waits on a pull from a peer
    that accepts the connection but never answers."""
    import socket as _socket
    import threading
    import time as _time

    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint), [PROMPTS[1]],
                             "base", max_tokens=5)]

    # A silent peer: accepts connections, never replies, so the pull
    # stays genuinely in flight.
    silent = _socket.socket()
    silent.bind(("127.0.0.1", 0))
    silent.listen(4)
    conns = []
    threading.Thread(target=lambda: conns.append(silent.accept()),
                     daemon=True).start()
    params = {"remote_req_id": "held", "pull_host": "127.0.0.1",
              "pull_port": silent.getsockname()[1], "num_tokens": 12,
              "remote_page_ids": [0, 1, 2]}

    consumer = make_engine(checkpoint, role="kv_consumer")
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)
    consumer.add_request("held-0", PROMPTS[1], sp,
                         kv_transfer_params=params)
    consumer.add_request("local-0", PROMPTS[0], sp)

    local_done = None
    for _ in range(300):
        for out in consumer.step():
            if out.finished and out.request_id == "local-0":
                local_done = out
        if local_done:
            break
    assert local_done is not None
    csched = scheduler(consumer)
    assert "held-0" in csched.waiting_for_remote_kv

    # Kill the silent peer: the pull errors, the span recomputes
    # locally, and the held request still produces correct output.
    for c, _addr in conns:
        c.close()
    silent.close()
    done = {}
    for _ in range(3000):
        for out in consumer.step():
            if out.finished:
                done[out.request_id] = out
        if "held-0" in done:
            break
        _time.sleep(0.002)
    assert "held-0" in done
    assert done["held-0"].outputs[0].token_ids == baseline[0]


def test_failed_pull_recomputes_locally(checkpoint):
    """An unreachable producer must not corrupt output: the held request
    rejoins the queue and prefills its span locally, matching baseline."""
    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint), [PROMPTS[0]], "base")]

    consumer = make_engine(checkpoint, role="kv_consumer")
    # A bound-but-never-listening socket: connects are refused, and
    # holding the bind stops any other process reusing the port while
    # the test runs (a bind/close trick is racy on a busy box).
    import socket as _socket
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    bogus = {"remote_req_id": "gone", "pull_host": "127.0.0.1",
             "pull_port": dead_port, "num_tokens": 8,
             "remote_page_ids": [0, 1]}
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    consumer.add_request("cons-0", PROMPTS[0], sp, kv_transfer_params=bogus)

    done = {}
    import time as _time
    for _ in range(2000):
        for out in consumer.step():
            if out.finished:
                done[out.request_id] = out
        if done:
            break
        _time.sleep(0.002)  # the failing pull thread needs GIL slots
    assert "cons-0" in done
    assert done["cons-0"].outputs[0].token_ids == baseline[0]
    # The span was NOT treated as externally cached.
    assert done["cons-0"].num_cached_tokens == 0
    s.close()


def test_abort_while_pull_in_flight_keeps_pages_safe(checkpoint):
    """Aborting a held request must keep its pages allocated until the
    worker reports the (moot) pull finished — a late apply must never
    write into reallocated pages."""
    producer = make_engine(checkpoint, role="kv_producer")
    prod_out = run(producer, [PROMPTS[1]], "prod", max_tokens=1)
    params = prod_out[0].kv_transfer_params

    consumer = make_engine(checkpoint, role="kv_consumer")
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)
    consumer.add_request("gone-0", PROMPTS[1], sp, kv_transfer_params=params)
    consumer.step()  # admission -> held + pull kicked off
    csched = scheduler(consumer)
    assert "gone-0" in csched.waiting_for_remote_kv

    consumer.abort_request(["gone-0"])
    consumer.step()
    assert "gone-0" in csched.cancelled_remote_kv

    # Once the producer serves the pull, the cancelled hold resolves and
    # the pages free.
    for _ in range(2000):
        consumer.step()
        producer.step()
        if not csched.cancelled_remote_kv:
            break
    assert not csched.cancelled_remote_kv
    assert not consumer.has_unfinished_requests()


def test_large_pull_applies_in_chunks_without_stalling_a_step(
        checkpoint, monkeypatch):
    """The apply path is bounded per step: a pull larger than
    VDT_KV_APPLY_CHUNK_PAGES lands over several get_finished calls via
    the donated scatter (transfer thread already staged the pages on
    device), so no single decode step absorbs the whole pull
    (VERDICT r3 weak #5; reference: nixl's async-completion +
    layerwise-load overlap)."""
    monkeypatch.setenv("VDT_KV_APPLY_CHUNK_PAGES", "2")
    long_prompt = list(range(2, 2 + 30))  # 8 pages at block_size 4

    producer = make_engine(checkpoint, role="kv_producer")
    (prod_out, ) = run(producer, [long_prompt], "bigp", max_tokens=1)
    params = prod_out.kv_transfer_params
    assert len(params["remote_page_ids"]) == 7  # full pages of 30 tokens

    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint), [long_prompt],
                             "bigbase")]

    consumer = make_engine(checkpoint, role="kv_consumer")
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    consumer.add_request("bigc-0", long_prompt, sp,
                         kv_transfer_params=params)
    outs = _pump_until(consumer, producer, "bigc", 1)
    assert [o.outputs[0].token_ids for o in outs] == baseline

    conn = (consumer.engine_core.engine_core.executor
            .worker.model_runner.kv_connector)
    # 7 pulled pages with a 2-page budget: at least 4 steps, and no
    # step ever applied more than the chunk bound.
    assert 0 < conn.max_pages_applied_per_step <= 2


def test_async_pull_under_pipeline_parallelism(checkpoint):
    """Disaggregated prefill with pp=2 on both sides (BASELINE config
    #5 shape): wire pages span all stages' layer slices; parity with a
    plain pp=2 engine."""
    pp = dict(pipeline_parallel_size=2)
    baseline = [o.outputs[0].token_ids
                for o in run(make_engine(checkpoint, **pp), PROMPTS,
                             "ppbase")]

    producer = make_engine(checkpoint, role="kv_producer", **pp)
    prod_outs = run(producer, PROMPTS, "ppprod", max_tokens=1)
    params = [o.kv_transfer_params for o in prod_outs]
    assert all(p is not None for p in params)

    consumer = make_engine(checkpoint, role="kv_consumer", **pp)
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    for i, (p, kvp) in enumerate(zip(PROMPTS, params)):
        consumer.add_request(f"ppcons-{i}", p, sp, kv_transfer_params=kvp)
    outs = _pump_until(consumer, producer, "ppcons", len(PROMPTS))
    got = [o.outputs[0].token_ids for o in outs]
    assert got == baseline
    # The pulled span skipped local prefill.
    assert all(o.num_cached_tokens > 0 for o in outs)
