"""Pallas ragged paged attention kernel vs the dense reference.

Runs the kernel in interpret mode on CPU (reference test strategy: CPU/
interpret-mode Pallas path for kernel tests, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.ops.attention import naive_ragged_attention
from vllm_distributed_tpu.ops.pallas_attention import (
    ragged_paged_attention_pallas)


def build_case(rng, *, seqs, page_size, pages_per_req, num_q_heads,
               num_kv_heads, head_dim, max_q, dtype=jnp.float32):
    """seqs: list of (q_len, kv_len) with kv_len >= q_len."""
    R = len(seqs)
    max_reqs = R + 1  # one inactive padding row
    num_pages = max_reqs * pages_per_req
    T = sum(q for q, _ in seqs)
    bq = min(max_q, 128)
    T_pad = T + bq

    k_pages = jnp.asarray(rng.standard_normal(
        (num_pages, num_kv_heads, page_size, head_dim)), dtype)
    v_pages = jnp.asarray(rng.standard_normal(
        (num_pages, num_kv_heads, page_size, head_dim)), dtype)
    q = jnp.asarray(rng.standard_normal((T_pad, num_q_heads, head_dim)),
                    dtype)

    # Page tables: request r owns pages [r*P, (r+1)*P).
    bt = np.zeros((max_reqs, pages_per_req), np.int32)
    for r in range(max_reqs):
        bt[r] = np.arange(r * pages_per_req, (r + 1) * pages_per_req)

    seq_info = np.zeros((max_reqs, 4), np.int32)
    req_idx = np.zeros((T_pad, ), np.int32)
    q_pos = np.zeros((T_pad, ), np.int32)
    t = 0
    for r, (q_len, kv_len) in enumerate(seqs):
        seq_info[r] = (t, q_len, kv_len, r)
        req_idx[t:t + q_len] = r
        q_pos[t:t + q_len] = np.arange(kv_len - q_len, kv_len)
        t += q_len

    return dict(
        q=q, k_pages=k_pages, v_pages=v_pages,
        seq_info=jnp.asarray(seq_info),
        num_seqs=jnp.asarray([R], jnp.int32),
        block_tables=jnp.asarray(bt),
        req_idx=jnp.asarray(req_idx), q_pos=jnp.asarray(q_pos),
        T=T, max_q=max_q,
    )


def run_both(case, sm_scale=0.125):
    out_pallas = ragged_paged_attention_pallas(
        case["q"], case["k_pages"], case["v_pages"], case["seq_info"],
        case["num_seqs"], case["block_tables"], sm_scale=sm_scale,
        max_q=case["max_q"], interpret=True)
    out_ref = naive_ragged_attention(
        case["q"], case["k_pages"], case["v_pages"], case["block_tables"],
        case["req_idx"], case["q_pos"], sm_scale=sm_scale)
    T = case["T"]
    return np.asarray(out_pallas)[:T], np.asarray(out_ref)[:T]


@pytest.mark.parametrize("seqs,max_q", [
    # Pure decode: one token per sequence, varying kv lens.
    ([(1, 1), (1, 5), (1, 17), (1, 32)], 1),
    # Pure prefill from scratch.
    ([(7, 7), (16, 16), (3, 3)], 16),
    # Chunked prefill: later chunk attends earlier kv.
    ([(8, 24), (4, 9)], 8),
    # Mixed prefill + decode.
    ([(1, 13), (12, 12), (1, 30), (5, 21)], 16),
])
def test_matches_reference(seqs, max_q):
    rng = np.random.default_rng(0)
    case = build_case(rng, seqs=seqs, page_size=8, pages_per_req=4,
                      num_q_heads=8, num_kv_heads=4, head_dim=128,
                      max_q=max_q)
    got, want = run_both(case)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_gqa_group_and_mha():
    rng = np.random.default_rng(1)
    for kvh in (1, 2, 8):
        case = build_case(rng, seqs=[(3, 11), (1, 4)], page_size=8,
                          pages_per_req=4, num_q_heads=8, num_kv_heads=kvh,
                          head_dim=128, max_q=8)
        got, want = run_both(case)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_multi_q_tile_long_prefill():
    """q_len spanning several q tiles (bq < max_q would need max_q > 128;
    here exercise several kv blocks + full tile boundary instead)."""
    rng = np.random.default_rng(2)
    case = build_case(rng, seqs=[(32, 32), (32, 48)], page_size=8,
                      pages_per_req=8, num_q_heads=4, num_kv_heads=4,
                      head_dim=128, max_q=32)
    got, want = run_both(case)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_batched_decode_many_seqs():
    """The SB-batched decode kernel: enough sequences for several grid
    programs, ragged kv lens, an sb that does not divide num_seqs."""
    rng = np.random.default_rng(7)
    seqs = [(1, k) for k in (1, 5, 17, 32, 9, 25, 13, 2, 31, 8, 20)]
    case = build_case(rng, seqs=seqs, page_size=8, pages_per_req=4,
                      num_q_heads=8, num_kv_heads=4, head_dim=128,
                      max_q=1)
    got, want = run_both(case)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_batched_decode_scattered_q_start():
    """Decode rows addressed through q_start, not the run index — the
    layout token parallelism's per-rank compacted seq lists produce."""
    rng = np.random.default_rng(8)
    case = build_case(rng, seqs=[(1, 7), (1, 19), (1, 3)], page_size=8,
                      pages_per_req=4, num_q_heads=8, num_kv_heads=4,
                      head_dim=128, max_q=1)
    # Scatter the three queries to rows 3, 0, 2 of the token array.
    si = np.asarray(case["seq_info"]).copy()
    perm = [3, 0, 2]
    q_old = np.asarray(case["q"])
    q_new = np.zeros_like(q_old)
    req_idx = np.full((q_old.shape[0], ), len(perm), np.int32)
    q_pos = np.zeros((q_old.shape[0], ), np.int32)
    for r, row in enumerate(perm):
        q_new[row] = q_old[si[r, 0]]
        si[r, 0] = row
        req_idx[row] = r
        q_pos[row] = si[r, 2] - 1
    out = ragged_paged_attention_pallas(
        jnp.asarray(q_new), case["k_pages"], case["v_pages"],
        jnp.asarray(si), case["num_seqs"], case["block_tables"],
        sm_scale=0.125, max_q=1, interpret=True)
    want = naive_ragged_attention(
        jnp.asarray(q_new), case["k_pages"], case["v_pages"],
        case["block_tables"], jnp.asarray(req_idx), jnp.asarray(q_pos),
        sm_scale=0.125)
    got = np.asarray(out)
    want = np.asarray(want)
    for row in perm:
        np.testing.assert_allclose(got[row], want[row], rtol=2e-3,
                                   atol=2e-3)


def test_inactive_rows_and_bf16():
    rng = np.random.default_rng(3)
    case = build_case(rng, seqs=[(1, 9), (1, 3)], page_size=8,
                      pages_per_req=2, num_q_heads=4, num_kv_heads=2,
                      head_dim=128, max_q=1, dtype=jnp.bfloat16)
    got, want = run_both(case)
    np.testing.assert_allclose(np.float32(got), np.float32(want),
                               rtol=3e-2, atol=3e-2)
