import functools
import numpy as np
import jax
import jax.numpy as jnp
from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.ops.attention import (write_kv_cache,
                                                paged_attention,
                                                naive_ragged_attention)


def make(ps=4, n=5, L=2, N=16, KVH=2, QH=4, D=16, max_q=8, T=24,
         max_reqs=8, ppr=16):
    rng = np.random.default_rng(0)
    k_all = jnp.zeros((L, N, KVH, ps, D), jnp.float32)
    v_all = jnp.zeros((L, N, KVH, ps, D), jnp.float32)
    k_new = jnp.asarray(rng.standard_normal((T, KVH, D)), jnp.float32)
    v_new = jnp.asarray(rng.standard_normal((T, KVH, D)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((T, QH, D)), jnp.float32)
    bt = np.zeros((max_reqs, ppr), np.int32)
    bt[0, 0] = 1; bt[0, 1] = 2
    slot = np.full((T,), -1, np.int32)
    slot[:n] = bt[0, np.arange(n) // ps] * ps + np.arange(n) % ps
    seq_info = np.zeros((max_reqs, 4), np.int32)
    seq_info[0] = (0, n, n, 0)
    kv_runs = []
    consumed = 0
    while consumed < n:
        off = consumed % ps
        run_len = min(ps - off, n - consumed)
        kv_runs.append((int(bt[0, consumed // ps]), off,
                        consumed - off + ps, run_len))
        consumed += run_len
    kvr = np.zeros((8, 4), np.int32)
    kvr[:len(kv_runs)] = kv_runs
    positions = np.zeros((T,), np.int32); positions[:n] = np.arange(n)
    batch = AttentionBatch(
        req_idx=jnp.zeros((T,), jnp.int32),
        positions=jnp.asarray(positions),
        slot_mapping=jnp.asarray(slot), block_tables=jnp.asarray(bt),
        seq_lens=jnp.zeros((max_reqs,), jnp.int32),
        seq_info=jnp.asarray(seq_info),
        num_seqs=jnp.asarray([1], jnp.int32),
        kv_runs=jnp.asarray(kvr),
        num_kv_runs=jnp.asarray([len(kv_runs)], jnp.int32),
        max_q=max_q)
    return k_all, v_all, k_new, v_new, q, batch, n


def test_combo_jit(monkeypatch):
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    k_all, v_all, k_new, v_new, q, batch, n = make()
    layer = jnp.asarray([1], jnp.int32)

    def f(k_all, v_all, k_new, v_new, q):
        k_all, v_all = write_kv_cache(k_all, v_all, k_new, v_new, batch,
                                      layer)
        out = paged_attention(q, k_all, v_all, batch, sm_scale=0.125,
                              layer=layer)
        return out, k_all, v_all

    out, k2, v2 = jax.jit(f)(k_all, v_all, k_new, v_new, q)
    ref = naive_ragged_attention(
        q, k2[1], v2[1], batch.block_tables, batch.req_idx,
        batch.positions, sm_scale=0.125)
    got = np.asarray(out)[:n]
    want = np.asarray(ref)[:n]
    print("combo max diff:", np.abs(got - want).max())
    print("got row0:", got[0, 0, :4])
    print("want row0:", want[0, 0, :4])
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_combo_scan(monkeypatch):
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    k_all, v_all, k_new, v_new, q, batch, n = make()

    def layer_fn(carry, xs):
        k_all, v_all = carry
        layer = xs
        k_all, v_all = write_kv_cache(k_all, v_all, k_new, v_new, batch,
                                      layer)
        out = paged_attention(q, k_all, v_all, batch, sm_scale=0.125,
                              layer=layer)
        return (k_all, v_all), out

    def f(k_all, v_all):
        layer_ids = jnp.arange(2, dtype=jnp.int32)[:, None]
        (k2, v2), outs = jax.lax.scan(layer_fn, (k_all, v_all), layer_ids)
        return outs, k2, v2

    outs, k2, v2 = jax.jit(f)(k_all, v_all)
    for l in range(2):
        ref = naive_ragged_attention(
            q, k2[l], v2[l], batch.block_tables, batch.req_idx,
            batch.positions, sm_scale=0.125)
        got = np.asarray(outs[l])[:n]
        want = np.asarray(ref)[:n]
        print(f"layer {l} max diff:", np.abs(got - want).max(),
              "finite:", np.isfinite(got).all())
