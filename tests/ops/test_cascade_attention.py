"""Cascade (shared-prefix) attention: numeric parity with the plain
ragged path, the merge helper, and the end-to-end detection trigger
(model: reference cascade path of flash_attn.py + merge_attn_states)."""

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

import jax.numpy as jnp

from vllm_distributed_tpu.ops.attention import (
    cascade_ragged_paged_attention, merge_attention_states,
    ragged_paged_attention)


def test_cascade_matches_plain_ragged():
    rng = np.random.default_rng(0)
    T, Hq, Hkv, D, PS, P = 12, 4, 2, 16, 4, 8
    S = 3
    N = 32
    q = jnp.asarray(rng.standard_normal((T, Hq, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((N, Hkv, PS, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((N, Hkv, PS, D)).astype(np.float32))
    # Two requests sharing the first S pages.
    shared = [5, 9, 11]
    bt = np.zeros((4, P), np.int32)
    bt[0, :6] = shared + [1, 2, 3]
    bt[1, :5] = shared + [7, 8]
    block_tables = jnp.asarray(bt)
    req_idx = jnp.asarray([0] * 6 + [1] * 6, jnp.int32)
    q_pos = jnp.asarray(list(range(14, 20)) + list(range(12, 18)),
                        jnp.int32)

    want = ragged_paged_attention(q, k, v, block_tables, req_idx, q_pos,
                                  sm_scale=0.25)
    got = cascade_ragged_paged_attention(
        q, k, v, block_tables, req_idx, q_pos,
        jnp.asarray(shared, jnp.int32), sm_scale=0.25)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_merge_attention_states_exact():
    """Merging disjoint-range partial states must equal one-shot
    softmax attention over the union."""
    rng = np.random.default_rng(1)
    scores = rng.standard_normal((2, 3, 8)).astype(np.float32)
    values = rng.standard_normal((2, 3, 8, 4)).astype(np.float32)

    def partial(lo, hi):
        s = jnp.asarray(scores[..., lo:hi])
        m = s.max(axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = p.sum(axis=-1, keepdims=True)
        acc = jnp.einsum("abj,abjd->abd", p,
                         jnp.asarray(values[..., lo:hi, :]))
        return m, l, acc[..., None, :].squeeze(-2)

    m, l, acc = merge_attention_states(partial(0, 5), partial(5, 8))
    got = np.asarray(acc / l)
    w = np.exp(scores - scores.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    want = np.einsum("abj,abjd->abd", w, values)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_casc")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def test_cascade_end_to_end_fires_and_matches(checkpoint, monkeypatch):
    """Shared-prefix batch: detection fires (prefix cache makes the page
    tables literally share pages) and outputs equal the non-cascade
    engine exactly."""
    monkeypatch.setenv("VDT_CASCADE_ATTENTION", "1")
    monkeypatch.setenv("VDT_CASCADE_SHARED_PAGES", "2")
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    def make_engine():
        return LLMEngine(EngineArgs(
            model=checkpoint, dtype="float32", block_size=4,
            num_gpu_blocks_override=128, max_model_len=64,
            max_num_batched_tokens=64, max_num_seqs=8,
            skip_tokenizer_init=True).create_engine_config())

    prefix = [3, 17, 92, 45, 8, 21, 33, 64]  # 2 full pages
    prompts = [prefix + [50 + i] for i in range(3)]
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)

    def run(engine):
        for i, p in enumerate(prompts):
            engine.add_request(f"c-{i}", p, sp)
        done = {}
        for _ in range(200):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
            if not engine.has_unfinished_requests():
                break
        return [done[f"c-{i}"] for i in range(3)]

    cascade_engine = make_engine()
    got = run(cascade_engine)
    runner = (cascade_engine.engine_core.engine_core.executor
              .worker.model_runner)
    assert runner.cascade_steps > 0, "cascade never triggered"

    monkeypatch.setenv("VDT_CASCADE_ATTENTION", "0")
    want = run(make_engine())
    assert got == want


def test_cascade_end_to_end_pallas_backend(checkpoint, monkeypatch):
    """Same end-to-end trigger on the Pallas backend (interpret mode):
    the suffix runs the kernel with emit_state=True and merges with the
    dense shared phase (VERDICT r3 weak #4 — cascade previously bailed
    whenever backend == pallas)."""
    monkeypatch.setenv("VDT_CASCADE_ATTENTION", "1")
    monkeypatch.setenv("VDT_CASCADE_SHARED_PAGES", "2")
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    monkeypatch.setenv("VDT_PALLAS_INTERPRET", "1")
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    def make_engine():
        return LLMEngine(EngineArgs(
            model=checkpoint, dtype="float32", block_size=8,
            num_gpu_blocks_override=128, max_model_len=64,
            max_num_batched_tokens=64, max_num_seqs=8,
            skip_tokenizer_init=True).create_engine_config())

    prefix = list(range(3, 19))  # 2 full size-8 pages
    prompts = [prefix + [50 + i] for i in range(3)]
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)

    def run(engine):
        for i, p in enumerate(prompts):
            engine.add_request(f"cp-{i}", p, sp)
        done = {}
        for _ in range(200):
            for out in engine.step():
                if out.finished:
                    done[out.request_id] = out.outputs[0].token_ids
            if not engine.has_unfinished_requests():
                break
        return [done[f"cp-{i}"] for i in range(3)]

    cascade_engine = make_engine()
    got = run(cascade_engine)
    runner = (cascade_engine.engine_core.engine_core.executor
              .worker.model_runner)
    assert runner.cascade_steps > 0, "cascade never triggered on pallas"

    monkeypatch.setenv("VDT_CASCADE_ATTENTION", "0")
    want = run(make_engine())
    assert got == want
