"""Mixed-batch attention mega-kernel vs the dense reference.

Interpret-mode parity matrix for ops/pallas_attention.py's unified
kernel (ISSUE 6 tentpole): mixed prefill+decode batches, GQA grouping,
q_len spanning page boundaries and multiple q tiles, single-token
prefill tails, the emit_state cascade path, and the fused KV-write +
attend variant. The partition-descriptor builder is unit-tested on the
same cases."""

import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.ops.attention import (_shared_prefix_state,
                                                merge_attention_states,
                                                naive_ragged_attention,
                                                write_kv_pages)
from vllm_distributed_tpu.ops.pallas_attention import (
    KIND_DECODE, KIND_KV_WRITE, KIND_NOOP, KIND_PREFILL, Q_TILE_PAD,
    build_partition_descriptor, decode_group_size, num_partition_programs,
    prefill_tile_size, unified_ragged_paged_attention_pallas,
    unified_write_attend_pallas)


def build_case(rng, *, seqs, page_size, pages_per_req, num_q_heads,
               num_kv_heads, head_dim, dtype=jnp.float32):
    """seqs: list of (q_len, kv_len) with kv_len >= q_len."""
    R = len(seqs)
    max_reqs = R + 1  # one inactive padding row
    num_pages = max_reqs * pages_per_req
    T = sum(q for q, _ in seqs)
    T_pad = T + Q_TILE_PAD

    k_pages = jnp.asarray(rng.standard_normal(
        (num_pages, num_kv_heads, page_size, head_dim)), dtype)
    v_pages = jnp.asarray(rng.standard_normal(
        (num_pages, num_kv_heads, page_size, head_dim)), dtype)
    q = jnp.asarray(rng.standard_normal((T_pad, num_q_heads, head_dim)),
                    dtype)

    bt = np.zeros((max_reqs, pages_per_req), np.int32)
    for r in range(max_reqs):
        bt[r] = np.arange(r * pages_per_req, (r + 1) * pages_per_req)

    seq_info = np.zeros((max_reqs, 4), np.int32)
    req_idx = np.zeros((T_pad, ), np.int32)
    q_pos = np.zeros((T_pad, ), np.int32)
    t = 0
    for r, (q_len, kv_len) in enumerate(seqs):
        seq_info[r] = (t, q_len, kv_len, r)
        req_idx[t:t + q_len] = r
        q_pos[t:t + q_len] = np.arange(kv_len - q_len, kv_len)
        t += q_len

    bq = prefill_tile_size(num_q_heads, head_dim)
    sb = decode_group_size(num_q_heads, num_kv_heads)
    P = num_partition_programs(T, max_reqs, bq=bq, sb=sb)
    desc, dl = build_partition_descriptor(seq_info, R, bq=bq, sb=sb,
                                          num_programs=P)
    return dict(
        q=q, k_pages=k_pages, v_pages=v_pages,
        seq_info=jnp.asarray(seq_info), seq_info_np=seq_info,
        desc=jnp.asarray(desc), desc_np=desc,
        decode_list=jnp.asarray(dl),
        block_tables=jnp.asarray(bt), block_tables_np=bt,
        req_idx=jnp.asarray(req_idx), q_pos=jnp.asarray(q_pos),
        T=T, bq=bq, sb=sb, num_seqs=R,
    )


def run_both(case, sm_scale=0.125):
    out = unified_ragged_paged_attention_pallas(
        case["q"], case["k_pages"], case["v_pages"], case["desc"],
        case["seq_info"], case["decode_list"], case["block_tables"],
        sm_scale=sm_scale, bq=case["bq"], sb=case["sb"], interpret=True)
    want = naive_ragged_attention(
        case["q"], case["k_pages"], case["v_pages"], case["block_tables"],
        case["req_idx"], case["q_pos"], sm_scale=sm_scale)
    T = case["T"]
    return np.asarray(out)[:T], np.asarray(want)[:T]


@pytest.mark.parametrize("seqs", [
    # Pure decode: one token per sequence, varying kv lens.
    [(1, 1), (1, 5), (1, 17), (1, 32)],
    # Pure prefill from scratch.
    [(7, 7), (16, 16), (3, 3)],
    # Chunked prefill: later chunk attends earlier kv; q spans a page
    # boundary (page_size 8, q_len 8 starting mid-page).
    [(8, 24), (4, 9)],
    # Mixed prefill + decode in one wave — the mega-kernel's target.
    [(1, 13), (12, 12), (1, 30), (5, 21)],
    # Single-token prefill tails (q_len == 1 with backlog) ride the
    # decode-group path; the math is identical to decode.
    [(1, 13), (2, 2), (1, 9)],
])
def test_matches_reference(seqs):
    rng = np.random.default_rng(0)
    case = build_case(rng, seqs=seqs, page_size=8, pages_per_req=4,
                      num_q_heads=8, num_kv_heads=4, head_dim=128)
    got, want = run_both(case)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_gqa_group_and_mha():
    rng = np.random.default_rng(1)
    for kvh in (1, 2, 8):
        case = build_case(rng, seqs=[(3, 11), (1, 4)], page_size=8,
                          pages_per_req=4, num_q_heads=8,
                          num_kv_heads=kvh, head_dim=128)
        got, want = run_both(case)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_multi_tile_prefill_spans_tiles_and_pages():
    """q_len > bq spans several prefill tiles of one sequence; kv spans
    several pages. The exact chunked writeback must stitch tiles
    seamlessly (no spill into the neighbouring decode row)."""
    rng = np.random.default_rng(2)
    case = build_case(rng, seqs=[(40, 40), (1, 30), (33, 48)],
                      page_size=8, pages_per_req=8, num_q_heads=4,
                      num_kv_heads=4, head_dim=128)
    assert case["bq"] < 40  # the case really is multi-tile
    got, want = run_both(case)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_many_decode_seqs_ragged_groups():
    """Enough decode sequences for several SB groups, with a group count
    that does not divide the batch."""
    rng = np.random.default_rng(7)
    seqs = [(1, k) for k in (1, 5, 17, 32, 9, 25, 13, 2, 31, 8, 20)]
    case = build_case(rng, seqs=seqs, page_size=8, pages_per_req=4,
                      num_q_heads=8, num_kv_heads=4, head_dim=128)
    got, want = run_both(case)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_bf16_mixed():
    rng = np.random.default_rng(3)
    case = build_case(rng, seqs=[(1, 9), (6, 6), (1, 3)], page_size=8,
                      pages_per_req=2, num_q_heads=4, num_kv_heads=2,
                      head_dim=128, dtype=jnp.bfloat16)
    got, want = run_both(case)
    np.testing.assert_allclose(np.float32(got), np.float32(want),
                               rtol=3e-2, atol=3e-2)


def test_emit_state_cascade_merge_matches_full_attention():
    """The cascade contract: shared-prefix dense phase + mega-kernel
    suffix phase (kv_len shifted, shared slots stripped) merged via the
    exported (m, l) state must equal plain attention over the full KV —
    including decode rows, whose state now comes from the decode-group
    branch."""
    rng = np.random.default_rng(4)
    page_size, S = 8, 2
    case = build_case(rng, seqs=[(1, 20), (4, 24), (1, 33), (6, 22)],
                      page_size=page_size, pages_per_req=6,
                      num_q_heads=8, num_kv_heads=4, head_dim=128)
    D = case["k_pages"].shape[-1]
    # Make the first S page-table slots literally shared.
    bt = case["block_tables_np"].copy()
    shared = bt[0, :S].copy()
    for r in range(case["num_seqs"]):
        bt[r, :S] = shared
    shift = S * page_size
    si_sfx = case["seq_info_np"].copy()
    si_sfx[:, 2] = np.maximum(si_sfx[:, 2] - shift, 0)

    out_sf, st_sf = unified_ragged_paged_attention_pallas(
        case["q"], case["k_pages"], case["v_pages"], case["desc"],
        jnp.asarray(si_sfx), case["decode_list"],
        jnp.asarray(bt[:, S:]), sm_scale=0.125, bq=case["bq"],
        sb=case["sb"], interpret=True, emit_state=True)
    m_sh, l_sh, acc_sh = _shared_prefix_state(
        case["q"], case["k_pages"], case["v_pages"], jnp.asarray(shared),
        case["q_pos"], 0.125)
    m_sf = st_sf[..., 0:1]
    l_sf = st_sf[..., D // 2:D // 2 + 1]
    acc_sf = out_sf.astype(jnp.float32) * l_sf
    _, l, acc = merge_attention_states((m_sh, l_sh, acc_sh),
                                       (m_sf, l_sf, acc_sf))
    got = np.asarray(acc / jnp.maximum(l, 1e-20))[:case["T"]]
    want = np.asarray(naive_ragged_attention(
        case["q"], case["k_pages"], case["v_pages"], jnp.asarray(bt),
        case["req_idx"], case["q_pos"], sm_scale=0.125))[:case["T"]]
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_fused_write_attend_matches_write_then_naive():
    """kind-3 kv-write programs + attention in ONE call: the cache must
    come back bit-identical to the XLA scatter, and the attention output
    must see this step's freshly written pages."""
    rng = np.random.default_rng(5)
    page_size = 8
    seqs = [(1, 20), (5, 24), (1, 33), (7, 22)]
    case = build_case(rng, seqs=seqs, page_size=page_size,
                      pages_per_req=6, num_q_heads=8, num_kv_heads=4,
                      head_dim=128)
    T, bq, sb = case["T"], case["bq"], case["sb"]
    max_reqs = case["seq_info_np"].shape[0]
    kvh, hd = 4, 128
    bt = case["block_tables_np"]
    k_new = jnp.asarray(
        rng.standard_normal((T + Q_TILE_PAD, kvh, hd)), jnp.float32)
    v_new = jnp.asarray(
        rng.standard_normal((T + Q_TILE_PAD, kvh, hd)), jnp.float32)

    slot = np.full((T + Q_TILE_PAD, ), -1, np.int32)
    kv_runs = []
    t = 0
    for r, (ql, kl) in enumerate(seqs):
        start = kl - ql
        pos = np.arange(start, kl)
        slot[t:t + ql] = (bt[r, pos // page_size] * page_size +
                          pos % page_size)
        consumed = 0
        while consumed < ql:
            p = start + consumed
            off = p % page_size
            run_len = min(page_size - off, ql - consumed)
            src = t + consumed
            kv_runs.append((int(bt[r, p // page_size]), off,
                            src - off + page_size, run_len))
            consumed += run_len
        t += ql
    G = len(kv_runs)
    P = num_partition_programs(T, max_reqs, bq=bq, sb=sb,
                               num_kv_writes=G)
    desc, dl = build_partition_descriptor(
        case["seq_info_np"], case["num_seqs"], bq=bq, sb=sb,
        num_programs=P, num_kv_writes=G)
    assert (desc[:G, 0] == KIND_KV_WRITE).all()

    pad = [(0, 0), (page_size, 2 * page_size), (0, 0)]
    k_hl = jnp.pad(k_new.swapaxes(0, 1), pad)
    v_hl = jnp.pad(v_new.swapaxes(0, 1), pad)
    out, k2, v2 = unified_write_attend_pallas(
        case["q"], case["k_pages"][None], case["v_pages"][None], k_hl,
        v_hl, jnp.asarray(desc), case["seq_info"], jnp.asarray(dl),
        jnp.asarray(np.asarray(kv_runs, np.int32)), case["block_tables"],
        jnp.zeros((1, ), jnp.int32), sm_scale=0.125, bq=bq, sb=sb,
        interpret=True)

    k_ref, v_ref = write_kv_pages(case["k_pages"], case["v_pages"],
                                  k_new, v_new, jnp.asarray(slot))
    np.testing.assert_array_equal(np.asarray(k2[0]), np.asarray(k_ref))
    np.testing.assert_array_equal(np.asarray(v2[0]), np.asarray(v_ref))
    want = np.asarray(naive_ragged_attention(
        case["q"], k_ref, v_ref, case["block_tables"],
        case["req_idx"], case["q_pos"], sm_scale=0.125))[:T]
    np.testing.assert_allclose(np.asarray(out)[:T], want, rtol=2e-3,
                               atol=2e-3)


# ---------------------------------------------------------------------------
# Attention features (sliding window / softcap / ALiBi / sinks) folded
# into the mega-kernel — ISSUE 11 satellite: Gemma/Mistral/Bloom/
# gpt-oss-class models stop forcing the XLA fallback.
# ---------------------------------------------------------------------------


def run_both_feat(case, sm_scale=0.125, *, window=0, logit_cap=0.0,
                  slopes=None, sinks=None):
    QH = case["q"].shape[1]
    feat = jnp.stack([
        jnp.asarray(slopes if slopes is not None else np.zeros(QH),
                    jnp.float32),
        jnp.asarray(sinks if sinks is not None else np.zeros(QH),
                    jnp.float32),
    ])
    out = unified_ragged_paged_attention_pallas(
        case["q"], case["k_pages"], case["v_pages"], case["desc"],
        case["seq_info"], case["decode_list"], case["block_tables"],
        None, feat, sm_scale=sm_scale, bq=case["bq"], sb=case["sb"],
        interpret=True, window=window, logit_cap=logit_cap,
        has_alibi=slopes is not None, has_sinks=sinks is not None)
    want = naive_ragged_attention(
        case["q"], case["k_pages"], case["v_pages"],
        case["block_tables"], case["req_idx"], case["q_pos"],
        sm_scale=sm_scale, window=window, logit_cap=logit_cap,
        alibi_slopes=(tuple(slopes) if slopes is not None else None),
        sinks=(jnp.asarray(sinks) if sinks is not None else None))
    T = case["T"]
    return np.asarray(out)[:T], np.asarray(want)[:T]


MIXED_SEQS = [(1, 13), (12, 12), (1, 30), (5, 21)]


def test_sliding_window_mixed_wave():
    rng = np.random.default_rng(10)
    case = build_case(rng, seqs=MIXED_SEQS, page_size=8,
                      pages_per_req=4, num_q_heads=8, num_kv_heads=4,
                      head_dim=128)
    got, want = run_both_feat(case, window=9)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
    # The window genuinely restricts attention (long kv sequences
    # diverge from full-causal).
    got_full, _ = run_both_feat(case)
    assert np.max(np.abs(got - got_full)) > 1e-3


def test_softcap_mixed_wave():
    rng = np.random.default_rng(11)
    case = build_case(rng, seqs=MIXED_SEQS, page_size=8,
                      pages_per_req=4, num_q_heads=8, num_kv_heads=4,
                      head_dim=128)
    got, want = run_both_feat(case, logit_cap=5.0)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_alibi_mixed_wave():
    from vllm_distributed_tpu.models.common import alibi_slopes
    rng = np.random.default_rng(12)
    case = build_case(rng, seqs=MIXED_SEQS, page_size=8,
                      pages_per_req=4, num_q_heads=8, num_kv_heads=4,
                      head_dim=128)
    got, want = run_both_feat(case, slopes=np.asarray(alibi_slopes(8)))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_sinks_mixed_wave():
    rng = np.random.default_rng(13)
    case = build_case(rng, seqs=MIXED_SEQS, page_size=8,
                      pages_per_req=4, num_q_heads=8, num_kv_heads=4,
                      head_dim=128)
    got, want = run_both_feat(
        case, sinks=rng.standard_normal(8).astype(np.float32))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_all_features_together_mixed_wave():
    from vllm_distributed_tpu.models.common import alibi_slopes
    rng = np.random.default_rng(14)
    seqs = [(1, 13), (12, 12), (1, 30), (5, 21), (1, 9), (8, 17)]
    case = build_case(rng, seqs=seqs, page_size=8, pages_per_req=4,
                      num_q_heads=8, num_kv_heads=4, head_dim=128)
    got, want = run_both_feat(
        case, window=11, logit_cap=4.0,
        slopes=np.asarray(alibi_slopes(8)),
        sinks=rng.standard_normal(8).astype(np.float32))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# Descriptor builder
# ---------------------------------------------------------------------------


def test_descriptor_partition_shape():
    """Mixed batch: kv-write rows first, one prefill tile per bq rows,
    decode groups of sb covering every q_len == 1 sequence, noop
    padding after."""
    si = np.zeros((8, 4), np.int32)
    # rows: 40-token prefill, decode, 3-token prefill, decode, decode
    for r, (ql, kl) in enumerate([(40, 40), (1, 9), (3, 7), (1, 2),
                                  (1, 30)]):
        si[r] = (0, ql, kl, r)
    bq, sb = 32, 2
    P = num_partition_programs(64, 8, bq=bq, sb=sb, num_kv_writes=4)
    desc, dl = build_partition_descriptor(si, 5, bq=bq, sb=sb,
                                          num_programs=P,
                                          num_kv_writes=4)
    kinds = desc[:, 0]
    assert list(kinds[:4]) == [KIND_KV_WRITE] * 4
    assert list(desc[:4, 1]) == [0, 1, 2, 3]
    prefill = desc[kinds == KIND_PREFILL]
    # 40 tokens -> tiles at 0 and 32; 3 tokens -> one tile.
    assert {(int(a), int(b)) for _, a, b in prefill} == {
        (0, 0), (0, 32), (2, 0)}
    groups = desc[kinds == KIND_DECODE]
    # 3 decode rows in sb=2 groups: (start 0, 2 slots), (start 2, 1).
    assert [(int(a), int(b)) for _, a, b in groups] == [(0, 2), (2, 1)]
    assert list(dl[:3]) == [1, 3, 4]
    # Everything else is noop padding.
    n_active = 4 + len(prefill) + len(groups)
    assert (kinds[n_active:] == KIND_NOOP).all()


def test_descriptor_fast_decode_rows_bypass():
    """The runner's pure-decode fast path hands its row vector straight
    in; the builder must not rescan q_lens."""
    si = np.zeros((4, 4), np.int32)
    si[:, 1] = 99  # garbage q_lens: must be ignored with decode_rows
    desc, dl = build_partition_descriptor(
        si, 3, bq=32, sb=8,
        num_programs=num_partition_programs(16, 4, bq=32, sb=8),
        decode_rows=np.arange(3, dtype=np.int32))
    kinds = desc[:, 0]
    assert (kinds != KIND_PREFILL).all()
    groups = desc[kinds == KIND_DECODE]
    assert [(int(a), int(b)) for _, a, b in groups] == [(0, 3)]
    assert list(dl[:3]) == [0, 1, 2]


def test_descriptor_length_is_deterministic_in_bucket():
    """num_partition_programs depends only on (t_bucket, max_reqs, bq,
    sb, kv bound) — the descriptor adds no compile-lattice dimension."""
    for t in (16, 64, 256):
        sizes = {
            num_partition_programs(t, 8, bq=32, sb=4, num_kv_writes=g)
            for g in (0, 0, 0)
        }
        assert len(sizes) == 1
