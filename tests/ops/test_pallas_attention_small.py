import numpy as np
import pytest
from tests.ops.test_pallas_attention import build_case, run_both


@pytest.mark.parametrize("ps,ppr,hd,maxq", [
    (4, 16, 16, 8),   # engine e2e config
    (4, 4, 16, 8),
    (8, 4, 16, 8),
    (4, 16, 128, 8),
    (8, 4, 128, 8),
])
def test_small(ps, ppr, hd, maxq):
    rng = np.random.default_rng(0)
    case = build_case(rng, seqs=[(5, 5)], page_size=ps, pages_per_req=ppr,
                      num_q_heads=4, num_kv_heads=2, head_dim=hd,
                      max_q=maxq)
    got, want = run_both(case)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
