"""Pallas MLA (latent MQA) kernel vs the XLA reference path
(ops/pallas_mla.py vs ops/mla.ragged_latent_attention), interpret mode."""

import numpy as np
import pytest

import jax.numpy as jnp

from vllm_distributed_tpu.ops.mla import ragged_latent_attention
from vllm_distributed_tpu.ops.pallas_mla import \
    ragged_latent_attention_pallas


@pytest.mark.parametrize("max_q", [1, 8])
def test_kernel_matches_xla_reference(max_q):
    rng = np.random.default_rng(0)
    N, Lkv, R_dim, PS = 4, 32, 8, 8
    num_pages, PPR = 16, 4
    L = 2
    layer = 1
    kdim = Lkv + R_dim

    # Two sequences: a decode row and (for max_q=8) a prefill chunk.
    if max_q == 1:
        runs = [(0, 1, 13, 0), (1, 1, 7, 1)]   # (q_start, q_len, kv, row)
        T = 2
    else:
        runs = [(0, 6, 14, 0), (6, 1, 9, 1)]
        T = 7
    T_pad = T + max_q

    c_pages = jnp.asarray(
        rng.standard_normal((L, num_pages, PS, kdim)).astype(np.float32))
    bt = np.zeros((4, PPR), np.int32)
    bt[0, :PPR] = [3, 5, 7, 9]
    bt[1, :PPR] = [2, 4, 6, 8]
    ql = rng.standard_normal((T_pad, N, Lkv)).astype(np.float32)
    qpe = rng.standard_normal((T_pad, N, R_dim)).astype(np.float32)

    req_idx, q_pos = [], []
    for (qs, qlen, kv, row) in runs:
        for j in range(qlen):
            req_idx.append(row)
            q_pos.append(kv - qlen + j)
    want = ragged_latent_attention(
        jnp.asarray(ql[:T]), jnp.asarray(qpe[:T]), c_pages[layer],
        jnp.asarray(bt), jnp.asarray(req_idx, jnp.int32),
        jnp.asarray(q_pos, jnp.int32), sm_scale=0.25,
        kv_lora_rank=Lkv, rope_dim=R_dim)

    seq_info = np.zeros((4, 4), np.int32)
    for i, r in enumerate(runs):
        seq_info[i] = r
    qc = jnp.concatenate([jnp.asarray(ql), jnp.asarray(qpe)], axis=-1)
    got = ragged_latent_attention_pallas(
        qc, c_pages, jnp.asarray(seq_info),
        jnp.asarray([len(runs)], jnp.int32), jnp.asarray(bt),
        jnp.asarray([layer], jnp.int32), sm_scale=0.25, max_q=max_q,
        kv_lora_rank=Lkv, rope_dim=R_dim, interpret=True)

    np.testing.assert_allclose(np.asarray(got[:T, :, :Lkv]),
                               np.asarray(want), rtol=2e-5, atol=2e-5)
