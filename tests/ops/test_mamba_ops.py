"""Unit tests for the ragged segmented-scan Mamba ops.

Strategy (SURVEY.md §4 kernel tests): build a ragged batch of chunks —
fresh prefills, resumed chunks with carried state, single-token decodes,
padding — and check the flat segmented ops against a per-request
sequential numpy recurrence.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.ops.mamba import (SegmentInfo,
                                            build_segment_info,
                                            causal_conv1d_ragged,
                                            segmented_linear_scan,
                                            selective_scan_ragged,
                                            ssd_scan_ragged)


def _make_seg(chunks, T, S):
    """chunks: list of (row, chunk_start_pos, q_len). Flat tokens are
    laid out contiguously in order; the tail up to T is padding."""
    row = np.full((T, ), S, np.int32)
    valid = np.zeros((T, ), bool)
    off = np.zeros((T, ), np.int32)
    start = np.zeros((T, ), bool)
    end = np.zeros((T, ), bool)
    has_init = np.zeros((T, ), bool)
    q_len_by_row = np.zeros((S + 1, ), np.int32)
    q_start_by_row = np.zeros((S + 1, ), np.int32)
    has_init_by_row = np.zeros((S + 1, ), bool)
    t = 0
    for r, pos0, n in chunks:
        row[t:t + n] = r
        valid[t:t + n] = True
        off[t:t + n] = np.arange(n)
        start[t] = True
        end[t + n - 1] = True
        has_init[t:t + n] = pos0 > 0
        q_len_by_row[r] = n
        q_start_by_row[r] = t
        has_init_by_row[r] = pos0 > 0
        t += n
    return SegmentInfo(
        row=jnp.asarray(row), valid=jnp.asarray(valid),
        off=jnp.asarray(off), start=jnp.asarray(start),
        end=jnp.asarray(end), has_init=jnp.asarray(has_init),
        q_len_by_row=jnp.asarray(q_len_by_row),
        q_start_by_row=jnp.asarray(q_start_by_row),
        has_init_by_row=jnp.asarray(has_init_by_row))


CHUNKS = [(2, 0, 5), (0, 7, 3), (4, 1, 1), (1, 0, 1)]  # mixed batch
T, S = 16, 6


def test_segmented_linear_scan_matches_loop():
    rng = np.random.default_rng(0)
    a = rng.uniform(0.5, 1.0, (T, 3)).astype(np.float32)
    b = rng.normal(size=(T, 3)).astype(np.float32)
    reset = np.zeros((T, ), bool)
    reset[[0, 5, 9]] = True
    h = segmented_linear_scan(jnp.asarray(a), jnp.asarray(b),
                              jnp.asarray(reset))
    expect = np.zeros_like(b)
    carry = np.zeros((3, ), np.float32)
    for t in range(T):
        if reset[t]:
            carry = np.zeros((3, ), np.float32)
        carry = a[t] * carry + b[t]
        expect[t] = carry
    np.testing.assert_allclose(np.asarray(h), expect, rtol=1e-5,
                               atol=1e-5)


def test_causal_conv1d_ragged_matches_sequential():
    rng = np.random.default_rng(1)
    Di, K = 4, 4
    seg = _make_seg(CHUNKS, T, S)
    x = rng.normal(size=(T, Di)).astype(np.float32)
    w = rng.normal(size=(K, Di)).astype(np.float32)
    bias = rng.normal(size=(Di, )).astype(np.float32)
    conv_state = rng.normal(size=(S + 1, K - 1, Di)).astype(np.float32)

    y, new_state = causal_conv1d_ragged(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(bias),
        jnp.asarray(conv_state), seg)
    y, new_state = np.asarray(y), np.asarray(new_state)

    t = 0
    for r, pos0, n in CHUNKS:
        # Sequential reference: full input history for the chunk is
        # [carried (or zeros), chunk tokens].
        hist = (conv_state[r] if pos0 > 0 else
                np.zeros((K - 1, Di), np.float32))
        buf = np.concatenate([hist, x[t:t + n]], axis=0)
        for i in range(n):
            want = bias + sum(w[k] * buf[i + k] for k in range(K))
            np.testing.assert_allclose(y[t + i], want, rtol=1e-5,
                                       atol=1e-5)
        np.testing.assert_allclose(new_state[r], buf[n:n + K - 1],
                                   rtol=1e-6, atol=1e-6)
        t += n
    # Inactive rows keep their carried state.
    np.testing.assert_allclose(new_state[3], conv_state[3])


def test_selective_scan_ragged_matches_sequential():
    rng = np.random.default_rng(2)
    Di, N = 6, 4
    seg = _make_seg(CHUNKS, T, S)
    x = rng.normal(size=(T, Di)).astype(np.float32)
    dt = rng.uniform(0.01, 0.5, (T, Di)).astype(np.float32)
    A = -rng.uniform(0.1, 1.0, (Di, N)).astype(np.float32)
    B = rng.normal(size=(T, N)).astype(np.float32)
    C = rng.normal(size=(T, N)).astype(np.float32)
    D = rng.normal(size=(Di, )).astype(np.float32)
    ssm_state = rng.normal(size=(S + 1, Di, N)).astype(np.float32)

    y, new_state = selective_scan_ragged(
        jnp.asarray(x), jnp.asarray(dt), jnp.asarray(A), jnp.asarray(B),
        jnp.asarray(C), jnp.asarray(D), jnp.asarray(ssm_state), seg)
    y, new_state = np.asarray(y), np.asarray(new_state)

    t = 0
    for r, pos0, n in CHUNKS:
        h = (ssm_state[r].copy() if pos0 > 0 else
             np.zeros((Di, N), np.float32))
        for i in range(n):
            a = np.exp(dt[t + i][:, None] * A)
            h = a * h + (dt[t + i] * x[t + i])[:, None] * B[t + i][None]
            want = h @ C[t + i] + D * x[t + i]
            np.testing.assert_allclose(y[t + i], want, rtol=1e-4,
                                       atol=1e-4)
        np.testing.assert_allclose(new_state[r], h, rtol=1e-4, atol=1e-4)
        t += n
    np.testing.assert_allclose(new_state[3], ssm_state[3])


def test_ssd_scan_ragged_matches_sequential():
    rng = np.random.default_rng(3)
    Hm, P, N, G = 4, 3, 5, 2
    seg = _make_seg(CHUNKS, T, S)
    x = rng.normal(size=(T, Hm, P)).astype(np.float32)
    dt = rng.uniform(0.01, 0.5, (T, Hm)).astype(np.float32)
    A = -rng.uniform(0.1, 1.0, (Hm, )).astype(np.float32)
    B = rng.normal(size=(T, G, N)).astype(np.float32)
    C = rng.normal(size=(T, G, N)).astype(np.float32)
    D = rng.normal(size=(Hm, )).astype(np.float32)
    ssm_state = rng.normal(size=(S + 1, Hm, P, N)).astype(np.float32)

    y, new_state = ssd_scan_ragged(
        jnp.asarray(x), jnp.asarray(dt), jnp.asarray(A), jnp.asarray(B),
        jnp.asarray(C), jnp.asarray(D), jnp.asarray(ssm_state), seg)
    y, new_state = np.asarray(y), np.asarray(new_state)

    rep = Hm // G
    t = 0
    for r, pos0, n in CHUNKS:
        h = (ssm_state[r].copy() if pos0 > 0 else
             np.zeros((Hm, P, N), np.float32))
        for i in range(n):
            for hd in range(Hm):
                g = hd // rep
                a = np.exp(dt[t + i, hd] * A[hd])
                h[hd] = (a * h[hd] + dt[t + i, hd] *
                         x[t + i, hd][:, None] * B[t + i, g][None])
                want = h[hd] @ C[t + i, g] + D[hd] * x[t + i, hd]
                np.testing.assert_allclose(y[t + i, hd], want, rtol=1e-4,
                                           atol=1e-4)
        np.testing.assert_allclose(new_state[r], h, rtol=1e-4, atol=1e-4)
        t += n


def test_build_segment_info_from_attention_batch():
    from vllm_distributed_tpu.models.common import AttentionBatch
    # Two chunks: row 1 resumed at pos 4 (3 tokens), row 0 fresh decode
    # at pos 0 (1 token); 2 padding tokens.
    max_reqs = 4
    req_idx = jnp.asarray([1, 1, 1, 0, 0, 0], jnp.int32)
    positions = jnp.asarray([4, 5, 6, 0, 0, 0], jnp.int32)
    slot = jnp.asarray([8, 9, 10, 0, -1, -1], jnp.int32)
    seq_info = jnp.zeros((max_reqs, 4), jnp.int32)
    seq_info = seq_info.at[0].set(jnp.asarray([0, 3, 7, 1]))
    seq_info = seq_info.at[1].set(jnp.asarray([3, 1, 1, 0]))
    batch = AttentionBatch(
        req_idx=req_idx, positions=positions, slot_mapping=slot,
        block_tables=jnp.zeros((max_reqs, 2), jnp.int32),
        seq_lens=jnp.zeros((max_reqs, ), jnp.int32),
        seq_info=seq_info, num_seqs=jnp.asarray([2], jnp.int32))
    seg = build_segment_info(batch, max_reqs)
    np.testing.assert_array_equal(np.asarray(seg.row),
                                  [1, 1, 1, 0, 4, 4])
    np.testing.assert_array_equal(np.asarray(seg.valid),
                                  [True, True, True, True, False, False])
    np.testing.assert_array_equal(np.asarray(seg.off)[:4], [0, 1, 2, 0])
    np.testing.assert_array_equal(np.asarray(seg.start)[:4],
                                  [True, False, False, True])
    np.testing.assert_array_equal(np.asarray(seg.end)[:4],
                                  [False, False, True, True])
    np.testing.assert_array_equal(np.asarray(seg.has_init)[:4],
                                  [True, True, True, False])
    assert int(seg.q_len_by_row[1]) == 3
    assert int(seg.q_len_by_row[0]) == 1
