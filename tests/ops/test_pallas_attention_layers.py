import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from tests.ops.test_pallas_attention import build_case
from vllm_distributed_tpu.ops.pallas_attention import (
    ragged_paged_attention_pallas)
from vllm_distributed_tpu.ops.attention import naive_ragged_attention
from vllm_distributed_tpu.parallel.mesh import build_mesh
from vllm_distributed_tpu.config import ParallelConfig


def run(case, L=2, layer=1, mesh=None, shard=False):
    k1 = case["k_pages"]
    # stack L layers; put real data at `layer`, garbage elsewhere
    k = jnp.stack([jnp.full_like(k1, jnp.nan)] * L).at[layer].set(k1)
    v = jnp.stack([jnp.full_like(k1, jnp.nan)] * L).at[layer].set(
        case["v_pages"])
    q = case["q"]
    if shard and mesh is not None:
        k = jax.device_put(k, NamedSharding(mesh, P(None, None, "model", None, None)))
        v = jax.device_put(v, NamedSharding(mesh, P(None, None, "model", None, None)))
        q = jax.device_put(q, NamedSharding(mesh, P(None, "model", None)))
    ctx = mesh if mesh is not None else jax.sharding.Mesh(np.array(jax.devices()[:1]), ("x",))
    with ctx:
        out = ragged_paged_attention_pallas(
            q, k, v, case["seq_info"], case["num_seqs"],
            case["block_tables"], jnp.asarray([layer], jnp.int32),
            sm_scale=0.125, max_q=case["max_q"], interpret=True)
    ref = naive_ragged_attention(
        case["q"], case["k_pages"], case["v_pages"], case["block_tables"],
        case["req_idx"], case["q_pos"], sm_scale=0.125)
    T = case["T"]
    return np.asarray(out)[:T], np.asarray(ref)[:T]


def test_stacked_layer_nomesh():
    rng = np.random.default_rng(0)
    case = build_case(rng, seqs=[(5, 5)], page_size=4, pages_per_req=16,
                      num_q_heads=4, num_kv_heads=2, head_dim=16, max_q=8)
    got, want = run(case, mesh=None)
    print("nomesh max diff:", np.abs(got - want).max())
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_stacked_layer_mesh():
    rng = np.random.default_rng(0)
    case = build_case(rng, seqs=[(5, 5)], page_size=4, pages_per_req=16,
                      num_q_heads=4, num_kv_heads=2, head_dim=16, max_q=8)
    mesh = build_mesh(ParallelConfig(tensor_parallel_size=1,
                                     data_parallel_size=1))
    print("mesh:", mesh)
    got, want = run(case, mesh=mesh, shard=True)
    print("mesh max diff:", np.abs(got - want).max())
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
