"""Fused transformer-block decode kernel vs the XLA-composed reference.

Interpret-mode parity matrix for ops/pallas_block.py (ISSUE 11
tentpole): the whole layer — RMSNorm -> fused QKV -> rope -> KV-page
write -> paged attention (current token folded in register) -> O-proj
-> RMSNorm -> gated MLP — in ONE Pallas call, across dtypes, GQA
shapes, ragged decode groups and the window/softcap/ALiBi/sinks
feature matrix. The reference composes the same math from the XLA ops
(flat-scatter KV write + ragged attention)."""

import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.models.common import (alibi_slopes,
                                                compute_rope_cos_sin)
from vllm_distributed_tpu.ops.pallas_block import (fused_block_decode_pallas,
                                                   fused_block_decode_xla,
                                                   weight_tile)


def build_case(rng, *, kv_lens, H=64, I=128, QH=8, KVH=4, hd=32, PS=8,
               pages_per_req=6, dtype=jnp.float32, L=2, layer=1):
    """Decode-only case: seq r's token row is row r, position
    kv_len - 1 (this step's token is NOT yet in the cache — the layer
    writes it)."""
    R = len(kv_lens)
    max_reqs = R + 2
    num_pages = max_reqs * pages_per_req
    T_pad = max_reqs + 8
    k_pages = jnp.asarray(rng.standard_normal(
        (L, num_pages, KVH, PS, hd)), dtype)
    v_pages = jnp.asarray(rng.standard_normal(
        (L, num_pages, KVH, PS, hd)), dtype)
    hidden = jnp.asarray(rng.standard_normal((T_pad, H)), dtype)
    bt = np.zeros((max_reqs, pages_per_req), np.int32)
    for r in range(max_reqs):
        bt[r] = np.arange(r * pages_per_req, (r + 1) * pages_per_req)
    seq_info = np.zeros((max_reqs, 4), np.int32)
    pos = np.zeros((T_pad, ), np.int32)
    for r, kl in enumerate(kv_lens):
        seq_info[r] = (r, 1, kl, r)
        pos[r] = kl - 1
    Dq, Dkv = QH * hd, KVH * hd
    sc = 0.1
    cos, sin = compute_rope_cos_sin(jnp.asarray(pos), hd, 10000.0, None)
    return dict(
        hidden=hidden, k=k_pages, v=v_pages,
        wqkv=jnp.asarray(rng.standard_normal((H, Dq + 2 * Dkv)) * sc,
                         dtype),
        wo=jnp.asarray(rng.standard_normal((Dq, H)) * sc, dtype),
        wg=jnp.asarray(rng.standard_normal((H, I)) * sc, dtype),
        wu=jnp.asarray(rng.standard_normal((H, I)) * sc, dtype),
        wd=jnp.asarray(rng.standard_normal((I, H)) * sc, dtype),
        ln_w=jnp.asarray(1.0 + 0.1 * rng.standard_normal((2, H)), dtype),
        rope=jnp.stack([cos, sin]),
        seq_info=jnp.asarray(seq_info),
        num_seqs=jnp.asarray([R], np.int32),
        bt=jnp.asarray(bt),
        layer=jnp.asarray([layer], np.int32),
        QH=QH, hd=hd, R=R,
    )


def run_both(case, rng, *, window=0, logit_cap=0.0, has_alibi=False,
             has_sinks=False):
    QH = case["QH"]
    feat = jnp.stack([
        jnp.asarray(alibi_slopes(QH) if has_alibi else np.zeros(QH),
                    jnp.float32),
        jnp.asarray(rng.standard_normal(QH) if has_sinks else
                    np.zeros(QH), jnp.float32),
    ])
    args = (case["hidden"], case["k"], case["v"], case["wqkv"],
            case["wo"], case["wg"], case["wu"], case["wd"],
            case["ln_w"], case["rope"], feat, case["seq_info"],
            case["num_seqs"], case["bt"], case["layer"])
    kw = dict(sm_scale=case["hd"] ** -0.5, eps=1e-6,
              num_q_heads=QH, head_dim=case["hd"], window=window,
              logit_cap=logit_cap, has_alibi=has_alibi,
              has_sinks=has_sinks)
    got = fused_block_decode_pallas(*args, interpret=True, **kw)
    want = fused_block_decode_xla(*args, **kw)
    return got, want


def assert_parity(case, got, want, tol=2e-4):
    R = case["R"]
    h_p, k_p, v_p = (np.asarray(x) for x in got)
    h_x, k_x, v_x = (np.asarray(x) for x in want)
    np.testing.assert_allclose(np.float32(h_p[:R]), np.float32(h_x[:R]),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.float32(k_p), np.float32(k_x),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.float32(v_p), np.float32(v_x),
                               rtol=tol, atol=tol)
    # Padding rows pass through the aliased buffer untouched.
    np.testing.assert_array_equal(h_p[R:],
                                  np.asarray(case["hidden"])[R:])


def test_plain_ragged_groups():
    """Ragged kv lens spanning multiple pages and a group count that
    does not divide the batch."""
    rng = np.random.default_rng(0)
    case = build_case(rng, kv_lens=[2, 9, 17, 40, 3])
    got, want = run_both(case, rng)
    assert_parity(case, got, want)


def test_single_sequence_and_fresh_page():
    """One sequence whose new token opens a fresh page (kv_len - 1 on a
    page boundary)."""
    rng = np.random.default_rng(1)
    case = build_case(rng, kv_lens=[9])  # PS=8: position 8 = page 1 row 0
    got, want = run_both(case, rng)
    assert_parity(case, got, want)


def test_zero_cached_positions():
    """kv_len == 1 for every sequence (empty prefix: the step's token
    IS the whole context): the cached-block loop runs zero iterations
    and the warm-up fetch must not start DMAs nothing waits on."""
    rng = np.random.default_rng(8)
    case = build_case(rng, kv_lens=[1, 1, 1])
    got, want = run_both(case, rng)
    assert_parity(case, got, want)


def test_mha_and_mqa_groups():
    rng = np.random.default_rng(2)
    for kvh in (1, 8):
        case = build_case(rng, kv_lens=[2, 20, 33], KVH=kvh)
        got, want = run_both(case, rng)
        assert_parity(case, got, want)


def test_window_and_softcap():
    rng = np.random.default_rng(3)
    case = build_case(rng, kv_lens=[2, 9, 17, 40])
    got, want = run_both(case, rng, window=7, logit_cap=5.0)
    assert_parity(case, got, want)


def test_alibi_and_sinks():
    rng = np.random.default_rng(4)
    case = build_case(rng, kv_lens=[2, 9, 17, 40])
    got, want = run_both(case, rng, has_alibi=True, has_sinks=True)
    assert_parity(case, got, want)


def test_weight_streaming_tiles():
    """Dims larger than the tile cap force multi-tile weight streams
    (the QKV/O-proj/MLP loops actually iterate)."""
    rng = np.random.default_rng(5)
    case = build_case(rng, kv_lens=[5, 26], H=64, I=256, QH=16, KVH=4,
                      hd=32)
    assert weight_tile(case["wqkv"].shape[1], cap=128) < \
        case["wqkv"].shape[1]
    got, want = run_both(case, rng)
    assert_parity(case, got, want)


@pytest.mark.slow
def test_bf16_parity():
    rng = np.random.default_rng(6)
    case = build_case(rng, kv_lens=[2, 9, 17, 40, 3],
                      dtype=jnp.bfloat16)
    got, want = run_both(case, rng)
    assert_parity(case, got, want, tol=5e-2)


@pytest.mark.slow
def test_full_feature_matrix():
    """Every window/cap/alibi/sinks combination on one ragged case."""
    rng = np.random.default_rng(7)
    case = build_case(rng, kv_lens=[2, 9, 17, 40, 3, 11, 26])
    for window in (0, 9):
        for cap in (0.0, 4.0):
            for alibi in (False, True):
                for sinks in (False, True):
                    got, want = run_both(case, rng, window=window,
                                         logit_cap=cap,
                                         has_alibi=alibi,
                                         has_sinks=sinks)
                    assert_parity(case, got, want)


def test_weight_tile_divides():
    for n in (64, 128, 384, 512, 1024, 14336, 6144):
        t = weight_tile(n)
        assert n % t == 0 and t <= max(512, n if n <= 512 else 512)
