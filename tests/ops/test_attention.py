"""Ragged paged attention numeric tests (model: reference tests/kernels/ —
per-op checks against a dense reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.ops.attention import (naive_ragged_attention,
                                                ragged_paged_attention,
                                                write_kv_pages)


def dense_attention(q, k, v, sm_scale):
    """Plain attention for a single (q_len, kv_len) pair; expands kv heads
    to match GQA query heads."""
    group = q.shape[1] // k.shape[1]
    k = np.repeat(k, group, axis=1)
    v = np.repeat(v, group, axis=1)
    scores = np.einsum("qhd,khd->hqk", q, k) * sm_scale
    w = jax.nn.softmax(jnp.asarray(scores), axis=-1)
    return np.einsum("hqk,khd->qhd", np.asarray(w), v)


def build_batch(seqs, page_size=4, num_kv_heads=2, num_q_heads=4,
                head_dim=8, pages_per_req=8, num_pages=64, seed=0):
    """seqs: list of (context_len, num_new_tokens). Returns everything the
    op needs plus per-request dense K/V for the reference check."""
    rng = np.random.default_rng(seed)
    max_reqs = len(seqs)
    k_pages = np.zeros((num_pages, num_kv_heads, page_size, head_dim),
                       np.float32)
    v_pages = np.zeros_like(k_pages)
    block_tables = np.zeros((max_reqs, pages_per_req), np.int32)
    next_page = 1  # page 0 kept for padding
    qs, req_idx, q_pos = [], [], []
    dense = []
    for r, (ctx, new) in enumerate(seqs):
        total = ctx + new
        k_full = rng.standard_normal((total, num_kv_heads, head_dim),
                                     dtype=np.float32)
        v_full = rng.standard_normal((total, num_kv_heads, head_dim),
                                     dtype=np.float32)
        npages = -(-total // page_size)
        pages = list(range(next_page, next_page + npages))
        next_page += npages
        block_tables[r, :npages] = pages
        for i in range(total):
            p, off = pages[i // page_size], i % page_size
            k_pages[p, :, off] = k_full[i]
            v_pages[p, :, off] = v_full[i]
        q_new = rng.standard_normal((new, num_q_heads, head_dim),
                                    dtype=np.float32)
        qs.append(q_new)
        req_idx.extend([r] * new)
        q_pos.extend(range(ctx, total))
        dense.append((q_new, k_full, v_full, ctx))
    return (jnp.asarray(np.concatenate(qs)), jnp.asarray(k_pages),
            jnp.asarray(v_pages), jnp.asarray(block_tables),
            jnp.asarray(np.array(req_idx, np.int32)),
            jnp.asarray(np.array(q_pos, np.int32)), dense)


@pytest.mark.parametrize("seqs", [
    [(0, 1)],                      # single fresh token
    [(5, 1), (13, 1), (2, 1)],     # pure decode batch, ragged lengths
    [(0, 7), (0, 12)],             # pure prefill
    [(9, 1), (0, 10), (4, 3)],     # mixed decode + prefill + chunk
])
def test_matches_dense_reference(seqs):
    sm_scale = 8 ** -0.5
    q, kp, vp, bt, ri, qp, dense = build_batch(seqs)
    out = ragged_paged_attention(q, kp, vp, bt, ri, qp, sm_scale=sm_scale)
    out_naive = naive_ragged_attention(q, kp, vp, bt, ri, qp,
                                       sm_scale=sm_scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_naive),
                               rtol=2e-5, atol=2e-5)
    # Cross-check against a per-request dense causal attention.
    t = 0
    for q_new, k_full, v_full, ctx in dense:
        for i in range(q_new.shape[0]):
            pos = ctx + i
            expect = dense_attention(q_new[i:i + 1], k_full[:pos + 1],
                                     v_full[:pos + 1], sm_scale)
            np.testing.assert_allclose(np.asarray(out[t]), expect[0],
                                       rtol=2e-4, atol=2e-4)
            t += 1


def test_gqa_groups():
    # 8 query heads sharing 2 kv heads.
    q, kp, vp, bt, ri, qp, dense = build_batch([(6, 2)], num_q_heads=8,
                                               num_kv_heads=2)
    out = ragged_paged_attention(q, kp, vp, bt, ri, qp, sm_scale=0.3)
    ref = naive_ragged_attention(q, kp, vp, bt, ri, qp, sm_scale=0.3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_write_then_read_roundtrip():
    page_size, num_kv_heads, head_dim = 4, 2, 8
    k_pages = jnp.zeros((8, num_kv_heads, page_size, head_dim))
    v_pages = jnp.zeros_like(k_pages)
    k_new = jnp.arange(3 * num_kv_heads * head_dim,
                       dtype=jnp.float32).reshape(3, num_kv_heads, head_dim)
    v_new = -k_new
    # Tokens land at slots: page 2 offset 1, page 2 offset 2, page 5 off 0.
    slots = jnp.asarray([2 * 4 + 1, 2 * 4 + 2, 5 * 4 + 0], jnp.int32)
    k_pages, v_pages = write_kv_pages(k_pages, v_pages, k_new, v_new, slots)
    np.testing.assert_array_equal(np.asarray(k_pages[2, :, 1]),
                                  np.asarray(k_new[0]))
    np.testing.assert_array_equal(np.asarray(k_pages[2, :, 2]),
                                  np.asarray(k_new[1]))
    np.testing.assert_array_equal(np.asarray(v_pages[5, :, 0]),
                                  np.asarray(v_new[2]))
    # Untouched slots remain zero.
    assert float(jnp.abs(k_pages[0]).sum()) == 0.0


def test_write_padded_slots_dropped():
    k_pages = jnp.ones((2, 1, 4, 4))
    v_pages = jnp.ones_like(k_pages)
    k_new = jnp.full((2, 1, 4), 9.0)
    # Slot -1 and out-of-range slot are both dropped.
    slots = jnp.asarray([-1, 99], jnp.int32)
    k2, v2 = write_kv_pages(k_pages, v_pages, k_new, k_new, slots)
    np.testing.assert_array_equal(np.asarray(k2), np.asarray(k_pages))


def test_padded_tokens_do_not_nan():
    # Padding rows (req 0 / pos 0 over an empty cache) must yield finite
    # output — the engine discards them but NaNs would poison XLA fusions.
    q, kp, vp, bt, ri, qp, _ = build_batch([(0, 2)])
    pad_q = jnp.concatenate([q, jnp.zeros_like(q)])
    pad_ri = jnp.concatenate([ri, jnp.zeros_like(ri)])
    pad_qp = jnp.concatenate([qp, jnp.zeros_like(qp)])
    out = ragged_paged_attention(pad_q, kp, vp, bt, pad_ri, pad_qp,
                                 sm_scale=0.35)
    assert bool(jnp.isfinite(out).all())


def test_sliding_window_masks_old_positions():
    """window=W must equal full attention restricted to the last W
    positions (checked against the naive reference with an explicit
    window, and window >= seqlen must equal full causal)."""
    import numpy as np
    import jax.numpy as jnp

    from vllm_distributed_tpu.ops.attention import (
        naive_ragged_attention, ragged_paged_attention)

    rng = np.random.default_rng(0)
    T, Hq, Hkv, D, PS, P = 10, 4, 2, 16, 4, 6
    q = jnp.asarray(rng.standard_normal((T, Hq, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((24, Hkv, PS, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((24, Hkv, PS, D)).astype(np.float32))
    bt = jnp.asarray(np.arange(2 * P, dtype=np.int32).reshape(2, P))
    req_idx = jnp.asarray([0] * 5 + [1] * 5, jnp.int32)
    q_pos = jnp.asarray(list(range(15, 20)) + list(range(10, 15)),
                        jnp.int32)

    full = ragged_paged_attention(q, k, v, bt, req_idx, q_pos,
                                  sm_scale=0.25)
    for W in (4, 8):
        got = ragged_paged_attention(q, k, v, bt, req_idx, q_pos,
                                     sm_scale=0.25, window=W)
        want = naive_ragged_attention(q, k, v, bt, req_idx, q_pos,
                                      sm_scale=0.25, window=W)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        # Windowed differs from full for small W.
        assert not np.allclose(np.asarray(got), np.asarray(full))
    # Huge window == full causal.
    wide = ragged_paged_attention(q, k, v, bt, req_idx, q_pos,
                                  sm_scale=0.25, window=1000)
    np.testing.assert_allclose(np.asarray(wide), np.asarray(full),
                               rtol=1e-6, atol=1e-6)


def test_logit_softcap_matches_naive():
    """Gemma2 attn soft-capping: paged path == dense reference, and a
    cap actually changes the output (scores get bounded)."""
    import jax.numpy as jnp
    from vllm_distributed_tpu.ops.attention import (
        naive_ragged_attention, ragged_paged_attention)

    rng = np.random.default_rng(1)
    T, Hq, Hkv, D, PS, P = 8, 4, 2, 16, 4, 4
    q = jnp.asarray(3 * rng.standard_normal((T, Hq, D)).astype(np.float32))
    k = jnp.asarray(3 * rng.standard_normal((16, Hkv, PS, D)).astype(
        np.float32))
    v = jnp.asarray(rng.standard_normal((16, Hkv, PS, D)).astype(
        np.float32))
    bt = jnp.asarray(np.arange(2 * P, dtype=np.int32).reshape(2, P))
    req_idx = jnp.asarray([0] * 4 + [1] * 4, jnp.int32)
    q_pos = jnp.asarray(list(range(8, 12)) + list(range(6, 10)), jnp.int32)

    got = ragged_paged_attention(q, k, v, bt, req_idx, q_pos,
                                 sm_scale=0.25, logit_cap=5.0)
    want = naive_ragged_attention(q, k, v, bt, req_idx, q_pos,
                                  sm_scale=0.25, logit_cap=5.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    uncapped = ragged_paged_attention(q, k, v, bt, req_idx, q_pos,
                                      sm_scale=0.25)
    assert not np.allclose(np.asarray(got), np.asarray(uncapped))
