"""Quantized communication plane: parity gates per in-graph path.

Every quantized collective must stay within one block-scaled int8
round-trip of its exact counterpart (bounded divergence), and the
engine-level greedy decode must be TOKEN-IDENTICAL on the CPU smoke
configs with a fine scale block (VDT_QCOMM_BLOCK=16 — at toy-model
scale the random-weight logit gaps sit near the coarse-block noise
floor; real checkpoints tolerate the default 256, which is what the
EQuARX quality results are about). VDT_QCOMM=0 must revert every path
byte-identically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from jax.sharding import PartitionSpec as P
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.config import ParallelConfig
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.parallel import collectives
from vllm_distributed_tpu.parallel.mesh import (build_mesh, global_mesh,
                                                shard_map)
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture()
def qcomm_on(monkeypatch):
    monkeypatch.setenv("VDT_QCOMM", "1")
    collectives.refresh()
    yield
    collectives.refresh()


@pytest.fixture(autouse=True)
def _refresh_after(monkeypatch):
    # Every test leaves the cached env gating the way it found it.
    yield
    collectives.refresh()


def _mesh(k: int):
    return build_mesh(ParallelConfig(tensor_parallel_size=k),
                      devices=jax.devices("cpu")[:k])


# ---------------------------------------------------------------------------
# Dispatcher gating
# ---------------------------------------------------------------------------

def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("VDT_QCOMM", raising=False)
    collectives.refresh()
    for path in ("tknp", "ep", "tp", "dcn_pull"):
        assert not collectives.enabled(path)


def test_path_override(monkeypatch):
    monkeypatch.setenv("VDT_QCOMM", "1")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp,kv")
    collectives.refresh()
    assert collectives.enabled("tknp")
    assert not collectives.enabled("ep")
    assert not collectives.enabled("tp")
    assert not collectives.enabled("tknp_kv")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp_kv")
    collectives.refresh()
    assert collectives.enabled("tknp_kv")
    assert not collectives.enabled("tknp")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp,kv")
    collectives.refresh()
    # "kv" is the group token for every connector payload path.
    assert collectives.enabled("dcn_pull")
    assert collectives.enabled("p2p")
    assert collectives.enabled("shared_storage")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "")
    collectives.refresh()
    assert all(collectives.enabled(p)
               for p in ("tknp", "ep", "tp", "shared_storage"))


def test_psum_off_is_exact_lax_psum(monkeypatch):
    monkeypatch.setenv("VDT_QCOMM", "0")
    collectives.refresh()
    mesh = _mesh(2)
    x = np.arange(2 * 24, dtype=np.float32).reshape(2, 24)
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.psum(x_[0], "model", path="tknp"),
            mesh=mesh, in_specs=(P("model", None), ), out_specs=P(),
            check_vma=False)(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), x.sum(axis=0))


def test_divisor_block():
    assert collectives.divisor_block(64, 256) == 64
    assert collectives.divisor_block(256, 256) == 256
    assert collectives.divisor_block(96, 64) == 48
    assert collectives.divisor_block(7, 4) == 1


# ---------------------------------------------------------------------------
# Quantized psum (the TKNP decode merge + EP combine + TP reduce form)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [2, 4])
def test_quantized_psum_bounded_divergence(qcomm_on, k):
    mesh = _mesh(k)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(k, 37, 64)).astype(np.float32)
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.psum(x_[0], "model", path="ep"),
            mesh=mesh, in_specs=(P("model", None, None), ),
            out_specs=P(), check_vma=False)(jnp.asarray(x))
    want = x.sum(axis=0)
    # One int8 block round-trip per leg: 2 * amax/127 per contributing
    # rank, summed — loose analytic bound.
    bound = 2.0 * (k + 1) * np.max(np.abs(x)) / 127.0
    assert np.max(np.abs(np.asarray(got) - want)) < bound


def test_quantized_psum_disjoint_rows_like_tknp(qcomm_on):
    """The TKNP merge shape: each rank owns disjoint rows, foreign rows
    are zero. All-zero blocks must contribute exactly zero."""
    mesh = _mesh(2)
    rng = np.random.default_rng(1)
    full = rng.normal(size=(8, 4, 16)).astype(np.float32)
    per_rank = np.zeros((2, ) + full.shape, np.float32)
    per_rank[0, :4] = full[:4]
    per_rank[1, 4:] = full[4:]
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.psum(x_[0], "model", path="tknp"),
            mesh=mesh, in_specs=(P("model", None, None, None), ),
            out_specs=P(), check_vma=False)(jnp.asarray(per_rank))
    bound = 2.0 * 3 * np.max(np.abs(full)) / 127.0
    assert np.max(np.abs(np.asarray(got) - full)) < bound


def test_quantized_psum_zeros_exact(qcomm_on):
    mesh = _mesh(2)
    z = np.zeros((2, 5, 33), np.float32)
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.psum(x_[0], "model", path="ep"),
            mesh=mesh, in_specs=(P("model", None, None), ),
            out_specs=P(), check_vma=False)(jnp.asarray(z))
    np.testing.assert_array_equal(np.asarray(got), z[0])


def test_psum_integer_operand_falls_back_exact(qcomm_on):
    """Lossy rounding of integer sums is silently wrong — the drop-in
    must take the exact psum (counted as a fallback) for non-floats."""
    collectives.reset_counters()
    mesh = _mesh(2)
    x = np.arange(2 * 1000, dtype=np.int32).reshape(2, 1000) * 1000
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.psum(x_[0], "model", path="ep"),
            mesh=mesh, in_specs=(P("model", None), ), out_specs=P(),
            check_vma=False)(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), x.sum(axis=0))
    assert collectives.traced_snapshot()["fallbacks"].get("ep") == 1


def test_all_to_all_no_win_falls_back_exact(qcomm_on):
    """A bf16 payload with a 2-wide feature dim would ship MORE bytes
    quantized (scales outweigh the dtype shrink) — must stay raw."""
    import ml_dtypes
    collectives.reset_counters()
    k = 2
    mesh = _mesh(k)
    y = (np.arange(k * k * 4 * 2).reshape(k, k, 4, 2)
         .astype(ml_dtypes.bfloat16))
    specs = (P("model", None, None, None), )
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda y_: collectives.all_to_all(y_[0], "model", 0, 0,
                                              path="ep"),
            mesh=mesh, in_specs=specs, out_specs=specs[0],
            check_vma=False)(jnp.asarray(y))
        want = shard_map(
            lambda y_: jax.lax.all_to_all(y_[0], "model", 0, 0),
            mesh=mesh, in_specs=specs, out_specs=specs[0],
            check_vma=False)(jnp.asarray(y))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert collectives.traced_snapshot()["fallbacks"].get("ep") == 1


def test_trace_counters_record_savings(qcomm_on):
    collectives.reset_counters()
    mesh = _mesh(2)
    x = np.ones((2, 16, 64), np.float32)
    with global_mesh(mesh), mesh:
        shard_map(lambda x_: collectives.psum(x_[0], "model", path="ep"),
                  mesh=mesh, in_specs=(P("model", None, None), ),
                  out_specs=P(), check_vma=False)(jnp.asarray(x))
    snap = collectives.traced_snapshot()
    assert snap["bytes_saved"].get("ep", 0) > 0


# ---------------------------------------------------------------------------
# Quantized all_to_all (the MoE-EP dispatch/combine shuffle)
# ---------------------------------------------------------------------------

def test_quantized_all_to_all_bounded_divergence(qcomm_on):
    k = 4
    mesh = _mesh(k)
    rng = np.random.default_rng(2)
    y = rng.normal(size=(k, k, 8, 64)).astype(np.float32)
    specs = (P("model", None, None, None), )
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda y_: collectives.all_to_all(y_[0], "model", 0, 0,
                                              path="ep"),
            mesh=mesh, in_specs=specs, out_specs=specs[0],
            check_vma=False)(jnp.asarray(y))
        want = shard_map(
            lambda y_: jax.lax.all_to_all(y_[0], "model", 0, 0),
            mesh=mesh, in_specs=specs, out_specs=specs[0],
            check_vma=False)(jnp.asarray(y))
    bound = np.max(np.abs(y)) / 127.0 + 1e-6
    assert np.max(np.abs(np.asarray(got) - np.asarray(want))) < bound


# ---------------------------------------------------------------------------
# Quantized all_gather (the MoE-EP re-replicate step; path "ep" —
# and the TPLA "tpla" path's gating rides the same dispatcher)
# ---------------------------------------------------------------------------

def test_quantized_all_gather_bounded_divergence(qcomm_on):
    k = 4
    mesh = _mesh(k)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(k * 8, 64)).astype(np.float32)
    in_specs = (P("model", None), )
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.all_gather(x_, "model", tiled=True,
                                              path="ep"),
            mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False)(jnp.asarray(x))
        want = shard_map(
            lambda x_: jax.lax.all_gather(x_, "model", tiled=True),
            mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False)(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(want), x)
    bound = np.max(np.abs(x)) / 127.0 + 1e-6
    assert np.max(np.abs(np.asarray(got) - x)) < bound
    assert collectives.traced_snapshot()["bytes_saved"].get("ep", 0) > 0


def test_all_gather_off_is_exact_lax(monkeypatch):
    monkeypatch.delenv("VDT_QCOMM", raising=False)
    collectives.refresh()
    k = 2
    mesh = _mesh(k)
    x = np.arange(k * 4 * 8, dtype=np.float32).reshape(k * 4, 8)
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.all_gather(x_, "model", tiled=True,
                                              path="ep"),
            mesh=mesh, in_specs=(P("model", None), ), out_specs=P(),
            check_vma=False)(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), x)


def test_all_gather_integer_operand_falls_back_exact(qcomm_on):
    collectives.reset_counters()
    k = 2
    mesh = _mesh(k)
    x = np.arange(k * 4 * 8, dtype=np.int32).reshape(k * 4, 8)
    with global_mesh(mesh), mesh:
        got = shard_map(
            lambda x_: collectives.all_gather(x_, "model", tiled=True,
                                              path="ep"),
            mesh=mesh, in_specs=(P("model", None), ), out_specs=P(),
            check_vma=False)(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), x)
    assert collectives.traced_snapshot()["fallbacks"].get("ep") == 1


# ---------------------------------------------------------------------------
# TKNP KV-write shuffle (path "tknp_kv") — the last raw collective of
# ROADMAP item 5: the step's new K/V rows crossing the token-axis
# shard_map boundary ship block-scaled int8.
# ---------------------------------------------------------------------------


def test_kv_shuffle_quantize_bounded_divergence(monkeypatch):
    monkeypatch.setenv("VDT_QCOMM", "1")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp_kv")
    monkeypatch.setenv("VDT_QCOMM_BLOCK", "16")
    collectives.refresh()
    collectives.reset_counters()
    rng = np.random.default_rng(5)
    k_new = rng.normal(size=(12, 4, 32)).astype(np.float32)
    v_new = rng.normal(size=(12, 4, 32)).astype(np.float32)
    pack = collectives.kv_shuffle_quantize(jnp.asarray(k_new),
                                           jnp.asarray(v_new), 2)
    assert pack is not None
    k_d, v_d = collectives.kv_shuffle_dequantize(*pack, jnp.float32)
    bound = np.max(np.abs(np.stack([k_new, v_new]))) / 127.0 + 1e-6
    assert np.max(np.abs(np.asarray(k_d) - k_new)) < bound
    assert np.max(np.abs(np.asarray(v_d) - v_new)) < bound
    assert collectives.traced_snapshot()["bytes_saved"]["tknp_kv"] > 0


def test_kv_shuffle_no_win_falls_back(monkeypatch):
    """Axis size 1 (no shuffle) and integer payloads must keep the raw
    path, counted as fallbacks."""
    monkeypatch.setenv("VDT_QCOMM", "1")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp_kv")
    collectives.refresh()
    collectives.reset_counters()
    x = jnp.ones((4, 2, 16), jnp.float32)
    assert collectives.kv_shuffle_quantize(x, x, 1) is None
    xi = jnp.ones((4, 2, 16), jnp.int32)
    assert collectives.kv_shuffle_quantize(xi, xi, 2) is None
    assert collectives.traced_snapshot()["fallbacks"]["tknp_kv"] == 2


def test_kv_shuffle_off_is_inert(monkeypatch):
    monkeypatch.delenv("VDT_QCOMM", raising=False)
    collectives.refresh()
    x = jnp.ones((4, 2, 16), jnp.float32)
    assert collectives.kv_shuffle_quantize(x, x, 2) is None


def test_tknp_kv_write_parity_through_ops(monkeypatch):
    """The full _write_kv_cache_tknp path on a 2-rank token mesh:
    quantized writes stay within one int8 block round-trip of the raw
    writes, untouched pages stay byte-identical."""
    from vllm_distributed_tpu.models.common import (AttentionBatch,
                                                    TknpAttentionBatch)
    from vllm_distributed_tpu.ops.attention import write_kv_cache
    K, L, Nl, KVH, PS, D = 2, 1, 8, 2, 4, 16
    N = K * Nl
    rng = np.random.default_rng(9)
    k_all = jnp.zeros((L, N, KVH, PS, D), jnp.float32)
    v_all = jnp.zeros((L, N, KVH, PS, D), jnp.float32)
    T = 4
    k_new = jnp.asarray(rng.normal(size=(T, KVH, D)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(T, KVH, D)), jnp.float32)
    # Two tokens per rank: rank 0 owns pages [0, Nl), rank 1 the rest.
    slots = np.full((K, T), -1, np.int32)
    kv_runs = np.zeros((K, 4, 4), np.int32)
    n_runs = np.zeros((K, 1), np.int32)
    for t in range(T):
        owner = t % K
        local_page, off = t, 1
        slots[owner, t] = local_page * PS + off
        g = n_runs[owner, 0]
        kv_runs[owner, g] = (local_page, off, t - off + PS, 1)
        n_runs[owner, 0] = g + 1
    tk = TknpAttentionBatch(
        slot_mapping=jnp.asarray(slots),
        block_tables=jnp.zeros((K, 4, 4), jnp.int32),
        seq_info=jnp.zeros((K, 4, 4), jnp.int32),
        num_seqs=jnp.zeros((K, 1), jnp.int32),
        kv_runs=jnp.asarray(kv_runs),
        num_kv_runs=jnp.asarray(n_runs),
    )
    batch = AttentionBatch(
        req_idx=jnp.zeros((T, ), jnp.int32),
        positions=jnp.zeros((T, ), jnp.int32),
        slot_mapping=jnp.zeros((T, ), jnp.int32),
        block_tables=jnp.zeros((4, 4), jnp.int32),
        seq_lens=jnp.zeros((4, ), jnp.int32),
        tknp=tk,
    )
    from vllm_distributed_tpu.config import ParallelConfig
    mesh = build_mesh(
        ParallelConfig(token_parallel_size=K),
        devices=jax.devices("cpu")[:K])
    layer = jnp.zeros((1, ), jnp.int32)
    with global_mesh(mesh), mesh:
        monkeypatch.delenv("VDT_QCOMM", raising=False)
        collectives.refresh()
        k_raw, v_raw = write_kv_cache(k_all, v_all, k_new, v_new,
                                      batch, layer)
        monkeypatch.setenv("VDT_QCOMM", "1")
        monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp_kv")
        monkeypatch.setenv("VDT_QCOMM_BLOCK", "16")
        collectives.refresh()
        collectives.reset_counters()
        k_q, v_q = write_kv_cache(k_all, v_all, k_new, v_new, batch,
                                  layer)
    bound = np.max(np.abs(np.asarray(k_new))) / 127.0 + 1e-6
    assert np.max(np.abs(np.asarray(k_q) - np.asarray(k_raw))) < bound
    bound_v = np.max(np.abs(np.asarray(v_new))) / 127.0 + 1e-6
    assert np.max(np.abs(np.asarray(v_q) - np.asarray(v_raw))) < bound_v
    # The raw leg actually wrote the rows it claims to have written.
    assert np.max(np.abs(np.asarray(k_raw))) > 0
    assert collectives.traced_snapshot()["bytes_saved"]["tknp_kv"] > 0


def test_tknp_kv_engine_greedy_parity(checkpoint, baseline,
                                      monkeypatch):
    """Engine-level: the quantized KV-write shuffle keeps greedy decode
    token-identical at the fine scale block (like the other paths)."""
    monkeypatch.setenv("VDT_QCOMM", "1")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "tknp_kv")
    monkeypatch.setenv("VDT_QCOMM_BLOCK", "16")
    collectives.refresh()
    got = _run(_make_engine(checkpoint, token_parallel_size=2), PROMPTS,
               "qtknpkv")
    assert got == baseline


# ---------------------------------------------------------------------------
# EP MoE block: quantized dispatch/combine vs exact, both EP modes
# ---------------------------------------------------------------------------

@pytest.fixture()
def ep_setup():
    from vllm_distributed_tpu.models.llama import LlamaArchConfig
    from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM
    ep = 4
    T, H, I, E = 8, 32, 16, 4
    mesh = _mesh(ep)
    cfg = LlamaArchConfig(
        vocab_size=64, hidden_size=H, intermediate_size=I,
        num_layers=1, num_q_heads=4, num_kv_heads=4, head_dim=8,
        num_experts=E, num_experts_per_tok=2, norm_topk_prob=True,
        expert_parallel=True, expert_parallel_ranks=ep,
        dtype=jnp.float32)
    model = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(0)
    lp = {
        "router": jnp.asarray(rng.normal(size=(H, E)), jnp.float32),
        "w_gate": jnp.asarray(rng.normal(size=(E, H, I)) * 0.1,
                              jnp.float32),
        "w_up": jnp.asarray(rng.normal(size=(E, H, I)) * 0.1,
                            jnp.float32),
        "w_down": jnp.asarray(rng.normal(size=(E, I, H)) * 0.1,
                              jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(T, H)), jnp.float32)
    return mesh, model, lp, x


@pytest.mark.parametrize("mode", ["a2a", "replicate"])
def test_moe_ep_quantized_bounded_divergence(ep_setup, monkeypatch,
                                             mode):
    mesh, model, lp, x = ep_setup
    monkeypatch.setenv("VDT_MOE_EP_MODE", mode)
    with global_mesh(mesh), mesh:
        monkeypatch.setenv("VDT_QCOMM", "1")
        collectives.refresh()
        got = np.asarray(model.mlp_block(lp, x))
        monkeypatch.setenv("VDT_QCOMM", "0")
        collectives.refresh()
        want = np.asarray(model.mlp_block(lp, x))
    assert np.max(np.abs(got - want)) < 0.05 * (np.max(np.abs(want))
                                                + 1.0)


# ---------------------------------------------------------------------------
# Engine-level greedy parity (CPU smoke config, fine scale block)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_qcomm")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
    [11, 12, 13, 14, 15, 16],
    [7, 44, 101, 13, 2, 64, 99],
]


def _make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def _run(engine, prompts, tag, max_tokens=8):
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


@pytest.fixture(scope="module")
def baseline(checkpoint):
    return _run(_make_engine(checkpoint), PROMPTS, "base")


@pytest.fixture()
def qcomm_fine_block(monkeypatch):
    monkeypatch.setenv("VDT_QCOMM", "1")
    monkeypatch.setenv("VDT_QCOMM_BLOCK", "16")
    collectives.refresh()
    yield
    collectives.refresh()


def test_tknp_engine_greedy_parity(checkpoint, baseline,
                                   qcomm_fine_block):
    got = _run(_make_engine(checkpoint, token_parallel_size=2), PROMPTS,
               "qtknp")
    assert got == baseline


def test_tp_engine_greedy_parity(checkpoint, baseline,
                                 qcomm_fine_block):
    got = _run(_make_engine(checkpoint, tensor_parallel_size=2), PROMPTS,
               "qtp")
    assert got == baseline


def test_tp_engine_qcomm_off_reverts(checkpoint, baseline, monkeypatch):
    """VDT_QCOMM=1 with the tp path excluded keeps the GSPMD reduce:
    byte-identical greedy to the stock engine."""
    monkeypatch.setenv("VDT_QCOMM", "1")
    monkeypatch.setenv("VDT_QCOMM_PATHS", "ep")
    collectives.refresh()
    got = _run(_make_engine(checkpoint, tensor_parallel_size=2), PROMPTS,
               "qtpoff")
    assert got == baseline
