"""Pallas KV-write kernel vs the XLA scatter reference (interpret mode)."""

import jax.numpy as jnp
import numpy as np
import pytest

from vllm_distributed_tpu.ops.pallas_kv_write import write_kv_pages_pallas


def run_pallas(k_all, v_all, k_new, v_new, runs, num_runs, layer, ps):
    # k_new [T, KVH, D] -> head-leading with PS front / 2*PS back padding.
    pad = [(0, 0), (ps, 2 * ps), (0, 0)]
    k_hl = jnp.pad(jnp.asarray(k_new).swapaxes(0, 1), pad)
    v_hl = jnp.pad(jnp.asarray(v_new).swapaxes(0, 1), pad)
    return write_kv_pages_pallas(
        jnp.asarray(k_all), jnp.asarray(v_all), k_hl, v_hl,
        jnp.asarray(runs, jnp.int32), jnp.asarray([num_runs], jnp.int32),
        jnp.asarray([layer], jnp.int32), interpret=True)


def reference(k_all, k_new, runs, num_runs, layer, ps):
    out = np.array(k_all)
    for page, off_start, window_start, run_len in runs[:num_runs]:
        if run_len == 0:
            continue
        src0 = window_start - ps + off_start
        for i in range(run_len):
            out[layer, page, :, off_start + i] = k_new[src0 + i]
    return out


def make_runs(slot_spans, ps):
    """slot_spans: list of (first_slot, length) with flat src order."""
    runs, src = [], 0
    for slot, length in slot_spans:
        consumed = 0
        while consumed < length:
            s = slot + consumed
            off = s % ps
            run_len = min(ps - off, length - consumed)
            runs.append((s // ps, off, (src + consumed) - off + ps,
                         run_len))
            consumed += run_len
        src += length
    return runs


@pytest.mark.parametrize("spans,layer", [
    ([(3, 1), (17, 1), (40, 1)], 0),        # decode: single tokens
    ([(0, 8), (32, 8)], 1),                  # full pages
    ([(5, 20)], 2),                          # partial + full + partial
    ([(2, 3), (24, 8), (50, 5)], 0),         # mixed
])
def test_matches_reference(spans, layer):
    rng = np.random.default_rng(0)
    L, N, KVH, PS, D = 3, 8, 2, 8, 128
    k_all = rng.standard_normal((L, N, KVH, PS, D)).astype(np.float32)
    v_all = rng.standard_normal((L, N, KVH, PS, D)).astype(np.float32)
    T = sum(n for _, n in spans)
    k_new = rng.standard_normal((T, KVH, D)).astype(np.float32)
    v_new = rng.standard_normal((T, KVH, D)).astype(np.float32)
    runs = make_runs(spans, PS)
    G = len(runs) + 2  # padded rows must be ignored
    runs_arr = np.zeros((G, 4), np.int32)
    runs_arr[:len(runs)] = runs

    k_out, v_out = run_pallas(k_all, v_all, k_new, v_new, runs_arr,
                              len(runs), layer, PS)
    np.testing.assert_allclose(
        np.asarray(k_out), reference(k_all, k_new, runs, len(runs), layer,
                                     PS))
    np.testing.assert_allclose(
        np.asarray(v_out), reference(v_all, v_new, runs, len(runs), layer,
                                     PS))


def test_inactive_and_zero_len_runs_ignored():
    L, N, KVH, PS, D = 1, 4, 1, 8, 128
    k_all = np.zeros((L, N, KVH, PS, D), np.float32)
    k_new = np.ones((4, KVH, D), np.float32)
    runs = np.zeros((4, 4), np.int32)
    runs[0] = (2, 0, PS, 0)     # zero-length: skip
    runs[1] = (1, 0, PS, 1)     # active
    runs[2] = (3, 0, PS, PS)    # beyond num_runs: skip
    k_out, _ = run_pallas(k_all, k_all, k_new, k_new, runs, 2, 0, PS)
    k_out = np.asarray(k_out)
    assert k_out[0, 1, 0, 0].sum() == D  # written
    assert k_out[0, 2].sum() == 0 and k_out[0, 3].sum() == 0
