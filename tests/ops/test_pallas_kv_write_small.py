import numpy as np
import pytest
from tests.ops.test_pallas_kv_write import (run_pallas, reference, make_runs)


@pytest.mark.parametrize("PS,D", [(4, 16), (4, 128), (8, 16), (16, 64)])
def test_small(PS, D):
    rng = np.random.default_rng(0)
    L, N, KVH = 2, 8, 2
    spans = [(1, 5), (PS * 4, 1)]
    k_all = rng.standard_normal((L, N, KVH, PS, D)).astype(np.float32)
    v_all = rng.standard_normal((L, N, KVH, PS, D)).astype(np.float32)
    T = sum(n for _, n in spans)
    k_new = rng.standard_normal((T, KVH, D)).astype(np.float32)
    v_new = rng.standard_normal((T, KVH, D)).astype(np.float32)
    runs = make_runs(spans, PS)
    G = len(runs)
    runs_arr = np.zeros((G, 4), np.int32)
    runs_arr[:len(runs)] = runs
    k_out, v_out = run_pallas(k_all, v_all, k_new, v_new, runs_arr,
                              len(runs), 1, PS)
    np.testing.assert_allclose(
        np.asarray(k_out), reference(k_all, k_new, runs, len(runs), 1, PS))
