"""Fused dequant-GEMM kernel vs the XLA dequantize-then-dot reference
(reference capability: csrc/quantization/gptq_marlin fused kernels)."""

import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from vllm_distributed_tpu.ops.pallas_quant_matmul import quant_matmul


def _quantize(w32, scheme):
    absmax = np.max(np.abs(w32), axis=0, keepdims=True)
    if scheme == "int8":
        scale = np.maximum(absmax / 127.0, 1e-8)
        q = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    elif scheme == "int4":
        scale = np.maximum(absmax / 7.0, 1e-8)
        q = np.clip(np.round(w32 / scale), -8, 7).astype(ml_dtypes.int4)
    else:
        scale = np.maximum(absmax / 448.0, 1e-8)
        q = (w32 / scale).astype(ml_dtypes.float8_e4m3fn)
    return q, scale.astype(np.float32)


@pytest.mark.parametrize("scheme", ["int4", "int8", "fp8"])
@pytest.mark.parametrize("shape", [(8, 256, 128), (17, 512, 384),
                                   (4, 64, 64)])
def test_matches_dequant_reference(scheme, shape):
    T, K, N = shape
    rng = np.random.default_rng(0)
    x = rng.standard_normal((T, K)).astype(np.float32)
    w32 = rng.standard_normal((K, N)).astype(np.float32)
    q, scale = _quantize(w32, scheme)

    got = quant_matmul(jnp.asarray(x), jnp.asarray(q),
                       jnp.asarray(scale), interpret=True)
    want = x @ (np.asarray(q, np.float32) * scale)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-2,
                               atol=2e-2 * np.abs(want).max())


def test_bf16_activations():
    rng = np.random.default_rng(1)
    T, K, N = 8, 256, 128
    x = rng.standard_normal((T, K)).astype(np.float32)
    w32 = rng.standard_normal((K, N)).astype(np.float32)
    q, scale = _quantize(w32, "int4")
    got = quant_matmul(jnp.asarray(x, jnp.bfloat16), jnp.asarray(q),
                       jnp.asarray(scale), interpret=True)
    want = x @ (np.asarray(q, np.float32) * scale)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.float32(np.asarray(got)), want,
                               rtol=5e-2, atol=5e-2 * np.abs(want).max())


@pytest.mark.parametrize("shape,group", [((8, 256, 128), 128),
                                         ((17, 512, 256), 64),
                                         ((4, 64, 64), 64)])
def test_grouped_matches_reference(shape, group):
    from vllm_distributed_tpu.ops.pallas_quant_matmul import \
        quant_matmul_grouped
    T, K, N = shape
    rng = np.random.default_rng(2)
    x = rng.standard_normal((T, K)).astype(np.float32)
    w32 = rng.standard_normal((K, N)).astype(np.float32)
    G = K // group
    wg = w32.reshape(G, group, N)
    wmin = wg.min(axis=1)
    scale = np.maximum((wg.max(axis=1) - wmin) / 15.0, 1e-8)
    q = np.clip(np.round((wg - wmin[:, None]) / scale[:, None]), 0,
                15).astype(ml_dtypes.uint4)
    want = x @ (np.asarray(q, np.float32).reshape(G, group, N) *
                scale[:, None] + wmin[:, None]).reshape(K, N)
    got = quant_matmul_grouped(
        jnp.asarray(x), jnp.asarray(np.asarray(q).reshape(K, N)),
        jnp.asarray(scale), jnp.asarray(wmin), interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-2,
                               atol=2e-2 * np.abs(want).max())
