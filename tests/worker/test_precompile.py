"""Precompile warm-up + recompile guard (reference:
tpu_model_runner.py:1248-1443 precompilation suite and :318
_update_num_xla_graphs recompile detection)."""

import numpy as np
import pytest

from tests.engine.test_llm_engine import checkpoint, make_engine  # noqa: F401
from vllm_distributed_tpu.sampling_params import SamplingParams


def _runner(engine):
    return engine.engine_core.executor.worker.model_runner


def test_forward_shapes_closed_lattice(checkpoint, monkeypatch):
    """Every shape mixed traffic can hit is in forward_shapes()."""
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16)
    r = _runner(engine)
    shapes = r.forward_shapes()
    # Decode at every request count and prefill at every token count must
    # land inside the precomputed lattice.
    for n_reqs in range(1, r.max_num_reqs + 1):
        assert r._batch_shape(n_reqs, 1) in shapes
    for total in range(1, 17):
        assert r._batch_shape(total, 2) in shapes


def test_unified_lattice_strictly_smaller_than_legacy(checkpoint):
    """ISSUE 6 acceptance: at unchanged bucket configs, the mega-kernel
    lattice (one forward shape per token bucket — composition lives in
    the partition descriptor) warms strictly fewer graphs than the
    legacy decode/prefill split (max_q keyed to the token bucket)."""
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16)
    r = _runner(engine)
    assert r._use_unified()
    unified = r.forward_shapes()
    # Every unified shape pins max_q == 1: no composition static.
    assert {s[1] for s in unified} == {1}
    r._unified = False  # same buckets, legacy composition-split shapes
    legacy = r.forward_shapes()
    r._unified = True
    assert len(unified) < len(legacy)


def test_no_recompile_after_warmup(checkpoint, monkeypatch):
    """Mixed traffic (ragged prefills, chunked prefill, decode, stops)
    after precompile() must never compile a new graph."""
    monkeypatch.setenv("VDT_PRECOMPILE", "1")
    monkeypatch.setenv("VDT_ASSERT_NO_RECOMPILE", "1")
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4)
    rng = np.random.default_rng(0)
    prompts = [[int(x) for x in rng.integers(2, 127, size=n)]
               for n in (3, 11, 23, 2, 7)]  # 23 forces chunked prefill
    for i, p in enumerate(prompts):
        engine.add_request(f"r{i}", p,
                           SamplingParams(temperature=0.0,
                                          max_tokens=4 + i % 3,
                                          ignore_eos=True))
    for _ in range(200):
        engine.step()  # raises RuntimeError on any post-warmup compile
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()


def test_no_recompile_after_warmup_pp(checkpoint, monkeypatch):
    """The pipeline-parallel runner's per-stage warm-up must also close
    the lattice: mixed traffic after precompile() never compiles."""
    monkeypatch.setenv("VDT_PRECOMPILE", "1")
    monkeypatch.setenv("VDT_ASSERT_NO_RECOMPILE", "1")
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4,
                         pipeline_parallel_size=2)
    rng = np.random.default_rng(1)
    prompts = [[int(x) for x in rng.integers(2, 127, size=n)]
               for n in (3, 11, 23, 2)]
    for i, p in enumerate(prompts):
        engine.add_request(f"pp{i}", p,
                           SamplingParams(temperature=0.0,
                                          max_tokens=4 + i % 3,
                                          ignore_eos=True))
    for _ in range(200):
        engine.step()
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()


def test_no_recompile_multi_step(checkpoint, monkeypatch):
    monkeypatch.setenv("VDT_PRECOMPILE", "1")
    monkeypatch.setenv("VDT_ASSERT_NO_RECOMPILE", "1")
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4,
                         num_scheduler_steps=4)
    for i in range(3):
        engine.add_request(f"m{i}", [5 + i, 9, 3],
                           SamplingParams(temperature=0.0, max_tokens=8,
                                          ignore_eos=True))
    for _ in range(200):
        engine.step()
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
