"""vdt:recompiles_total enforces the _compiled_shapes contract.

The recompile guard used to be a comment + log line; the telemetry
plane turns it into a counter an alert (and this tier-1 test) can
watch: after ``precompile()`` a steady-state decode loop must report
ZERO recompiles, and traffic over a deliberately un-warmed lattice
must report more than zero — through the full stats path (runner ->
worker label -> engine get_stats -> /metrics rendering)."""

import numpy as np

from tests.engine.test_llm_engine import checkpoint, make_engine  # noqa: F401
from vllm_distributed_tpu.metrics.prometheus import render_metrics
from vllm_distributed_tpu.sampling_params import SamplingParams


def _runner(engine):
    return engine.engine_core.executor.worker.model_runner


def _run_traffic(engine, n_prompts=4, max_tokens=6):
    rng = np.random.default_rng(7)
    prompts = [[int(x) for x in rng.integers(2, 127, size=n)]
               for n in (3, 9, 5, 12)][:n_prompts]
    for i, p in enumerate(prompts):
        engine.add_request(f"rg{i}", p,
                           SamplingParams(temperature=0.0,
                                          max_tokens=max_tokens,
                                          ignore_eos=True))
    for _ in range(200):
        engine.step()
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()


def test_steady_state_decode_reports_zero_recompiles(checkpoint,
                                                     monkeypatch):
    monkeypatch.setenv("VDT_PRECOMPILE", "1")
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4)
    assert _runner(engine)._precompiled
    _run_traffic(engine)
    stats = engine.get_stats()
    assert stats["num_recompiles"] == 0
    # The labeled per-worker series flows up the same stats RPC and
    # renders on /metrics.
    workers = stats["workers"]
    [(label, per)] = workers.items()
    assert label == "dp0-h0"
    assert per["num_recompiles"] == 0
    # Device-wait telemetry rode along: the runner blocked on at least
    # one device fetch during decode.
    assert per["device_wait_seconds"]["count"] > 0
    # The other telemetry legs ride the same get_stats poll: the
    # core's transport snapshot (empty — no connector configured) and
    # the scheduler's block-pool introspection.
    assert stats["transport"] == {"kv": {}, "shm": {},
                                  "shm_lag_chunks": 0, "qcomm": {}}
    kv = stats["kv_cache"]
    assert kv["total_blocks"] == 128
    assert kv["free_blocks"] + kv["used_blocks"] == kv["total_blocks"]
    text = render_metrics(stats)
    assert 'vdt:recompiles_total{worker="dp0-h0"} 0.0' in text
    assert 'vdt:kv_blocks{state="free"}' in text


def test_mixed_wave_zero_recompiles_after_precompile(checkpoint,
                                                     monkeypatch):
    """ROADMAP item #1's acceptance test: after precompile(), a wave
    mixing a chunked-prefill chunk with running decodes must trigger 0
    recompiles — the mega-kernel batch shape carries the composition in
    the partition descriptor, not in any static."""
    monkeypatch.setenv("VDT_PRECOMPILE", "1")
    monkeypatch.setenv("VDT_ASSERT_NO_RECOMPILE", "1")
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4)
    runner = _runner(engine)
    assert runner._precompiled
    rng = np.random.default_rng(11)
    # Two short prompts reach decode first; then a 40-token prompt
    # chunk-prefills across >= 3 waves (budget 16) while they decode,
    # so several waves mix a prefill chunk with running decode rows.
    for i in range(2):
        engine.add_request(
            f"mx{i}", [int(x) for x in rng.integers(2, 127, size=3)],
            SamplingParams(temperature=0.0, max_tokens=14,
                           ignore_eos=True))
    for _ in range(3):
        engine.step()
    engine.add_request(
        "mx-long", [int(x) for x in rng.integers(2, 127, size=40)],
        SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True))
    for _ in range(200):
        engine.step()  # raises RuntimeError on any post-warmup compile
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    stats = engine.get_stats()
    assert stats["num_recompiles"] == 0
    assert stats["workers"]["dp0-h0"]["num_recompiles"] == 0
    # The warmed lattice is itself observable (and collapsed: one
    # forward graph per token bucket — see test_precompile).
    assert stats["precompile_graphs"] > 0
    assert (f'vdt:precompile_graphs_total '
            f'{float(stats["precompile_graphs"])}'
            in render_metrics(stats))


def test_mixed_wave_dispatches_unified_kernel(checkpoint, monkeypatch):
    """Acceptance: mixed prefill+decode waves dispatch to the unified
    mega-kernel, asserted via vdt:attn_kernel_calls_total through the
    full stats path (interpret-mode Pallas backend on CPU)."""
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4)
    rng = np.random.default_rng(12)
    for i in range(2):
        engine.add_request(
            f"uk{i}", [int(x) for x in rng.integers(2, 127, size=3)],
            SamplingParams(temperature=0.0, max_tokens=10,
                           ignore_eos=True))
    for _ in range(3):
        engine.step()
    engine.add_request(
        "uk-long", [int(x) for x in rng.integers(2, 127, size=24)],
        SamplingParams(temperature=0.0, max_tokens=3, ignore_eos=True))
    for _ in range(200):
        engine.step()
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    stats = engine.get_stats()
    calls = stats["attn_kernel_calls"]
    # Every step (decode-only, prefill-only, and the mixed waves) rides
    # the ONE unified kernel; no step fell back to the per-composition
    # legacy kernels.
    assert calls.get("unified", 0) > 0
    assert "general" not in calls and "decode" not in calls
    text = render_metrics(stats)
    assert 'vdt:attn_kernel_calls_total{kernel="unified"}' in text


def _greedy_tokens(engine, n_prompts=4, max_tokens=8):
    rng = np.random.default_rng(21)
    prompts = [[int(x) for x in rng.integers(2, 127, size=n)]
               for n in (3, 9, 5, 12)][:n_prompts]
    for i, p in enumerate(prompts):
        engine.add_request(f"bf{i}", p,
                           SamplingParams(temperature=0.0,
                                          max_tokens=max_tokens,
                                          ignore_eos=True))
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    return [done[f"bf{i}"].outputs[0].token_ids
            for i in range(n_prompts)]


def test_block_fusion_zero_recompiles_and_token_parity(checkpoint,
                                                       monkeypatch):
    """ISSUE 11 acceptance: with VDT_BLOCK_FUSION=1, decode-only waves
    dispatch the fused block (ONE Pallas call per layer, counted by
    vdt:block_fusion_calls_total), greedy output is token-identical to
    VDT_BLOCK_FUSION=0, and the recompile guard reports zero
    post-precompile graphs — fusion's variants are warmed by
    precompile(), not compiled at serving time."""
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    path, _ = checkpoint
    base = _greedy_tokens(
        make_engine(path, max_num_batched_tokens=16, max_num_seqs=4))

    monkeypatch.setenv("VDT_BLOCK_FUSION", "1")
    monkeypatch.setenv("VDT_PRECOMPILE", "1")
    monkeypatch.setenv("VDT_ASSERT_NO_RECOMPILE", "1")
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4)
    runner = _runner(engine)
    assert runner._precompiled
    assert runner.model.cfg.block_fusion
    got = _greedy_tokens(engine)  # raises on any post-warmup compile
    assert got == base
    stats = engine.get_stats()
    assert stats["num_recompiles"] == 0
    assert stats["block_fusion_calls"] > 0
    calls = stats["attn_kernel_calls"]
    # Decode-only waves took the fused block; prefill/mixed waves kept
    # the mega-kernel; nothing fell back to the XLA reference.
    assert calls.get("fused_block", 0) == stats["block_fusion_calls"]
    assert calls.get("unified", 0) > 0
    assert "naive" not in calls
    # Fallback reasons cover exactly the non-decode waves.
    fb = stats["block_fusion_fallbacks"]
    assert set(fb) <= {"mixed_wave", "cascade", "multi_step"}
    text = render_metrics(stats)
    assert 'vdt:attn_kernel_calls_total{kernel="fused_block"}' in text
    assert "vdt:block_fusion_calls_total" in text


def test_windowed_model_reaches_unified_kernel(tmp_path_factory,
                                               monkeypatch):
    """ISSUE 11 acceptance: sliding-window waves no longer increment the
    XLA-fallback counter — the window rides the mega-kernel's per-layer
    statics, so a Mistral-class model's every wave dispatches
    unified."""
    import torch
    from transformers import MistralConfig, MistralForCausalLM
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    torch.manual_seed(0)
    cfg = MistralConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        sliding_window=6, max_position_embeddings=64,
                        eos_token_id=1)
    path = tmp_path_factory.mktemp("tiny_mistral_rg")
    MistralForCausalLM(cfg).save_pretrained(path,
                                            safe_serialization=True)
    engine = make_engine(str(path), max_num_batched_tokens=16,
                         max_num_seqs=4)
    _run_traffic(engine, max_tokens=8)
    stats = engine.get_stats()
    calls = stats["attn_kernel_calls"]
    assert calls.get("unified", 0) > 0
    assert "naive" not in calls and "general" not in calls


def test_unwarmed_shape_reports_recompiles(checkpoint, monkeypatch):
    """An empty warm-up set marked as precompiled: every compile the
    traffic triggers is, by the guard's contract, a recompile — the
    counter must say so."""
    monkeypatch.setenv("VDT_PRECOMPILE", "0")
    path, _ = checkpoint
    engine = make_engine(path, max_num_batched_tokens=16, max_num_seqs=4)
    runner = _runner(engine)
    assert not runner._precompiled
    runner._precompiled = True  # deliberately un-warmed lattice
    _run_traffic(engine)
    stats = engine.get_stats()
    assert stats["num_recompiles"] > 0
    assert stats["workers"]["dp0-h0"]["num_recompiles"] > 0
    text = render_metrics(stats)
    assert 'vdt:recompiles_total{worker="dp0-h0"}' in text
