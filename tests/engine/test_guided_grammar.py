"""guided_grammar: non-recursive EBNF compiled onto the regex DFA
(reference: the guided_grammar option of GuidedDecodingParams; the
reference's xgrammar backend accepts Lark-style EBNF)."""

import pytest

from vllm_distributed_tpu.structured_output.ebnf import (GrammarError,
                                                         ebnf_to_regex)
from vllm_distributed_tpu.structured_output.fsm import compile_regex


def _accepts(dfa, text: bytes) -> bool:
    state = dfa.walk_bytes(1, text)  # start state is 1, 0 = dead
    return state != 0 and bool(dfa.accept[state])


def test_ebnf_literals_alternatives_repetition():
    rx = ebnf_to_regex('''
        start: greeting " " name
        greeting: "hello" | "hi"
        name: /[a-z]/+
    ''')
    dfa = compile_regex(rx)
    assert _accepts(dfa, b"hello bob")
    assert _accepts(dfa, b"hi x")
    assert not _accepts(dfa, b"hello ")
    assert not _accepts(dfa, b"yo bob")


def test_ebnf_optional_and_groups():
    rx = ebnf_to_regex('''
        start: "a" [ "," "b" ] ( "x" | "y" )*
    ''')
    dfa = compile_regex(rx)
    for ok in (b"a", b"a,b", b"axyx", b"a,bxy"):
        assert _accepts(dfa, ok), ok
    for bad in (b"ab", b",b", b"a,"):
        assert not _accepts(dfa, bad), bad


def test_ebnf_recursion_rejected():
    with pytest.raises(GrammarError, match="recursive"):
        ebnf_to_regex('start: "(" start ")" | "x"')
    with pytest.raises(GrammarError, match="recursive"):
        ebnf_to_regex('''
            start: a
            a: b
            b: a | "x"
        ''')


def test_ebnf_undefined_rule_rejected():
    with pytest.raises(GrammarError, match="undefined"):
        ebnf_to_regex('start: missing')


def test_guided_grammar_end_to_end(tmp_path_factory):
    """A grammar-constrained generation emits only grammar words
    (reuses the word-level-tokenizer server checkpoint)."""
    from tests.entrypoints.test_openai_server import \
        _save_checkpoint_with_tokenizer
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    path = str(tmp_path_factory.mktemp("tiny_grammar"))
    _save_checkpoint_with_tokenizer(path)
    engine = LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=64,
        max_num_batched_tokens=64,
        max_num_seqs=8).create_engine_config())
    # The grammar constrains the BYTE stream of concatenated token
    # pieces (no inter-token spaces in a WordLevel vocab); the
    # detokenizer re-inserts spaces in the returned text.
    sp = SamplingParams(
        temperature=0.0, max_tokens=8,
        structured={"grammar": 'start: ("yes" | "no") "true"'})
    engine.add_request("g", "w3 w17", sp)
    final = None
    for _ in range(100):
        for out in engine.step():
            if out.finished:
                final = out
        if not engine.has_unfinished_requests():
            break
    assert final is not None
    assert final.outputs[0].text in ("yes true", "no true")