"""Engine-level per-tenant QoS gates.

Acceptance contract (ISSUE 13): ``VDT_QOS=0`` (the default) must stay
byte-identical to pre-QoS scheduling — the scheduler constructs no QoS
state and no ``tenants`` entry reaches the stats RPC — and turning QoS
ON must reorder only the *schedule*, never the *tokens*: greedy
outputs stay token-identical per request while the vdt:tenant_*
accounting lights up end to end (scheduler -> get_stats -> /metrics
render). The scheduler-level drills (DRR splits, quota preemption,
flood step-gaps, quota_thrash hysteresis) live in
tests/core/test_sched_qos.py where they run without a model."""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=128, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_qos")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path) -> LLMEngine:
    return LLMEngine(EngineArgs(
        model=path, dtype="float32", block_size=4,
        num_gpu_blocks_override=128, max_model_len=128,
        max_num_batched_tokens=32, max_num_seqs=8,
        skip_tokenizer_init=True).create_engine_config())


# Two tenants, adversarially shaped: a flood tenant with long prompts
# and greedy max_tokens against short interactive turns.
WORK = [
    ("flood-0", "flood", [3 + (i % 90) for i in range(70)], 12),
    ("chat-0", "chat", [5, 9, 2, 44], 8),
    ("flood-1", "flood", [7 + (i % 80) for i in range(60)], 12),
    ("chat-1", "chat", [91, 17, 3], 8),
    ("anon-0", None, [12, 13, 14, 15, 16], 6),
]


def run(engine):
    for req_id, tenant, prompt, max_tokens in WORK:
        engine.add_request(
            req_id, list(prompt),
            SamplingParams(temperature=0.0, max_tokens=max_tokens,
                           ignore_eos=True),
            tenant=tenant)
    done = {}
    for _ in range(500):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    return {k: list(v.outputs[0].token_ids) for k, v in done.items()}


def test_qos_off_default_and_on_token_parity(checkpoint, monkeypatch):
    # OFF (default env): no QoS state anywhere in the stats plane.
    engine = make_engine(checkpoint)
    baseline = run(engine)
    stats = engine.get_stats()
    assert "tenants" not in stats
    engine.shutdown()

    # ON: same traffic, token-identical greedy outputs, and the
    # per-tenant accounting reaches get_stats and the /metrics render.
    monkeypatch.setenv("VDT_QOS", "1")
    engine = make_engine(checkpoint)
    routed = run(engine)
    assert routed == baseline
    tenants = engine.get_stats()["tenants"]
    total_prompt = {t: 0 for t in ("flood", "chat", "_anon")}
    for _, tenant, prompt, max_tokens in WORK:
        total_prompt[tenant or "_anon"] += len(prompt) + max_tokens - 1
    for key, want in total_prompt.items():
        assert tenants[key]["granted_tokens"] >= want, (key, tenants)
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    text = render_metrics(engine.get_stats())
    assert 'vdt:tenant_granted_tokens_total{tenant="flood"}' in text
    assert 'vdt:tenant_kv_blocks{tenant="chat"}' in text
    engine.shutdown()
