"""Async scheduling: the depth-2 in-flight batch pipeline on the
non-PP path (reference: the V1 --async-scheduling overlap of host
scheduling/input-prep with device execution).

Acceptance contract: the async path is token-identical to sync under
greedy sampling (the same contract crash-replay locked in PR 2), abort
and preemption stay safe with batches in flight, the zero-token-grant
contract extends to the async queue, and incompatible features force
sync (config-level auto-off + per-request fallback)."""

import asyncio

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_async")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


PROMPTS = [
    [3, 17, 92, 45, 8],
    [7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7],
    [120, 44],
    [1, 2, 3, 4, 5, 6],
]

_TAG = [0]


def run(engine, prompts, sps):
    _TAG[0] += 1
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"as{_TAG[0]}-{i}", p, sp)
    done = {}
    for _ in range(500):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


def greedy_sps(n, max_tokens=8, **kw):
    return [SamplingParams(temperature=0.0, max_tokens=max_tokens,
                           ignore_eos=True, **kw) for _ in range(n)]


def core_of(engine):
    return engine.engine_core.engine_core


# ---------------------------------------------------------------------------
# Greedy token parity + overlap actually happening
# ---------------------------------------------------------------------------

def test_async_greedy_matches_sync(checkpoint):
    baseline = run(make_engine(checkpoint), PROMPTS, greedy_sps(4))
    engine = make_engine(checkpoint, async_scheduling=True)
    core = core_of(engine)
    assert core.async_scheduling
    assert core.batch_queue is not None and core.batch_queue_size == 2
    got = run(engine, PROMPTS, greedy_sps(4))
    assert got == baseline
    # The pipeline really ran ahead: >= 2 batches in flight at once and
    # speculative grants were issued.
    assert core.max_concurrent_batches >= 2
    assert core.scheduler.num_async_spec_grants > 0
    assert core.steps_overlapped > 0
    # No pages leaked through the pending-retire path.
    pool = core.scheduler.kv_cache_manager.block_pool
    assert pool.get_num_free_blocks() == pool.num_blocks
    assert not core.scheduler._finished_pending_retire
    assert not core.scheduler.in_flight_req_ids


def test_async_chunked_prefill_matches_sync(checkpoint):
    prompt = [int(x) for x in
              np.random.default_rng(0).integers(2, 127, size=40)]
    baseline = run(make_engine(checkpoint, max_num_batched_tokens=16),
                   [prompt], greedy_sps(1, max_tokens=5))
    got = run(make_engine(checkpoint, max_num_batched_tokens=16,
                          async_scheduling=True),
              [prompt], greedy_sps(1, max_tokens=5))
    assert got == baseline


def test_async_stop_token_lags_but_truncates_exactly(checkpoint):
    """EOS/stop detection lags one step under async (the over-issued
    position's sample is discarded); the emitted stream must still stop
    on exactly the same token as sync."""
    sync = make_engine(checkpoint)
    base = run(sync, [PROMPTS[0]], greedy_sps(1, max_tokens=10))[0]
    stop_tok = base[4]
    sps = [SamplingParams(temperature=0.0, max_tokens=10, ignore_eos=True,
                          stop_token_ids=[stop_tok])]
    expect = run(sync, [PROMPTS[0]], sps)
    got = run(make_engine(checkpoint, async_scheduling=True),
              [PROMPTS[0]], sps)
    assert got == expect
    # Truncated at the FIRST occurrence of the stop token, exactly.
    assert got[0] == base[:base.index(stop_tok) + 1]


def test_async_mixed_sync_fallback_requests(checkpoint):
    """A batch mixing plain greedy rows (chained device-to-device) with
    requests that need host-synchronous sampling (penalties) stays
    token-identical to the sync engine for every stream."""
    sps = [
        SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True),
        SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True,
                       repetition_penalty=1.3),
        SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True,
                       presence_penalty=0.8),
        SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True),
    ]
    baseline = run(make_engine(checkpoint), PROMPTS, sps)
    got = run(make_engine(checkpoint, async_scheduling=True), PROMPTS, sps)
    assert got == baseline


def test_async_sync_only_requests_never_speculate(checkpoint):
    """A workload of ONLY host-synchronous requests degrades to
    PP-style one-batch-at-a-time scheduling: no speculative grants."""
    engine = make_engine(checkpoint, async_scheduling=True)
    sps = [SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True,
                          repetition_penalty=1.2) for _ in range(2)]
    run(engine, PROMPTS[:2], sps)
    core = core_of(engine)
    assert core.scheduler.num_async_spec_grants == 0


# ---------------------------------------------------------------------------
# Abort / preemption with batches in flight
# ---------------------------------------------------------------------------

def test_async_abort_in_flight_is_safe(checkpoint):
    engine = make_engine(checkpoint, async_scheduling=True)
    core = core_of(engine)
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    for i, p in enumerate(PROMPTS):
        engine.add_request(f"abort-{i}", p, sp)
    aborted = None
    for _ in range(50):
        engine.step()
        if core.scheduler.in_flight_req_ids:
            aborted = next(iter(core.scheduler.in_flight_req_ids))
            engine.abort_request([aborted])
            break
    assert aborted is not None
    done = set()
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done.add(out.request_id)
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    assert done == {f"abort-{i}" for i in range(4)} - {aborted}
    assert not core.scheduler._deferred_finishes
    assert not core.scheduler._finished_pending_retire
    pool = core.scheduler.kv_cache_manager.block_pool
    assert pool.get_num_free_blocks() == pool.num_blocks


def test_async_preemption_with_batch_in_flight(checkpoint):
    """A page pool too small for the full batch forces preemption while
    the pipeline is active: in-flight requests are never evicted (their
    pages are being written), and the greedy output still matches an
    ample-pool baseline exactly (preempted requests recompute)."""
    prompts = [[i * 11 + j for j in range(1, 9)] for i in range(3)]
    baseline = run(make_engine(checkpoint), prompts,
                   greedy_sps(3, max_tokens=12))
    # 12 pages x 4 tokens = 48-token capacity < 3 x (8 prompt + 12 out)
    # = 60 tokens needed -> at least one preemption is forced.
    engine = make_engine(checkpoint, async_scheduling=True,
                         num_gpu_blocks_override=12)
    got = run(engine, prompts, greedy_sps(3, max_tokens=12))
    assert got == baseline
    core = core_of(engine)
    assert core.scheduler.num_preemptions >= 1
    pool = core.scheduler.kv_cache_manager.block_pool
    assert pool.get_num_free_blocks() == pool.num_blocks


# ---------------------------------------------------------------------------
# Zero-token-grant contract (async sibling of
# test_zero_token_dispatch_does_no_device_work)
# ---------------------------------------------------------------------------

def test_async_zero_token_dispatch_does_no_device_work(checkpoint):
    """The async queue keeps the PP queue's contract: an empty grant
    resolves entirely at dispatch time (no device work that could
    interleave with in-flight speculative batches)."""
    from vllm_distributed_tpu.core.sched.output import SchedulerOutput
    engine = make_engine(checkpoint, async_scheduling=True)
    core = core_of(engine)
    assert core.batch_queue is not None  # the async queue is active
    runner = core.executor.worker.model_runner
    handle = runner.dispatch_model(SchedulerOutput(async_scheduled=True))
    assert "ready" in handle and "dev" not in handle
    out = runner.wait_model(handle)
    assert not out.sampled_token_ids


# ---------------------------------------------------------------------------
# Auto-fallback matrix: incompatible features force sync
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("overrides", [
    dict(speculative_method="ngram", num_speculative_tokens=3),
    dict(pipeline_parallel_size=2),
    dict(num_scheduler_steps=4),
    dict(kv_connector="SharedStorageConnector", kv_role="kv_both"),
    dict(token_parallel_size=2),
    dict(num_hosts=2),
])
def test_async_auto_off_matrix(overrides):
    """Config-level auto-off: features whose step contract conflicts
    with run-ahead grants force async_scheduling back to False at
    config normalization (spec decode / PP / multi-step / KV connector
    / token parallelism / multi-host). Build only the config (no
    engine): normalization happens in EngineConfig.__post_init__."""
    from vllm_distributed_tpu.config import (EngineConfig, KVTransferConfig,
                                             ModelConfig, ParallelConfig,
                                             SchedulerConfig,
                                             SpeculativeConfig)
    config = EngineConfig(
        model_config=ModelConfig(model="dummy", max_model_len=64),
        scheduler_config=SchedulerConfig(
            async_scheduling=True,
            num_scheduler_steps=overrides.get("num_scheduler_steps", 1),
            max_model_len=64),
        parallel_config=ParallelConfig(
            pipeline_parallel_size=overrides.get(
                "pipeline_parallel_size", 1),
            token_parallel_size=overrides.get("token_parallel_size", 1),
            num_hosts=overrides.get("num_hosts", 1)),
        speculative_config=SpeculativeConfig(
            method=overrides.get("speculative_method"),
            num_speculative_tokens=overrides.get(
                "num_speculative_tokens", 0)),
        kv_transfer_config=KVTransferConfig(
            kv_connector=overrides.get("kv_connector"),
            kv_role=overrides.get("kv_role")),
    )
    assert config.scheduler_config.async_scheduling is False


def test_async_stays_on_for_plain_config():
    from vllm_distributed_tpu.config import (EngineConfig, ModelConfig,
                                             SchedulerConfig)
    config = EngineConfig(
        model_config=ModelConfig(model="dummy", max_model_len=64),
        scheduler_config=SchedulerConfig(async_scheduling=True,
                                         max_model_len=64),
    )
    assert config.scheduler_config.async_scheduling is True


# ---------------------------------------------------------------------------
# CI smoke: overlap through AsyncLLM on a toy model (tier-1-safe)
# ---------------------------------------------------------------------------

def _make_async_llm(checkpoint, **overrides):
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    args = dict(model=checkpoint, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True, async_scheduling=True,
                restart_backoff_base_s=0.01, restart_backoff_max_s=0.05)
    args.update(overrides)
    return AsyncLLM(EngineArgs(**args).create_engine_config(),
                    load_tokenizer=False)


async def _collect_one(engine, prompt, request_id, max_tokens=16,
                       arm_fault=None):
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    final = None
    got_first = False
    async for out in engine.generate(prompt, sp, request_id=request_id):
        if not got_first:
            got_first = True
            if arm_fault:
                arm_fault()
        final = out
    assert final is not None and final.finished
    return final.outputs[0].token_ids


def test_asyncllm_overlap_smoke(checkpoint):
    """CPU smoke for the tentpole: a toy model served through AsyncLLM
    must actually keep >= 2 batches in flight (max_concurrent_batches),
    proving the overlap engages outside hand-driven step() loops."""
    engine = _make_async_llm(checkpoint)

    async def go():
        return await asyncio.gather(*[
            _collect_one(engine, PROMPTS[i], f"smoke-{i}")
            for i in range(4)
        ])

    try:
        outs = asyncio.run(asyncio.wait_for(go(), timeout=120.0))
        assert all(len(o) == 16 for o in outs)
        core = engine.core.core  # BackgroundEngineCore -> EngineCore
        assert core.max_concurrent_batches >= 2
        stats = core.get_stats()
        assert stats["decode_overlap_frac"] > 0
        assert stats["step_host_gap_seconds"]["count"] > 0
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# Faults: the crash-recovery ladder still fires with batches in flight
# ---------------------------------------------------------------------------

@pytest.fixture
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


@pytest.mark.faults
def test_reconcile_stall_death_recovers_mid_pipeline(checkpoint,
                                                     _clean_faults):
    """step.reconcile_stall (raise mode) kills the core at the batch
    queue's reconcile point — i.e. with speculative batches in flight.
    The PR 1/2 ladder (health monitor -> supervisor respawn -> journal
    replay) must recover token-identically."""
    base_engine = _make_async_llm(checkpoint)
    try:
        baseline = asyncio.run(asyncio.wait_for(
            _collect_one(base_engine, PROMPTS[0], "rs-base",
                         max_tokens=20), timeout=120.0))
    finally:
        base_engine.shutdown()

    engine = _make_async_llm(checkpoint)
    try:
        resumed = asyncio.run(asyncio.wait_for(
            _collect_one(
                engine, PROMPTS[0], "rs-die", max_tokens=20,
                arm_fault=lambda: fi.inject("step.reconcile_stall",
                                            max_fires=1)),
            timeout=180.0))
        assert resumed == baseline
        assert not engine.errored
        stats = engine.output_processor.stats
        assert stats.num_engine_deaths >= 1
        assert stats.num_requests_replayed >= 1
        assert fi.counters().get("step.reconcile_stall", 0) >= 1
    finally:
        engine.shutdown()


@pytest.mark.faults
def test_reconcile_stall_delay_is_survived(checkpoint, _clean_faults):
    """Delay mode: a host stall between device completion and
    reconciliation is absorbed (paced, not fatal) — the stream
    completes and the engine stays healthy."""
    engine = _make_async_llm(checkpoint)
    try:
        fi.inject("step.reconcile_stall", rate=0.25, delay_s=0.02)
        out = asyncio.run(asyncio.wait_for(
            _collect_one(engine, PROMPTS[0], "rs-delay", max_tokens=12),
            timeout=120.0))
        assert len(out) == 12
        assert not engine.errored
        assert engine.output_processor.stats.num_engine_deaths == 0
        assert fi.counters().get("step.reconcile_stall", 0) >= 1
    finally:
        engine.shutdown()
