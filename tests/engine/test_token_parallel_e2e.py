"""End-to-end token parallelism: greedy decode through the full engine on
a token-parallel mesh must match the single-device baseline exactly.

TPU analogue of the fork's TKNP inference benchmarks / tests
(examples/offline_inference/TKNP/): the KV cache page axis is sharded
over the ``token`` mesh axis, the scheduler assigns each request's pages
to one rank's partition, and attention merges per-rank outputs with a
psum. Runs on the 8-device virtual CPU mesh from tests/conftest.py.
"""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_tknp")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
    [11, 12, 13, 14, 15, 16],
    [7, 44, 101, 13, 2, 64, 99],
]


def run(engine, prompts, tag, max_tokens=8):
    sps = [SamplingParams(temperature=0.0, max_tokens=max_tokens,
                          ignore_eos=True) for _ in prompts]
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


@pytest.fixture(scope="module")
def baseline(checkpoint):
    return run(make_engine(checkpoint), PROMPTS, "base")


def test_tknp2_matches_baseline(checkpoint, baseline):
    got = run(make_engine(checkpoint, token_parallel_size=2), PROMPTS,
              "tknp2")
    assert got == baseline


def test_tknp2_tp2_matches_baseline(checkpoint, baseline):
    got = run(make_engine(checkpoint, token_parallel_size=2,
                          tensor_parallel_size=2), PROMPTS, "tknp2tp2")
    assert got == baseline


def test_tknp4_matches_baseline(checkpoint, baseline):
    got = run(make_engine(checkpoint, token_parallel_size=4), PROMPTS,
              "tknp4")
    assert got == baseline


def test_tknp2_pallas_matches_baseline(checkpoint, baseline, monkeypatch):
    """Token parallelism through the Pallas kernels (interpret mode):
    per-rank seq lists + local page tables + the in-place KV-write runs."""
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    got = run(make_engine(checkpoint, token_parallel_size=2,
                          max_num_batched_tokens=32), PROMPTS, "tknp2pl")
    assert got == baseline


def test_tknp2_chunked_prefill_matches_baseline(checkpoint, baseline):
    """Chunked prefill across token-parallel ranks (small step budget
    forces multi-chunk prefill)."""
    got = run(make_engine(checkpoint, token_parallel_size=2,
                          max_num_batched_tokens=8), PROMPTS, "tknp2cp")
    assert got == baseline
