"""End-to-end pipeline parallelism: greedy decode through the staged
runner must match the single-program baseline exactly (model: reference
tests/distributed/test_pipeline_parallel.py comparing configs)."""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    # 3 layers: pp=2 gets an UNEVEN split (2+1), exercising remainder
    # handling in partition_layers.
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=3, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_pp")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


PROMPTS = [
    [3, 17, 92, 45, 8],
    [5, 9, 33, 71],
    [11, 12, 13, 14, 15, 16],
]


def run(engine, prompts, tag, max_tokens=8):
    sps = [SamplingParams(temperature=0.0, max_tokens=max_tokens,
                          ignore_eos=True) for _ in prompts]
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    order = sorted(done, key=lambda s: int(s.split("-")[-1]))
    return [done[k].outputs[0].token_ids for k in order]


@pytest.fixture(scope="module")
def baseline(checkpoint):
    return run(make_engine(checkpoint), PROMPTS, "base")


def test_pp2_matches_baseline(checkpoint, baseline):
    got = run(make_engine(checkpoint, pipeline_parallel_size=2), PROMPTS,
              "pp2")
    assert got == baseline


def test_pp2_tp2_matches_baseline(checkpoint, baseline):
    got = run(make_engine(checkpoint, pipeline_parallel_size=2,
                          tensor_parallel_size=2), PROMPTS, "pp2tp2")
    assert got == baseline


def test_pp2_tp2_dp2_matches_baseline(checkpoint, baseline):
    """The full 8-device dp x pp x tp mesh."""
    got = run(make_engine(checkpoint, pipeline_parallel_size=2,
                          tensor_parallel_size=2, data_parallel_size=2),
              PROMPTS, "pp2tp2dp2")
    assert got == baseline


def test_pp3_uneven_layers_matches_baseline(checkpoint, baseline):
    """pp=3 over 3 layers: one layer per stage."""
    got = run(make_engine(checkpoint, pipeline_parallel_size=3), PROMPTS,
              "pp3")
    assert got == baseline


def test_pp2_pallas_matches_baseline(checkpoint, baseline, monkeypatch):
    monkeypatch.setenv("VDT_ATTENTION_BACKEND", "pallas")
    got = run(make_engine(checkpoint, pipeline_parallel_size=2,
                          max_num_batched_tokens=32), PROMPTS, "pp2pl")
    assert got == baseline


def test_pp2_chunked_prefill_matches_baseline(checkpoint, baseline):
    got = run(make_engine(checkpoint, pipeline_parallel_size=2,
                          max_num_batched_tokens=8), PROMPTS, "pp2cp")
    assert got == baseline


def test_pp2_spec_decode_matches_baseline(checkpoint, baseline):
    got = run(make_engine(checkpoint, pipeline_parallel_size=2,
                          speculative_method="ngram",
                          num_speculative_tokens=3), PROMPTS, "pp2spec")
    assert got == baseline


def test_pp2_batch_queue_overlaps_microbatches(checkpoint):
    """The PP engine core must keep >1 batch in flight (reference:
    core.py:242 step_with_batch_queue): with a token budget that fits
    only half the requests per batch, the two halves pipeline — and
    the interleaved decode still matches the sequential baseline."""
    prompts = [[i * 7 + j for j in range(1, 9)] for i in range(4)]
    baseline = run(make_engine(checkpoint), prompts, "bq-base")

    engine = make_engine(checkpoint, pipeline_parallel_size=2,
                         max_num_batched_tokens=16)
    core = engine.engine_core.engine_core
    assert core.batch_queue is not None
    assert core.batch_queue_size == 2
    got = run(engine, prompts, "bq")
    assert got == baseline
    # The load was split into >=2 concurrent microbatches at some point
    # (prefill splits 4x8 tokens over a 16-token budget, decode then
    # alternates the two halves through the queue).
    assert core.max_concurrent_batches == 2


def test_pp2_batch_queue_abort_in_flight_is_safe(checkpoint):
    """Aborting a request while its batch is dispatched defers the
    finish until the batch retires; other requests are unaffected."""
    prompts = [[i * 7 + j for j in range(1, 9)] for i in range(4)]
    engine = make_engine(checkpoint, pipeline_parallel_size=2,
                         max_num_batched_tokens=16)
    core = engine.engine_core.engine_core
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    for i, p in enumerate(prompts):
        engine.add_request(f"ab-{i}", p, sp)
    # Step until at least one batch is in flight, then abort a request
    # that is part of it.
    aborted = None
    for _ in range(50):
        engine.step()
        if core.scheduler.in_flight_req_ids:
            aborted = next(iter(core.scheduler.in_flight_req_ids))
            engine.abort_request([aborted])
            break
    assert aborted is not None
    done = set()
    for _ in range(300):
        for out in engine.step():
            if out.finished:
                done.add(out.request_id)
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    assert done == {f"ab-{i}" for i in range(4)} - {aborted}
    assert not core.scheduler._deferred_finishes
    # All pages returned (no leak from the deferred finish).
    pool = core.scheduler.kv_cache_manager.block_pool
    assert pool.get_num_free_blocks() == pool.num_blocks
