"""AsyncLLM streaming semantics (reference: tests/v1/engine/
test_async_llm.py — generate streams, cancellation aborts upstream)."""

import asyncio

import pytest

from tests.engine.test_llm_engine import checkpoint, hf_greedy  # noqa: F401
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.async_llm import AsyncLLM
from vllm_distributed_tpu.sampling_params import SamplingParams


def make_async_engine(path, **overrides) -> AsyncLLM:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=64,
                max_num_batched_tokens=64, max_num_seqs=8)
    args.update(overrides)
    return AsyncLLM(EngineArgs(**args).create_engine_config(),
                    load_tokenizer=False)


def test_async_generate_streams_and_matches_hf(checkpoint):
    path, hf = checkpoint
    engine = make_async_engine(path)

    async def run():
        prompt = [3, 17, 92, 45, 8]
        sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
        seen = []
        async for out in engine.generate(prompt, sp, request_id="a1"):
            seen.append(list(out.outputs[0].token_ids))
        return seen

    try:
        seen = asyncio.run(run())
    finally:
        engine.shutdown()
    _, hf_model = checkpoint
    want = hf_greedy(hf_model, [3, 17, 92, 45, 8], 8)
    assert seen[-1] == want
    assert len(seen) >= 2, "outputs must stream incrementally"
    for a, b in zip(seen, seen[1:]):
        assert b[:len(a)] == a, "streamed outputs must be monotone"


def test_async_concurrent_requests(checkpoint):
    path, hf = checkpoint
    engine = make_async_engine(path)
    prompts = [[3, 17, 92, 45, 8], [5, 9, 101], [120, 44]]

    async def one(i, prompt):
        sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
        final = None
        async for out in engine.generate(prompt, sp, request_id=f"c{i}"):
            final = out
        return final.outputs[0].token_ids

    async def run():
        return await asyncio.gather(
            *(one(i, p) for i, p in enumerate(prompts)))

    try:
        results = asyncio.run(run())
    finally:
        engine.shutdown()
    for prompt, got in zip(prompts, results):
        assert got == hf_greedy(hf, prompt, 6)


def test_async_cancellation_aborts(checkpoint):
    path, _ = checkpoint
    engine = make_async_engine(path)

    async def run():
        sp = SamplingParams(temperature=0.0, max_tokens=40,
                            ignore_eos=True)
        gen = engine.generate([7, 8, 9], sp, request_id="cancel-me")
        async for _ in gen:
            break  # consume one output then drop the stream
        await gen.aclose()
        # Give the abort a moment to reach the core thread.
        for _ in range(100):
            if not engine.core.core.has_unfinished_requests():
                return True
            await asyncio.sleep(0.05)
        return False

    try:
        aborted = asyncio.run(run())
    finally:
        engine.shutdown()
    assert aborted, "cancelled stream must abort the core request"
    assert not engine.request_queues


def test_async_mp_core(checkpoint, monkeypatch):
    monkeypatch.setenv("VDT_PLATFORM", "cpu")
    monkeypatch.setenv("VDT_RPC_TIMEOUT", "300")
    path, hf = checkpoint
    engine = make_async_engine(path, multiprocess_engine_core=True)

    async def run():
        sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
        final = None
        async for out in engine.generate([3, 17, 92, 45, 8], sp,
                                         request_id="mp1"):
            final = out
        stats = await engine.get_stats()
        return final.outputs[0].token_ids, stats

    try:
        got, stats = asyncio.run(run())
    finally:
        engine.shutdown()
    assert got == hf_greedy(hf, [3, 17, 92, 45, 8], 6)
    assert isinstance(stats, dict) and "num_running_reqs" in stats
