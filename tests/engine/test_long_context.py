"""Long-context proof at 128k+ tokens (VERDICT r4 #6; reference
capability: dual_chunk_flash_attn.py serves 1M-token contexts).

A 131k-token prompt runs through the real engine stack — chunked
prefill over the bucket lattice, paged KV across ~8200 pages, decode
afterwards — asserting the compile lattice stays bounded (no
recompile storm as kv_len grows: shapes key on the TOKEN bucket, never
on sequence length) and recording TTFT. The model is deliberately tiny
(1 layer) so the quadratic attention cost, not the machinery, is the
only scale factor on this CPU host.
"""

import time

import numpy as np
import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams

CTX = 131072


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=256, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=1,
                      num_attention_heads=2, num_key_value_heads=1,
                      max_position_embeddings=CTX + 1024,
                      rope_theta=500000.0, eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_128k")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


@pytest.mark.slow  # ~25 min of quadratic attention on a 1-core CPU box
def test_128k_prompt_through_the_lattice(ckpt):
    engine = LLMEngine(EngineArgs(
        model=ckpt, dtype="float32", block_size=16,
        num_gpu_blocks_override=CTX // 16 + 64,
        max_model_len=CTX,
        max_num_batched_tokens=8192, max_num_seqs=4,
        enable_prefix_caching=False,
        skip_tokenizer_init=True).create_engine_config())
    runner = engine.engine_core.engine_core.executor.worker.model_runner

    rng = np.random.default_rng(0)
    prompt = [int(t) for t in rng.integers(2, 250, size=CTX - 64)]
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    engine.add_request("long-0", prompt, sp)

    compiled_before = len(runner._compiled_shapes)
    t0 = time.perf_counter()
    ttft = None
    tokens = []
    # Budget: chunked prefill is ~16 x 8192-token steps of a 1-layer
    # model; a recompile storm or O(len^2)-per-step bug would blow far
    # past this. (Measured: ~25 min on a contended 1-core CPU host.)
    deadline = t0 + 3600
    while engine.has_unfinished_requests():
        assert time.perf_counter() < deadline, (
            "128k prefill exceeded the wall-clock budget")
        for out in engine.step():
            if out.outputs[0].token_ids and ttft is None:
                ttft = time.perf_counter() - t0
            if out.finished:
                tokens = out.outputs[0].token_ids
    assert len(tokens) == 4
    assert ttft is not None
    # The compile lattice must NOT grow with sequence length: the
    # handful of new (T, R) buckets this request touches is all that
    # compiles (shapes key on token buckets, kv_len stays dynamic).
    compiled_after = len(runner._compiled_shapes)
    assert compiled_after - compiled_before <= 8, (
        runner._compiled_shapes)
    print(f"TTFT@{CTX - 64} tokens: {ttft:.1f}s, "
          f"{compiled_after - compiled_before} new graphs")
