import numpy as np
import jax
import jax.numpy as jnp
from tests.engine.test_llm_engine import checkpoint, make_engine
from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.sampling_params import SamplingParams


def get_runner(engine):
    ex = engine.engine_core.model_executor if hasattr(
        engine.engine_core, "model_executor") else None
    if ex is None:
        for a in dir(engine.engine_core):
            o = getattr(engine.engine_core, a)
            if hasattr(o, "driver_worker") or "Executor" in type(o).__name__:
                ex = o
                break
    w = getattr(ex, "driver_worker", None) or getattr(ex, "worker", None)
    return w.model_runner


def test_compare_forward(checkpoint, monkeypatch):
    path, hf = checkpoint
    prompt = [3, 17, 92, 45, 8]
    n = len(prompt)
    hid = {}
    for backend in ["xla", "pallas"]:
        monkeypatch.setenv("VDT_ATTENTION_BACKEND", backend)
        engine = make_engine(path, max_num_batched_tokens=16)
        r = get_runner(engine)
        ps = r.page_size
        T = 24  # same as runner: bucket16 + qtile 8
        max_q = 8
        token_ids = np.zeros((T,), np.int32); token_ids[:n] = prompt
        positions = np.zeros((T,), np.int32); positions[:n] = np.arange(n)
        req_idx = np.zeros((T,), np.int32)
        slot = np.full((T,), -1, np.int32)
        # pages 1..2 allocated to request row 0 (avoid page 0 to catch garbage)
        bt = np.zeros((r.max_num_reqs, r.max_pages_per_req), np.int32)
        bt[0, 0] = 1; bt[0, 1] = 2
        slot[:n] = bt[0, np.arange(n) // ps] * ps + np.arange(n) % ps
        seq_info = np.zeros((r.max_num_reqs, 4), np.int32)
        seq_info[0] = (0, n, n, 0)
        kv_runs = []
        consumed = 0
        while consumed < n:
            p = consumed
            off = p % ps
            run_len = min(ps - off, n - consumed)
            kv_runs.append((int(bt[0, p // ps]), off, consumed - off + ps, run_len))
            consumed += run_len
        G = 8
        kvr = np.zeros((G, 4), np.int32)
        kvr[:len(kv_runs)] = kv_runs
        batch = AttentionBatch(
            req_idx=jnp.asarray(req_idx), positions=jnp.asarray(positions),
            slot_mapping=jnp.asarray(slot), block_tables=jnp.asarray(bt),
            seq_lens=jnp.asarray(np.zeros((r.max_num_reqs,), np.int32)),
            seq_info=jnp.asarray(seq_info),
            num_seqs=jnp.asarray([1], np.int32),
            kv_runs=jnp.asarray(kvr),
            num_kv_runs=jnp.asarray([len(kv_runs)], np.int32),
            max_q=max_q)
        with r.mesh:
            hidden, kv = r.model.forward(r.params, r.kv_caches,
                                         jnp.asarray(token_ids), batch)
        hid[backend] = np.asarray(hidden)[:n]
        # also check the cache contents written for layer 0
        k = np.asarray(kv["k"]) if isinstance(kv, dict) else None
        print(backend, "hidden[:,0:3]:\n", hid[backend][:, :3])
        print(backend, "k cache page1 layer0 head0 row0:", k[0, 1, 0, 0, :4])
    diff = np.abs(hid["xla"] - hid["pallas"]).max()
    print("max diff:", diff)
    assert diff < 1e-3
