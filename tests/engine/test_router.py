"""Cluster routing tier (engine/router.py): prefix-affinity placement,
SLO-aware spillover, stale-stats degradation (the ``router.stale_stats``
fault drill), failover re-homing, the VDT_ROUTER kill switch, and the
vdt:router_*/vdt:dp_replica_load metric families."""

import time

import pytest

from tests.conftest import make_config
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine import dp_client as dp_mod
from vllm_distributed_tpu.engine.core_client import (EngineCoreClient,
                                                     EngineDeadError)
from vllm_distributed_tpu.engine.dp_client import DPEngineClient
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.faults

BLOCK = 4  # make_config block_size


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


class _StubReplica(EngineCoreClient):
    """Scriptable replica exposing the in-process stats surface the
    router refreshes from (``engine_core`` marker + call_utility)."""

    def __init__(self, config) -> None:
        self.config = config
        self.engine_core = object()  # marks the inproc refresh path
        self.stats = {"num_running_reqs": 0, "num_waiting_reqs": 0,
                      "kv_cache_usage": 0.0}
        self.added: list[EngineCoreRequest] = []
        self.outputs: list[list[EngineCoreOutput]] = []
        self.dead = False

    def _check(self) -> None:
        if self.dead:
            raise EngineDeadError("stub replica is dead")

    def add_request(self, request: EngineCoreRequest) -> None:
        self._check()
        self.added.append(request)

    def abort_requests(self, request_ids: list[str]) -> None:
        self._check()

    def recv_outputs(self, timeout_ms: int):
        self._check()
        return self.outputs.pop(0) if self.outputs else None

    def call_utility(self, method: str, *args):
        self._check()
        assert method == "get_stats"
        return dict(self.stats)

    def get_stats(self) -> dict:
        return dict(self.stats)

    def restart(self) -> None:
        self.dead = False

    def shutdown(self) -> None:
        pass


def _dp2(monkeypatch, **env) -> DPEngineClient:
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    config = make_config()
    config.parallel_config.data_parallel_size = 2
    config.fault_tolerance_config.replica_probe_interval_s = 3600
    monkeypatch.setattr(dp_mod, "SyncMPClient", _StubReplica)
    return DPEngineClient(config, force_mp=True)


def _req(rid: str, prompt: list[int],
         max_tokens: int = 8) -> EngineCoreRequest:
    return EngineCoreRequest(
        request_id=rid, prompt_token_ids=list(prompt),
        sampling_params=SamplingParams(temperature=0.0,
                                       max_tokens=max_tokens))


SESSION = list(range(100, 100 + 3 * BLOCK))  # 3 full pages


def _finish(dp, rid: str, tokens: list[int]) -> None:
    owner = dp._owner[rid]
    dp.clients[owner].outputs.append([EngineCoreOutput(
        req_id=rid, new_token_ids=tokens, finish_reason="stop")])
    dp.recv_outputs(timeout_ms=10)


# ---------------------------------------------------------------------------
# Prefix affinity
# ---------------------------------------------------------------------------

def test_session_turn_routes_back_to_home(monkeypatch):
    dp = _dp2(monkeypatch)
    assert dp.router is not None
    dp.add_request(_req("t1", SESSION))
    home = dp._owner["t1"]
    _finish(dp, "t1", [7, 8, 9, 10])
    # Next turn: previous prompt + generated + new user tokens. The
    # 4 generated tokens complete page 4, which on_finish indexed.
    turn2 = SESSION + [7, 8, 9, 10] + [55, 56]
    dp.add_request(_req("t2", turn2))
    assert dp._owner["t2"] == home
    assert dp.router.affinity_hits >= 1


def test_distinct_prompts_balance_across_replicas(monkeypatch):
    dp = _dp2(monkeypatch)
    for i in range(4):
        dp.add_request(_req(f"r{i}", [i * 50 + j for j in range(8)]))
    assert dp.request_counts() == [2, 2]


def test_pressured_home_spills_over(monkeypatch):
    dp = _dp2(monkeypatch, VDT_ROUTER_STATS_TTL_S="0")
    dp.add_request(_req("t1", SESSION))
    home = dp._owner["t1"]
    _finish(dp, "t1", [7])
    # The home replica's KV pool pressure crosses the spill threshold
    # (but not the eviction-decay one): affinity credit is forfeited
    # and the session turn spills to the healthy replica.
    dp.clients[home].stats["kv_cache_usage"] = 0.90
    dp.add_request(_req("t2", SESSION + [200, 201]))
    assert dp._owner["t2"] == 1 - home
    assert dp.router.spillovers >= 1


def test_eviction_pressure_halves_residency_index(monkeypatch):
    dp = _dp2(monkeypatch)
    dp.add_request(_req("t1", SESSION))
    home = dp._owner["t1"]
    before = len(dp.router._residency[home])
    assert before >= 3
    # The replica reports near-saturation: half our hints about it are
    # presumed evicted and dropped (oldest first).
    dp.router.observe_stats(home, {"num_running_reqs": 1,
                                   "kv_cache_usage": 0.99})
    assert len(dp.router._residency[home]) == before - before // 2


def test_mm_requests_skip_affinity(monkeypatch):
    dp = _dp2(monkeypatch)
    req = _req("mm", SESSION)
    req.mm_inputs = [object()]
    assert dp.router.request_hashes(req) == []


# ---------------------------------------------------------------------------
# Stale-stats degradation (router.stale_stats fault drill)
# ---------------------------------------------------------------------------

def test_stale_stats_degrades_to_load_balancing(monkeypatch):
    dp = _dp2(monkeypatch, VDT_ROUTER_STATS_TTL_S="0",
              VDT_ROUTER_STALE_S="0.05")
    # Seed affinity: a finished session lives on one replica.
    dp.add_request(_req("t1", SESSION))
    home = dp._owner["t1"]
    _finish(dp, "t1", [7])
    # Healthy signals: same-prefix turns herd onto the home replica.
    dp.add_request(_req("warm", SESSION + [1, 2]))
    assert dp._owner["warm"] == home
    dp.abort_requests(["warm"])
    # Drill: freeze the signal plane and let every snapshot expire.
    fi.inject("router.stale_stats")
    time.sleep(0.08)
    for i in range(4):
        dp.add_request(_req(f"s{i}", SESSION + [10 + i]))
    # Degraded routing spreads by live count instead of herding the
    # whole session wave onto the (blind) home replica.
    assert dp.request_counts() == [2, 2]
    assert dp.router.stale_degradations >= 4
    assert fi.counters().get("router.stale_stats", 0) >= 1


# ---------------------------------------------------------------------------
# Failover re-homing
# ---------------------------------------------------------------------------

def test_failover_rehomes_session_affinity(monkeypatch):
    dp = _dp2(monkeypatch)
    dp.add_request(_req("a", SESSION, max_tokens=10))
    home = dp._owner["a"]
    survivor = 1 - home
    # Two pages of tokens stream out, then the home replica dies.
    dp.clients[home].outputs.append([EngineCoreOutput(
        req_id="a", new_token_ids=list(range(2 * BLOCK)))])
    dp.recv_outputs(timeout_ms=10)
    dp.clients[home].dead = True
    dp.recv_outputs(timeout_ms=10)
    assert home in dp._down
    # The dead replica's residency index is gone...
    assert len(dp.router._residency[home]) == 0
    # ...and the migrated continuation re-homed its prefix: a new turn
    # over the same session routes to the survivor.
    assert dp._owner["a"] == survivor
    _finish(dp, "a", [3])
    dp.add_request(_req("b", SESSION + list(range(2 * BLOCK))))
    assert dp._owner["b"] == survivor
    assert dp.router.affinity_hits >= 1


# ---------------------------------------------------------------------------
# Kill switch + metrics
# ---------------------------------------------------------------------------

def test_kill_switch_restores_round_robin(monkeypatch):
    dp = _dp2(monkeypatch, VDT_ROUTER="0")
    assert dp.router is None
    # Same-prefix traffic balances by live count exactly like the
    # pre-router balancer (no affinity, no scoring).
    for i in range(4):
        dp.add_request(_req(f"r{i}", SESSION))
    assert dp.request_counts() == [2, 2]
    stats = dp.get_stats()
    assert "router" not in stats
    # The balancer-state gauges render with the router OFF too (they
    # exist to debug either path).
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    text = render_metrics(stats)
    assert 'vdt:dp_replica_load{replica="0"} 2' in text
    assert "vdt:replicas_in_rotation 2" in text
    assert "vdt:router_requests_routed_total" not in text


def test_router_metrics_render(monkeypatch):
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    dp = _dp2(monkeypatch)
    dp.add_request(_req("t1", SESSION))
    _finish(dp, "t1", [7])
    dp.add_request(_req("t2", SESSION + [1, 2]))
    text = render_metrics(dp.get_stats())
    assert "vdt:router_requests_routed_total 2" in text
    assert "vdt:router_affinity_hits_total 1" in text
    assert 'vdt:dp_replica_load{replica="0"}' in text
    assert 'vdt:dp_replica_load{replica="1"}' in text
    assert "vdt:replicas_in_rotation 2" in text
    assert 'vdt:router_prefix_index_entries{replica=' in text


def test_stats_feed_updates_router_snapshots(monkeypatch):
    """The DP stats aggregation path IS the router's passive signal
    feed (the 'existing get_stats RPC' channel)."""
    dp = _dp2(monkeypatch, VDT_ROUTER_STATS_TTL_S="3600")
    assert dp.router._stats_at[0] == float("-inf")
    dp.clients[0].stats["kv_cache_usage"] = 0.5
    dp.get_stats()
    assert dp.router._stats[0]["kv_cache_usage"] == 0.5
    assert dp.router._stats_at[0] > 0


def test_coordinator_honors_router_preference(monkeypatch):
    dp = _dp2(monkeypatch)

    class _Coord:
        def __init__(self):
            self.counts = [0, 0]
            self.healthy = [True, True]

        def route(self, prefer=None):
            i = (prefer if prefer is not None and self.healthy[prefer]
                 else min(range(2), key=self.counts.__getitem__))
            self.counts[i] += 1
            return i

        def report(self, engine, delta):
            self.counts[engine] += delta

        def set_health(self, engine, up, *, clear=False):
            self.healthy[engine] = up
            if clear:
                self.counts[engine] = 0

    dp.coordinator = _Coord()
    dp.add_request(_req("t1", SESSION))
    home = dp._owner["t1"]
    _finish(dp, "t1", [7, 8, 9, 10])
    dp.add_request(_req("t2", SESSION + [7, 8, 9, 10, 1]))
    assert dp._owner["t2"] == home
    assert dp.coordinator.counts[home] == 1


# ---------------------------------------------------------------------------
# Two-stage disagg placement (engine/disagg.py): pool restriction +
# explicit least-loaded mode on route().
# ---------------------------------------------------------------------------

def test_route_pool_restriction_scores_inside_the_pool():
    from vllm_distributed_tpu.engine.router import ReplicaRouter
    router = ReplicaRouter(4, make_config())
    for i in range(4):
        router.observe_stats(i, {"num_running_reqs": 0,
                                 "num_waiting_reqs": 0,
                                 "kv_cache_usage": 0.0})
    # Replica 0 holds the session prefix, but it is outside the pool:
    # the pick must come from {2, 3}, by cost.
    router.on_admit(_req("seed", SESSION), 0)
    req = _req("x", SESSION)
    pick = router.route(req, [0, 5, 3, 1], set(), pool=[2, 3])
    assert pick == 3  # lowest live count inside the pool
    router.on_admit(req, pick)
    assert router.stale_degradations == 0


def test_route_least_loaded_mode_ignores_affinity():
    from vllm_distributed_tpu.engine.router import ReplicaRouter
    router = ReplicaRouter(2, make_config())
    for i in range(2):
        router.observe_stats(i, {"num_running_reqs": 0,
                                 "num_waiting_reqs": 0,
                                 "kv_cache_usage": 0.0})
    router.on_admit(_req("seed", SESSION), 0)
    # Replica 0 holds the prefix but carries more live requests: the
    # prefill-pool placement mode (least_loaded=True) must ignore the
    # affinity credit — produced pages leave with the pull anyway.
    req = _req("y", SESSION)
    pick = router.route(req, [2, 0], set(), least_loaded=True)
    assert pick == 1
    router.on_admit(req, pick)
    # Not a stale degradation, and no phantom affinity hit.
    assert router.stale_degradations == 0
    assert router.affinity_hits == 0
