"""End-to-end speculative decoding: ngram drafts verified in-step must
reproduce non-spec greedy output exactly (model: reference
tests/v1/e2e/test_ngram_spec_decode.py semantics)."""

import pytest
import torch
from transformers import LlamaConfig
from transformers import LlamaForCausalLM as HFLlama

from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128,
                      eos_token_id=1)
    hf = HFLlama(cfg).eval()
    path = tmp_path_factory.mktemp("tiny_llama_spec")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def make_engine(path, **overrides) -> LLMEngine:
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=256, max_model_len=128,
                max_num_batched_tokens=128, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def run(engine, prompts, sps, tag):
    for i, (p, sp) in enumerate(zip(prompts, sps)):
        engine.add_request(f"{tag}-{i}", p, sp)
    done = {}
    for _ in range(500):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
        if not engine.has_unfinished_requests():
            break
    assert not engine.has_unfinished_requests()
    return [done[k] for k in sorted(done, key=lambda s: int(s.split("-")[-1]))]


def test_ngram_spec_matches_greedy_exactly(checkpoint):
    # Repetitive prompts make ngram lookup productive; random-weight
    # models also repeat quickly under greedy decode.
    prompts = [
        [7, 8, 9, 7, 8, 9, 7, 8],
        [3, 17, 92, 45, 8, 3, 17, 92, 45],
        [11, 12, 11, 12, 11, 12, 11],
    ]
    sps = [SamplingParams(temperature=0.0, max_tokens=24, ignore_eos=True)
           for _ in prompts]

    baseline = make_engine(checkpoint)
    expect = [o.outputs[0].token_ids
              for o in run(baseline, prompts, sps, "base")]
    baseline.shutdown() if hasattr(baseline, "shutdown") else None

    spec = make_engine(checkpoint, speculative_method="ngram",
                       num_speculative_tokens=3)
    got = [o.outputs[0].token_ids for o in run(spec, prompts, sps, "spec")]

    assert got == expect

    stats = spec.get_stats()
    # The harness must actually have speculated, and acceptance stats must
    # be reported (reference: SpecDecodingStats).
    assert stats["spec_num_draft_tokens"] > 0
    assert 0.0 <= stats["spec_acceptance_rate"] <= 1.0
    # Repetitive greedy continuations accept at a healthy rate.
    assert stats["spec_num_accepted_tokens"] > 0


def test_spec_with_seeded_sampling_is_unbiased_smoke(checkpoint):
    """Seeded non-greedy requests still run under spec decode (the
    emitted token at each position IS the target sample, so the output
    law is unchanged); smoke-check determinism across two runs."""
    prompts = [[5, 6, 5, 6, 5, 6]]
    sp = [SamplingParams(temperature=0.8, seed=1234, max_tokens=12,
                         ignore_eos=True)]
    e1 = make_engine(checkpoint, speculative_method="ngram",
                     num_speculative_tokens=3)
    out1 = run(e1, prompts, sp, "s1")[0].outputs[0].token_ids
    e2 = make_engine(checkpoint, speculative_method="ngram",
                     num_speculative_tokens=3)
    out2 = run(e2, prompts, sp, "s2")[0].outputs[0].token_ids
    assert out1 == out2


def test_draft_model_spec_matches_greedy_and_beats_ngram(checkpoint):
    """Draft-model proposals (the draft IS the target here, the
    strongest drafter) verified in-step: exact greedy parity, and an
    acceptance rate above ngram's on non-repetitive prompts (VERDICT r3
    missing #4 — learned-drafter path; ngram stays as fallback)."""
    prompts = [
        [3, 17, 92, 45, 8, 21],
        [60, 41, 2, 99, 14],
        [25, 26, 27, 90, 33, 47, 58],
    ]
    sps = [SamplingParams(temperature=0.0, max_tokens=20, ignore_eos=True)
           for _ in prompts]

    expect = [o.outputs[0].token_ids
              for o in run(make_engine(checkpoint), prompts, sps, "dbase")]

    ngram = make_engine(checkpoint, speculative_method="ngram",
                        num_speculative_tokens=3)
    got_n = [o.outputs[0].token_ids
             for o in run(ngram, prompts, sps, "dngram")]
    assert got_n == expect
    n_stats = ngram.get_stats()

    draft = make_engine(checkpoint, speculative_method="draft_model",
                        speculative_model=checkpoint,
                        num_speculative_tokens=3)
    got_d = [o.outputs[0].token_ids
             for o in run(draft, prompts, sps, "ddraft")]
    assert got_d == expect
    d_stats = draft.get_stats()

    assert d_stats["spec_num_draft_tokens"] > 0
    # Target-as-draft with the full (short) context in the window is a
    # near-perfect proposer; ngram has nothing to match on these
    # prompts.
    def rate(s):
        return (s["spec_num_accepted_tokens"] /
                max(1, s["spec_num_draft_tokens"]))
    assert rate(d_stats) > rate(n_stats)
    assert rate(d_stats) > 0.8, d_stats


def test_spec_composes_with_prefix_caching(checkpoint):
    """Spec drafts + prefix-cache hits on a shared prompt prefix: the
    second request reuses cached pages while draft verification
    continues to match plain greedy output exactly."""
    long_prefix = [7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8, 9]
    prompts = [long_prefix + [3], long_prefix + [5]]
    sps = [SamplingParams(temperature=0.0, max_tokens=16,
                          ignore_eos=True) for _ in prompts]

    base = make_engine(checkpoint, enable_prefix_caching=True)
    expect = [o.outputs[0].token_ids
              for o in run(base, prompts, sps, "pcbase")]

    spec = make_engine(checkpoint, speculative_method="ngram",
                       num_speculative_tokens=3,
                       enable_prefix_caching=True)
    # Serve sequentially so the second prompt actually hits the cache.
    got0 = run(spec, [prompts[0]], [sps[0]], "pc0")[0]
    got1 = run(spec, [prompts[1]], [sps[1]], "pc1")[0]
    assert [got0.outputs[0].token_ids,
            got1.outputs[0].token_ids] == expect
    stats = spec.get_stats()
    assert stats["spec_num_draft_tokens"] > 0
    assert stats["hits"] > 0  # the prefix cache actually engaged


def test_eagle_token_parallel_rejected(checkpoint):
    from vllm_distributed_tpu.engine.arg_utils import EngineArgs as EA
    with pytest.raises(ValueError, match="token parallelism"):
        EA(model=checkpoint, dtype="float32", block_size=4,
           num_gpu_blocks_override=64, max_model_len=64,
           max_num_batched_tokens=64, max_num_seqs=8,
           skip_tokenizer_init=True, token_parallel_size=2,
           speculative_method="eagle", speculative_model=checkpoint,
           num_speculative_tokens=1).create_engine_config()
