"""SSM state cache (core/state_cache.py): greedy parity matrix,
eviction/capacity, kill switch, corruption drill, and O(1) crash
recovery.

Acceptance (ISSUE 8): greedy outputs must be token-identical with the
state cache on vs off for mamba AND jamba (hybrid stacks must restore
state rows and attention KV pages coherently); preempt-park-resume must
match the no-preempt run; journal replay of a stateful request must
resume from the last checkpoint, re-prefilling at most
``VDT_SSM_CKPT_INTERVAL`` tokens instead of O(prompt).
"""

import asyncio
import os

import numpy as np
import pytest
import torch
from transformers import JambaConfig, MambaConfig
from transformers import JambaForCausalLM as HFJamba
from transformers import MambaForCausalLM as HFMamba

from vllm_distributed_tpu.core.state_cache import (StateCacheManager,
                                                   journal_path,
                                                   read_journal,
                                                   write_journal)
from vllm_distributed_tpu.engine.arg_utils import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import fault_injection as fi


@pytest.fixture(scope="module")
def mamba_ckpt(tmp_path_factory):
    torch.manual_seed(0)
    cfg = MambaConfig(vocab_size=128, hidden_size=32, state_size=8,
                      num_hidden_layers=2, conv_kernel=4, expand=2,
                      time_step_rank=4, use_conv_bias=True,
                      use_bias=False, eos_token_id=1)
    hf = HFMamba(cfg)
    path = tmp_path_factory.mktemp("mamba-sc-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


@pytest.fixture(scope="module")
def jamba_ckpt(tmp_path_factory):
    torch.manual_seed(0)
    cfg = JambaConfig(vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=4,
                      num_attention_heads=4, num_key_value_heads=2,
                      mamba_d_state=8, mamba_d_conv=4, mamba_expand=2,
                      mamba_dt_rank=4, attn_layer_period=4,
                      attn_layer_offset=2, expert_layer_period=2,
                      expert_layer_offset=1, num_experts=4,
                      num_experts_per_tok=2, max_position_embeddings=96,
                      eos_token_id=1, tie_word_embeddings=False,
                      use_mamba_kernels=False)
    hf = HFJamba(cfg)
    path = tmp_path_factory.mktemp("jamba-sc-tiny")
    hf.save_pretrained(path, safe_serialization=True)
    return str(path)


def _make_engine(path, monkeypatch, cache_on=True, interval=8,
                 **overrides):
    # The scheduler/runner read the envs at CONSTRUCTION.
    monkeypatch.setenv("VDT_SSM_STATE_CACHE", "1" if cache_on else "0")
    monkeypatch.setenv("VDT_SSM_CKPT_INTERVAL", str(interval))
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=96,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True)
    args.update(overrides)
    return LLMEngine(EngineArgs(**args).create_engine_config())


def _drain(engine, max_steps=500):
    done = {}
    for _ in range(max_steps):
        for out in engine.step():
            if out.finished:
                done[out.request_id] = list(out.outputs[0].token_ids)
        if not engine.has_unfinished_requests():
            break
    return done


def _run_session(engine, tag, turns=3, prompt_len=20, max_tokens=6):
    """Multi-turn chat shape: each turn's prompt extends the previous
    turn's full sequence — the traffic state-snapshot reuse exists for."""
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                        ignore_eos=True)
    prompt = [(i * 7 + 3) % 128 for i in range(prompt_len)]
    outs = []
    for turn in range(turns):
        engine.add_request(f"{tag}-{turn}", list(prompt), sp)
        done = _drain(engine)
        toks = done[f"{tag}-{turn}"]
        outs.append(toks)
        prompt = prompt + toks + [(turn * 13 + 5) % 128]
    return outs


def _ssm_stats(engine):
    return {k: v for k, v in engine.get_stats().items()
            if k.startswith("ssm_")}


# ---------------------------------------------------------------------------
# Greedy parity matrix
# ---------------------------------------------------------------------------
def test_mamba_multi_turn_parity_and_hits(mamba_ckpt, monkeypatch):
    """Turn N+1 resumes from turn N's snapshot; greedy outputs are
    token-identical to the cache-off engine on identical traffic."""
    on_engine = _make_engine(mamba_ckpt, monkeypatch, cache_on=True)
    on = _run_session(on_engine, "on")
    stats = _ssm_stats(on_engine)
    off_engine = _make_engine(mamba_ckpt, monkeypatch, cache_on=False)
    off = _run_session(off_engine, "off")
    assert on == off
    assert stats["ssm_state_cache_hits"] >= 2, stats
    assert stats["ssm_resume_tokens_saved"] > 0, stats
    assert stats["ssm_checkpoints"] >= 1, stats
    assert stats["ssm_state_bytes_held"] > 0, stats
    # Kill switch: the cache-off engine runs no state-cache machinery.
    assert off_engine.engine_core.engine_core.scheduler.state_cache \
        is None
    assert not _ssm_stats(off_engine)


def test_mamba_chunked_prefill_snapshot_parity(mamba_ckpt, monkeypatch):
    """A prompt far longer than the interval forces mid-prefill
    snapshots (grant clipping); parity must hold and the second session
    turn resumes deep into the prompt."""
    on_engine = _make_engine(mamba_ckpt, monkeypatch, cache_on=True,
                             max_num_batched_tokens=16)
    on = _run_session(on_engine, "on", turns=2, prompt_len=40)
    stats = _ssm_stats(on_engine)
    off_engine = _make_engine(mamba_ckpt, monkeypatch, cache_on=False,
                              max_num_batched_tokens=16)
    off = _run_session(off_engine, "off", turns=2, prompt_len=40)
    assert on == off
    assert stats["ssm_state_cache_hits"] >= 1, stats
    # The resume skipped at least the first interval boundaries of the
    # 40-token shared prefix.
    assert stats["ssm_resume_tokens_saved"] >= 32, stats


def test_jamba_hybrid_multi_turn_parity(jamba_ckpt, monkeypatch):
    """Hybrid stacks must restore mamba state rows AND attention KV
    pages coherently — token-identical greedy outputs prove both sides
    re-entered at the same boundary."""
    on_engine = _make_engine(jamba_ckpt, monkeypatch, cache_on=True)
    on = _run_session(on_engine, "on")
    stats = _ssm_stats(on_engine)
    off_engine = _make_engine(jamba_ckpt, monkeypatch, cache_on=False)
    off = _run_session(off_engine, "off")
    assert on == off
    assert stats["ssm_state_cache_hits"] >= 1, stats
    # Hybrid hits ride the page prefix cache (forced on): the KV pages
    # of the shared prefix were reused, not recomputed.
    sched = on_engine.engine_core.engine_core.scheduler
    assert sched.kv_cache_manager.enable_caching


def test_jamba_preempt_park_resume_parity(jamba_ckpt, monkeypatch):
    """A page pool too small for the batch forces preemption; parked
    state lets victims resume as continuations, token-identical to the
    cache-off run (which re-prefills from scratch)."""
    def run(cache_on):
        engine = _make_engine(jamba_ckpt, monkeypatch, cache_on=cache_on,
                              interval=4, num_gpu_blocks_override=16,
                              max_model_len=64, max_num_seqs=4)
        sp = SamplingParams(temperature=0.0, max_tokens=16,
                            ignore_eos=True)
        prompts = [[(i * 5 + j) % 128 for j in range(8)]
                   for i in range(4)]
        for i, p in enumerate(prompts):
            engine.add_request(f"r-{i}", p, sp)
        done = _drain(engine)
        stats = engine.get_stats()
        return ([done[f"r-{i}"] for i in range(4)],
                int(stats["num_preemptions"]), _ssm_stats(engine))

    on, preempts_on, stats = run(True)
    off, preempts_off, _ = run(False)
    assert on == off
    assert preempts_on > 0 and preempts_off > 0
    # Parked/periodic snapshots turned at least one resume into a
    # continuation (re-prefill bounded by the interval, not O(seq)).
    assert stats["ssm_state_cache_hits"] >= 1, stats
    assert stats["ssm_resume_tokens_saved"] > 0, stats


def test_mamba_async_scheduling_parity(mamba_ckpt, monkeypatch):
    """Async run-ahead grants snapshot at speculative boundaries whose
    key resolves at commit; a stop before the boundary discards the
    snapshot. Greedy outputs must still match the sync cache-off run."""
    on_engine = _make_engine(mamba_ckpt, monkeypatch, cache_on=True,
                             async_scheduling=True)
    on = _run_session(on_engine, "on")
    stats = _ssm_stats(on_engine)
    off_engine = _make_engine(mamba_ckpt, monkeypatch, cache_on=False)
    off = _run_session(off_engine, "off")
    assert on == off
    assert stats["ssm_state_cache_hits"] >= 1, stats


# ---------------------------------------------------------------------------
# O(1) crash recovery
# ---------------------------------------------------------------------------
PROMPT = [(i * 7 + 3) % 128 for i in range(40)]


def _make_async_engine(path, monkeypatch, tmp_path, cache_on=True):
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    monkeypatch.setenv("VDT_SSM_STATE_CACHE", "1" if cache_on else "0")
    monkeypatch.setenv("VDT_SSM_CKPT_INTERVAL", "8")
    monkeypatch.setenv("VDT_SSM_CKPT_DIR", str(tmp_path))
    args = dict(model=path, dtype="float32", block_size=4,
                num_gpu_blocks_override=128, max_model_len=96,
                max_num_batched_tokens=64, max_num_seqs=8,
                skip_tokenizer_init=True,
                restart_backoff_base_s=0.01, restart_backoff_max_s=0.05)
    return AsyncLLM(EngineArgs(**args).create_engine_config(),
                    load_tokenizer=False)


async def _collect(engine, rid, die_after=False):
    sp = SamplingParams(temperature=0.0, max_tokens=20, ignore_eos=True)
    final, first = None, False
    async for out in engine.generate(PROMPT, sp, request_id=rid):
        if not first:
            first = True
            if die_after:
                fi.inject("engine_core.die", max_fires=1)
        final = out
    assert final is not None and final.finished
    return final.outputs[0].token_ids


def test_replay_resumes_from_checkpoint(mamba_ckpt, monkeypatch,
                                        tmp_path):
    """Kill the core mid-decode: the journaled request replays into the
    respawned core, which resumes from the last host checkpoint — the
    replayed prefill is bounded by VDT_SSM_CKPT_INTERVAL (8), not the
    40-token prompt — and the stream stays token-identical."""
    base = _make_async_engine(mamba_ckpt, monkeypatch,
                              tmp_path / "base")
    try:
        baseline = asyncio.run(asyncio.wait_for(
            _collect(base, "base-0"), timeout=120))
    finally:
        base.shutdown()

    engine = _make_async_engine(mamba_ckpt, monkeypatch,
                                tmp_path / "rec")
    try:
        resumed = asyncio.run(asyncio.wait_for(
            _collect(engine, "die-0", die_after=True), timeout=180))
        assert resumed == baseline
        assert not engine.errored
        assert engine.output_processor.stats.num_requests_replayed >= 1
        # The FRESH core's stats prove the O(1) resume: the replayed
        # continuation knew >= 41 tokens (prompt + first delivered) and
        # re-prefilled at most one interval past the last checkpoint.
        sc = engine.core.core.scheduler.state_cache
        stats = sc.stats()
        assert stats["ssm_state_cache_hits"] >= 1, stats
        known = len(PROMPT) + 1
        assert stats["ssm_resume_tokens_saved"] >= known - 8, stats
    finally:
        engine.shutdown()


def test_restore_corrupt_degrades_to_reprefill(mamba_ckpt, monkeypatch,
                                               tmp_path):
    """ssm.restore_corrupt simulates a checksum mismatch on every
    journal read: recovery must degrade to a full re-prefill (counted)
    and stay token-identical."""
    base = _make_async_engine(mamba_ckpt, monkeypatch,
                              tmp_path / "base")
    try:
        baseline = asyncio.run(asyncio.wait_for(
            _collect(base, "base-0"), timeout=120))
    finally:
        base.shutdown()

    engine = _make_async_engine(mamba_ckpt, monkeypatch,
                                tmp_path / "rec")
    try:
        fi.inject("ssm.restore_corrupt")
        resumed = asyncio.run(asyncio.wait_for(
            _collect(engine, "die-0", die_after=True), timeout=180))
        assert resumed == baseline
        sc = engine.core.core.scheduler.state_cache
        stats = sc.stats()
        assert stats["ssm_restore_corruptions"] >= 1, stats
        assert fi.counters().get("ssm.restore_corrupt", 0) >= 1
    finally:
        fi.clear("ssm.restore_corrupt")
        engine.shutdown()


# ---------------------------------------------------------------------------
# Manager units: eviction/capacity, dedupe, pending lifecycle, journal
# ---------------------------------------------------------------------------
class _Req:
    """Minimal Request stand-in for manager-level tests."""

    def __init__(self, rid, tokens):
        self.request_id = rid
        self.all_token_ids = list(tokens)
        self.mm_hash = None

    @property
    def num_tokens(self):
        return len(self.all_token_ids)


def _mgr(slots=2, interval=4, paged=False, journal_dir=""):
    m = StateCacheManager(num_slots=slots, block_size=4,
                          interval=interval, paged_kv=paged,
                          journal_dir=journal_dir)
    m.bytes_per_slot = 100
    return m


def test_manager_lru_eviction_and_capacity():
    m = _mgr(slots=2)
    reqs = [_Req(f"r{i}", [i * 31 + j for j in range(12)])
            for i in range(3)]
    for r in reqs:
        d = m.maybe_save(r, 4)
        assert d is not None
        m.commit_save(d, r)
    assert m.checkpoints == 3
    assert m.evictions == 1  # r0's snapshot was the LRU victim
    assert len(m.by_key) == 2
    assert m.stats()["ssm_state_bytes_held"] == 200
    # The evicted prefix misses; the survivors hit.
    _, b0, _ = m.get_computed_state(_Req("q0", reqs[0].all_token_ids),
                                    None)
    _, b2, d2 = m.get_computed_state(_Req("q2", reqs[2].all_token_ids),
                                     None)
    assert b0 == 0
    assert b2 == 4 and d2 is not None and d2.slot >= 0
    # Hits count at successful ADMISSION (scheduler-side), not per
    # lookup; bare lookups only tally queries.
    assert m.hits == 0 and m.queries == 2


def test_manager_dedupes_identical_prefixes():
    m = _mgr(slots=4)
    a, b = _Req("a", range(12)), _Req("b", range(12))
    d = m.maybe_save(a, 4)
    m.commit_save(d, a)
    assert m.maybe_save(b, 4) is None  # same content hash: no new slot
    assert len(m.free_slots) == 3


def test_manager_off_boundary_and_pending_abort():
    m = _mgr(slots=2)
    r = _Req("r", range(20))
    assert m.maybe_save(r, 5) is None  # not interval-aligned
    d = m.maybe_save(r, 8)
    assert d is not None and m.is_pending(d)
    # Restart-from-scratch aborts the pending copy: the slot frees and
    # a later commit of the shipped directive is a no-op.
    m.abort_pending("r")
    assert not m.is_pending(d)
    assert len(m.free_slots) == 2
    m.commit_save(d, r)
    assert m.checkpoints == 0 and not m.by_key


def test_manager_speculative_save_commit_validity():
    """An async run-ahead save past the known tokens resolves its key at
    commit; a request that stopped short discards the snapshot."""
    m = _mgr(slots=2)
    r = _Req("r", range(8))  # 8 known tokens
    d = m.maybe_save(r, 12)  # boundary past known: key deferred
    assert d is not None
    m.commit_save(d, r)  # never reached 12 tokens -> discarded
    assert not m.by_key and len(m.free_slots) == 2
    d = m.maybe_save(r, 12)
    r.all_token_ids = list(range(12))  # speculative token committed
    m.commit_save(d, r)
    assert m.checkpoints == 1 and len(m.by_key) == 1


def test_manager_async_save_owes_journal_persist(tmp_path):
    """A speculative (async) save resolves its key at commit, AFTER the
    runner's copy+journal window: the manager owes a persist_only
    directive, pins the slot against eviction until it ships, and
    drains it into the next output."""
    m = _mgr(slots=1, journal_dir=str(tmp_path))
    r = _Req("r", range(8))
    d = m.maybe_save(r, 12)  # key (and journal path) unresolvable
    assert d is not None and d.journal is None
    r.all_token_ids = list(range(12))
    m.commit_save(d, r)
    persists = m.pending_persists
    assert len(persists) == 1 and persists[0].persist_only
    assert persists[0].journal is not None
    # The only slot is journal-pinned: a new save cannot evict it.
    assert m.maybe_save(_Req("x", range(40, 52)), 4) is None
    drained = m.take_persists()
    assert [p.journal for p in drained] == [persists[0].journal]
    assert m.take_persists() == []
    # Unpinned now: the next save may evict it.
    assert m.maybe_save(_Req("x", range(40, 52)), 4) is not None
    assert m.evictions == 1


def test_journal_fingerprint_guards_shared_dirs(tmp_path):
    """A CRC-valid checkpoint written under another model's state
    geometry must miss (and survive — it is someone else's file)."""
    from vllm_distributed_tpu.core.state_cache import state_fingerprint
    arrays = {"conv": np.ones((2, 3, 4), np.float32)}
    m = _mgr(slots=2, journal_dir=str(tmp_path))
    m.journal_fingerprint = state_fingerprint(
        {"conv": (((2, 9, 3, 4)), "float32")})
    r = _Req("r", range(12))
    key = m._key_at(r, 4)
    path = journal_path(str(tmp_path), key)
    write_journal(path, arrays, 4, fingerprint=state_fingerprint(
        {"conv": (((2, 9, 9, 9)), "bfloat16")}))
    _, boundary, _ = m.get_computed_state(r, None)
    assert boundary == 0
    assert os.path.exists(path)  # foreign file NOT quarantined
    assert m.restore_corruptions == 0
    # Matching fingerprint: the same file becomes a hit.
    write_journal(path, arrays, 4,
                  fingerprint=m.journal_fingerprint)
    _, boundary, d = m.get_computed_state(r, None)
    assert boundary == 4 and d.slot == -1 and d.arrays is not None


def test_journal_roundtrip_and_corruption(tmp_path):
    arrays = {"conv": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
              "ssm": np.ones((2, 8), np.float32)}
    path = journal_path(str(tmp_path), b"\x01" * 16)
    write_journal(path, arrays, 16)
    out = read_journal(path)
    assert out is not None
    np.testing.assert_array_equal(out["conv"], arrays["conv"])
    np.testing.assert_array_equal(out["ssm"], arrays["ssm"])
    # Bit-flip the payload: the CRC must catch it.
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    assert read_journal(path) is None
    # Injected corruption on a GOOD file (the deterministic drill).
    write_journal(path, arrays, 16)
    fi.inject("ssm.restore_corrupt", max_fires=1)
    try:
        assert read_journal(path) is None
        assert read_journal(path) is not None  # single fire
    finally:
        fi.clear("ssm.restore_corrupt")


def test_journal_gc_reclaims_orphans_keeps_live(tmp_path, monkeypatch):
    """Retention sweep (PR8 known gap: content-addressed journal files
    were never deleted): TTL-expired orphans and over-budget old files
    are reclaimed on manager init and on reset()/sleep; fresh and
    still-referenced checkpoints survive both passes."""
    import time

    from vllm_distributed_tpu.core.state_cache import (journal_path,
                                                       sweep_journal,
                                                       write_journal)
    jd = str(tmp_path / "journal")
    os.makedirs(jd)
    arrays = {"conv": np.arange(8, dtype=np.float32)}

    def make_file(tag, age_s=0.0, size=0):
        path = journal_path(jd, tag.encode())
        write_journal(path, arrays, num_tokens=8)
        if size:
            with open(path, "ab") as f:  # inflate for budget tests
                f.write(b"\0" * size)
        if age_s:
            old = time.time() - age_s
            os.utime(path, (old, old))
        return path

    # TTL pass: a week-old orphan dies at init, fresh files survive.
    expired = make_file("expired", age_s=8 * 86400)
    fresh = make_file("fresh")
    monkeypatch.setenv("VDT_SSM_CKPT_TTL_S", "604800")
    monkeypatch.setenv("VDT_SSM_CKPT_MAX_MB", "1024")
    m = _mgr(journal_dir=jd)
    assert not os.path.exists(expired)
    assert os.path.exists(fresh)
    assert m.journal_files_reclaimed == 1
    assert m.stats()["ssm_journal_reclaimed"] == 1

    # Budget pass at sleep: oldest-first eviction down to the budget —
    # but a checkpoint a pending persist still OWES is never reclaimed,
    # whatever its age.
    owed = make_file("owed", age_s=3600, size=1 << 20)
    bulk = [make_file(f"bulk{i}", age_s=1800 - i, size=1 << 20)
            for i in range(3)]

    class _Persist:
        journal = owed

    m.pending_persists.append(_Persist())
    monkeypatch.setenv("VDT_SSM_CKPT_MAX_MB", "2")
    m.reset()
    assert os.path.exists(owed)  # referenced: survives over-budget
    survivors = [p for p in bulk if os.path.exists(p)]
    total = sum(os.path.getsize(p) for p in (owed, fresh, *survivors))
    assert len(survivors) < len(bulk)  # oldest bulk files reclaimed
    # Unreferenced files were evicted oldest-first until the
    # unprotected remainder fit the budget.
    assert sum(os.path.getsize(p) for p in survivors) <= 2 << 20
    assert m.journal_files_reclaimed > 1

    # Direct sweep unit: keep-set beats both TTL and budget.
    kept = make_file("kept", age_s=30 * 86400)
    removed, _ = sweep_journal(jd, max_bytes=1, ttl_s=60,
                               keep={kept, owed, fresh})
    assert os.path.exists(kept) and os.path.exists(owed)
    assert removed >= len(survivors)


def test_dp_merge_sums_ssm_counters():
    """The vdt:ssm_* families merge across DP replicas through the
    aggregator's numeric-sum loop — flat keys, no special cases."""
    from vllm_distributed_tpu.engine.dp_client import DPEngineClient
    client = DPEngineClient.__new__(DPEngineClient)
    client.clients = [None, None]
    client._down = set()
    client.replica_failovers = 0
    client.replica_resurrections = 0
    client.request_counts = lambda: [0, 0]
    per = [
        {"ssm_state_cache_hits": 3, "ssm_state_cache_queries": 5,
         "ssm_state_cache_evictions": 1, "ssm_checkpoints": 7,
         "ssm_state_bytes_held": 100, "ssm_resume_tokens_saved": 64},
        {"ssm_state_cache_hits": 2, "ssm_state_cache_queries": 4,
         "ssm_state_cache_evictions": 0, "ssm_checkpoints": 3,
         "ssm_state_bytes_held": 50, "ssm_resume_tokens_saved": 16},
    ]
    agg = client._aggregate_stats(per)
    assert agg["ssm_state_cache_hits"] == 5
    assert agg["ssm_state_cache_queries"] == 9
    assert agg["ssm_state_cache_evictions"] == 1
    assert agg["ssm_checkpoints"] == 10
    assert agg["ssm_state_bytes_held"] == 150
    assert agg["ssm_resume_tokens_saved"] == 80
    # And they render on /metrics with their registered names.
    from vllm_distributed_tpu.metrics.prometheus import render_metrics
    text = render_metrics(agg)
    assert "vdt:ssm_state_cache_hits_total 5.0" in text
    assert "vdt:ssm_checkpoints_total 10.0" in text
    assert "vdt:ssm_state_bytes_held 150.0" in text
