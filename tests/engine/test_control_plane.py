"""HA fleet control plane (engine/control_plane.py).

Split-brain and failover drills over ``HAFleetController``: leader
election with TTL-lease renewal, ``fleet.controller_die`` failover
within the TTL, the ``fleet.lease_expire`` split-brain (an ex-leader's
queued drain rung and scale decisions are rejected by the coordinator's
epoch fence — counted, never raised — with zero request loss), the
leader-crash-mid-drain journal-replay acceptance drill (the successor
completes the retire with token parity), ``coordinator.partition``
degradation (frozen placement, serving continues, local routing
fallback), the standby fenced-resurrect single-owner guard, and the
``VDT_FLEET_SIGNALS`` decision matrix (roofline phase + per-tenant
goodput shift the scale decision; occupancy-only when off). Two
front-ends are modeled as two controllers sharing one DP client,
coordinator socket, and journal directory — exactly the state two API
servers would share."""

import time

import pytest

from tests.conftest import make_config
from tests.engine.test_fleet import (FLEET_ENV, _Collector, _FleetStub,
                                     _pressure, _pump, _req, _tick,
                                     _tok, make_fleet)
from vllm_distributed_tpu.engine import dp_client as dp_mod
from vllm_distributed_tpu.engine.control_plane import HAFleetController
from vllm_distributed_tpu.engine.dp_client import DPEngineClient
from vllm_distributed_tpu.engine.fleet import FleetController
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.prometheus import render_metrics
from vllm_distributed_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.faults

# Tiny lease so takeover drills finish in well under a second; MIN=2
# keeps an idle 2-replica fleet from retiring into the drills (the
# drain tests override it back to 1).
TTL_S = 0.3
HA_ENV = {
    **FLEET_ENV,
    "VDT_FLEET_CONTROLLER": "1",
    "VDT_FLEET_LEASE_TTL_S": str(TTL_S),
    "VDT_FLEET_MIN_REPLICAS": "2",
}


@pytest.fixture
def ha(monkeypatch, tmp_path):
    """Factory for a controller-on stub fleet; tears the DP clients
    down afterwards so every spawned coordinator process is reaped."""
    created = []

    def make(n: int = 2, coordinator_routes: bool = False,
             **env) -> DPEngineClient:
        e = {**HA_ENV, "VDT_FLEET_JOURNAL_DIR": str(tmp_path), **env}
        for key, val in e.items():
            monkeypatch.setenv(key, val)
        config = make_config()
        config.parallel_config.data_parallel_size = n
        config.parallel_config.data_parallel_coordinator = \
            coordinator_routes
        ft = config.fault_tolerance_config
        ft.replica_probe_interval_s = 0.01
        ft.restart_backoff_base_s = 0.0
        ft.restart_max_attempts = 100
        monkeypatch.setattr(dp_mod, "SyncMPClient", _FleetStub)
        dp = DPEngineClient(config, force_mp=True)
        created.append(dp)
        return dp

    yield make
    fi.clear()
    for dp in created:
        try:
            dp.shutdown()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass


def _expire_lease() -> None:
    time.sleep(TTL_S + 0.05)


# ---------------------------------------------------------------------------
# Inert default: VDT_FLEET_CONTROLLER unset keeps PR-16 behavior
# ---------------------------------------------------------------------------
def test_controller_off_is_plain_fleet(monkeypatch):
    dp = make_fleet(monkeypatch)
    assert type(dp.fleet) is FleetController
    assert not getattr(dp.fleet, "ha", False)
    assert dp.coordinator is None  # no control-plane process spawned
    # The base hooks are declared no-ops: fence always passes, no
    # journal, no leader keys in the stats entry.
    assert dp.fleet._fence("scale_out") is True
    assert "leader" not in dp.fleet.get_stats()


# ---------------------------------------------------------------------------
# Leader election + lease renewal + telemetry
# ---------------------------------------------------------------------------
def test_leader_election_renewal_and_metrics(ha):
    dp = ha()
    primary = dp.fleet
    assert isinstance(primary, HAFleetController)
    assert not primary.is_leader  # nothing until the first tick
    _tick(dp)
    assert primary.is_leader and primary.epoch == 1
    assert primary.leader_transitions == 1
    # Renewal keeps the epoch (no holder change).
    _tick(dp)
    assert primary.is_leader and primary.epoch == 1
    info = dp.coordinator.lease_info()
    assert info["holder"] == primary.holder and info["epoch"] == 1
    # A second front-end's controller stays a standby.
    standby = HAFleetController(dp, dp.config, holder="standby")
    standby.tick()
    assert not standby.is_leader
    assert standby.fenced_actions == {}
    assert len(dp.clients) == 2  # nobody actuated anything
    # Leadership renders on the vdt:fleet_* families.
    agg = dp._aggregate_stats([{}, {}], indices=[0, 1])
    assert agg["fleet"]["leader"] == 1
    assert agg["fleet"]["lease_epoch"] == 1
    text = render_metrics(agg)
    assert "vdt:fleet_leader 1" in text
    assert "vdt:fleet_lease_epoch 1" in text
    assert "vdt:fleet_leader_transitions_total 1" in text


def test_controller_die_failover_within_ttl(ha):
    """Leader death (``fleet.controller_die``): the lease lapses and a
    standby's next acquire wins within the TTL; the new leader owns the
    fleet (its fenced actuations pass at the bumped epoch)."""
    dp = ha()
    primary = dp.fleet
    _tick(dp)
    standby = HAFleetController(dp, dp.config, holder="standby")
    standby.tick()
    assert primary.is_leader and not standby.is_leader
    fi.inject("fleet.controller_die", max_fires=1)
    try:
        _tick(dp)
    finally:
        fi.clear("fleet.controller_die")
    assert primary.dead and not primary.is_leader
    assert any(e[2] == ev.FLEET_CONTROLLER_DOWN
               for e in primary.drain_events())
    # The old lease is still live: the standby cannot jump the TTL.
    standby.tick()
    assert not standby.is_leader
    _expire_lease()
    standby.tick()
    assert standby.is_leader
    assert standby.epoch == 2  # takeover bumped the fencing epoch
    assert standby.leader_transitions == 2
    assert any(e[2] == ev.FLEET_LEADER_TAKEOVER
               for e in standby.drain_events())
    # A dead controller's tick stays a no-op.
    _tick(dp)
    assert primary.get_stats()["leader"] == 0
    # The new leader actuates: scale-out passes its epoch-2 fence.
    _pressure(dp, 20)
    standby.tick()
    assert len(dp.clients) == 3
    assert standby.scale_outs == 1
    assert standby.fenced_actions == {}


# ---------------------------------------------------------------------------
# Split-brain: lease expiry fences the ex-leader, zero request loss
# ---------------------------------------------------------------------------
def test_lease_expiry_fences_ex_leader_drain_zero_loss(ha, tmp_path):
    """``fleet.lease_expire``: the leader's renewals stop but it still
    believes it leads. A standby takes over at a bumped epoch and
    replays the journaled drain; the ex-leader's queued drain rung is
    rejected by the fence (counted on
    ``vdt:fleet_fenced_actions_total``, fleet state untouched) and the
    drained session finishes token-exact — zero loss, no failover."""
    dp = ha(VDT_FLEET_MIN_REPLICAS="1", VDT_FLEET_DRAIN_S="60")
    col = _Collector()
    dp.add_request(_req("s-0", max_tokens=10))
    dp.add_request(_req("s-1", max_tokens=10))
    assert dp._owner["s-0"] == 0 and dp._owner["s-1"] == 1
    primary = dp.fleet
    _tick(dp)  # elect + begin retiring replica 1 (low occupancy)
    assert primary.is_leader and primary.epoch == 1
    assert primary._draining[1]["mode"] == "retire"
    assert (tmp_path / "drain-1.json").exists()  # intent journaled
    # The draining replica keeps serving: one token lands.
    dp.clients[1].serve()
    col.take(dp.recv_outputs(timeout_ms=10))
    assert col.tokens["s-1"] == [_tok(1, 0)]
    standby = HAFleetController(dp, dp.config, holder="standby")
    standby.tick()
    assert not standby.is_leader
    fi.inject("fleet.lease_expire")
    try:
        _expire_lease()
        # The standby's acquire wins (epoch 2) and REPLAYS the journal:
        # the half-done retire is re-entered under the new leader.
        standby.tick()
        assert standby.is_leader and standby.epoch == 2
        assert standby.journal_replays == 1
        assert standby._draining[1]["mode"] == "retire"
        assert any(e[2] == ev.FLEET_JOURNAL_REPLAY
                   for e in standby.drain_events())
        # The ex-leader still believes it leads (skipped renewals);
        # its queued drain rung and follow-up retire decision are
        # both fenced off — counted, fleet state untouched.
        primary._draining[1]["deadline"] = 0.0
        _tick(dp)
        assert primary.fenced_actions.get("retire") == 1
        assert primary.fenced_actions.get("scale_in") == 1
        assert not primary.is_leader  # demoted by the rejection
        assert 1 not in primary._draining  # local record abandoned
        assert 1 not in dp._retired  # ...without touching the fleet
        assert (tmp_path / "drain-1.json").exists()
    finally:
        fi.clear("fleet.lease_expire")
    # The new leader completes the retire through the normal ladder.
    standby._draining[1]["deadline"] = 0.0
    standby.tick()
    assert 1 in dp._retired
    assert standby.scale_ins == 1
    assert standby.journal.pending() == {}
    # Quiet the ex-leader (as if its process died) so the output-path
    # ticks below cannot re-elect it mid-pump.
    fi.inject("fleet.controller_die", max_fires=1)
    try:
        _tick(dp)
    finally:
        fi.clear("fleet.controller_die")
    assert primary.dead
    # Zero loss: both sessions finish token-exact (s-1 as a migrated
    # continuation on replica 0), and none of it counted as a death.
    deadline = time.monotonic() + 10.0
    while ((col.finishes.get("s-0") != 1 or col.finishes.get("s-1") != 1)
           and time.monotonic() < deadline):
        _pump(dp, col)
        standby.tick()
    col.assert_exact("s-0", 10)
    col.assert_exact("s-1", 10)
    assert dp.replica_failovers == 0
    # The fence rejections render with their action label.
    agg = dp._aggregate_stats([{}, {}], indices=[0, 1])
    text = render_metrics(agg)
    assert 'vdt:fleet_fenced_actions_total{action="retire"} 1' in text
    assert 'vdt:fleet_fenced_actions_total{action="scale_in"} 1' in text


# ---------------------------------------------------------------------------
# Acceptance drill: leader crash mid-drain, successor replays journal
# ---------------------------------------------------------------------------
def test_leader_crash_mid_drain_journal_replay_parity(ha, tmp_path):
    """Kill the leader (``fleet.controller_die``) between a drain's
    intent record and its completion: the successor finds the journal
    entry, replays the retire, and the drained session's stream is
    token-identical — the crash is invisible to the request."""
    dp = ha(VDT_FLEET_MIN_REPLICAS="1", VDT_FLEET_DRAIN_S="60")
    col = _Collector()
    dp.add_request(_req("s-0", max_tokens=10))
    dp.add_request(_req("s-1", max_tokens=10))
    _tick(dp)  # elect + begin retiring replica 1
    primary = dp.fleet
    assert primary._draining[1]["mode"] == "retire"
    dp.clients[1].serve()  # mid-stream: one token delivered pre-crash
    col.take(dp.recv_outputs(timeout_ms=10))
    fi.inject("fleet.controller_die", max_fires=1)
    try:
        _tick(dp)
    finally:
        fi.clear("fleet.controller_die")
    assert primary.dead
    assert (tmp_path / "drain-1.json").exists()  # intent survives
    standby = HAFleetController(dp, dp.config, holder="standby")
    standby.tick()
    assert not standby.is_leader  # old lease still live
    _expire_lease()
    standby.tick()
    assert standby.is_leader and standby.journal_replays == 1
    assert standby.get_stats()["journal_replays"] == 1
    # The successor finishes the retire it never started.
    standby._draining[1]["deadline"] = 0.0
    standby.tick()
    assert 1 in dp._retired and standby.scale_ins == 1
    assert standby.journal.pending() == {}
    deadline = time.monotonic() + 10.0
    while ((col.finishes.get("s-0") != 1 or col.finishes.get("s-1") != 1)
           and time.monotonic() < deadline):
        _pump(dp, col)
        standby.tick()
    col.assert_exact("s-0", 10)
    col.assert_exact("s-1", 10)
    assert dp.replica_failovers == 0  # scheduled maintenance, no death


# ---------------------------------------------------------------------------
# Partition degradation: serving continues with frozen placement
# ---------------------------------------------------------------------------
def test_partition_freezes_placement_serving_continues(ha):
    dp = ha()
    _tick(dp)
    assert dp.fleet.is_leader
    col = _Collector()
    fi.inject("coordinator.partition")
    try:
        _tick(dp, 3)
        # Partitioned from the control plane: demoted + frozen, one
        # counted freeze per suppressed tick.
        assert not dp.fleet.is_leader
        assert dp.fleet.freezes.get("partition", 0) >= 3
        # The front-end keeps serving (placement is local here: the
        # control-plane-only coordinator never owned routing).
        dp.add_request(_req("p-0", max_tokens=4))
        deadline = time.monotonic() + 5.0
        while (col.finishes.get("p-0") != 1
               and time.monotonic() < deadline):
            _pump(dp, col)
        col.assert_exact("p-0", 4)
    finally:
        fi.clear("coordinator.partition")
    # Partition heals: the same holder re-acquires without an epoch
    # bump (the coordinator saw no other holder in between).
    _tick(dp)
    assert dp.fleet.is_leader and dp.fleet.epoch == 1


def test_partition_routing_falls_back_to_local(ha):
    """With the coordinator also owning routing
    (``data_parallel_coordinator=True``), a partition degrades
    admission to local least-loaded — requests still land, finish
    deltas are swallowed onto the freeze ladder, nothing raises."""
    dp = ha(coordinator_routes=True)
    assert dp._coord_routes
    _tick(dp)
    col = _Collector()
    dp.add_request(_req("r-0", max_tokens=4))  # coordinator-routed
    fi.inject("coordinator.partition")
    try:
        dp.add_request(_req("r-1", max_tokens=4))  # local fallback
        assert "r-1" in dp._owner
        assert dp.fleet.freezes.get("partition", 0) >= 1
        deadline = time.monotonic() + 5.0
        while ((col.finishes.get("r-0") != 1
                or col.finishes.get("r-1") != 1)
               and time.monotonic() < deadline):
            _pump(dp, col)
        col.assert_exact("r-0", 4)
        col.assert_exact("r-1", 4)
    finally:
        fi.clear("coordinator.partition")


# ---------------------------------------------------------------------------
# Single-owner actuation guard: standby resurrect is a fenced no-op
# ---------------------------------------------------------------------------
def test_standby_resurrect_is_fenced_noop(ha):
    dp = ha()
    _tick(dp)
    primary = dp.fleet
    standby = HAFleetController(dp, dp.config, holder="standby")
    standby.tick()
    dp.clients[0].dead = True
    dp.add_request(_req("x-0", max_tokens=4))  # discovers the death
    assert 0 in dp._down and dp.replica_failovers == 1
    # The standby sees the dead replica but only COUNTS the respawn
    # opportunity — scheduling probes is the leaseholder's job.
    standby.tick()
    assert standby.fenced_actions.get("resurrect") == 1
    assert dp.clients[0].restarts == 0
    assert any(e[2] == ev.FLEET_FENCED for e in standby.drain_events())
    # The leader resurrects it through the verified-probe ladder.
    deadline = time.monotonic() + 5.0
    while 0 in dp._down and time.monotonic() < deadline:
        time.sleep(0.02)
        _tick(dp)
    assert 0 not in dp._down
    assert dp.replica_resurrections == 1
    assert dp.clients[0].restarts == 1
    assert primary.is_leader


# ---------------------------------------------------------------------------
# Richer scaling signals (VDT_FLEET_SIGNALS): decision matrix
# ---------------------------------------------------------------------------
_BANDWIDTH_PHASE = {"device_seconds": 1.0, "host_seconds": 0.0,
                    "flops": 1.0, "bytes": 1e12}
_COMPUTE_PHASE = {"device_seconds": 1.0, "host_seconds": 0.0,
                  "flops": 1e12, "bytes": 1.0}
_PEAKS = {"flops": 1e12, "hbm": 1e12}


def _feed_phases(dp, entry) -> None:
    for c in dp.clients:
        c.stats["perf_phases"] = {"decode": dict(entry)}
        c.stats["perf_peaks"] = dict(_PEAKS)


def test_signals_off_is_occupancy_only(monkeypatch):
    """Default: bandwidth-bound phases and starved tenants shift
    nothing — the decision is exactly PR 16's occupancy comparison."""
    dp = make_fleet(monkeypatch, VDT_FLEET_LOW_WATERMARK="0")
    assert dp.fleet.signals is False
    _pressure(dp, 5)  # occupancy 10/16 = 0.625 < 0.85
    _feed_phases(dp, _BANDWIDTH_PHASE)
    dp.observe_goodput({"gold": 0.1})  # stored, but not consulted
    assert dp.fleet._goodput == {"gold": 0.1}
    _tick(dp, 3)
    assert len(dp.clients) == 2 and dp.fleet.scale_outs == 0


def test_signals_roofline_phase_shifts_scale_out(monkeypatch):
    """A memory-bound fleet scales out at occupancy a compute-bound
    one rides: 0.625 * (1 + 0.5 * bandwidth_frac) crosses 0.85 only
    when the attributed phases sit on the bandwidth roof."""
    dp = make_fleet(monkeypatch, VDT_FLEET_SIGNALS="1",
                    VDT_FLEET_ROOFLINE_WEIGHT="0.5",
                    VDT_FLEET_LOW_WATERMARK="0")
    _pressure(dp, 5)
    _feed_phases(dp, _COMPUTE_PHASE)
    _tick(dp, 2)
    assert len(dp.clients) == 2  # compute-bound: no inflation
    _feed_phases(dp, _BANDWIDTH_PHASE)
    _tick(dp)
    assert dp.fleet._memory_bound_frac([0, 1]) == 1.0
    assert len(dp.clients) == 3  # same occupancy, memory-bound: grow
    assert dp.fleet.scale_outs == 1


def test_signals_goodput_floor_forces_out_and_vetoes_in(monkeypatch):
    """A tenant under its goodput floor is scale-out pressure at ANY
    occupancy and a standing scale-in veto; recovery re-enables the
    low-watermark path."""
    dp = make_fleet(monkeypatch, VDT_FLEET_SIGNALS="1",
                    VDT_FLEET_MAX_REPLICAS="2")
    dp.observe_goodput({"gold": 0.2})  # floor defaults to 0.5
    _tick(dp, 3)
    # Starved at zero occupancy: the out path fires every tick (frozen
    # at the device budget, proving the attempt), the in path never.
    assert dp.fleet.freezes.get("at_max", 0) >= 3
    assert dp.fleet._draining == {} and dp.fleet.scale_ins == 0
    dp.observe_goodput({"gold": 0.9})
    _tick(dp, 2)  # healthy again: low occupancy retires as before
    assert dp.fleet.scale_ins == 1


def test_goodput_floor_ignored_when_signals_off(monkeypatch):
    dp = make_fleet(monkeypatch, VDT_FLEET_MAX_REPLICAS="2")
    dp.observe_goodput({"gold": 0.2})
    _tick(dp, 2)
    assert dp.fleet.scale_ins == 1  # no veto: occupancy-only
